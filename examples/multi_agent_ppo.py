"""Multi-agent PPO: two policies with opposing objectives.

    python examples/multi_agent_ppo.py

Agent a0 is rewarded for action 1, agent a1 for action 0 — a shared
policy cannot satisfy both, so the two mapped policies must diverge
(the canonical policy-map smoke test).
"""

import gymnasium as gym
import numpy as np

from ray_tpu.rllib import MultiAgentEnv, PPOConfig


class OpposingBandits(MultiAgentEnv):
    agent_ids = {"a0", "a1"}
    observation_space = gym.spaces.Box(-1, 1, (2,), np.float32)
    action_space = gym.spaces.Discrete(2)

    def __init__(self, episode_len=10):
        self.episode_len = episode_len
        self._t = 0

    def _obs(self):
        return {a: np.zeros(2, np.float32) for a in ("a0", "a1")}

    def reset(self, *, seed=None, options=None):
        self._t = 0
        return self._obs(), {}

    def step(self, action_dict):
        self._t += 1
        rewards = {"a0": float(action_dict["a0"] == 1),
                   "a1": float(action_dict["a1"] == 0)}
        done = self._t >= self.episode_len
        return (self._obs(), rewards,
                {"a0": done, "a1": done, "__all__": done},
                {"a0": False, "a1": False, "__all__": False}, {})


def main():
    import ray_tpu
    if not ray_tpu.is_initialized():
        # Rollout workers are thread-based: oversubscribing a small host
        # is fine, but the logical CPU pool must fit the worker count.
        ray_tpu.init(num_cpus=8)
    algo = (PPOConfig()
            .environment(lambda cfg: OpposingBandits())
            .rollouts(num_rollout_workers=2)
            .multi_agent(policies={"p0": None, "p1": None},
                         policy_mapping_fn=lambda aid: "p" + aid[1])
            .training(lr=5e-3, train_batch_size=400,
                      num_sgd_iter=6, sgd_minibatch_size=100)
            .debugging(seed=0)).build()
    for i in range(10):
        res = algo.train()
        print(f"iter {i + 1}: joint reward "
              f"{res['episode_reward_mean']:.1f}/20  "
              f"p0 loss {res['p0/total_loss']:.3f}  "
              f"p1 loss {res['p1/total_loss']:.3f}")
    obs = np.zeros(2, np.float32)
    print("greedy actions: p0 ->",
          algo.compute_single_action(obs, policy_id="p0"),
          " p1 ->", algo.compute_single_action(obs, policy_id="p1"))
    algo.stop()


if __name__ == "__main__":
    main()
