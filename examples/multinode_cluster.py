"""Multi-process cluster demo: head + node daemons + autoscaler.

Run it directly (spawns its own daemon subprocesses on this machine):

    python examples/multinode_cluster.py

Or run the pieces by hand across hosts:

    # on the head host
    python -c "import ray_tpu; ray_tpu.init(); \
               print(ray_tpu.start_head_server(6380))"
    # on each worker host
    ray-tpu start --address head-host:6380 --num-cpus 8
"""

import os
import subprocess
import sys
import time

import ray_tpu


def wait_for(resource, amount, timeout=30):
    deadline = time.monotonic() + timeout
    while ray_tpu.cluster_resources().get(resource, 0) < amount:
        assert time.monotonic() < deadline, "node never joined"
        time.sleep(0.2)


def main():
    ray_tpu.init(num_cpus=2)
    host, port = ray_tpu.start_head_server(port=0, host="127.0.0.1")
    print(f"head listening on {host}:{port}")

    daemon = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu._private.multinode",
         "--address", f"127.0.0.1:{port}",
         "--num-cpus", "4", "--resources", '{"worker": 4}'])
    wait_for("worker", 4)
    print("node joined:", ray_tpu.cluster_resources())

    @ray_tpu.remote(resources={"worker": 1})
    def where(x):
        return os.getpid(), x * x

    results = ray_tpu.get([where.remote(i) for i in range(8)])
    print("task results (pid, x^2):", results)
    assert all(pid != os.getpid() for pid, _ in results)

    @ray_tpu.remote(resources={"worker": 1})
    class Counter:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

    c = Counter.remote()
    print("remote actor counts:", ray_tpu.get(
        [c.bump.remote() for _ in range(3)]))

    daemon.terminate()
    daemon.wait(timeout=10)
    time.sleep(1)
    print("after daemon exit:", ray_tpu.cluster_resources())
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
