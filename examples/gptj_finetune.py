"""GPT-J fine-tune through JaxTrainer — the north-star workload.

BASELINE.json's headline: fine-tune GPT-J-6B at >=40% MFU on a v4-64 via
``JaxTrainer`` with pjit/GSPMD sharding, no GPU resources requested. This
script is that workload, parameterized so the same code runs three ways:

* ``--preset gpt-tiny`` (default): smoke-run anywhere on a virtual CPU
  mesh (the SURVEY §4 fake-TPU strategy) — CI-sized shapes.
* ``--preset gpt-410m``: the single-chip benchmark model (bench.py's
  tuned recipe: Pallas flash attention, selective remat, chunked CE).
* ``--preset gptj-6b``: the real thing on a TPU pod slice — the mesh in
  ScalingConfig is laid over the slice's ICI topology, parameters are
  initialized directly in sharded form (a 6B model never materializes on
  one host), gradients psum over ICI.

Run (CPU mesh smoke):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
        python examples/gptj_finetune.py --steps 4 --cpu-mesh
"""

from __future__ import annotations

import argparse
import time


def train_loop(config: dict) -> None:
    import numpy as np

    import jax.numpy as jnp

    from ray_tpu.air import session
    from ray_tpu.models import gpt
    from ray_tpu.parallel import MeshConfig
    from ray_tpu.parallel.sharding import ShardingRules
    from ray_tpu.parallel.train_step import (default_optimizer,
                                             init_train_state,
                                             make_train_step)
    from ray_tpu.train import prepare_mesh

    mesh = prepare_mesh(MeshConfig(**config["mesh"]))
    cfg = gpt.config(config["preset"], **config.get("overrides", {}))
    rules = ShardingRules(
        sequence="sp" if config["mesh"].get("sp", 1) > 1 else None)
    optimizer = default_optimizer(learning_rate=config["lr"],
                                  total_steps=config["steps"])
    state = init_train_state(cfg, mesh, rules, optimizer,
                             seed=config["seed"])
    step = make_train_step(cfg, mesh, rules, optimizer)

    # Synthetic next-token data; swap in ray_tpu.data iter_jax_batches for
    # a real corpus (session.get_dataset_shard gives the per-worker shard).
    rng = np.random.default_rng(config["seed"] + session.get_world_rank())
    batch, seq = config["batch"], config["seq"]
    n_params = cfg.num_params()
    flops_per_token = gpt.flops_per_token(cfg)
    # Per-device peak matmul FLOP/s for the MFU estimate (same table as
    # bench.py); meaningless on the CPU smoke run, labeled accordingly.
    import jax
    kind = getattr(jax.devices()[0], "device_kind", "cpu").lower()
    peaks = {"tpu v4": 275e12, "tpu v5 lite": 197e12, "tpu v5": 459e12,
             "tpu v6 lite": 918e12}
    peak = next((v for k, v in peaks.items() if k in kind), None)
    n_devices = max(jax.device_count(), 1)

    for i in range(config["steps"]):
        toks = rng.integers(0, cfg.vocab_size, (batch, seq + 1))
        data = {"tokens": jnp.asarray(toks[:, :-1], jnp.int32),
                "targets": jnp.asarray(toks[:, 1:], jnp.int32)}
        t0 = time.perf_counter()
        state, metrics = step(state, data)
        loss = float(metrics["loss"])  # full sync
        dt = time.perf_counter() - t0
        tokens_per_s = batch * seq / dt
        report = {
            "step": i,
            "loss": loss,
            "tokens_per_s": tokens_per_s,
            "n_params": n_params,
        }
        if peak is not None:
            report["approx_mfu"] = (tokens_per_s * flops_per_token
                                    / (peak * n_devices))
        session.report(report)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--preset", default="gpt-tiny",
                        choices=["gpt-tiny", "gpt-410m", "gptj-6b"])
    parser.add_argument("--steps", type=int, default=4)
    parser.add_argument("--batch", type=int, default=None)
    parser.add_argument("--seq", type=int, default=None)
    parser.add_argument("--num-workers", type=int, default=1)
    parser.add_argument("--dp", type=int, default=2)
    parser.add_argument("--fsdp", type=int, default=2)
    parser.add_argument("--tp", type=int, default=2)
    parser.add_argument("--sp", type=int, default=1)
    parser.add_argument("--lr", type=float, default=1e-5)
    parser.add_argument("--cpu-mesh", action="store_true",
                        help="pin JAX to the virtual CPU platform in-process"
                             " (env vars can be overridden by site hooks)")
    args = parser.parse_args()

    if args.cpu_mesh:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import ray_tpu
    from ray_tpu.air import ScalingConfig
    from ray_tpu.train import JaxTrainer

    ray_tpu.init()
    sizes = {"gpt-tiny": (4, 128), "gpt-410m": (16, 1024),
             "gptj-6b": (32, 2048)}
    batch, seq = sizes[args.preset]
    overrides = {}
    if args.preset != "gpt-tiny":
        # bench.py's tuned single-chip recipe scales up unchanged.
        overrides = dict(attn_impl="flash", remat_policy="selective",
                         loss_chunk=2048)
    trainer = JaxTrainer(
        train_loop,
        train_loop_config={
            "preset": args.preset,
            "overrides": overrides,
            "mesh": {"dp": args.dp, "fsdp": args.fsdp, "tp": args.tp,
                     "sp": args.sp},
            "steps": args.steps,
            "batch": args.batch or batch,
            "seq": args.seq or seq,
            "lr": args.lr,
            "seed": 0,
        },
        scaling_config=ScalingConfig(
            num_workers=args.num_workers,
            # Reserve chips when the cluster has them; the CPU-mesh smoke
            # run (fake-TPU strategy) schedules on CPU only.
            use_tpu=ray_tpu.cluster_resources().get("TPU", 0) >= 1),
    )
    result = trainer.fit()
    print("final metrics:", result.metrics)


if __name__ == "__main__":
    main()
