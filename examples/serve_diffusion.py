"""Stable-Diffusion-style batch inference on Serve TPU replicas.

BASELINE.json config 5: "Ray Serve Stable-Diffusion batch inference on
TPU replicas". A Serve deployment holds the jitted DDIM sampler
(models/diffusion.py — the whole 50-step reverse process is ONE
compiled XLA program); ``@serve.batch`` coalesces concurrent requests
into one device batch, so replica throughput rides the chip's batched
UNet rate instead of request-at-a-time latency.

Run (CPU smoke, tiny UNet):
    python examples/serve_diffusion.py --preset unet-tiny --requests 8

Run (real chip, SD-shaped latent UNet — first compile takes a minute):
    python examples/serve_diffusion.py --preset sd-base --requests 8
"""

from __future__ import annotations

import argparse
import time

import numpy as np


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--preset", default="unet-tiny",
                        choices=["unet-tiny", "ddpm-cifar", "sd-base"])
    parser.add_argument("--requests", type=int, default=8)
    parser.add_argument("--ddim-steps", type=int, default=10)
    parser.add_argument("--max-batch", type=int, default=8)
    args = parser.parse_args()

    import jax

    import ray_tpu
    from ray_tpu import serve

    ray_tpu.init()

    @serve.deployment(name="diffusion")
    class DiffusionModel:
        def __init__(self, preset: str, ddim_steps: int,
                     max_batch: int):
            import jax
            import jax.numpy as jnp

            from ray_tpu.models import diffusion
            self.cfg = diffusion.config(preset)
            self.ddim_steps = ddim_steps
            # Init on host, transfer once (the initializer is hundreds
            # of small RNG ops — op-by-op on a remote chip is minutes).
            with jax.default_device(jax.devices("cpu")[0]):
                params = diffusion.init(self.cfg, jax.random.PRNGKey(0))
            self.params = jax.device_put(params, jax.devices()[0])
            self._seed = 0

            def sample(key, batch):
                return diffusion.ddim_sample(
                    self.params, self.cfg, key, batch,
                    n_steps=self.ddim_steps)

            # One compiled program per batch size; @serve.batch pads
            # demand into at most two sizes in practice (full + tail).
            self._sample = jax.jit(sample, static_argnums=1)

            # Dynamic batching: concurrent callers coalesce into one
            # device batch (reference: serve/batching.py).
            @serve.batch(max_batch_size=max_batch,
                         batch_wait_timeout_s=0.05)
            async def generate(prompts):
                import jax
                self._seed += 1
                imgs = self._sample(jax.random.PRNGKey(self._seed),
                                    len(prompts))
                arr = np.asarray(imgs)
                return [arr[i] for i in range(len(prompts))]

            self._generate = generate

        async def __call__(self, prompt: str = "an image"):
            return await self._generate(prompt)

    handle = serve.run(DiffusionModel.bind(
        args.preset, args.ddim_steps, args.max_batch))

    # Warmup compiles the batched program.
    img = ray_tpu.get(handle.remote("warmup"))
    print(f"image shape: {np.asarray(img).shape}")

    t0 = time.perf_counter()
    refs = [handle.remote(f"prompt {i}") for i in range(args.requests)]
    imgs = ray_tpu.get(refs)
    dt = time.perf_counter() - t0
    print(f"{len(imgs)} images in {dt:.2f}s "
          f"({len(imgs) / dt:.2f} images/s, preset={args.preset}, "
          f"ddim_steps={args.ddim_steps}, "
          f"device={jax.devices()[0].platform})")
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
