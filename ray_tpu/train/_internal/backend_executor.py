"""BackendExecutor: drives the training gang and streams results.

Analog of the reference's train/_internal/backend_executor.py:43 (start:94
creates the WorkerGroup in a placement group; start_training:315;
get_next_results:414 gathers one result per worker per round). Gang
fault-tolerance is TPU-shaped: a mesh/slice fails as a unit, so recovery
restarts the WHOLE worker group from the latest checkpoint (SURVEY.md §7
hard parts), not one worker.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import FailureConfig, ScalingConfig
from ray_tpu.air.result import Result
from ray_tpu.exceptions import RayError
from ray_tpu.train._internal.worker_group import WorkerGroup
from ray_tpu.train.backend import BackendConfig

logger = logging.getLogger("ray_tpu.train")


class TrainingFailedError(RayError):
    pass


class BackendExecutor:
    def __init__(self, backend_config: BackendConfig,
                 scaling_config: ScalingConfig,
                 failure_config: Optional[FailureConfig] = None,
                 result_timeout: Optional[float] = None):
        self.backend_config = backend_config
        self.backend = backend_config.backend_cls()
        self.scaling_config = scaling_config
        self.failure_config = failure_config or FailureConfig()
        # None = block indefinitely between reports (first steps of large
        # models can spend many minutes in XLA compilation).
        self.result_timeout = result_timeout
        self.worker_group: Optional[WorkerGroup] = None

    def start(self) -> None:
        self.worker_group = WorkerGroup(
            self.scaling_config.num_workers,
            self.scaling_config.worker_resources(),
            self.scaling_config.placement_strategy,
            bundles=self.scaling_config.as_placement_group_bundles(),
            runtime_env=getattr(self.scaling_config, "runtime_env", None))
        self.backend.on_start(self.worker_group, self.backend_config)

    def run(self, train_fn: Callable, config: dict, trial_info: dict,
            checkpoint: Optional[Checkpoint] = None,
            dataset_shards_per_worker: Optional[List[dict]] = None,
            result_callback: Optional[Callable[[dict], bool]] = None
            ) -> Result:
        """Run the loop on all workers; returns the final Result.

        result_callback receives each per-round rank-0 metrics dict; if it
        returns False, training is stopped early.
        """
        failures_left = self.failure_config.max_failures
        while True:
            try:
                return self._run_once(train_fn, config, trial_info,
                                      checkpoint, dataset_shards_per_worker,
                                      result_callback)
            except TrainingFailedError as e:
                latest = getattr(e, "latest_checkpoint", None)
                if failures_left == 0:
                    raise
                failures_left -= 1 if failures_left > 0 else 0
                logger.warning(
                    "Training failed (%s); gang-restarting worker group "
                    "from %s (%d retries left)", e,
                    latest, failures_left)
                checkpoint = latest or checkpoint
                self.shutdown()
                self.start()

    def _run_once(self, train_fn, config, trial_info, checkpoint,
                  dataset_shards_per_worker, result_callback) -> Result:
        group = self.worker_group
        self.backend.on_training_start(group, self.backend_config)
        starts = []
        for rank, worker in enumerate(group.workers):
            shards = (dataset_shards_per_worker[rank]
                      if dataset_shards_per_worker else None)
            starts.append(worker.start_training.remote(
                train_fn, config, trial_info, checkpoint, shards))
        import ray_tpu
        ray_tpu.get(starts)

        history: List[Dict[str, Any]] = []
        latest_checkpoint = checkpoint
        final_error: Optional[BaseException] = None
        stop_sent = False
        finished = [False] * len(group.workers)
        while not all(finished):
            # Submit one result request to every live worker, then gather —
            # a single round-trip per round, not N sequential ones.
            refs = {
                rank: group.workers[rank].get_next_result.remote(
                    self.result_timeout)
                for rank in range(len(group.workers)) if not finished[rank]
            }
            round_payloads: Dict[int, dict] = {
                rank: ray_tpu.get(ref, timeout=None)
                for rank, ref in refs.items()
            }
            for rank, payload in round_payloads.items():
                if payload.get("timeout"):
                    final_error = TimeoutError(
                        f"Worker {rank} produced no result within "
                        f"{self.result_timeout}s")
                    finished[rank] = True
                elif payload.get("finished"):
                    finished[rank] = True
                    if payload.get("error") is not None:
                        final_error = payload["error"]
                        logger.error("Worker %d failed:\n%s", rank,
                                     payload.get("traceback", ""))
            if final_error is not None:
                err = TrainingFailedError(str(final_error))
                err.latest_checkpoint = latest_checkpoint
                err.__cause__ = final_error
                raise err
            for payload in round_payloads.values():
                if not payload.get("finished") and \
                        payload.get("checkpoint") is not None:
                    latest_checkpoint = payload["checkpoint"]
            # Rank 0's stream is canonical for metrics (reference behavior);
            # rounds after rank 0 finishes aren't recorded.
            rank0 = round_payloads.get(0)
            if rank0 is None or rank0.get("finished"):
                continue
            metrics = rank0.get("metrics", {})
            history.append(metrics)
            if result_callback is not None and not stop_sent:
                if result_callback(metrics) is False:
                    stop_sent = True
                    for worker in group.workers:
                        worker.request_stop.remote()
        return Result(
            metrics=history[-1] if history else {},
            checkpoint=latest_checkpoint,
            metrics_history=history,
            config=config,
            trial_id=trial_info.get("trial_id", ""),
        )

    def shutdown(self) -> None:
        if self.worker_group is not None:
            self.backend.on_shutdown(self.worker_group, self.backend_config)
            self.worker_group.shutdown()
            self.worker_group = None
