"""BackendExecutor: drives the training gang and streams results.

Analog of the reference's train/_internal/backend_executor.py:43 (start:94
creates the WorkerGroup in a placement group; start_training:315;
get_next_results:414 gathers one result per worker per round). Gang
fault-tolerance is TPU-shaped: a mesh/slice fails as a unit, so recovery
restarts the WHOLE worker group from the latest checkpoint (SURVEY.md §7
hard parts), not one worker.

Failure handling covers both halves of the reference contract
(backend_executor poll loop + TrainingWorkerError gang restart):

* **Application errors** travel inside result payloads and surface as
  ``TrainingFailedError(cause_kind="app")``.
* **System failures** — a worker/daemon that actually dies raises
  ``ActorDiedError``/``NodeDiedError``/… straight out of the gang RPCs
  (``start_training``, ``get_next_result``, ``on_training_start``).
  Every such RPC is wrapped and classified with the shared
  ``ray_tpu.exceptions.is_system_failure`` (same helper as serve
  failover), so a SIGKILLed rank takes the gang-restart path too,
  resuming from ``latest_checkpoint`` — the durable URI checkpoint when
  a ``CheckpointManager`` is attached.
* **Hangs** — the result gather is ``ray_tpu.wait``-based (one dead or
  hung worker can't wedge the round behind rank order), and after
  ``RAY_TPU_train_hang_timeout_s`` without any result every pending
  rank is liveness-probed (``ping``); a failed probe is treated as a
  system failure.

Restarts are **elastic and bounded**: jittered ``Backoff`` between
attempts, a ``RAY_TPU_train_restart_wait_s`` bounded wait for resources,
and ``ScalingConfig.min_workers`` lets the gang come back smaller when
the cluster shrank.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ray_tpu._private.channel import Backoff
from ray_tpu._private.ray_config import runtime_config_value
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import FailureConfig, ScalingConfig
from ray_tpu.air.result import Result
from ray_tpu.exceptions import RayError, is_system_failure
from ray_tpu.train._internal.worker_group import WorkerGroup
from ray_tpu.train.backend import BackendConfig

logger = logging.getLogger("ray_tpu.train")


class TrainingFailedError(RayError):
    """Training failed. ``latest_checkpoint`` carries the newest
    checkpoint reported before the failure (a durable URI checkpoint
    when a storage_path was configured); ``cause_kind`` is ``"system"``
    (infrastructure died / hung) or ``"app"`` (the train loop raised).
    The original failure stays chained as ``__cause__``."""

    def __init__(self, message: str = "",
                 latest_checkpoint: Optional[Checkpoint] = None,
                 cause_kind: str = "app"):
        super().__init__(message)
        self.latest_checkpoint = latest_checkpoint
        self.cause_kind = cause_kind


def _count_gang_restart(cause: str) -> None:
    try:
        from ray_tpu._private import builtin_metrics, events
        builtin_metrics.train_gang_restarts().inc(tags={"cause": cause})
        events.emit("train", f"gang restart ({cause} failure)",
                    severity="warning", labels={"cause": cause})
    except Exception:  # noqa: BLE001 - metrics never break recovery
        pass


class BackendExecutor:
    def __init__(self, backend_config: BackendConfig,
                 scaling_config: ScalingConfig,
                 failure_config: Optional[FailureConfig] = None,
                 result_timeout: Optional[float] = None,
                 checkpoint_manager: Optional[Any] = None):
        self.backend_config = backend_config
        self.backend = backend_config.backend_cls()
        self.scaling_config = scaling_config
        self.failure_config = failure_config or FailureConfig()
        # None = block indefinitely between reports (first steps of large
        # models can spend many minutes in XLA compilation).
        self.result_timeout = result_timeout
        # Persists reported checkpoints through a durable spill backend
        # (train/_internal/checkpoint_manager.py); None keeps the
        # process-local dict/directory behavior.
        self.checkpoint_manager = checkpoint_manager
        self.worker_group: Optional[WorkerGroup] = None
        self._num_workers = scaling_config.num_workers
        # Set by a membership death push: the result gather probes
        # pending ranks immediately instead of waiting out the full
        # train_hang_timeout_s.
        self._node_death = threading.Event()

    def start(self, num_workers: Optional[int] = None) -> None:
        if num_workers is not None:
            self._num_workers = num_workers
        n = self._num_workers
        self.worker_group = WorkerGroup(
            n,
            self.scaling_config.worker_resources(),
            self.scaling_config.placement_strategy,
            bundles=self.scaling_config.as_placement_group_bundles()[:n],
            runtime_env=getattr(self.scaling_config, "runtime_env", None))
        self.backend.on_start(self.worker_group, self.backend_config)

    def run(self, train_fn: Callable, config: dict, trial_info: dict,
            checkpoint: Optional[Checkpoint] = None,
            dataset_shards_per_worker: Optional[List[dict]] = None,
            result_callback: Optional[Callable[[dict], bool]] = None
            ) -> Result:
        """Run the loop on all workers; returns the final Result.

        result_callback receives each per-round rank-0 metrics dict; if it
        returns False, training is stopped early.

        ``FailureConfig.max_failures``: 0 fails fast (original cause
        chained), N allows N gang restarts, -1 retries forever. Each
        restart resumes from the newest checkpoint reported so far.
        """
        failures_left = self.failure_config.max_failures
        restart_backoff = Backoff(initial=0.5, cap=10.0)
        membership = self._subscribe_membership()
        try:
            while True:
                try:
                    return self._run_once(train_fn, config, trial_info,
                                          checkpoint,
                                          dataset_shards_per_worker,
                                          result_callback)
                except TrainingFailedError as e:
                    latest = getattr(e, "latest_checkpoint", None)
                    if failures_left == 0:
                        raise
                    failures_left -= 1 if failures_left > 0 else 0
                    cause = getattr(e, "cause_kind", "app")
                    _count_gang_restart(cause)
                    logger.warning(
                        "Training failed (%s, cause=%s); gang-restarting "
                        "worker group from %s (%s retries left)", e, cause,
                        latest,
                        "inf" if failures_left < 0 else failures_left)
                    checkpoint = latest or checkpoint
                    self.shutdown()
                    # Jittered pause so N drivers restarting against one
                    # shrunken cluster don't stampede the scheduler.
                    time.sleep(restart_backoff.next())
                    self._restart_elastic()
        finally:
            if membership is not None:
                membership.unsubscribe(self._on_membership_event)

    def _subscribe_membership(self):
        """Subscribe to the head's membership table for node-death
        pushes when the driver runs in the head process. Best effort:
        without it the hang-timeout probe still catches dead ranks."""
        try:
            from ray_tpu._private.worker import global_worker
            membership = getattr(global_worker._runtime, "membership",
                                 None)
        except Exception:  # noqa: BLE001 - no in-process runtime
            return None
        if membership is not None:
            membership.subscribe(self._on_membership_event)
        return membership

    def _on_membership_event(self, event: dict) -> None:
        if event.get("event") == "dead":
            self._node_death.set()

    # -- elastic restart ---------------------------------------------------

    def _placeable_workers(self, desired: int) -> int:
        """How many train workers the cluster could place right now,
        judged by available resources against one worker's demand."""
        import ray_tpu
        try:
            avail = ray_tpu.available_resources()
        except Exception:  # noqa: BLE001 - no introspection: assume full
            return desired
        need = self.scaling_config.worker_resources()
        fits = desired
        for key, per_worker in need.items():
            if per_worker <= 0:
                continue
            fits = min(fits, int(avail.get(key, 0.0) // per_worker))
        return fits

    def _restart_elastic(self) -> None:
        """Re-create the worker group, waiting a bounded
        ``RAY_TPU_train_restart_wait_s`` for the full complement and
        shrinking down to ``ScalingConfig.min_workers`` if the cluster
        cannot place it (e.g. the failed slice has not been replaced)."""
        desired = self.scaling_config.num_workers
        minimum = self.scaling_config.min_workers or desired
        wait_s = float(runtime_config_value("train_restart_wait_s", 30.0))
        deadline = time.monotonic() + max(0.0, wait_s)
        last_exc: Optional[BaseException] = None
        fit = 0
        while True:
            fit = self._placeable_workers(desired)
            # Hold out for the full complement until the deadline; only
            # then settle for an elastic (>= minimum) gang.
            settle = time.monotonic() >= deadline
            if fit >= desired or (settle and fit >= minimum):
                n = desired if fit >= desired else max(minimum, fit)
                if n < desired:
                    logger.warning(
                        "Elastic gang restart with %d/%d workers "
                        "(min_workers=%d): cluster shrank and "
                        "train_restart_wait_s=%ss expired", n, desired,
                        minimum, wait_s)
                try:
                    self.start(num_workers=n)
                    return
                except Exception as exc:  # noqa: BLE001
                    # Raced a node death between sizing and reservation
                    # (the scheduler can refuse the placement group it
                    # just advertised room for). Clean up and re-size.
                    last_exc = exc
                    logger.warning(
                        "gang restart with %d workers failed (%s); "
                        "re-sizing", n, exc)
                    self.shutdown()
            if settle:
                break
            time.sleep(0.25)
        err = TrainingFailedError(
            f"cluster cannot place even min_workers={minimum} train "
            f"workers (room for {fit}) within "
            f"train_restart_wait_s={wait_s}s", cause_kind="system")
        if last_exc is not None:
            err.__cause__ = last_exc
        raise err

    # -- failure classification --------------------------------------------

    def _system_failure(self, exc: BaseException,
                        latest_checkpoint: Optional[Checkpoint]
                        ) -> TrainingFailedError:
        err = TrainingFailedError(
            f"system failure in training gang: "
            f"{type(exc).__name__}: {exc}",
            latest_checkpoint=latest_checkpoint, cause_kind="system")
        err.__cause__ = exc
        return err

    def _probe_liveness(self, ranks: List[int],
                        hang_timeout: float) -> List[int]:
        """Ping every pending rank with a bounded get; any failure
        (dead actor, lost node, probe timeout) marks the rank dead."""
        import ray_tpu
        probe_timeout = max(0.2, min(5.0, hang_timeout))
        refs = {rank: self.worker_group.workers[rank].ping.remote()
                for rank in ranks}
        dead = []
        for rank, ref in refs.items():
            try:
                ray_tpu.get(ref, timeout=probe_timeout)
            except BaseException as exc:  # noqa: BLE001
                logger.warning("liveness probe of train rank %d failed: %s",
                               rank, exc)
                dead.append(rank)
        return dead

    def _drain(self, pending: Dict[Any, int],
               latest_checkpoint: Optional[Checkpoint],
               on_payload: Callable[[int, Any], None]) -> None:
        """Gather every pending ref with ``ray_tpu.wait`` (no rank-order
        blocking: whichever rank finishes — or dies — first is observed
        first). System failures raise ``TrainingFailedError``; after
        ``RAY_TPU_train_hang_timeout_s`` with no result, unresponsive
        ranks (failed liveness probe) are treated the same way."""
        import ray_tpu
        hang_timeout = float(
            runtime_config_value("train_hang_timeout_s", 60.0))
        slice_s = min(1.0, hang_timeout / 4.0) if hang_timeout > 0 else 1.0
        last_progress = time.monotonic()
        while pending:
            ready, _ = ray_tpu.wait(list(pending), num_returns=1,
                                    timeout=slice_s)
            if ready:
                last_progress = time.monotonic()
                for ref in ready:
                    rank = pending.pop(ref)
                    try:
                        payload = ray_tpu.get(ref)
                    except BaseException as exc:  # noqa: BLE001
                        if is_system_failure(exc):
                            raise self._system_failure(
                                exc, latest_checkpoint) from exc
                        raise
                    on_payload(rank, payload)
                continue
            pushed = self._node_death.is_set()
            if pushed:
                self._node_death.clear()
            if pushed or (hang_timeout > 0 and
                          time.monotonic() - last_progress >= hang_timeout):
                # Probe now: either a membership death push arrived (a
                # node this gang may live on was declared dead — no
                # reason to wait out the hang timeout) or the gang has
                # been silent past the timeout.
                dead = self._probe_liveness(sorted(pending.values()),
                                            hang_timeout or 5.0)
                if dead:
                    why = ("a node was declared dead" if pushed else
                           f"no result for {hang_timeout}s")
                    exc = TimeoutError(
                        f"train ranks {dead} failed their liveness "
                        f"probe ({why})")
                    raise self._system_failure(exc, latest_checkpoint)
                # Alive but slow (XLA compile, giant step): keep waiting.
                last_progress = time.monotonic()

    # -- one gang attempt --------------------------------------------------

    def _reshard_accounting(self, checkpoint, new_world: int) -> None:
        """When the gang resumes a sharded checkpoint, record whether
        the mesh changed — and refuse if resharding was disabled."""
        from ray_tpu.train._internal.sharded_checkpoint import \
            ShardedCheckpoint
        if not isinstance(checkpoint, ShardedCheckpoint):
            return
        saved = checkpoint.world_size
        direction = "same" if new_world == saved else \
            ("shrink" if new_world < saved else "grow")
        if direction != "same" and not bool(
                runtime_config_value("train_reshard_on_restart", True)):
            # Deliberately NOT a TrainingFailedError: a config veto must
            # not be retried away by the gang-restart loop.
            raise RuntimeError(
                f"checkpoint seq={checkpoint.seq} was saved on {saved} "
                f"ranks but the gang now has {new_world} and "
                f"train_reshard_on_restart is disabled")
        try:
            from ray_tpu._private import builtin_metrics, events
            builtin_metrics.train_reshards().inc(
                tags={"direction": direction})
            events.emit(
                "train",
                f"resuming sharded checkpoint seq={checkpoint.seq} on "
                f"{new_world} rank(s) (saved on {saved}: {direction})",
                severity="warning" if direction != "same" else "info",
                labels={"event": "reshard", "direction": direction,
                        "saved_world": str(saved),
                        "new_world": str(new_world)})
        except Exception:  # noqa: BLE001 - accounting never breaks resume
            pass

    def _ckpt_ctx(self) -> Optional[dict]:
        """The sharded-save context handed to every rank: run identity,
        storage URI, and the seq base this attempt's saves start at."""
        mgr = self.checkpoint_manager
        if mgr is None:
            return None
        return {"run": mgr.run_name, "storage_uri": mgr.base_uri,
                "session_id": getattr(mgr._backend, "session_id", ""),
                "seq_base": mgr.next_seq_base()}

    def _commit_sharded(self, shard_acks: Dict[int, dict], world: int,
                        metrics: Optional[dict]):
        """Phase two of a sharded save: commit iff EVERY rank acked a
        clean shard write under one agreed seq. Anything less — a rank
        that reported an error, a missing ack, disagreeing seqs — fails
        this save attempt cleanly (the previous committed checkpoint
        still stands) and never writes a manifest."""
        records = [shard_acks[r] for r in sorted(shard_acks)]
        errors = {r["rank"]: r["error"] for r in records if r.get("error")}
        seqs = {int(r["seq"]) for r in records}
        why = None
        if errors:
            why = f"shard write failed on rank(s) {sorted(errors)}: " \
                  f"{list(errors.values())[0]}"
        elif len(shard_acks) != world:
            why = f"only {len(shard_acks)}/{world} ranks acked a shard"
        elif len(seqs) != 1:
            why = f"ranks disagree on save seq: {sorted(seqs)}"
        elif not any("tree_meta" in r for r in records):
            why = "no rank supplied the tree metadata"
        if why is not None:
            logger.warning("sharded save attempt not committed: %s", why)
            try:
                from ray_tpu._private import builtin_metrics, events
                builtin_metrics.train_checkpoint_persist_failures().inc()
                events.emit("train", f"sharded save aborted: {why}",
                            severity="error",
                            labels={"event": "ckpt_abort",
                                    "seq": str(min(seqs)) if seqs else ""})
            except Exception:  # noqa: BLE001
                pass
            return None
        if self.checkpoint_manager is None:
            logger.warning("sharded save reported but no checkpoint "
                           "manager is attached; dropping")
            return None
        seq = seqs.pop()
        meta = next(r["tree_meta"] for r in records if "tree_meta" in r)
        t0 = time.perf_counter()
        handle = self.checkpoint_manager.register_sharded(
            seq, meta, records, metrics=metrics)
        if handle is not None:
            # Wall time of the save: the slowest rank's shard write
            # plus the manifest commit.
            elapsed = max(float(r.get("write_s", 0.0)) for r in records) \
                + (time.perf_counter() - t0)
            try:
                from ray_tpu._private import builtin_metrics
                builtin_metrics.train_ckpt_save_seconds().observe(elapsed)
            except Exception:  # noqa: BLE001
                pass
        return handle

    def _run_once(self, train_fn, config, trial_info, checkpoint,
                  dataset_shards_per_worker, result_callback) -> Result:
        group = self.worker_group
        latest_checkpoint = checkpoint
        self._reshard_accounting(checkpoint, len(group.workers))
        ckpt_ctx = self._ckpt_ctx()
        try:
            self.backend.on_training_start(group, self.backend_config)
        except BaseException as exc:  # noqa: BLE001
            if is_system_failure(exc):
                raise self._system_failure(exc, latest_checkpoint) from exc
            raise
        starts: Dict[Any, int] = {}
        for rank, worker in enumerate(group.workers):
            shards = (dataset_shards_per_worker[rank]
                      if dataset_shards_per_worker and
                      rank < len(dataset_shards_per_worker) else None)
            starts[worker.start_training.remote(
                train_fn, config, trial_info, checkpoint, shards,
                ckpt_ctx)] = rank
        self._drain(starts, latest_checkpoint, lambda rank, payload: None)

        history: List[Dict[str, Any]] = []
        final_error: Optional[BaseException] = None
        stop_sent = False
        finished = [False] * len(group.workers)
        while not all(finished):
            # Submit one result request to every live worker, then gather
            # via wait — a dead/hung rank 0 can't stall detection of the
            # other ranks' results.
            pending = {
                group.workers[rank].get_next_result.remote(
                    self.result_timeout): rank
                for rank in range(len(group.workers)) if not finished[rank]
            }
            round_payloads: Dict[int, dict] = {}
            self._drain(pending, latest_checkpoint,
                        round_payloads.__setitem__)
            for rank, payload in round_payloads.items():
                if payload.get("timeout"):
                    final_error = TimeoutError(
                        f"Worker {rank} produced no result within "
                        f"{self.result_timeout}s")
                    finished[rank] = True
                elif payload.get("finished"):
                    finished[rank] = True
                    if payload.get("error") is not None:
                        final_error = payload["error"]
                        logger.error("Worker %d failed:\n%s", rank,
                                     payload.get("traceback", ""))
            if final_error is not None:
                err = TrainingFailedError(
                    str(final_error), latest_checkpoint=latest_checkpoint,
                    cause_kind="app")
                err.__cause__ = final_error
                raise err
            # Persist at most one checkpoint per round (ranks report
            # replicas of the same state; rank 0 is canonical).
            for rank in sorted(round_payloads):
                payload = round_payloads[rank]
                if not payload.get("finished") and \
                        payload.get("checkpoint") is not None:
                    reported = payload["checkpoint"]
                    if self.checkpoint_manager is not None:
                        latest_checkpoint = self.checkpoint_manager.register(
                            reported, payload.get("metrics"))
                    else:
                        latest_checkpoint = reported
                    break
            # Sharded saves: each live rank's payload carries its shard
            # write ack; all acks clean -> commit the manifest.
            shard_acks = {rank: p["shard"]
                          for rank, p in round_payloads.items()
                          if not p.get("finished") and p.get("shard")}
            if shard_acks:
                committed = self._commit_sharded(
                    shard_acks, len(group.workers),
                    round_payloads.get(0, {}).get("metrics"))
                if committed is not None:
                    latest_checkpoint = committed
            # Rank 0's stream is canonical for metrics (reference behavior);
            # rounds after rank 0 finishes aren't recorded.
            rank0 = round_payloads.get(0)
            if rank0 is None or rank0.get("finished"):
                continue
            metrics = rank0.get("metrics", {})
            history.append(metrics)
            if result_callback is not None and not stop_sent:
                if result_callback(metrics) is False:
                    stop_sent = True
                    for worker in group.workers:
                        worker.request_stop.remote()
        return Result(
            metrics=history[-1] if history else {},
            checkpoint=latest_checkpoint,
            metrics_history=history,
            config=config,
            trial_id=trial_info.get("trial_id", ""),
        )

    def shutdown(self) -> None:
        if self.worker_group is not None:
            self.backend.on_shutdown(self.worker_group, self.backend_config)
            self.worker_group.shutdown()
            self.worker_group = None
