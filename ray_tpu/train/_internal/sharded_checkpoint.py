"""Per-rank sharded train checkpoints with two-phase commit + reshard.

The t5x/Orbax-shaped answer to the single-writer checkpoint bottleneck:
the GSPMD layout that shards parameters across the mesh also shards the
*checkpoint* across ranks. Each rank persists only its local
parameter/optimizer blocks through a spill backend
(``train-<run>-ckpt-<seq>.shard-<rank>`` files, N parallel crash-safe
writes), and the save commits in two phases:

1. every rank writes its shard (atomic tmp → fsync → rename through
   :mod:`ray_tpu._private.spill`) and acks it to the driver through the
   ordinary result gather;
2. only after ALL shard acks does the driver write the **manifest**
   (``train-<run>-ckpt-<seq>.manifest`` — param tree structure, per-param
   spec, mesh shape, shard → file map with per-block byte offsets and
   crc32 checksums).

The manifest IS the commit record: a rank SIGKILLed mid-save can never
leave a torn checkpoint, because a shard set without a manifest is
invisible to ``CheckpointManager.latest()`` and garbage-collected on
the next index load (``_gc_orphans``).

Resharding: block boundaries are balanced ``array_split`` bounds
(:func:`ray_tpu.parallel.sharding.axis_split_bounds`), so a checkpoint
saved on 8 ranks restores onto 6 or 4 without divisibility constraints —
:meth:`ShardedCheckpoint.load_for_rank` computes the new rank's index
block per parameter and pulls only the overlapping **byte ranges** from
each saved shard (``SpillBackend.read_range``; a contiguous-rows fast
path when only dim 0 is sharded), reassembling arrays that are
numerically identical to the originals.
"""

from __future__ import annotations

import json
import logging
import time
import zlib
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ray_tpu._private import chaos, spill
from ray_tpu._private.ray_config import runtime_config_value
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.parallel.sharding import (axis_split_bounds,  # noqa: F401
                                       shard_slices, slices_overlap)

logger = logging.getLogger("ray_tpu.train")

MANIFEST_FORMAT = "ray_tpu-sharded-ckpt-v1"

#: axes_items: ordered [(mesh_axis_name, size), ...]; rank -> coords is
#: row-major over this order, matching Mesh device enumeration.
AxesItems = Sequence[Tuple[str, int]]


def _shard_parallelism() -> int:
    return max(1, int(runtime_config_value("train_ckpt_shard_parallelism",
                                           8)))


def verify_checksums_default() -> bool:
    return bool(runtime_config_value("train_ckpt_verify_checksums", True))


# ---------------------------------------------------------------------------
# File naming
# ---------------------------------------------------------------------------


def ckpt_prefix(run: str) -> str:
    return f"train-{run}-ckpt-"


def shard_filename(run: str, seq: int, rank: int) -> str:
    return f"train-{run}-ckpt-{seq:06d}.shard-{rank:04d}"


def manifest_filename(run: str, seq: int) -> str:
    return f"train-{run}-ckpt-{seq:06d}.manifest"


def is_shard_file(name: str) -> bool:
    return ".shard-" in name


def is_manifest_file(name: str) -> bool:
    return name.endswith(".manifest")


# ---------------------------------------------------------------------------
# Pytree flatten/unflatten (JSON-serializable structure skeleton)
# ---------------------------------------------------------------------------


def flatten_tree(tree: Any) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Flatten a nested dict/list/tuple of array leaves into
    ``{"a/b/0": leaf}`` plus a JSON skeleton that rebuilds the exact
    container types (dict keys are coerced to str)."""
    flat: Dict[str, Any] = {}

    def rec(node: Any, path: Tuple[str, ...]) -> Dict[str, Any]:
        if isinstance(node, dict):
            return {"kind": "dict",
                    "children": {str(k): rec(node[k], path + (str(k),))
                                 for k in sorted(node, key=str)}}
        if isinstance(node, (list, tuple)):
            kind = "list" if isinstance(node, list) else "tuple"
            return {"kind": kind,
                    "children": [rec(v, path + (str(i),))
                                 for i, v in enumerate(node)]}
        flat["/".join(path)] = node
        return {"kind": "leaf"}

    structure = rec(tree, ())
    return flat, structure


def unflatten_tree(structure: Dict[str, Any],
                   flat: Dict[str, Any]) -> Any:
    def rec(skel: Dict[str, Any], path: Tuple[str, ...]) -> Any:
        kind = skel["kind"]
        if kind == "leaf":
            return flat["/".join(path)]
        if kind == "dict":
            return {k: rec(c, path + (k,))
                    for k, c in skel["children"].items()}
        vals = [rec(c, path + (str(i),))
                for i, c in enumerate(skel["children"])]
        return vals if kind == "list" else tuple(vals)

    return rec(structure, ())


# ---------------------------------------------------------------------------
# Specs / mesh coordinates
# ---------------------------------------------------------------------------


def normalize_spec(spec: Any, ndim: int) -> List[List[str]]:
    """Per-dim spec entry → list of mesh axis names (JSON form).
    Accepts a ``jax.sharding.PartitionSpec``, tuple/list, or None
    (fully replicated)."""
    entries = list(spec) if spec is not None else []
    out: List[List[str]] = []
    for d in range(ndim):
        e = entries[d] if d < len(entries) else None
        if e is None:
            out.append([])
        elif isinstance(e, str):
            out.append([e])
        else:
            out.append([str(a) for a in e])
    return out


def default_specs(flat: Dict[str, Any], axis: str = "fsdp"
                  ) -> Dict[str, List[List[str]]]:
    """FSDP-style default: shard dim 0 of every >=1-d leaf over ``axis``
    (the ZeRO-3 analog); scalars stay replicated."""
    specs = {}
    for path, leaf in flat.items():
        ndim = np.asarray(leaf).ndim
        specs[path] = [[axis] if d == 0 else [] for d in range(ndim)]
    return specs


def rank_coords(rank: int, axes_items: AxesItems) -> Dict[str, int]:
    """Row-major rank → per-axis mesh coordinates."""
    sizes = [int(s) for _, s in axes_items]
    idx = list(np.unravel_index(rank, sizes)) if sizes else []
    return {name: int(i) for (name, _), i in zip(axes_items, idx)}


def world_size_of(axes_items: AxesItems) -> int:
    n = 1
    for _, s in axes_items:
        n *= int(s)
    return n


def extract_local_shard(flat: Dict[str, Any],
                        specs: Dict[str, Any],
                        axes_items: AxesItems,
                        rank: int) -> Dict[str, np.ndarray]:
    """This rank's index block of every leaf (C-contiguous copies).
    On a real multi-controller mesh the slice of a jax array resolves
    from the rank's addressable shards; on CPU/replicated state it is a
    plain numpy slice — either way only 1/N of the bytes survive."""
    axes = dict(axes_items)
    coords = rank_coords(rank, axes_items)
    out = {}
    for path, leaf in flat.items():
        a = np.asarray(leaf)
        spec = normalize_spec(specs.get(path), a.ndim)
        block = a[shard_slices(a.shape, spec, axes, coords)]
        # ascontiguousarray promotes 0-d to (1,); keep scalar shapes.
        out[path] = np.ascontiguousarray(block).reshape(np.shape(block))
    return out


# ---------------------------------------------------------------------------
# Shard write (runs in the rank's worker process)
# ---------------------------------------------------------------------------


def write_shard(backend: spill.SpillBackend, run: str, seq: int, rank: int,
                local_flat: Dict[str, np.ndarray]) -> Dict[str, Any]:
    """One rank's crash-safe shard write. The shard file is the pure
    concatenation of C-order blocks (one per leaf, sorted by path); all
    metadata — offsets, shapes, checksums — rides the returned record
    into the manifest, so a byte-range reader never parses the file.

    Chaos sites: ``train.ckpt_shard_write_error`` (``io_oserror`` —
    surfaces as :class:`spill.SpillFailure`, failing this save attempt
    cleanly) and ``train.ckpt_shard_kill`` (``kill`` — the SIGKILL-mid-
    save stand-in; :class:`chaos.ChaosKill` propagates so the rank can
    play dead with its shard unwritten).
    """
    blocks: Dict[str, Dict[str, Any]] = {}
    parts: List[bytes] = []
    offset = 0
    file_crc = 0
    for path in sorted(local_flat):
        a = np.ascontiguousarray(np.asarray(local_flat[path]))
        raw = a.tobytes()
        blocks[path] = {
            "offset": offset,
            "length": len(raw),
            "crc32": zlib.crc32(raw) & 0xFFFFFFFF,
            "shape": [int(s) for s in a.shape],
            "dtype": str(a.dtype),
        }
        parts.append(raw)
        file_crc = zlib.crc32(raw, file_crc)
        offset += len(raw)
    filename = shard_filename(run, seq, rank)
    t0 = time.perf_counter()
    try:
        if chaos.ACTIVE:
            chaos.maybe_inject("train.ckpt_shard_kill")
            chaos.maybe_inject("train.ckpt_shard_write_error")
    except chaos.ChaosKill:
        raise
    except OSError as exc:
        raise spill.SpillFailure(
            f"shard write of {filename} failed: {exc}") from exc
    uri = backend.write(filename, parts)
    elapsed = time.perf_counter() - t0
    try:
        from ray_tpu._private import builtin_metrics
        builtin_metrics.train_ckpt_shard_bytes().inc(
            offset, tags={"rank": str(rank)})
    except Exception:  # noqa: BLE001 - accounting never breaks a save
        pass
    return {"seq": int(seq), "rank": int(rank), "file": filename,
            "uri": uri, "bytes": offset,
            "crc32": file_crc & 0xFFFFFFFF, "blocks": blocks,
            "write_s": round(elapsed, 6)}


def build_tree_meta(flat: Dict[str, Any], structure: Dict[str, Any],
                    specs: Dict[str, Any], axes_items: AxesItems,
                    extra: Optional[Dict[str, Any]] = None
                    ) -> Dict[str, Any]:
    """The global (rank-independent) half of a manifest; identical on
    every rank, so the driver takes rank 0's copy."""
    params = {}
    norm_specs = {}
    for path, leaf in flat.items():
        a = np.asarray(leaf)
        params[path] = {"shape": [int(s) for s in a.shape],
                        "dtype": str(a.dtype)}
        norm_specs[path] = normalize_spec(specs.get(path), a.ndim)
    return {
        "mesh": [[name, int(size)] for name, size in axes_items],
        "world_size": world_size_of(axes_items),
        "params": params,
        "specs": norm_specs,
        "structure": structure,
        "extra": dict(extra or {}),
    }


# ---------------------------------------------------------------------------
# Manifest (the commit record — written LAST, by the driver)
# ---------------------------------------------------------------------------


def build_manifest(run: str, seq: int, tree_meta: Dict[str, Any],
                   shard_records: List[Dict[str, Any]]) -> Dict[str, Any]:
    shards = sorted(
        ({k: rec[k] for k in ("rank", "file", "bytes", "crc32", "blocks")}
         for rec in shard_records), key=lambda r: r["rank"])
    manifest = {"format": MANIFEST_FORMAT, "run": run, "seq": int(seq)}
    manifest.update(tree_meta)
    manifest["shards"] = shards
    return manifest


def write_manifest(backend: spill.SpillBackend, run: str, seq: int,
                   manifest: Dict[str, Any]) -> str:
    return backend.write(manifest_filename(run, seq),
                         json.dumps(manifest).encode())


def read_manifest(uri: str) -> Optional[Dict[str, Any]]:
    raw = spill.read_uri(uri)
    if raw is None:
        return None
    try:
        manifest = json.loads(raw.decode())
    except (ValueError, UnicodeDecodeError):
        return None
    if manifest.get("format") != MANIFEST_FORMAT:
        return None
    return manifest


def validate_shards(backend: spill.SpillBackend,
                    manifest: Dict[str, Any],
                    verify_checksums: bool) -> bool:
    """Are all of a manifest's shard files present, full-size, and
    (optionally) checksum-clean? Drives orphan-GC adoption/removal of
    manifests whose index entry was lost."""
    for shard in manifest.get("shards", []):
        uri = backend.uri_for(shard["file"])
        size = backend.size_of(uri)
        if size is None or size < int(shard["bytes"]):
            return False
        if verify_checksums:
            data = backend.read(uri, expected_size=int(shard["bytes"]))
            if data is None or \
                    (zlib.crc32(data) & 0xFFFFFFFF) != int(shard["crc32"]):
                return False
    return True


# ---------------------------------------------------------------------------
# The restore/reshard handle
# ---------------------------------------------------------------------------


class ShardedCheckpoint(Checkpoint):
    """A committed sharded checkpoint: a manifest plus byte-range access
    to its shard files. Cheap to ship to every rank of a (re)started
    gang — nothing is read until ``load_for_rank``/``load_full``.

    ``to_dict()`` returns the small user ``extra`` dict (step counters
    etc.); parameter state comes back through :meth:`load_for_rank`
    (the rank's block under the NEW mesh — the reshard path when the
    gang shrank or grew) or :meth:`load_full`.
    """

    def __init__(self, manifest: Dict[str, Any], uri: str):
        super().__init__(uri=uri)
        self.manifest = manifest

    @classmethod
    def from_manifest_uri(cls, uri: str) -> "ShardedCheckpoint":
        manifest = read_manifest(uri)
        if manifest is None:
            raise ValueError(
                f"no readable sharded-checkpoint manifest at {uri}")
        return cls(manifest, uri)

    # -- metadata ---------------------------------------------------------

    @property
    def seq(self) -> int:
        return int(self.manifest["seq"])

    @property
    def world_size(self) -> int:
        return int(self.manifest["world_size"])

    @property
    def mesh_axes(self) -> List[Tuple[str, int]]:
        return [(name, int(size)) for name, size in self.manifest["mesh"]]

    @property
    def extra(self) -> Dict[str, Any]:
        return dict(self.manifest.get("extra", {}))

    def to_dict(self) -> Dict[str, Any]:
        return self.extra

    @property
    def extra_metadata(self) -> Dict[str, Any]:
        return self.extra

    def _hydrate(self) -> None:
        raise ValueError(
            "sharded checkpoints have no monolithic payload; restore "
            "state with load_for_rank()/load_full()")

    _payload_bytes = _hydrate

    # -- restore / reshard ------------------------------------------------

    def _new_axes(self, world_size: Optional[int],
                  axes_items: Optional[AxesItems]) -> List[Tuple[str, int]]:
        if axes_items is not None:
            return [(n, int(s)) for n, s in axes_items]
        old = self.mesh_axes
        if world_size is None or world_size == self.world_size:
            return old
        sharded = [n for n, s in old if s > 1]
        if len(sharded) > 1:
            raise ValueError(
                f"cannot infer a {world_size}-rank mesh from saved axes "
                f"{old}: more than one sharded axis — pass axes_items")
        axis = sharded[0] if sharded else (old[0][0] if old else "fsdp")
        return [(n, world_size if n == axis else 1) for n, s in old] or \
            [(axis, world_size)]

    def load_for_rank(self, rank: int, world_size: Optional[int] = None,
                      axes_items: Optional[AxesItems] = None,
                      verify: Optional[bool] = None) -> Any:
        """This rank's local state under the NEW mesh: per parameter,
        compute the rank's index block and pull only the overlapping
        byte ranges from the saved shards. world_size == saved world is
        a plain per-rank reload; anything else is a reshard."""
        new_axes = self._new_axes(world_size, axes_items)
        if world_size is not None and world_size_of(new_axes) != world_size:
            raise ValueError(
                f"axes {new_axes} describe {world_size_of(new_axes)} "
                f"ranks, not {world_size}")
        return self._load_local(new_axes, rank, verify)

    def load_full(self, verify: Optional[bool] = None) -> Any:
        """The whole tree, reassembled (rank 0 of a 1-rank mesh)."""
        axes = [(name, 1) for name, _ in self.mesh_axes] or [("fsdp", 1)]
        return self._load_local(axes, 0, verify)

    def restore_on_mesh(self, mesh, rules=None, spec_tree=None) -> Any:
        """Reassemble and ``device_put`` under a new jax mesh — the
        single-controller reshard path (multi-controller ranks use
        ``load_for_rank`` and place their own block)."""
        from ray_tpu.parallel.sharding import shard_tree, tree_shardings
        tree = self.load_full()
        if spec_tree is None:
            import jax
            from jax.sharding import PartitionSpec
            flat, _ = flatten_tree(tree)
            specs = {p: PartitionSpec(*[tuple(e) if len(e) > 1 else
                                        (e[0] if e else None)
                                        for e in self.manifest["specs"][p]])
                     for p in flat}
            spec_tree = unflatten_tree(self.manifest["structure"], specs)
            del jax, tree_shardings
        return shard_tree(tree, mesh, spec_tree)

    # -- internals --------------------------------------------------------

    def _load_local(self, new_axes: List[Tuple[str, int]], rank: int,
                    verify: Optional[bool]) -> Any:
        verify = verify_checksums_default() if verify is None else verify
        backend = spill.reader_for_uri(self._uri)
        if backend is None:
            raise ValueError(f"no spill backend can read {self._uri}")
        manifest = self.manifest
        old_axes = self.mesh_axes
        axes = dict(new_axes)
        coords = rank_coords(rank, new_axes)
        old_coord_cache = {s["rank"]: rank_coords(s["rank"], old_axes)
                           for s in manifest["shards"]}
        t0 = time.perf_counter()

        def load_param(path: str) -> np.ndarray:
            meta = manifest["params"][path]
            shape = tuple(meta["shape"])
            dtype = np.dtype(meta["dtype"])
            spec = manifest["specs"][path]
            sel = shard_slices(shape, spec, axes, coords)
            out = np.empty(tuple(s.stop - s.start for s in sel), dtype)
            for shard in manifest["shards"]:
                old_sl = shard_slices(shape, spec, dict(old_axes),
                                      old_coord_cache[shard["rank"]])
                ov = slices_overlap(sel, old_sl)
                if ov is None:
                    continue
                block = shard["blocks"][path]
                local_shape = tuple(s.stop - s.start for s in old_sl)
                uri = backend.uri_for(shard["file"])
                dest = tuple(slice(o.start - s.start, o.stop - s.start)
                             for o, s in zip(ov, sel))
                src = tuple(slice(o.start - s.start, o.stop - s.start)
                            for o, s in zip(ov, old_sl))
                whole = all(o == s for o, s in zip(ov, old_sl))
                rows_only = shape and all(
                    o == s for o, s in zip(ov[1:], old_sl[1:]))
                if whole or not rows_only:
                    # Whole block (also the general multi-dim fallback:
                    # read the block, slice in memory).
                    raw = backend.read_range(uri, int(block["offset"]),
                                             int(block["length"]))
                    if raw is None:
                        raise ValueError(
                            f"shard {shard['file']} unreadable for "
                            f"{path} (storage lost after commit?)")
                    if verify and (zlib.crc32(raw) & 0xFFFFFFFF) != \
                            int(block["crc32"]):
                        raise ValueError(
                            f"checksum mismatch in {shard['file']} "
                            f"block {path} — corrupt shard")
                    arr = np.frombuffer(raw, dtype).reshape(local_shape)
                    out[dest] = arr[src]
                else:
                    # Contiguous-rows fast path: only dim 0 differs, so
                    # the overlap is a contiguous byte range.
                    row_bytes = dtype.itemsize * int(
                        np.prod(local_shape[1:], dtype=np.int64))
                    lo = (ov[0].start - old_sl[0].start) * row_bytes
                    nrows = ov[0].stop - ov[0].start
                    raw = backend.read_range(
                        uri, int(block["offset"]) + lo, nrows * row_bytes)
                    if raw is None:
                        raise ValueError(
                            f"shard {shard['file']} unreadable for "
                            f"{path} (storage lost after commit?)")
                    arr = np.frombuffer(raw, dtype).reshape(
                        (nrows,) + local_shape[1:])
                    out[dest] = arr[(slice(None),) + src[1:]]
            return out

        paths = sorted(manifest["params"])
        flat: Dict[str, np.ndarray] = {}
        workers = min(_shard_parallelism(), max(1, len(paths)))
        if workers > 1 and len(paths) > 1:
            with ThreadPoolExecutor(max_workers=workers) as pool:
                for path, arr in zip(paths, pool.map(load_param, paths)):
                    flat[path] = arr
        else:
            for path in paths:
                flat[path] = load_param(path)
        try:
            from ray_tpu._private import builtin_metrics
            builtin_metrics.train_ckpt_restore_seconds().observe(
                time.perf_counter() - t0)
        except Exception:  # noqa: BLE001
            pass
        return unflatten_tree(manifest["structure"], flat)

    def __repr__(self):
        return (f"ShardedCheckpoint(id={self.id}, run="
                f"{self.manifest.get('run')!r}, seq={self.seq}, "
                f"world={self.world_size}, source={self._uri})")
