"""Durable train checkpoints: persist every reported checkpoint off-node.

Analog of the reference's train/_internal/checkpoint_manager.py
(_CheckpointManager: register_checkpoint, num_to_keep /
checkpoint_score_attribute pruning) — with the durability story built on
this repo's spill backends (_private/spill.py) instead of pyarrow
filesystems: ``RunConfig.storage_path`` is a spill URI (``file://`` /
``session://`` / ``mock-s3://`` or any registered scheme), every write
is crash-safe (tmp → fsync → rename), and the manager returns a
:meth:`Checkpoint.from_uri` handle, so the "latest checkpoint" a gang
restart resumes from survives the death of the node that reported it.

A small JSON index file per run (``train-<run>-ckpts.json``) records the
persisted sequence; a new ``Trainer`` under the same ``RunConfig.name``
loads it and auto-resumes from the newest entry. With ``session://``
this spans gang restarts within one cluster session; with ``file://`` on
shared storage or ``mock-s3://`` (and real remote schemes registered via
``register_spill_backend``) it also spans full driver restarts.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any, Dict, List, Optional

from ray_tpu._private import spill
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import CheckpointConfig

logger = logging.getLogger("ray_tpu.train")


def _count_persist_failure(what: str) -> None:
    try:
        from ray_tpu._private import builtin_metrics, events
        builtin_metrics.train_checkpoint_persist_failures().inc()
        events.emit("train", f"durable checkpoint {what} write failed",
                    severity="error", labels={"what": what})
    except Exception:  # noqa: BLE001 - accounting never breaks training
        pass


def normalize_storage_uri(storage_path: str) -> str:
    """``RunConfig.storage_path`` → spill URI: plain paths become
    absolute ``file://`` URIs; anything with a scheme passes through."""
    if "://" in storage_path:
        return storage_path
    return "file://" + os.path.abspath(storage_path)


def _current_session_id() -> str:
    try:
        from ray_tpu._private.worker import global_worker
        return global_worker.runtime.session_id
    except Exception:  # noqa: BLE001 - no runtime up (unit tests)
        return ""


def _sanitize(name: str) -> str:
    return "".join(c if (c.isalnum() or c in "-_.") else "-" for c in name)


class CheckpointManager:
    """Persists reported checkpoints for one run through a spill backend,
    honoring ``CheckpointConfig.num_to_keep`` /
    ``checkpoint_score_attribute``, and finds the newest durable
    checkpoint for auto-resume."""

    def __init__(self, storage_path: str, run_name: str,
                 checkpoint_config: Optional[CheckpointConfig] = None):
        self.config = checkpoint_config or CheckpointConfig()
        self.run_name = _sanitize(run_name or "train")
        self.base_uri = normalize_storage_uri(storage_path)
        self._backend = spill.backend_for_uri(
            self.base_uri, session_id=_current_session_id())
        # [{"uri","seq","score"}] oldest-first; seq is monotonic across
        # restarts of the same run (resumed from the index).
        self._tracked: List[Dict[str, Any]] = []
        self._seq = 0
        self._load_index()

    # -- index -------------------------------------------------------------

    @property
    def _index_filename(self) -> str:
        return f"train-{self.run_name}-ckpts.json"

    def _load_index(self) -> None:
        raw = self._backend.read(
            self._backend.uri_for(self._index_filename))
        if raw is None:
            return
        try:
            index = json.loads(raw.decode())
            self._seq = int(index.get("seq", 0))
            self._tracked = [
                e for e in index.get("checkpoints", [])
                if isinstance(e, dict) and e.get("uri")
            ]
        except (ValueError, UnicodeDecodeError):
            logger.warning("corrupt checkpoint index for run %r; starting "
                           "a fresh index", self.run_name)

    def _write_index(self) -> None:
        payload = json.dumps({
            "seq": self._seq,
            "checkpoints": self._tracked,
        }).encode()
        try:
            self._backend.write(self._index_filename, payload)
        except spill.SpillFailure as exc:
            # The checkpoint itself landed; a stale index only costs
            # auto-resume precision, never training progress.
            logger.warning("checkpoint index write failed: %s", exc)
            _count_persist_failure("index")

    # -- registration ------------------------------------------------------

    def register(self, checkpoint: Checkpoint,
                 metrics: Optional[Dict[str, Any]] = None) -> Checkpoint:
        """Persist one reported checkpoint; returns the durable
        :meth:`Checkpoint.from_uri` handle to restore from (or the
        original checkpoint unchanged if the write failed — training
        must not die because storage hiccuped)."""
        self._seq += 1
        filename = f"train-{self.run_name}-ckpt-{self._seq:06d}.ckpt"
        try:
            uri = self._backend.write(filename, checkpoint._payload_bytes())
        except spill.SpillFailure as exc:
            self._seq -= 1
            logger.warning(
                "durable checkpoint write failed (%s); gang restart will "
                "fall back to the in-memory checkpoint", exc)
            _count_persist_failure("checkpoint")
            return checkpoint
        score = None
        attr = self.config.checkpoint_score_attribute
        if attr and metrics is not None:
            value = metrics.get(attr)
            if isinstance(value, (int, float)):
                score = float(value)
        self._tracked.append({"uri": uri, "seq": self._seq, "score": score})
        self._prune()
        self._write_index()
        try:
            from ray_tpu._private import builtin_metrics
            builtin_metrics.train_checkpoints_persisted().inc()
        except Exception:  # noqa: BLE001 - metrics never break training
            pass
        return Checkpoint.from_uri(uri)

    def _prune(self) -> None:
        keep = self.config.num_to_keep
        if not keep or len(self._tracked) <= keep:
            return
        newest = max(self._tracked, key=lambda e: e["seq"])
        if self.config.checkpoint_score_attribute:
            # Best-by-score, but the newest checkpoint is always
            # retained — it is what a gang restart resumes from.
            reverse = self.config.checkpoint_score_order != "min"
            worst = float("-inf") if reverse else float("inf")
            ranked = sorted(
                self._tracked,
                key=lambda e: (e["score"] if e["score"] is not None
                               else worst),
                reverse=reverse)
            kept = ranked[:keep]
            if newest not in kept:
                kept[-1] = newest
        else:
            kept = sorted(self._tracked,
                          key=lambda e: e["seq"])[-keep:]
        kept_uris = {e["uri"] for e in kept}
        for entry in self._tracked:
            if entry["uri"] not in kept_uris:
                self._backend.delete(entry["uri"])
        self._tracked = sorted(kept, key=lambda e: e["seq"])

    # -- resume ------------------------------------------------------------

    def latest(self) -> Optional[Checkpoint]:
        """The newest persisted checkpoint of this run, or None."""
        if not self._tracked:
            return None
        entry = max(self._tracked, key=lambda e: e["seq"])
        return Checkpoint.from_uri(entry["uri"])

    def best(self) -> Optional[Checkpoint]:
        """The best-scored persisted checkpoint (falls back to newest
        when no score attribute is configured/recorded)."""
        scored = [e for e in self._tracked if e["score"] is not None]
        if not scored:
            return self.latest()
        reverse = self.config.checkpoint_score_order != "min"
        entry = sorted(scored, key=lambda e: e["score"], reverse=reverse)[0]
        return Checkpoint.from_uri(entry["uri"])
