"""Durable train checkpoints: persist every reported checkpoint off-node.

Analog of the reference's train/_internal/checkpoint_manager.py
(_CheckpointManager: register_checkpoint, num_to_keep /
checkpoint_score_attribute pruning) — with the durability story built on
this repo's spill backends (_private/spill.py) instead of pyarrow
filesystems: ``RunConfig.storage_path`` is a spill URI (``file://`` /
``session://`` / ``mock-s3://`` or any registered scheme), every write
is crash-safe (tmp → fsync → rename), and the manager returns a
:meth:`Checkpoint.from_uri` handle, so the "latest checkpoint" a gang
restart resumes from survives the death of the node that reported it.

A small JSON index file per run (``train-<run>-ckpts.json``) records the
persisted sequence; a new ``Trainer`` under the same ``RunConfig.name``
loads it and auto-resumes from the newest entry. With ``session://``
this spans gang restarts within one cluster session; with ``file://`` on
shared storage or ``mock-s3://`` (and real remote schemes registered via
``register_spill_backend``) it also spans full driver restarts.

Sharded checkpoints (per-rank ``.shard-<rank>`` files + a ``.manifest``
commit record — see ``sharded_checkpoint.py``) share the same index and
seq space. The *manifest* is the commit point: :meth:`register_sharded`
writes it only after every rank's shard write was acked, and
``_load_index`` reconciles storage against committed manifests — shard
files no committed manifest references (mid-save crash debris) and
manifests with missing/corrupt shards are garbage-collected
(``ray_tpu_train_ckpt_orphans_gc_total``), while valid manifests that
merely lost their index entry (crash between commit and index write)
are adopted back. The JSON index is a rebuildable cache, never the
source of truth.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any, Dict, List, Optional

from ray_tpu._private import spill
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import CheckpointConfig

logger = logging.getLogger("ray_tpu.train")


def _count_persist_failure(what: str) -> None:
    try:
        from ray_tpu._private import builtin_metrics, events
        builtin_metrics.train_checkpoint_persist_failures().inc()
        events.emit("train", f"durable checkpoint {what} write failed",
                    severity="error", labels={"what": what})
    except Exception:  # noqa: BLE001 - accounting never breaks training
        pass


def normalize_storage_uri(storage_path: str) -> str:
    """``RunConfig.storage_path`` → spill URI: plain paths become
    absolute ``file://`` URIs; anything with a scheme passes through."""
    if "://" in storage_path:
        return storage_path
    return "file://" + os.path.abspath(storage_path)


def _current_session_id() -> str:
    try:
        from ray_tpu._private.worker import global_worker
        return global_worker.runtime.session_id
    except Exception:  # noqa: BLE001 - no runtime up (unit tests)
        return ""


def _sanitize(name: str) -> str:
    return "".join(c if (c.isalnum() or c in "-_.") else "-" for c in name)


class CheckpointManager:
    """Persists reported checkpoints for one run through a spill backend,
    honoring ``CheckpointConfig.num_to_keep`` /
    ``checkpoint_score_attribute``, and finds the newest durable
    checkpoint for auto-resume."""

    def __init__(self, storage_path: str, run_name: str,
                 checkpoint_config: Optional[CheckpointConfig] = None):
        self.config = checkpoint_config or CheckpointConfig()
        self.run_name = _sanitize(run_name or "train")
        self.base_uri = normalize_storage_uri(storage_path)
        self._backend = spill.backend_for_uri(
            self.base_uri, session_id=_current_session_id())
        # [{"uri","seq","score"}] oldest-first; sharded entries add
        # {"sharded": True, "files": [shard filenames]}. seq is
        # monotonic across restarts of the same run (resumed from the
        # index).
        self._tracked: List[Dict[str, Any]] = []
        self._seq = 0
        self._load_index()

    # -- index -------------------------------------------------------------

    @property
    def _index_filename(self) -> str:
        return f"train-{self.run_name}-ckpts.json"

    def _load_index(self) -> None:
        raw = self._backend.read(
            self._backend.uri_for(self._index_filename))
        if raw is not None:
            try:
                index = json.loads(raw.decode())
                self._seq = int(index.get("seq", 0))
                self._tracked = [
                    e for e in index.get("checkpoints", [])
                    if isinstance(e, dict) and e.get("uri")
                ]
            except (ValueError, UnicodeDecodeError):
                logger.warning("corrupt checkpoint index for run %r; "
                               "starting a fresh index", self.run_name)
        self._gc_orphans()

    def _gc_orphans(self) -> None:
        """Reconcile storage against committed manifests (runs at every
        index load — i.e. manager construction, when no save is in
        flight). Three cases: shard files referenced by no committed
        manifest (a rank died mid-save, or a pre-shrink stale rank) are
        deleted; manifests whose shards are missing/short/corrupt are
        uncommitted (manifest + surviving shards deleted); valid
        manifests absent from the index (crash after commit, before the
        index write) are adopted back in."""
        from ray_tpu.train._internal import sharded_checkpoint as sc
        names = self._backend.list_files(
            prefix=sc.ckpt_prefix(self.run_name))
        shard_names = [n for n in names if sc.is_shard_file(n)]
        manifest_names = [n for n in names if sc.is_manifest_file(n)]
        if not shard_names and not manifest_names:
            return
        verify = sc.verify_checksums_default()
        indexed = {e["uri"] for e in self._tracked}
        referenced: set = set()
        removed = 0
        adopted = 0
        changed = False
        for name in manifest_names:
            uri = self._backend.uri_for(name)
            manifest = sc.read_manifest(uri)
            ok = manifest is not None and sc.validate_shards(
                self._backend, manifest, verify)
            if not ok:
                # Uncommitted/torn: drop the manifest first, then any
                # shards it names — they revert to unreferenced debris.
                self._backend.delete(uri)
                removed += 1
                if manifest is not None:
                    for shard in manifest.get("shards", []):
                        if shard["file"] in shard_names:
                            self._backend.delete(
                                self._backend.uri_for(shard["file"]))
                            shard_names.remove(shard["file"])
                            removed += 1
                if uri in indexed:
                    self._tracked = [e for e in self._tracked
                                     if e["uri"] != uri]
                    changed = True
                continue
            referenced.update(s["file"] for s in manifest["shards"])
            seq = int(manifest["seq"])
            self._seq = max(self._seq, seq)
            if uri not in indexed:
                self._tracked.append({
                    "uri": uri, "seq": seq, "score": None,
                    "sharded": True,
                    "files": [s["file"] for s in manifest["shards"]],
                })
                adopted += 1
                changed = True
        for name in shard_names:
            if name not in referenced:
                self._backend.delete(self._backend.uri_for(name))
                removed += 1
        if changed:
            self._tracked.sort(key=lambda e: e["seq"])
            self._write_index()
        if removed or adopted:
            try:
                from ray_tpu._private import builtin_metrics, events
                if removed:
                    builtin_metrics.train_ckpt_orphans_gc().inc(removed)
                events.emit(
                    "train",
                    f"checkpoint GC for run {self.run_name!r}: "
                    f"{removed} orphan file(s) removed, "
                    f"{adopted} committed manifest(s) adopted",
                    severity="warning" if removed else "info",
                    labels={"run": self.run_name, "event": "ckpt_gc",
                            "removed": str(removed),
                            "adopted": str(adopted)})
            except Exception:  # noqa: BLE001 - GC accounting is best-effort
                pass

    def _write_index(self) -> None:
        payload = json.dumps({
            "seq": self._seq,
            "checkpoints": self._tracked,
        }).encode()
        try:
            self._backend.write(self._index_filename, payload)
        except spill.SpillFailure as exc:
            # The checkpoint itself landed; a stale index only costs
            # auto-resume precision, never training progress.
            logger.warning("checkpoint index write failed: %s", exc)
            _count_persist_failure("index")

    # -- registration ------------------------------------------------------

    def register(self, checkpoint: Checkpoint,
                 metrics: Optional[Dict[str, Any]] = None) -> Checkpoint:
        """Persist one reported checkpoint; returns the durable
        :meth:`Checkpoint.from_uri` handle to restore from (or the
        original checkpoint unchanged if the write failed — training
        must not die because storage hiccuped)."""
        self._seq += 1
        filename = f"train-{self.run_name}-ckpt-{self._seq:06d}.ckpt"
        try:
            uri = self._backend.write(filename, checkpoint._payload_bytes())
        except spill.SpillFailure as exc:
            self._seq -= 1
            logger.warning(
                "durable checkpoint write failed (%s); gang restart will "
                "fall back to the in-memory checkpoint", exc)
            _count_persist_failure("checkpoint")
            return checkpoint
        score = None
        attr = self.config.checkpoint_score_attribute
        if attr and metrics is not None:
            value = metrics.get(attr)
            if isinstance(value, (int, float)):
                score = float(value)
        self._tracked.append({"uri": uri, "seq": self._seq, "score": score})
        self._prune()
        self._write_index()
        try:
            from ray_tpu._private import builtin_metrics
            builtin_metrics.train_checkpoints_persisted().inc()
        except Exception:  # noqa: BLE001 - metrics never break training
            pass
        return Checkpoint.from_uri(uri)

    def next_seq_base(self) -> int:
        """The seq the next sharded save attempt should use. Handed to
        the gang at (re)start so every rank writes shard files under the
        same agreed seq; a failed/uncommitted attempt may reuse its seq
        (shard writes are atomic overwrites, and GC reaps strays)."""
        return self._seq + 1

    def register_sharded(self, seq: int, tree_meta: Dict[str, Any],
                         shard_records: List[Dict[str, Any]],
                         metrics: Optional[Dict[str, Any]] = None):
        """Phase two of a sharded save: every rank's shard write has
        been acked — write the manifest (THE commit point), index it,
        prune. Returns the durable ``ShardedCheckpoint`` handle, or
        None when the commit failed (previous checkpoint still stands;
        the uncommitted shard set is invisible and GC'd later)."""
        from ray_tpu.train._internal import sharded_checkpoint as sc
        ranks = sorted(int(r["rank"]) for r in shard_records)
        if ranks != list(range(len(ranks))) or not ranks:
            raise ValueError(
                f"sharded save acked by ranks {ranks}; need a full "
                f"contiguous gang to commit")
        manifest = sc.build_manifest(self.run_name, seq, tree_meta,
                                     shard_records)
        try:
            uri = sc.write_manifest(self._backend, self.run_name, seq,
                                    manifest)
        except spill.SpillFailure as exc:
            logger.warning(
                "sharded checkpoint commit (manifest write) failed (%s); "
                "shard set seq=%d stays uncommitted", exc, seq)
            _count_persist_failure("manifest")
            return None
        self._seq = max(self._seq, int(seq))
        score = None
        attr = self.config.checkpoint_score_attribute
        if attr and metrics is not None:
            value = metrics.get(attr)
            if isinstance(value, (int, float)):
                score = float(value)
        self._tracked.append({
            "uri": uri, "seq": int(seq), "score": score, "sharded": True,
            "files": [s["file"] for s in manifest["shards"]],
        })
        self._tracked.sort(key=lambda e: e["seq"])
        self._prune()
        self._write_index()
        total_bytes = sum(int(s["bytes"]) for s in manifest["shards"])
        try:
            from ray_tpu._private import builtin_metrics, events
            builtin_metrics.train_checkpoints_persisted().inc()
            events.emit(
                "train",
                f"sharded checkpoint seq={seq} committed: "
                f"{len(shard_records)} shard(s), {total_bytes} bytes, "
                f"mesh {tree_meta.get('mesh')}",
                labels={"run": self.run_name, "event": "ckpt_commit",
                        "seq": str(seq),
                        "shards": str(len(shard_records)),
                        "bytes": str(total_bytes)})
        except Exception:  # noqa: BLE001 - accounting never breaks saves
            pass
        return sc.ShardedCheckpoint(manifest, uri)

    def _delete_entry(self, entry: Dict[str, Any]) -> None:
        """Remove one checkpoint's storage. Sharded entries delete the
        manifest FIRST (uncommitting the set), then the shard files —
        a crash mid-prune leaves only unreferenced shards, which is
        exactly the orphan-GC path."""
        self._backend.delete(entry["uri"])
        for name in entry.get("files", []):
            self._backend.delete(self._backend.uri_for(name))

    def _prune(self) -> None:
        keep = self.config.num_to_keep
        if not keep or len(self._tracked) <= keep:
            return
        newest = max(self._tracked, key=lambda e: e["seq"])
        if self.config.checkpoint_score_attribute:
            # Best-by-score, but the newest checkpoint is always
            # retained — it is what a gang restart resumes from.
            reverse = self.config.checkpoint_score_order != "min"
            worst = float("-inf") if reverse else float("inf")
            ranked = sorted(
                self._tracked,
                key=lambda e: (e["score"] if e["score"] is not None
                               else worst),
                reverse=reverse)
            kept = ranked[:keep]
            if newest not in kept:
                kept[-1] = newest
        else:
            kept = sorted(self._tracked,
                          key=lambda e: e["seq"])[-keep:]
        kept_uris = {e["uri"] for e in kept}
        for entry in self._tracked:
            if entry["uri"] not in kept_uris:
                self._delete_entry(entry)
        self._tracked = sorted(kept, key=lambda e: e["seq"])

    # -- resume ------------------------------------------------------------

    def _handle(self, entry: Dict[str, Any]) -> Optional[Checkpoint]:
        if not entry.get("sharded"):
            return Checkpoint.from_uri(entry["uri"])
        from ray_tpu.train._internal import sharded_checkpoint as sc
        try:
            return sc.ShardedCheckpoint.from_manifest_uri(entry["uri"])
        except ValueError:
            logger.warning("committed sharded checkpoint %s lost its "
                           "manifest; skipping", entry["uri"])
            return None

    def latest(self) -> Optional[Checkpoint]:
        """The newest persisted checkpoint of this run, or None. Only
        *committed* checkpoints live in ``_tracked`` — a shard set whose
        manifest was never written is invisible here by construction."""
        for entry in sorted(self._tracked, key=lambda e: e["seq"],
                            reverse=True):
            handle = self._handle(entry)
            if handle is not None:
                return handle
        return None

    def best(self) -> Optional[Checkpoint]:
        """The best-scored persisted checkpoint (falls back to newest
        when no score attribute is configured/recorded)."""
        scored = [e for e in self._tracked if e["score"] is not None]
        if not scored:
            return self.latest()
        reverse = self.config.checkpoint_score_order != "min"
        for entry in sorted(scored, key=lambda e: e["score"],
                            reverse=reverse):
            handle = self._handle(entry)
            if handle is not None:
                return handle
        return self.latest()
