"""WorkerGroup: the gang of train-worker actors.

Analog of the reference's train/_internal/worker_group.py:92 (WorkerGroup of
actors created inside the trainer's placement group). Each TrainWorker runs
the user's train loop on a side thread and streams results through its
session queue; the driver drains via ``get_next_result`` actor calls —
the same protocol as the reference's ``start_training``/``get_next_results``
(train/_internal/backend_executor.py:315,414).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu._private import chaos
from ray_tpu.air import session as air_session
from ray_tpu.air.session import StopSession, _Session
from ray_tpu.exceptions import ActorDiedError
from ray_tpu.util.placement_group import (PlacementGroup, placement_group,
                                          remove_placement_group)


@ray_tpu.remote
class TrainWorker:
    """One rank of the training gang.

    Chaos sites ``train.worker_kill`` / ``train.result_delay_ms`` /
    ``train.ping_delay_ms`` are evaluated at the top of the driver-facing
    RPCs: a fired kill makes this worker play dead (every subsequent
    call raises ActorDiedError — the same observable behavior as a real
    SIGKILLed rank), which the BackendExecutor classifies as a system
    failure and answers with a gang restart."""

    def __init__(self, world_rank: int, world_size: int):
        self.world_rank = world_rank
        self.world_size = world_size
        self.session: Optional[_Session] = None
        self.thread: Optional[threading.Thread] = None
        self.env: Dict[str, str] = {}
        self._chaos_dead = False

    def _chaos_gate(self, delay_site: str) -> None:
        if chaos.ACTIVE:
            chaos.maybe_inject(delay_site)
            try:
                chaos.maybe_inject("train.worker_kill")
            except chaos.ChaosKill:
                self._chaos_dead = True
        if self._chaos_dead:
            raise ActorDiedError(
                message=f"train worker rank {self.world_rank} is dead "
                        "(chaos kill)")

    def _mark_chaos_dead(self) -> None:
        self._chaos_dead = True

    def setup_env(self, env: Dict[str, str]) -> None:
        """Backend hook: set process env (e.g. jax.distributed coordinator)."""
        import os
        self.env.update(env)
        os.environ.update(env)

    def get_metadata(self) -> dict:
        import socket
        return {"rank": self.world_rank, "hostname": socket.gethostname(),
                "tpu_ids": ray_tpu.get_tpu_ids()}

    def jax_distributed_init(self) -> None:
        from ray_tpu.train.jax import distributed_init_if_needed
        distributed_init_if_needed()

    def ping(self) -> bool:
        """Liveness probe for the executor's hang detector: cheap, and
        subject to the same chaos gate as the result path, so a
        chaos-killed or chaos-hung worker fails its probe the way a
        SIGKILLed one would."""
        self._chaos_gate("train.ping_delay_ms")
        return True

    def start_training(self, train_fn: Callable, config: dict,
                       trial_info: dict,
                       checkpoint=None, dataset_shards: Optional[dict] = None,
                       ckpt_ctx: Optional[dict] = None
                       ) -> None:
        self._chaos_gate("train.start_delay_ms")
        self.session = _Session(
            world_rank=self.world_rank,
            world_size=self.world_size,
            local_rank=self.world_rank,  # single-node: local == world
            trial_id=trial_info.get("trial_id", ""),
            trial_name=trial_info.get("trial_name", ""),
            config=config,
            checkpoint=checkpoint,
            dataset_shards=dataset_shards,
            ckpt_ctx=ckpt_ctx,
        )
        # A chaos kill fired mid-shard-write takes the whole rank down:
        # the session flags the actor dead, so every later RPC raises
        # ActorDiedError — the same observable behavior as a real
        # SIGKILL landing between a shard write and its ack.
        self.session.on_chaos_kill = self._mark_chaos_dead
        sess = self.session
        # The actor's runtime_env env_vars are APPLIED around this
        # method call only — but the train loop runs in a thread that
        # outlives it and reads env (e.g. RAY_TPU_JAX_PLATFORM in
        # distributed_init_if_needed). Snapshot now, re-assert in the
        # thread: losing this race left multi-controller workers
        # initializing jax on the wrong platform/device count, where
        # the first cross-process collective deadlocks.
        import os
        env_snapshot = dict(os.environ)

        def _run():
            for k, v in env_snapshot.items():
                if os.environ.get(k) != v:
                    os.environ[k] = v
            air_session._set_session(sess)
            try:
                try:
                    result = train_fn(config) if _wants_config(train_fn) \
                        else train_fn()
                    sess.result_queue.put(
                        {"finished": True, "result": result})
                except StopSession:
                    sess.result_queue.put({"finished": True, "stopped": True})
                except BaseException as e:  # noqa: BLE001
                    import traceback
                    sess.result_queue.put({
                        "finished": True, "error": e,
                        "traceback": traceback.format_exc()})
            finally:
                air_session._set_session(None)

        self.thread = threading.Thread(
            target=_run, name=f"train-rank-{self.world_rank}", daemon=True)
        self.thread.start()

    def get_next_result(self, timeout: Optional[float] = None) -> dict:
        """Blocks until the worker reports or finishes, then lets it
        continue. timeout=None blocks indefinitely (a dead train thread
        always pushes a finished sentinel, so this cannot hang silently);
        pass a float to surface report gaps as {'timeout': True}."""
        self._chaos_gate("train.result_delay_ms")
        import queue as _q
        try:
            item = self.session.result_queue.get(timeout=timeout)
        except _q.Empty:
            return {"timeout": True}
        if not item.get("finished"):
            self.session.continue_event.set()
        return item

    def request_stop(self) -> None:
        if self.session is not None:
            self.session.stop_requested = True
            self.session.continue_event.set()

    def shutdown(self) -> None:
        self.request_stop()


def _wants_config(fn: Callable) -> bool:
    import inspect
    try:
        return len(inspect.signature(fn).parameters) >= 1
    except (TypeError, ValueError):
        return False


class WorkerGroup:
    def __init__(self, num_workers: int,
                 resources_per_worker: Dict[str, float],
                 placement_strategy: str = "PACK",
                 bundles: Optional[List[Dict[str, float]]] = None,
                 runtime_env: Optional[Dict[str, Any]] = None):
        self.num_workers = num_workers
        self._pg: Optional[PlacementGroup] = placement_group(
            bundles or [dict(resources_per_worker)
                        for _ in range(num_workers)],
            strategy=placement_strategy)
        self.workers: List[Any] = []
        for rank in range(num_workers):
            worker_cls = TrainWorker.options(
                num_cpus=resources_per_worker.get("CPU", 1),
                num_tpus=resources_per_worker.get("TPU", 0),
                resources={k: v for k, v in resources_per_worker.items()
                           if k not in ("CPU", "TPU", "memory")},
                placement_group=self._pg,
                placement_group_bundle_index=rank,
                max_concurrency=4,
                runtime_env=runtime_env,
            )
            self.workers.append(worker_cls.remote(rank, num_workers))

    def execute(self, method: str, *args, **kwargs) -> List[Any]:
        """Call a method on every worker, gather results."""
        refs = [getattr(w, method).remote(*args, **kwargs)
                for w in self.workers]
        return ray_tpu.get(refs)

    def execute_async(self, method: str, *args, **kwargs):
        return [getattr(w, method).remote(*args, **kwargs)
                for w in self.workers]

    def shutdown(self) -> None:
        for w in self.workers:
            try:
                ray_tpu.get(w.shutdown.remote(), timeout=5)
            except Exception:  # noqa: BLE001
                pass
            ray_tpu.kill(w)
        if self._pg is not None:
            remove_placement_group(self._pg)
            self._pg = None
