"""Predictors: checkpoint → batch inference.

Analog of the reference's train/predictor.py + batch_predictor.py: a
Predictor wraps restored model state and maps batches to predictions; a
BatchPredictor runs a predictor over a Dataset with an autoscaling actor
pool (each actor holds the model once — on TPU serving, a compiled pjit
program per actor).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Type

import numpy as np

from ray_tpu.air.checkpoint import Checkpoint


class Predictor:
    """Base predictor. Subclasses implement ``_predict_numpy``."""

    def __init__(self, preprocessor=None):
        self._preprocessor = preprocessor

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, **kwargs) -> "Predictor":
        raise NotImplementedError

    def predict(self, batch: Any) -> Any:
        if self._preprocessor is not None:
            batch = self._preprocessor.transform_batch(batch)
        return self._predict_numpy(batch)

    def _predict_numpy(self, batch: Any) -> Any:
        raise NotImplementedError


class JaxPredictor(Predictor):
    """Predictor over a functional JAX model: ``apply_fn(params, batch)``.

    The checkpoint holds {"params": pytree}; apply_fn is jitted once per
    process so repeated batches reuse the compiled program.
    """

    def __init__(self, params, apply_fn: Callable, preprocessor=None,
                 jit: bool = True):
        super().__init__(preprocessor)
        import jax
        self.params = params
        self._apply = jax.jit(apply_fn) if jit else apply_fn

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, *,
                        apply_fn: Callable, **kwargs) -> "JaxPredictor":
        data = checkpoint.to_dict()
        params = data.get("params", data.get("model"))
        if params is None:
            raise ValueError(
                "Checkpoint must contain 'params' (or 'model') for "
                "JaxPredictor")
        return cls(params, apply_fn,
                   preprocessor=data.get("preprocessor"), **kwargs)

    def _predict_numpy(self, batch: Any) -> Any:
        import jax.numpy as jnp
        if isinstance(batch, dict):
            inp = {k: jnp.asarray(v) for k, v in batch.items()}
        else:
            inp = jnp.asarray(batch)
        out = self._apply(self.params, inp)
        import jax
        return jax.tree.map(np.asarray, out)


class BatchPredictor:
    """Maps a Predictor over a Dataset (reference: batch_predictor.py):
    one predictor instance per actor, batches stream through the actor
    pool."""

    def __init__(self, checkpoint: Checkpoint,
                 predictor_cls: Type[Predictor], **predictor_kwargs):
        self._checkpoint = checkpoint
        self._predictor_cls = predictor_cls
        self._predictor_kwargs = predictor_kwargs

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint,
                        predictor_cls: Type[Predictor],
                        **predictor_kwargs) -> "BatchPredictor":
        return cls(checkpoint, predictor_cls, **predictor_kwargs)

    def predict(self, dataset, *, batch_size: int = 256,
                min_scoring_workers: int = 1,
                max_scoring_workers: int = 2,
                num_cpus_per_worker: float = 1.0,
                feature_columns=None,
                keep_columns=None):
        """Returns a Dataset of prediction batches."""
        from ray_tpu.data._internal.compute import ActorPoolStrategy

        checkpoint = self._checkpoint
        predictor_cls = self._predictor_cls
        predictor_kwargs = self._predictor_kwargs

        class _ScoringActor:
            def __init__(self):
                self.predictor = predictor_cls.from_checkpoint(
                    checkpoint, **predictor_kwargs)

            def __call__(self, batch: Dict[str, np.ndarray]):
                inp = batch
                if feature_columns:
                    inp = {k: batch[k] for k in feature_columns}
                out = self.predictor.predict(inp)
                if not isinstance(out, dict):
                    out = {"predictions": np.asarray(out)}
                if keep_columns:
                    for k in keep_columns:
                        out[k] = batch[k]
                return out

        return dataset.map_batches(
            _ScoringActor,
            batch_size=batch_size,
            compute=ActorPoolStrategy(min_size=min_scoring_workers,
                                      max_size=max_scoring_workers),
            num_cpus=num_cpus_per_worker)
