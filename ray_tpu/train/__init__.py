from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import (CheckpointConfig, FailureConfig, RunConfig,
                                ScalingConfig)
from ray_tpu.air.result import Result
from ray_tpu.train.backend import Backend, BackendConfig
from ray_tpu.train.base_trainer import BaseTrainer, DataParallelTrainer
from ray_tpu.train._internal.sharded_checkpoint import ShardedCheckpoint
from ray_tpu.train.jax import JaxBackendConfig, JaxTrainer, prepare_mesh
from ray_tpu.train.predictor import BatchPredictor, JaxPredictor, Predictor

__all__ = [
    "Backend",
    "BackendConfig",
    "BaseTrainer",
    "BatchPredictor",
    "Checkpoint",
    "CheckpointConfig",
    "DataParallelTrainer",
    "FailureConfig",
    "JaxBackendConfig",
    "JaxPredictor",
    "JaxTrainer",
    "Predictor",
    "Result",
    "RunConfig",
    "ScalingConfig",
    "ShardedCheckpoint",
    "prepare_mesh",
]
