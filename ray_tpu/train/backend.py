"""Backend plugin interface (reference: python/ray/train/backend.py:43,55)."""

from __future__ import annotations

from typing import TYPE_CHECKING

if TYPE_CHECKING:
    from ray_tpu.train._internal.worker_group import WorkerGroup


class BackendConfig:
    """Declarative config; backend_cls points at the runtime hooks."""

    @property
    def backend_cls(self):
        return Backend


class Backend:
    """Runtime hooks invoked by the BackendExecutor around training."""

    def on_start(self, worker_group: "WorkerGroup",
                 backend_config: BackendConfig) -> None:
        pass

    def on_training_start(self, worker_group: "WorkerGroup",
                          backend_config: BackendConfig) -> None:
        pass

    def on_shutdown(self, worker_group: "WorkerGroup",
                    backend_config: BackendConfig) -> None:
        pass
