"""JaxTrainer: the TPU-native Train backend (the north star).

Replaces the reference's `_TorchBackend` NCCL rendezvous
(train/torch/config.py:113,129 init_process_group) with the JAX coordination
service: on a multi-host gang each rank gets coordinator address/process id
env and calls `jax.distributed.initialize`, after which every worker sees the
global TPU slice and builds the SAME `jax.sharding.Mesh` from the
ScalingConfig's MeshConfig (deterministic multi-controller SPMD). On a
single host there is nothing to rendezvous — prepare_mesh() just builds the
local mesh.
"""

from __future__ import annotations

import os
from typing import Optional

from ray_tpu.air.config import ScalingConfig
from ray_tpu.train.backend import Backend, BackendConfig
from ray_tpu.train.base_trainer import DataParallelTrainer

DEFAULT_COORDINATOR_PORT = 7654


class JaxBackendConfig(BackendConfig):
    def __init__(self, mesh_config=None,
                 coordinator_port: int = DEFAULT_COORDINATOR_PORT,
                 force_distributed_init: bool = False):
        self.mesh_config = mesh_config
        self.coordinator_port = coordinator_port
        self.force_distributed_init = force_distributed_init

    @property
    def backend_cls(self):
        return _JaxBackend


class _JaxBackend(Backend):
    def on_start(self, worker_group, backend_config: JaxBackendConfig):
        """Distribute the coordination-service rendezvous info.

        reference parity: _TorchBackend.on_start sets MASTER_ADDR/PORT and
        calls dist.init_process_group on every rank; the JAX equivalent is
        JAX_COORDINATOR_ADDRESS + jax.distributed.initialize, needed only
        when the gang spans processes/hosts.
        """
        import ray_tpu
        metas = worker_group.execute("get_metadata")
        head = metas[0]["hostname"]
        world = len(worker_group.workers)
        multi_process = len({m["hostname"] for m in metas}) > 1 or \
            backend_config.force_distributed_init
        env_refs = []
        for rank, worker in enumerate(worker_group.workers):
            env = {
                "RAY_TPU_WORLD_SIZE": str(world),
                "RAY_TPU_RANK": str(rank),
            }
            if multi_process:
                env.update({
                    "JAX_COORDINATOR_ADDRESS":
                        f"{head}:{backend_config.coordinator_port}",
                    "JAX_NUM_PROCESSES": str(world),
                    "JAX_PROCESS_ID": str(rank),
                })
            env_refs.append(worker.setup_env.remote(env))
        # Real barrier: wait for every setup_env (and surface its errors) —
        # a follow-up call is not a barrier under max_concurrency > 1.
        ray_tpu.get(env_refs)
        if multi_process:
            worker_group.execute("jax_distributed_init")


def distributed_init_if_needed() -> None:
    """Call jax.distributed.initialize from coordinator env, once.

    RAY_TPU_JAX_PLATFORM=cpu selects the CPU backend with gloo
    cross-process collectives — the fake-TPU analog for testing true
    multi-controller training on one host (SURVEY §4: fake accelerators
    stand in for device fleets). Must run before the first device use."""
    platform = os.environ.get("RAY_TPU_JAX_PLATFORM")
    if platform == "cpu":
        # One device per process: gloo cross-process collectives deadlock
        # when xla_force_host_platform_device_count (inherited from the
        # spawning test process) multiplies the local device count — and
        # one-device-per-rank is the faithful analog of one-chip-per-host
        # multi-controller TPU anyway. Must happen before backend init.
        flags = os.environ.get("XLA_FLAGS", "")
        flags = " ".join(
            f for f in flags.split()
            if "xla_force_host_platform_device_count" not in f)
        os.environ["XLA_FLAGS"] = flags
    import jax
    if platform:
        jax.config.update("jax_platforms", platform)
        if platform == "cpu":
            try:
                jax.config.update(
                    "jax_cpu_collectives_implementation", "gloo")
            except Exception:  # noqa: BLE001 - older jax: no gloo knob
                pass
    if os.environ.get("JAX_COORDINATOR_ADDRESS"):
        try:
            jax.distributed.initialize(
                coordinator_address=os.environ["JAX_COORDINATOR_ADDRESS"],
                num_processes=int(os.environ["JAX_NUM_PROCESSES"]),
                process_id=int(os.environ["JAX_PROCESS_ID"]))
        except RuntimeError:
            pass  # already initialized


def prepare_mesh(mesh_config=None):
    """Build the training mesh inside a train worker.

    The TPU-native analog of the reference's prepare_model
    (train_loop_utils.py:51): instead of wrapping a model in DDP/FSDP, the
    worker gets a mesh and expresses DP/FSDP/TP/SP as sharding rules.
    """
    from ray_tpu.parallel import MeshConfig, build_mesh
    distributed_init_if_needed()
    return build_mesh(mesh_config or MeshConfig())


class JaxTrainer(DataParallelTrainer):
    """Train a JAX/pjit program on a TPU gang.

    north star (BASELINE.json): ray.train.jax.JaxTrainer runs the GPT-J
    fine-tune with pjit/GSPMD sharding and zero GPU resources.
    """

    _backend_config_cls = JaxBackendConfig

    def __init__(self, train_loop_per_worker, *,
                 train_loop_config: Optional[dict] = None,
                 jax_config: Optional[JaxBackendConfig] = None,
                 backend_config: Optional[JaxBackendConfig] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 **kwargs):
        scaling_config = scaling_config or ScalingConfig(use_tpu=True)
        # backend_config is accepted too so clone paths
        # (_with_config_overrides) can re-instantiate this class.
        backend_config = backend_config or jax_config or JaxBackendConfig(
            mesh_config=scaling_config.mesh)
        super().__init__(train_loop_per_worker,
                         train_loop_config=train_loop_config,
                         backend_config=backend_config,
                         scaling_config=scaling_config,
                         **kwargs)
