"""BaseTrainer + DataParallelTrainer.

Analog of the reference's train/base_trainer.py:344 (fit) and
train/data_parallel_trainer.py:56. The reference routes fit() through a
single-trial Tune run; here fit() drives the BackendExecutor directly and
Tune composes *on top of* trainers (same observable behavior, one less
inversion).
"""

from __future__ import annotations

import uuid
from typing import Any, Callable, Dict, Optional

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import FailureConfig, RunConfig, ScalingConfig
from ray_tpu.air.result import Result
from ray_tpu.train._internal.backend_executor import BackendExecutor
from ray_tpu.train.backend import BackendConfig


class BaseTrainer:
    def __init__(self, *, scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None,
                 datasets: Optional[Dict[str, Any]] = None):
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.resume_from_checkpoint = resume_from_checkpoint
        self.datasets = datasets or {}

    def fit(self) -> Result:
        raise NotImplementedError

    def as_trainable(self) -> Callable:
        """Adapter for Tune: a function trainable that runs this trainer with
        per-trial config overrides and re-reports its results."""
        trainer = self

        def _trainable(config: dict):
            from ray_tpu.air import session as tune_session
            sub = trainer._with_config_overrides(config)

            def relay(metrics):
                tune_session.report(metrics)
                return True

            result = sub._fit_with_callback(relay)
            return result.metrics

        return _trainable

    def _with_config_overrides(self, config: dict) -> "BaseTrainer":
        return self

    def _fit_with_callback(self, callback) -> Result:
        raise NotImplementedError


class DataParallelTrainer(BaseTrainer):
    """Runs train_loop_per_worker on every rank of the gang.

    reference: train/data_parallel_trainer.py:347 training_loop.
    """

    _backend_config_cls = BackendConfig

    def __init__(self, train_loop_per_worker: Callable,
                 *, train_loop_config: Optional[dict] = None,
                 backend_config: Optional[BackendConfig] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None,
                 datasets: Optional[Dict[str, Any]] = None):
        super().__init__(scaling_config=scaling_config,
                         run_config=run_config,
                         resume_from_checkpoint=resume_from_checkpoint,
                         datasets=datasets)
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = train_loop_config or {}
        self.backend_config = backend_config or self._backend_config_cls()

    def _with_config_overrides(self, config: dict) -> "DataParallelTrainer":
        merged = {**self.train_loop_config, **(config or {})}
        return type(self)(
            self.train_loop_per_worker,
            train_loop_config=merged,
            backend_config=self.backend_config,
            scaling_config=self.scaling_config,
            run_config=self.run_config,
            resume_from_checkpoint=self.resume_from_checkpoint,
            datasets=self.datasets,
        )

    def _shard_datasets(self, num_workers: int):
        """Per-worker dataset shards: Datasets split across ranks
        (reference: train/_internal/dataset_spec.py per-epoch splitting)."""
        if not self.datasets:
            return None
        shards = [dict() for _ in range(num_workers)]
        for name, ds in self.datasets.items():
            if hasattr(ds, "split") and num_workers > 1:
                parts = ds.split(num_workers, equal=True)
            else:
                parts = [ds] * num_workers
            for rank in range(num_workers):
                shards[rank][name] = (parts[rank]
                                      if rank < len(parts) else parts[-1])
        return shards

    def fit(self) -> Result:
        return self._fit_with_callback(None)

    def _fit_with_callback(self, callback) -> Result:
        run_config = self.run_config
        # Durable checkpoints: with a storage_path, every reported
        # checkpoint is persisted through the spill backends and a new
        # run under the same RunConfig.name auto-resumes from the
        # newest one (reference: trainer restoration via run(name=...)).
        checkpoint_manager = None
        resume = self.resume_from_checkpoint
        if run_config is not None and run_config.storage_path:
            from ray_tpu.train._internal.checkpoint_manager import \
                CheckpointManager
            checkpoint_manager = CheckpointManager(
                run_config.storage_path, run_config.name or "train",
                run_config.checkpoint_config)
            if resume is None:
                resume = checkpoint_manager.latest()
                if resume is not None:
                    import logging
                    logging.getLogger("ray_tpu.train").info(
                        "Auto-resuming run %r from durable checkpoint %s",
                        run_config.name or "train", resume.uri)
        executor = BackendExecutor(
            self.backend_config, self.scaling_config,
            (run_config.failure_config if run_config else None),
            checkpoint_manager=checkpoint_manager)
        executor.start()
        trial_info = {"trial_id": uuid.uuid4().hex[:8],
                      "trial_name": self.run_config.name or "train"}
        try:
            return executor.run(
                self.train_loop_per_worker,
                self.train_loop_config,
                trial_info,
                checkpoint=resume,
                dataset_shards_per_worker=self._shard_datasets(
                    self.scaling_config.num_workers),
                result_callback=callback,
            )
        finally:
            executor.shutdown()
