"""BERT-style bidirectional encoder with a masked-LM head, TPU-first.

The encoder-only family of the model zoo (alongside decoder-only
GPT/Llama, the T5 encoder-decoder, the ViT vision encoder, and the
diffusion UNet): learned absolute position + segment embeddings,
post-layernorm transformer blocks (the original BERT residual order),
GELU feed-forward, bidirectional self-attention with a padding mask, a
tied-embedding masked-LM head, and the tanh [CLS] pooler.

Same TPU design rules as models/gpt.py: pure-pytree params with logical
axis names for GSPMD sharding, `lax.scan` over stacked layers (O(1)
compile), bf16 matmuls with fp32 softmax/norm accumulation, static
shapes throughout.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, replace
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

from ray_tpu.parallel.sharding import ShardingRules


@dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    d_model: int = 768
    n_heads: int = 12
    d_ff: int = 3072
    n_layers: int = 12
    max_seq_len: int = 512
    n_segments: int = 2
    layernorm_eps: float = 1e-12
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    def num_params(self) -> int:
        d, f = self.d_model, self.d_ff
        attn = 4 * d * d + 4 * d  # qkvo weights + biases
        ffn = 2 * d * f + f + d
        block = attn + ffn + 4 * d  # + two layernorms (scale, bias)
        embeds = (self.vocab_size + self.max_seq_len
                  + self.n_segments) * d + 2 * d  # + embedding layernorm
        pooler = d * d + d
        mlm = d * d + d + 2 * d + self.vocab_size  # transform+ln+bias
        return embeds + self.n_layers * block + pooler + mlm


PRESETS: Dict[str, BertConfig] = {
    "bert-base": BertConfig(),
    "bert-large": BertConfig(d_model=1024, n_heads=16, d_ff=4096,
                             n_layers=24),
    "bert-tiny": BertConfig(vocab_size=256, d_model=64, n_heads=4,
                            d_ff=128, n_layers=2, max_seq_len=64,
                            dtype=jnp.float32, remat=False),
}


def config(name: str, **overrides) -> BertConfig:
    cfg = PRESETS[name]
    return replace(cfg, **overrides) if overrides else cfg


# -- init + sharding specs ----------------------------------------------


def init(cfg: BertConfig, key: jax.Array) -> Dict[str, Any]:
    d, f, h, hd = cfg.d_model, cfg.d_ff, cfg.n_heads, cfg.head_dim
    pd = cfg.param_dtype
    std = 0.02
    keys = jax.random.split(key, 8)

    def norm(k, shape, s=std):
        return (jax.random.normal(k, shape, jnp.float32) * s).astype(pd)

    def layer(k):
        ks = jax.random.split(k, 7)
        return {
            "wq": norm(ks[0], (d, h, hd)),
            "wk": norm(ks[1], (d, h, hd)),
            "wv": norm(ks[2], (d, h, hd)),
            "wo": norm(ks[3], (h, hd, d)),
            "bq": jnp.zeros((h, hd), pd), "bk": jnp.zeros((h, hd), pd),
            "bv": jnp.zeros((h, hd), pd), "bo": jnp.zeros((d,), pd),
            "ln1_s": jnp.ones((d,), pd), "ln1_b": jnp.zeros((d,), pd),
            "wi": norm(ks[4], (d, f)), "bi": jnp.zeros((f,), pd),
            "wo_ff": norm(ks[5], (f, d)), "bo_ff": jnp.zeros((d,), pd),
            "ln2_s": jnp.ones((d,), pd), "ln2_b": jnp.zeros((d,), pd),
        }

    stacked = jax.tree.map(
        lambda *xs: jnp.stack(xs),
        *[layer(k) for k in jax.random.split(keys[0], cfg.n_layers)])
    return {
        "wte": norm(keys[1], (cfg.vocab_size, d)),
        "wpe": norm(keys[2], (cfg.max_seq_len, d)),
        "wse": norm(keys[3], (cfg.n_segments, d)),
        "emb_ln_s": jnp.ones((d,), pd), "emb_ln_b": jnp.zeros((d,), pd),
        "layers": stacked,
        "pooler_w": norm(keys[4], (d, d)), "pooler_b": jnp.zeros((d,), pd),
        "mlm_w": norm(keys[5], (d, d)), "mlm_b": jnp.zeros((d,), pd),
        "mlm_ln_s": jnp.ones((d,), pd), "mlm_ln_b": jnp.zeros((d,), pd),
        "mlm_bias": jnp.zeros((cfg.vocab_size,), pd),
    }


def param_specs(cfg: BertConfig, rules: ShardingRules) -> Dict[str, Any]:
    r = rules
    layers = {
        "wq": r.spec("layers", "embed", "heads", "head_dim"),
        "wk": r.spec("layers", "embed", "heads", "head_dim"),
        "wv": r.spec("layers", "embed", "heads", "head_dim"),
        "wo": r.spec("layers", "heads", "head_dim", "embed"),
        "bq": r.spec("layers", "heads", "head_dim"),
        "bk": r.spec("layers", "heads", "head_dim"),
        "bv": r.spec("layers", "heads", "head_dim"),
        "bo": r.spec("layers", "embed"),
        "ln1_s": r.spec("layers", None), "ln1_b": r.spec("layers", None),
        "wi": r.spec("layers", "embed", "mlp"),
        "bi": r.spec("layers", "mlp"),
        "wo_ff": r.spec("layers", "mlp", "embed"),
        "bo_ff": r.spec("layers", "embed"),
        "ln2_s": r.spec("layers", None), "ln2_b": r.spec("layers", None),
    }
    return {
        "wte": r.spec("vocab", "embed"),
        "wpe": r.spec(None, "embed"),
        "wse": r.spec(None, "embed"),
        "emb_ln_s": PartitionSpec(), "emb_ln_b": PartitionSpec(),
        "layers": layers,
        "pooler_w": r.spec("embed", None), "pooler_b": PartitionSpec(),
        "mlm_w": r.spec("embed", None), "mlm_b": PartitionSpec(),
        "mlm_ln_s": PartitionSpec(), "mlm_ln_b": PartitionSpec(),
        "mlm_bias": r.spec("vocab"),
    }


def batch_spec(rules: ShardingRules) -> PartitionSpec:
    return rules.spec("batch", "sequence")


# -- forward -------------------------------------------------------------


def _layernorm(x, scale, bias, eps):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
    out = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (out * scale + bias).astype(x.dtype)


def _attention(x, layer, cfg: BertConfig, mask_bias):
    q = jnp.einsum("bsd,dhk->bshk", x, layer["wq"].astype(cfg.dtype)) + \
        layer["bq"].astype(cfg.dtype)
    k = jnp.einsum("bsd,dhk->bshk", x, layer["wk"].astype(cfg.dtype)) + \
        layer["bk"].astype(cfg.dtype)
    v = jnp.einsum("bsd,dhk->bshk", x, layer["wv"].astype(cfg.dtype)) + \
        layer["bv"].astype(cfg.dtype)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(cfg.head_dim)
    logits = logits.astype(jnp.float32) + mask_bias  # fp32 softmax
    probs = jax.nn.softmax(logits, axis=-1).astype(cfg.dtype)
    ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
    return jnp.einsum("bqhd,hdm->bqm", ctx,
                      layer["wo"].astype(cfg.dtype)) + \
        layer["bo"].astype(cfg.dtype)


def encode(params, cfg: BertConfig, tokens: jax.Array,
           segment_ids: Optional[jax.Array] = None,
           attention_mask: Optional[jax.Array] = None) -> jax.Array:
    """→ [B, S, d_model] contextual embeddings. ``attention_mask`` is 1
    for real tokens, 0 for padding (padding positions are excluded from
    every token's attention)."""
    B, S = tokens.shape
    if segment_ids is None:
        segment_ids = jnp.zeros_like(tokens)
    if attention_mask is None:
        attention_mask = jnp.ones_like(tokens)
    x = (jnp.take(params["wte"], tokens, axis=0)
         + params["wpe"][None, :S]
         + jnp.take(params["wse"], segment_ids, axis=0))
    x = _layernorm(x.astype(cfg.dtype), params["emb_ln_s"],
                   params["emb_ln_b"], cfg.layernorm_eps)
    # [B, 1, 1, S] additive bias: -inf on padding keys.
    mask_bias = jnp.where(attention_mask[:, None, None, :] > 0, 0.0,
                          jnp.float32(-1e9))

    def block(x, layer):
        # Post-LN residual order (original BERT): LN(x + sublayer(x)).
        attn = _attention(x, layer, cfg, mask_bias)
        x = _layernorm(x + attn, layer["ln1_s"], layer["ln1_b"],
                       cfg.layernorm_eps)
        hidden = jax.nn.gelu(
            jnp.einsum("bsd,df->bsf", x, layer["wi"].astype(cfg.dtype))
            + layer["bi"].astype(cfg.dtype))
        ffn = jnp.einsum("bsf,fd->bsd", hidden,
                         layer["wo_ff"].astype(cfg.dtype)) + \
            layer["bo_ff"].astype(cfg.dtype)
        x = _layernorm(x + ffn, layer["ln2_s"], layer["ln2_b"],
                       cfg.layernorm_eps)
        return x, None

    if cfg.remat:
        block = jax.checkpoint(block)
    x, _ = jax.lax.scan(block, x, params["layers"])
    return x


def mlm_logits(params, cfg: BertConfig, tokens: jax.Array,
               segment_ids: Optional[jax.Array] = None,
               attention_mask: Optional[jax.Array] = None) -> jax.Array:
    """Masked-LM head over the tied embedding → [B, S, vocab] (fp32)."""
    x = encode(params, cfg, tokens, segment_ids, attention_mask)
    x = jax.nn.gelu(
        jnp.einsum("bsd,de->bse", x, params["mlm_w"].astype(cfg.dtype))
        + params["mlm_b"].astype(cfg.dtype))
    x = _layernorm(x, params["mlm_ln_s"], params["mlm_ln_b"],
                   cfg.layernorm_eps)
    logits = jnp.einsum("bsd,vd->bsv", x.astype(jnp.float32),
                        params["wte"].astype(jnp.float32))
    return logits + params["mlm_bias"].astype(jnp.float32)


def pooled(params, cfg: BertConfig, tokens: jax.Array,
           segment_ids: Optional[jax.Array] = None,
           attention_mask: Optional[jax.Array] = None) -> jax.Array:
    """The tanh [CLS] pooler → [B, d_model] (sentence representation)."""
    x = encode(params, cfg, tokens, segment_ids, attention_mask)
    cls = x[:, 0].astype(jnp.float32)
    return jnp.tanh(cls @ params["pooler_w"].astype(jnp.float32)
                    + params["pooler_b"].astype(jnp.float32))


def mlm_loss(params, cfg: BertConfig, tokens: jax.Array,
             targets: jax.Array, mask_positions: jax.Array,
             attention_mask: Optional[jax.Array] = None) -> jax.Array:
    """Mean cross-entropy over masked positions (mask_positions is 1
    where a token was masked and must be predicted)."""
    logits = mlm_logits(params, cfg, tokens,
                        attention_mask=attention_mask)
    logp = jax.nn.log_softmax(logits, axis=-1)
    picked = jnp.take_along_axis(
        logp, targets[..., None].astype(jnp.int32), -1)[..., 0]
    weights = mask_positions.astype(jnp.float32)
    return -(picked * weights).sum() / jnp.maximum(weights.sum(), 1.0)
