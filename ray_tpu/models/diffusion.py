"""Denoising diffusion UNet + DDIM sampler, TPU-first.

The model behind the rebuild's Serve batch-inference config
(BASELINE.json: "Ray Serve Stable-Diffusion batch inference on TPU
replicas"; the reference itself ships no diffusion model — it serves
torch/diffusers models through generic Serve deployments,
reference: python/ray/serve/_private/replica.py).

Design: NHWC convolutions (`lax.conv_general_dilated` with dimension
numbers XLA maps onto the MXU), GroupNorm in fp32, sinusoidal timestep
embedding injected per res-block, self-attention at the lowest
resolution, fixed down/up factor of 2 per stage. Params are a pure
pytree; sampling is a `lax.scan` over DDIM steps so the entire sampler
is one compiled program.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, replace
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.parallel.sharding import ShardingRules


@dataclass(frozen=True)
class UNetConfig:
    image_size: int = 32
    in_channels: int = 3
    base_channels: int = 128
    channel_mults: Tuple[int, ...] = (1, 2, 2)
    n_res_blocks: int = 2
    n_groups: int = 32
    time_dim: int = 512
    n_timesteps: int = 1000
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @property
    def stage_channels(self) -> Tuple[int, ...]:
        return tuple(self.base_channels * m for m in self.channel_mults)


PRESETS: Dict[str, UNetConfig] = {
    "ddpm-cifar": UNetConfig(),
    "sd-base": UNetConfig(
        image_size=64, in_channels=4, base_channels=192,
        channel_mults=(1, 2, 3, 4), n_res_blocks=2),
    # Test-size config.
    "unet-tiny": UNetConfig(
        image_size=16, in_channels=3, base_channels=16,
        channel_mults=(1, 2), n_res_blocks=1, n_groups=4, time_dim=32,
        n_timesteps=50, dtype=jnp.float32),
}


def config(name: str, **overrides) -> UNetConfig:
    cfg = PRESETS[name]
    return replace(cfg, **overrides) if overrides else cfg


# -- primitives ---------------------------------------------------------

_CONV_DN = ("NHWC", "HWIO", "NHWC")


def _conv(x, w, b=None, stride=1):
    out = jax.lax.conv_general_dilated(
        x, w.astype(x.dtype), (stride, stride), "SAME",
        dimension_numbers=_CONV_DN)
    if b is not None:
        out = out + b.astype(x.dtype)
    return out


def _groupnorm(x, scale, bias, n_groups, eps=1e-5):
    B, H, W, C = x.shape
    g = min(n_groups, C)
    while C % g:
        g -= 1
    x32 = x.astype(jnp.float32).reshape(B, H, W, g, C // g)
    mu = x32.mean(axis=(1, 2, 4), keepdims=True)
    var = ((x32 - mu) ** 2).mean(axis=(1, 2, 4), keepdims=True)
    y = ((x32 - mu) * jax.lax.rsqrt(var + eps)).reshape(B, H, W, C)
    return (y * scale.astype(jnp.float32)
            + bias.astype(jnp.float32)).astype(x.dtype)


def timestep_embedding(t, dim):
    """Sinusoidal embedding. t: [B] int/float → [B, dim] fp32."""
    half = dim // 2
    freqs = jnp.exp(
        -math.log(10000.0) * jnp.arange(half, dtype=jnp.float32) / half)
    args = t.astype(jnp.float32)[:, None] * freqs[None]
    return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)


# -- init ---------------------------------------------------------------

def _conv_init(key, kh, kw, cin, cout, pd, scale=1.0):
    fan_in = kh * kw * cin
    std = scale / math.sqrt(fan_in)
    return (jax.random.normal(key, (kh, kw, cin, cout), jnp.float32)
            * std).astype(pd)


def _dense_init(key, cin, cout, pd, scale=1.0):
    std = scale / math.sqrt(cin)
    return (jax.random.normal(key, (cin, cout), jnp.float32)
            * std).astype(pd)


def _res_block_init(key, cin, cout, time_dim, pd):
    k1, k2, k3, k4 = jax.random.split(key, 4)
    p = {
        "gn1_scale": jnp.ones((cin,), pd), "gn1_bias": jnp.zeros((cin,), pd),
        "conv1": _conv_init(k1, 3, 3, cin, cout, pd),
        "conv1_b": jnp.zeros((cout,), pd),
        "time_w": _dense_init(k2, time_dim, cout, pd),
        "time_b": jnp.zeros((cout,), pd),
        "gn2_scale": jnp.ones((cout,), pd), "gn2_bias": jnp.zeros((cout,), pd),
        "conv2": _conv_init(k3, 3, 3, cout, cout, pd, scale=1e-2),
        "conv2_b": jnp.zeros((cout,), pd),
    }
    if cin != cout:
        p["skip"] = _conv_init(k4, 1, 1, cin, cout, pd)
    return p


def _attn_init(key, c, pd):
    k1, k2, k3, k4 = jax.random.split(key, 4)
    return {
        "gn_scale": jnp.ones((c,), pd), "gn_bias": jnp.zeros((c,), pd),
        "wq": _dense_init(k1, c, c, pd),
        "wk": _dense_init(k2, c, c, pd),
        "wv": _dense_init(k3, c, c, pd),
        "wo": _dense_init(k4, c, c, pd, scale=1e-2),
    }


def init(cfg: UNetConfig, key: jax.Array) -> Dict[str, Any]:
    pd = cfg.param_dtype
    chans = cfg.stage_channels
    keys = iter(jax.random.split(key, 256))

    params: Dict[str, Any] = {
        "time_w1": _dense_init(next(keys), cfg.time_dim // 4, cfg.time_dim,
                               pd),
        "time_b1": jnp.zeros((cfg.time_dim,), pd),
        "time_w2": _dense_init(next(keys), cfg.time_dim, cfg.time_dim, pd),
        "time_b2": jnp.zeros((cfg.time_dim,), pd),
        "conv_in": _conv_init(next(keys), 3, 3, cfg.in_channels, chans[0],
                              pd),
        "conv_in_b": jnp.zeros((chans[0],), pd),
    }

    down = []
    cin = chans[0]
    for si, c in enumerate(chans):
        blocks = []
        for _ in range(cfg.n_res_blocks):
            blocks.append(_res_block_init(next(keys), cin, c, cfg.time_dim,
                                          pd))
            cin = c
        stage = {"blocks": blocks}
        if si < len(chans) - 1:
            stage["down"] = _conv_init(next(keys), 3, 3, c, c, pd)
            stage["down_b"] = jnp.zeros((c,), pd)
        down.append(stage)
    params["down"] = down

    mid_c = chans[-1]
    params["mid1"] = _res_block_init(next(keys), mid_c, mid_c, cfg.time_dim,
                                     pd)
    params["mid_attn"] = _attn_init(next(keys), mid_c, pd)
    params["mid2"] = _res_block_init(next(keys), mid_c, mid_c, cfg.time_dim,
                                     pd)

    up = []
    for si in reversed(range(len(chans))):
        c = chans[si]
        blocks = []
        for _ in range(cfg.n_res_blocks):
            # Input = current features + same-resolution skip.
            blocks.append(_res_block_init(next(keys), cin + c, c,
                                          cfg.time_dim, pd))
            cin = c
        stage = {"blocks": blocks}
        if si > 0:
            stage["up"] = _conv_init(next(keys), 3, 3, c, c, pd)
            stage["up_b"] = jnp.zeros((c,), pd)
        up.append(stage)
    params["up"] = up

    params["gn_out_scale"] = jnp.ones((chans[0],), pd)
    params["gn_out_bias"] = jnp.zeros((chans[0],), pd)
    params["conv_out"] = _conv_init(next(keys), 3, 3, chans[0],
                                    cfg.in_channels, pd, scale=1e-2)
    params["conv_out_b"] = jnp.zeros((cfg.in_channels,), pd)
    return params


def param_specs(cfg: UNetConfig, rules: ShardingRules):
    """Replicated weights (conv UNets are batch-parallel; batch over dp)."""
    from jax.sharding import PartitionSpec
    return jax.tree.map(lambda _: PartitionSpec(), init_shapes(cfg))


def init_shapes(cfg: UNetConfig):
    return jax.eval_shape(lambda k: init(cfg, k), jax.random.PRNGKey(0))


def batch_spec(rules: ShardingRules):
    return rules.spec("batch", None, None, None)


# -- forward ------------------------------------------------------------

def _res_block(cfg, p, x, temb):
    h = _groupnorm(x, p["gn1_scale"], p["gn1_bias"], cfg.n_groups)
    h = _conv(jax.nn.silu(h), p["conv1"], p["conv1_b"])
    t = jnp.einsum("bt,tc->bc", jax.nn.silu(temb),
                   p["time_w"].astype(temb.dtype)) + p["time_b"].astype(
                       temb.dtype)
    h = h + t[:, None, None, :].astype(h.dtype)
    h = _groupnorm(h, p["gn2_scale"], p["gn2_bias"], cfg.n_groups)
    h = _conv(jax.nn.silu(h), p["conv2"], p["conv2_b"])
    skip = _conv(x, p["skip"]) if "skip" in p else x
    return skip + h


def _self_attention(cfg, p, x):
    B, H, W, C = x.shape
    h = _groupnorm(x, p["gn_scale"], p["gn_bias"], cfg.n_groups)
    flat = h.reshape(B, H * W, C)
    q = jnp.einsum("bnc,cd->bnd", flat, p["wq"].astype(flat.dtype))
    k = jnp.einsum("bnc,cd->bnd", flat, p["wk"].astype(flat.dtype))
    v = jnp.einsum("bnc,cd->bnd", flat, p["wv"].astype(flat.dtype))
    logits = (jnp.einsum("bqc,bkc->bqk", q, k)
              / math.sqrt(C)).astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1).astype(flat.dtype)
    out = jnp.einsum("bqk,bkc->bqc", probs, v)
    out = jnp.einsum("bnc,cd->bnd", out, p["wo"].astype(flat.dtype))
    return x + out.reshape(B, H, W, C)


def _downsample(x, w, b):
    return _conv(x, w, b, stride=2)


def _upsample(x, w, b):
    B, H, W, C = x.shape
    x = jnp.repeat(jnp.repeat(x, 2, axis=1), 2, axis=2)
    return _conv(x, w, b)


def forward(params: Dict[str, Any], cfg: UNetConfig, x: jax.Array,
            t: jax.Array) -> jax.Array:
    """Predict noise. x: [B, H, W, C] (compute dtype), t: [B] int32."""
    dt = cfg.dtype
    x = x.astype(dt)
    temb = timestep_embedding(t, cfg.time_dim // 4)
    temb = jnp.einsum("bt,td->bd", temb, params["time_w1"].astype(
        jnp.float32)) + params["time_b1"].astype(jnp.float32)
    temb = jnp.einsum("bt,td->bd", jax.nn.silu(temb),
                      params["time_w2"].astype(jnp.float32)) + \
        params["time_b2"].astype(jnp.float32)

    h = _conv(x, params["conv_in"], params["conv_in_b"])
    skips = [h]
    for si, stage in enumerate(params["down"]):
        for p in stage["blocks"]:
            h = _res_block(cfg, p, h, temb)
            skips.append(h)
        if "down" in stage:
            h = _downsample(h, stage["down"], stage["down_b"])
            skips.append(h)

    h = _res_block(cfg, params["mid1"], h, temb)
    h = _self_attention(cfg, params["mid_attn"], h)
    h = _res_block(cfg, params["mid2"], h, temb)

    for si, stage in enumerate(params["up"]):
        for p in stage["blocks"]:
            h = jnp.concatenate([h, skips.pop()], axis=-1)
            h = _res_block(cfg, p, h, temb)
        if "up" in stage:
            h = _upsample(h, stage["up"], stage["up_b"])
            skips.pop()  # consume the post-downsample skip at this res

    h = _groupnorm(h, params["gn_out_scale"], params["gn_out_bias"],
                   cfg.n_groups)
    out = _conv(jax.nn.silu(h), params["conv_out"], params["conv_out_b"])
    return out.astype(jnp.float32)


# -- diffusion process --------------------------------------------------

def make_schedule(cfg: UNetConfig):
    """Linear beta schedule → (betas, alphas_bar) as fp32 [T]."""
    betas = jnp.linspace(1e-4, 0.02, cfg.n_timesteps, dtype=jnp.float32)
    alphas_bar = jnp.cumprod(1.0 - betas)
    return betas, alphas_bar


def loss_fn(params: Dict[str, Any], cfg: UNetConfig, images: jax.Array,
            key: jax.Array) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Simple DDPM epsilon-prediction MSE loss."""
    _, alphas_bar = make_schedule(cfg)
    B = images.shape[0]
    k_t, k_eps = jax.random.split(key)
    t = jax.random.randint(k_t, (B,), 0, cfg.n_timesteps)
    eps = jax.random.normal(k_eps, images.shape, jnp.float32)
    ab = alphas_bar[t][:, None, None, None]
    x_t = jnp.sqrt(ab) * images.astype(jnp.float32) + jnp.sqrt(1 - ab) * eps
    pred = forward(params, cfg, x_t, t)
    loss = ((pred - eps) ** 2).mean()
    return loss, {"loss": loss}


def ddim_sample(params: Dict[str, Any], cfg: UNetConfig, key: jax.Array,
                batch: int, n_steps: int = 50,
                eta: float = 0.0) -> jax.Array:
    """DDIM sampler as one `lax.scan` — the whole reverse process is a
    single compiled program (jit this for Serve TPU replicas)."""
    _, alphas_bar = make_schedule(cfg)
    ts = jnp.linspace(cfg.n_timesteps - 1, 0, n_steps).astype(jnp.int32)
    shape = (batch, cfg.image_size, cfg.image_size, cfg.in_channels)
    k_init, k_noise = jax.random.split(key)
    x = jax.random.normal(k_init, shape, jnp.float32)

    def step(carry, idx):
        x, k = carry
        t = ts[idx]
        t_next = jnp.where(idx + 1 < n_steps, ts[jnp.minimum(
            idx + 1, n_steps - 1)], -1)
        ab_t = alphas_bar[t]
        ab_next = jnp.where(t_next >= 0, alphas_bar[jnp.maximum(t_next, 0)],
                            1.0)
        eps = forward(params, cfg, x, jnp.full((batch,), t, jnp.int32))
        x0 = (x - jnp.sqrt(1 - ab_t) * eps) / jnp.sqrt(ab_t)
        x0 = jnp.clip(x0, -3.0, 3.0)
        sigma = eta * jnp.sqrt((1 - ab_next) / (1 - ab_t)) * jnp.sqrt(
            1 - ab_t / ab_next)
        k, sub = jax.random.split(k)
        noise = jax.random.normal(sub, shape, jnp.float32)
        dir_xt = jnp.sqrt(jnp.maximum(1 - ab_next - sigma ** 2, 0.0)) * eps
        x = jnp.sqrt(ab_next) * x0 + dir_xt + sigma * noise
        return (x, k), None

    (x, _), _ = jax.lax.scan(step, (x, k_noise), jnp.arange(n_steps))
    return x
