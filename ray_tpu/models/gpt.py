"""GPT family — the flagship model (GPT-J-6B architecture), TPU-first.

This is the model behind the north-star benchmark (BASELINE.json: GPT-J-6B
fine-tune at ≥40% MFU): rotary position embeddings and the GPT-J *parallel*
residual block (one LayerNorm feeding attention and MLP simultaneously —
one fewer sequential matmul chain, friendlier to MXU pipelining). Design
choices for TPU:

* **Pure-pytree params + functional apply** — no module framework between
  the arrays and GSPMD; every parameter carries a logical-axis name so
  sharding is a `ShardingRules` table (parallel/sharding.py).
* **`lax.scan` over stacked layer params** — one compiled block body
  regardless of depth: O(1) XLA compile time, and GSPMD shards the stacked
  weights with a leading `layers` axis.
* **bf16 activations/matmuls, fp32 softmax & layernorm accumulation** —
  MXU-native without numerics drift.
* **Static shapes everywhere**; causal masking via iota comparison, no
  dynamic slicing in the hot path.

Capability parity note: the reference has no model zoo of its own (models
come from torch); this module is the JAX equivalent of what
`transformers.GPTJForCausalLM` provides to the reference's Train examples
(reference: release/air_tests/air_benchmarks/workloads/torch_benchmark.py
trains torchvision models; the GPT-J fine-tune config is driver-supplied).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, replace
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import PartitionSpec

from ray_tpu.parallel.sharding import ShardingRules


@dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50400
    n_layers: int = 28
    d_model: int = 4096
    n_heads: int = 16
    n_kv_heads: Optional[int] = None  # != n_heads → GQA/MQA
    d_ff: int = 16384
    max_seq_len: int = 2048
    rotary_dim: int = 64  # GPT-J applies rotary to a prefix of head_dim
    parallel_block: bool = True  # GPT-J parallel attn+MLP residual
    tie_embeddings: bool = False
    dtype: Any = jnp.bfloat16  # activation/compute dtype
    param_dtype: Any = jnp.float32
    remat: bool = True  # checkpoint each block (HBM ⇄ FLOPs trade)
    # "full": save only block boundaries, recompute everything in backward
    # (lowest memory). "selective": additionally save the named tensors
    # tagged in _block (rotary q/k/v, attention output, pre-activation FFN)
    # — the expensive-to-recompute matmul outputs — cutting backward
    # recompute to layernorms + the attention quadratic term for ~2.5x less
    # activation memory than no remat at all.
    remat_policy: str = "full"  # "full" | "selective"
    # Tokens per cross-entropy chunk (0 = unchunked). The [tokens, vocab]
    # fp32 logits and their cotangent are the single largest activation in
    # training; chunking streams them through a lax.scan so peak HBM holds
    # one chunk instead of the full batch (each chunk's logits matmul is
    # recomputed in backward — ~2*d*vocab extra FLOPs/token, a few percent).
    loss_chunk: int = 0
    attn_impl: str = "dot"  # "dot" | "flash" | "ring" | "ulysses"
    # Flash-attention tile sizes. 512x512 keeps both the Q tile and the
    # streamed KV tile comfortably in VMEM on v5e (measured ~4% faster
    # than 1024x1024 on the 410M single-chip recipe); _pick_block clamps
    # them for short sequences.
    attn_blk_q: int = 512
    attn_blk_k: int = 512
    layernorm_eps: float = 1e-5
    # Mixture-of-experts: n_experts > 0 replaces every block's dense FFN
    # with a top-k routed MoE FFN (expert weights sharded over the "ep"
    # mesh axis; dispatch/combine einsums lower to ICI all-to-all under
    # GSPMD). The reference has no EP at all (SURVEY.md §2.5) — this is a
    # new TPU-native capability.
    n_experts: int = 0
    expert_top_k: int = 2
    capacity_factor: float = 1.25
    router_aux_weight: float = 0.01

    @property
    def is_moe(self) -> bool:
        return self.n_experts > 0

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads or self.n_heads

    def num_params(self) -> int:
        d, f, v, L = self.d_model, self.d_ff, self.vocab_size, self.n_layers
        kvh = self.kv_heads * self.head_dim
        if self.n_experts:
            ffn = self.n_experts * (2 * d * f + f) + d * self.n_experts
        else:
            ffn = 2 * d * f + f
        per_layer = d * d + 2 * d * kvh + d * d + ffn + d + 2 * d
        head = 0 if self.tie_embeddings else v * d + v
        return v * d + L * per_layer + 2 * d + head


# -- presets ------------------------------------------------------------

PRESETS: Dict[str, GPTConfig] = {
    # The north-star model (matches EleutherAI/gpt-j-6b hyperparameters).
    "gptj-6b": GPTConfig(),
    # Single-v5e-chip benchmark model.
    "gpt-410m": GPTConfig(
        vocab_size=50304, n_layers=24, d_model=1024, n_heads=16,
        d_ff=4096, rotary_dim=32, max_seq_len=1024),
    "gpt2-124m": GPTConfig(
        vocab_size=50304, n_layers=12, d_model=768, n_heads=12, d_ff=3072,
        rotary_dim=32, max_seq_len=1024),
    # HBM-pressure benchmark model (GPT-neo-1.3B dims): adam state for
    # 1.3B params (~10GB fp32 moments) cannot fit a 16GB chip next to
    # params+grads — pairs with train_step.memory_efficient_optimizer
    # (factored second moments) for the single-chip bench.
    "gpt-1.3b": GPTConfig(
        vocab_size=50304, n_layers=24, d_model=2048, n_heads=16,
        d_ff=8192, rotary_dim=64, max_seq_len=1024),
    # Largest single-16GB-chip trainable point on the way to gptj-6b
    # (GPT-neo-2.7B dims): bf16 params (5.3GB) + grads (5.3GB) +
    # factored moments fit; the 6b config's params+grads alone are
    # 24.2GB (see bench.py gptj6b feasibility probe).
    "gpt-2.7b": GPTConfig(
        vocab_size=50304, n_layers=32, d_model=2560, n_heads=32,
        d_ff=10240, rotary_dim=64, max_seq_len=1024),
    # Test-size configs.
    "gpt-tiny": GPTConfig(
        vocab_size=256, n_layers=2, d_model=64, n_heads=4, d_ff=128,
        rotary_dim=8, max_seq_len=128, dtype=jnp.float32, remat=False),
    "gpt-micro": GPTConfig(
        vocab_size=512, n_layers=4, d_model=128, n_heads=8, d_ff=512,
        rotary_dim=16, max_seq_len=256, dtype=jnp.float32, remat=False),
    # MoE variants (expert parallelism over the "ep" mesh axis).
    "gpt-moe-tiny": GPTConfig(
        vocab_size=256, n_layers=2, d_model=64, n_heads=4, d_ff=128,
        rotary_dim=8, max_seq_len=128, dtype=jnp.float32, remat=False,
        n_experts=4),
    "gpt-moe-8x410m": GPTConfig(
        vocab_size=50304, n_layers=24, d_model=1024, n_heads=16,
        d_ff=4096, rotary_dim=32, max_seq_len=1024, n_experts=8),
}


def config(name: str, **overrides) -> GPTConfig:
    cfg = PRESETS[name]
    return replace(cfg, **overrides) if overrides else cfg


# -- parameter init + sharding specs -----------------------------------

def init(cfg: GPTConfig, key: jax.Array) -> Dict[str, Any]:
    """Initialize parameters (GPT-2-style scaled normal init)."""
    k_embed, k_layers, k_head = jax.random.split(key, 3)
    d, f, v, L = cfg.d_model, cfg.d_ff, cfg.vocab_size, cfg.n_layers
    h, kvh, hd = cfg.n_heads, cfg.kv_heads, cfg.head_dim
    pd = cfg.param_dtype
    std = 0.02
    out_std = std / math.sqrt(2 * L)

    def norm(k, shape, s=std):
        return (jax.random.normal(k, shape, jnp.float32) * s).astype(pd)

    ks = jax.random.split(k_layers, 6)

    def stack(k, shape, s=std):
        # One leading layers axis for lax.scan.
        return norm(k, (L,) + shape, s)

    layers = {
        "ln1_scale": jnp.ones((L, d), pd),
        "ln1_bias": jnp.zeros((L, d), pd),
        "wq": stack(ks[0], (d, h, hd)),
        "wk": stack(ks[1], (d, kvh, hd)),
        "wv": stack(ks[2], (d, kvh, hd)),
        "wo": stack(ks[3], (h, hd, d), out_std),
        "b_out": jnp.zeros((L, d), pd),
    }
    if cfg.is_moe:
        E = cfg.n_experts
        layers["router"] = stack(ks[4], (d, E))
        k_in, k_out = jax.random.split(ks[5])
        layers["w_in"] = norm(k_in, (L, E, d, f))
        layers["b_in"] = jnp.zeros((L, E, f), pd)
        layers["w_out"] = norm(k_out, (L, E, f, d), out_std)
    else:
        layers["w_in"] = stack(ks[4], (d, f))
        layers["b_in"] = jnp.zeros((L, f), pd)
        layers["w_out"] = stack(ks[5], (f, d), out_std)
    if not cfg.parallel_block:
        layers["ln2_scale"] = jnp.ones((L, d), pd)
        layers["ln2_bias"] = jnp.zeros((L, d), pd)
    params = {
        "wte": norm(k_embed, (v, d)),
        "layers": layers,
        "lnf_scale": jnp.ones((d,), pd),
        "lnf_bias": jnp.zeros((d,), pd),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = norm(k_head, (d, v))
        params["lm_head_bias"] = jnp.zeros((v,), pd)
    return params


def param_specs(cfg: GPTConfig, rules: ShardingRules) -> Dict[str, Any]:
    """PartitionSpec pytree matching init()'s structure."""
    r = rules
    layers = {
        "ln1_scale": r.spec("layers", "embed"),
        "ln1_bias": r.spec("layers", "embed"),
        "wq": r.spec("layers", "embed", "heads", "head_dim"),
        "wk": r.spec("layers", "embed", "kv_heads", "head_dim"),
        "wv": r.spec("layers", "embed", "kv_heads", "head_dim"),
        "wo": r.spec("layers", "heads", "head_dim", "embed"),
        "b_out": r.spec("layers", "embed"),
    }
    if cfg.is_moe:
        layers["router"] = r.spec("layers", "embed", None)
        layers["w_in"] = r.spec("layers", "expert", "embed", "mlp")
        layers["b_in"] = r.spec("layers", "expert", "mlp")
        layers["w_out"] = r.spec("layers", "expert", "mlp", "embed")
    else:
        layers["w_in"] = r.spec("layers", "embed", "mlp")
        layers["b_in"] = r.spec("layers", "mlp")
        layers["w_out"] = r.spec("layers", "mlp", "embed")
    if not cfg.parallel_block:
        layers["ln2_scale"] = r.spec("layers", "embed")
        layers["ln2_bias"] = r.spec("layers", "embed")
    specs = {
        "wte": r.spec("vocab", "embed"),
        "layers": layers,
        "lnf_scale": r.spec("embed"),
        "lnf_bias": r.spec("embed"),
    }
    if not cfg.tie_embeddings:
        specs["lm_head"] = r.spec("embed", "vocab")
        specs["lm_head_bias"] = r.spec("vocab")
    return specs


def batch_spec(rules: ShardingRules) -> PartitionSpec:
    return rules.spec("batch", "sequence")


# -- forward ------------------------------------------------------------

def _layernorm(x, scale, bias, eps):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (y * scale.astype(jnp.float32)
            + bias.astype(jnp.float32)).astype(x.dtype)


def _rotary(x, positions, rotary_dim):
    """Apply GPT-J (interleaved) rotary embedding to the first rotary_dim
    dims of each head. x: [B, S, H, D], positions: [B, S]."""
    if rotary_dim == 0:
        return x
    rot, rest = x[..., :rotary_dim], x[..., rotary_dim:]
    half = rotary_dim // 2
    freqs = 1.0 / (10000.0 ** (jnp.arange(half, dtype=jnp.float32) / half))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B, S, half]
    cos = jnp.cos(angles)[:, :, None, :].astype(x.dtype)
    sin = jnp.sin(angles)[:, :, None, :].astype(x.dtype)
    x1, x2 = rot[..., :half], rot[..., half:]
    rot_out = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return jnp.concatenate([rot_out, rest], axis=-1)


def _dot_attention(q, k, v, cfg: GPTConfig):
    """Causal attention; fp32 softmax. q,k,v: [B, S, H, D]/[B, S, KVH, D]."""
    B, S, H, D = q.shape
    kvh = k.shape[2]
    if kvh != H:  # GQA: repeat KV heads
        rep = H // kvh
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scale = 1.0 / math.sqrt(D)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    logits = logits.astype(jnp.float32)
    qpos = jax.lax.broadcasted_iota(jnp.int32, (S, S), 0)
    kpos = jax.lax.broadcasted_iota(jnp.int32, (S, S), 1)
    causal = qpos >= kpos
    logits = jnp.where(causal[None, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _attention(q, k, v, cfg: GPTConfig):
    if cfg.attn_impl == "dot":
        return _dot_attention(q, k, v, cfg)
    if cfg.attn_impl == "flash":
        from ray_tpu.ops.flash_attention import flash_attention
        return flash_attention(q, k, v, causal=True,
                               blk_q=cfg.attn_blk_q, blk_k=cfg.attn_blk_k)
    if cfg.attn_impl == "ring":
        from ray_tpu.ops.ring_attention import make_ring_attention
        from ray_tpu.parallel.mesh import current_mesh
        mesh = current_mesh()
        if mesh is None or "sp" not in mesh.axis_names:
            raise ValueError(
                "attn_impl='ring' needs a registered mesh with an 'sp' "
                "axis (parallel.mesh.set_current_mesh; make_train_step/"
                "make_eval_step do this automatically)")
        # Activation layout [B, S, H, D]: batch over (dp, fsdp), sequence
        # over the ring axis, heads over tp. Head axes whose size doesn't
        # divide tp (GQA/MQA) stay replicated; ring_attention's local
        # _repeat_kv bridges sharded-q / replicated-kv heads.
        sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        tp = sizes.get("tp", 1)
        H, kvh = q.shape[2], k.shape[2]
        q_spec = PartitionSpec(("dp", "fsdp"), "sp",
                               "tp" if H % tp == 0 else None, None)
        kv_spec = PartitionSpec(("dp", "fsdp"), "sp",
                                "tp" if kvh % tp == 0 else None, None)
        fn = make_ring_attention(mesh, "sp", causal=True, q_spec=q_spec,
                                 kv_spec=kv_spec)
        return fn(q, k, v)
    if cfg.attn_impl == "ulysses":
        from ray_tpu.ops.ulysses import make_ulysses_attention
        from ray_tpu.parallel.mesh import current_mesh
        mesh = current_mesh()
        if mesh is None or "sp" not in mesh.axis_names:
            raise ValueError(
                "attn_impl='ulysses' needs a registered mesh with an 'sp' "
                "axis (parallel.mesh.set_current_mesh)")
        return make_ulysses_attention(mesh)(q, k, v)
    raise ValueError(f"Unknown attn_impl {cfg.attn_impl!r}")


def _moe_ffn(cfg: GPTConfig, h, layer):
    """Top-k routed mixture-of-experts FFN with capacity-based token drop.

    Dispatch/combine are dense einsums against one-hot routing tensors (the
    canonical GSPMD MoE formulation): with ``w_in``/``w_out`` sharded over
    the "ep" mesh axis, XLA lowers the [tokens → experts] einsum to an ICI
    all-to-all — no hand-written communication. Returns (out, aux_loss)
    where aux_loss is the Switch-style load-balancing term.
    h: [B, S, d] → out [B, S, d]."""
    dt = cfg.dtype
    B, S, d = h.shape
    E = cfg.n_experts
    K = min(cfg.expert_top_k, E)
    C = max(1, int(cfg.capacity_factor * S * K / E))

    router_logits = jnp.einsum(
        "bsd,de->bse", h.astype(jnp.float32),
        layer["router"].astype(jnp.float32))
    probs = jax.nn.softmax(router_logits, axis=-1)  # [B, S, E] fp32
    gate_vals, gate_idx = jax.lax.top_k(probs, K)  # [B, S, K]
    gate_vals = gate_vals / jnp.maximum(
        gate_vals.sum(-1, keepdims=True), 1e-9)
    onehot = jax.nn.one_hot(gate_idx, E, dtype=jnp.float32)  # [B,S,K,E]

    # Position of each assignment within its expert's buffer, counted in
    # (sequence, k) order; assignments past capacity C are dropped.
    flat = onehot.reshape(B, S * K, E)
    pos = (jnp.cumsum(flat, axis=1) - flat).reshape(B, S, K, E)
    keep = onehot * (pos < C)
    cap_onehot = jax.nn.one_hot(
        jnp.minimum(pos, C - 1).astype(jnp.int32), C,
        dtype=jnp.float32)  # [B, S, K, E, C]
    dispatch = (keep[..., None] * cap_onehot).sum(axis=2)  # [B, S, E, C]
    combine = (gate_vals[..., None, None] * keep[..., None]
               * cap_onehot).sum(axis=2)  # [B, S, E, C]

    x_e = jnp.einsum("bsec,bsd->ebcd", dispatch.astype(dt), h)
    y = jnp.einsum("ebcd,edf->ebcf", x_e, layer["w_in"].astype(dt))
    y = jax.nn.gelu(y + layer["b_in"][:, None, None, :].astype(dt))
    y = jnp.einsum("ebcf,efd->ebcd", y, layer["w_out"].astype(dt))
    out = jnp.einsum("bsec,ebcd->bsd", combine.astype(dt), y)

    # Load-balancing aux (Switch Transformer): E * Σ_e f_e · p_e, where f_e
    # is the fraction of tokens whose top-1 choice is e and p_e the mean
    # router probability for e.
    f_e = onehot[:, :, 0, :].mean(axis=(0, 1))
    p_e = probs.mean(axis=(0, 1))
    aux = E * jnp.sum(f_e * p_e)
    return out, aux


def _block(cfg: GPTConfig, x, layer, positions):
    """One transformer block. x: [B, S, D]. Returns (x, aux_loss)."""
    dt = cfg.dtype
    h = _layernorm(x, layer["ln1_scale"], layer["ln1_bias"],
                   cfg.layernorm_eps)
    q = jnp.einsum("bsd,dhk->bshk", h, layer["wq"].astype(dt))
    k = jnp.einsum("bsd,dhk->bshk", h, layer["wk"].astype(dt))
    v = jnp.einsum("bsd,dhk->bshk", h, layer["wv"].astype(dt))
    q = checkpoint_name(_rotary(q, positions, cfg.rotary_dim), "attn_q")
    k = checkpoint_name(_rotary(k, positions, cfg.rotary_dim), "attn_k")
    v = checkpoint_name(v, "attn_v")
    attn = checkpoint_name(_attention(q, k, v, cfg), "attn_raw")
    attn_out = jnp.einsum("bshk,hkd->bsd", attn, layer["wo"].astype(dt))

    if cfg.parallel_block:
        mlp_in = h  # GPT-J: shared LN feeds both branches
    else:
        x = x + attn_out
        mlp_in = _layernorm(x, layer["ln2_scale"], layer["ln2_bias"],
                            cfg.layernorm_eps)
    aux = jnp.zeros((), jnp.float32)
    if cfg.is_moe:
        mlp_out, aux = _moe_ffn(cfg, mlp_in, layer)
    else:
        ff = checkpoint_name(
            jnp.einsum("bsd,df->bsf", mlp_in, layer["w_in"].astype(dt)),
            "ffn_in")
        ff = jax.nn.gelu(ff + layer["b_in"].astype(dt))
        mlp_out = jnp.einsum("bsf,fd->bsd", ff, layer["w_out"].astype(dt))
    mlp_out = mlp_out + layer["b_out"].astype(dt)

    if cfg.parallel_block:
        return x + attn_out + mlp_out, aux
    return x + mlp_out, aux


def hidden_states(params: Dict[str, Any], cfg: GPTConfig,
                  tokens: jax.Array,
                  positions: Optional[jax.Array] = None):
    """tokens [B, S] int32 → (final-layernormed hidden [B, S, d], aux)."""
    B, S = tokens.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
    x = jnp.take(params["wte"], tokens, axis=0).astype(cfg.dtype)

    block = partial(_block, cfg)
    if cfg.remat:
        if cfg.remat_policy == "selective":
            policy = jax.checkpoint_policies.save_only_these_names(
                "attn_q", "attn_k", "attn_v", "attn_raw", "ffn_in")
        elif cfg.remat_policy == "full":
            policy = jax.checkpoint_policies.nothing_saveable
        else:
            raise ValueError(
                f"Unknown remat_policy {cfg.remat_policy!r}; "
                "expected 'full' or 'selective'")
        block = jax.checkpoint(block, policy=policy)

    def scan_body(carry, layer):
        x, aux = carry
        x, a = block(x, layer, positions)
        return (x, aux + a), None

    (x, aux), _ = jax.lax.scan(
        scan_body, (x, jnp.zeros((), jnp.float32)), params["layers"])
    x = _layernorm(x, params["lnf_scale"], params["lnf_bias"],
                   cfg.layernorm_eps)
    return x, aux


def _head(params: Dict[str, Any], cfg: GPTConfig, x: jax.Array) -> jax.Array:
    """Hidden [..., d] → logits [..., vocab] (compute dtype)."""
    if cfg.tie_embeddings:
        return jnp.einsum("...d,vd->...v", x, params["wte"].astype(cfg.dtype))
    logits = jnp.einsum("...d,dv->...v", x, params["lm_head"].astype(cfg.dtype))
    return logits + params["lm_head_bias"].astype(cfg.dtype)


def forward_with_aux(params: Dict[str, Any], cfg: GPTConfig,
                     tokens: jax.Array,
                     positions: Optional[jax.Array] = None):
    """tokens [B, S] int32 → (logits [B, S, vocab], aux_loss scalar).
    aux_loss is the summed MoE load-balancing term (0 for dense models)."""
    x, aux = hidden_states(params, cfg, tokens, positions)
    return _head(params, cfg, x), aux


def forward(params: Dict[str, Any], cfg: GPTConfig, tokens: jax.Array,
            positions: Optional[jax.Array] = None) -> jax.Array:
    """tokens [B, S] int32 → logits [B, S, vocab] (compute dtype)."""
    return forward_with_aux(params, cfg, tokens, positions)[0]


def _ce_stats(logits: jax.Array, targets: jax.Array, mask: jax.Array,
              z_loss: float) -> Tuple[jax.Array, jax.Array]:
    """fp32 CE pieces for one [..., vocab] logits slab → (Σ nll·m, Σ hit·m)."""
    logits = logits.astype(jnp.float32)
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    tgt_logit = jnp.take_along_axis(
        logits, targets[..., None], axis=-1)[..., 0]
    nll = logz - tgt_logit
    if z_loss:
        nll = nll + z_loss * logz ** 2
    hits = (logits.argmax(-1) == targets).astype(jnp.float32)
    return (nll * mask).sum(), (hits * mask).sum()


def loss_fn(params: Dict[str, Any], cfg: GPTConfig, tokens: jax.Array,
            targets: jax.Array, mask: Optional[jax.Array] = None,
            z_loss: float = 0.0) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Next-token cross-entropy in fp32 (+ optional z-loss regularizer and,
    for MoE configs, the router load-balancing aux term).

    With ``cfg.loss_chunk > 0`` the head matmul + fp32 softmax run chunked
    under a rematerialized lax.scan, so the [tokens, vocab] fp32 logits
    never exist whole (see GPTConfig.loss_chunk)."""
    x, aux = hidden_states(params, cfg, tokens)
    B, S = tokens.shape
    if mask is None:
        mask32 = jnp.ones((B, S), jnp.float32)
    else:
        mask32 = mask.astype(jnp.float32)
    denom = jnp.maximum(mask32.sum(), 1.0)

    T = B * S
    chunk = cfg.loss_chunk
    if chunk and T % chunk and T > chunk:
        # Requested chunk doesn't divide the token count: use the largest
        # divisor <= chunk rather than silently materializing full logits
        # (defeating the feature's memory bound).
        chunk = max(c for c in range(1, chunk + 1) if T % c == 0)
    if chunk and T > chunk:
        d = x.shape[-1]
        xf = x.reshape(T // chunk, chunk, d)
        tf = targets.reshape(T // chunk, chunk)
        mf = mask32.reshape(T // chunk, chunk)

        @jax.checkpoint
        def chunk_stats(carry, xtm):
            x_c, t_c, m_c = xtm
            nll_sum, hit_sum = _ce_stats(
                _head(params, cfg, x_c), t_c, m_c, z_loss)
            return (carry[0] + nll_sum, carry[1] + hit_sum), None

        (nll_sum, hit_sum), _ = jax.lax.scan(
            chunk_stats, (jnp.zeros((), jnp.float32),) * 2, (xf, tf, mf))
    else:
        nll_sum, hit_sum = _ce_stats(
            _head(params, cfg, x), targets, mask32, z_loss)

    ce = nll_sum / denom
    loss = ce
    if cfg.is_moe:
        loss = ce + cfg.router_aux_weight * aux
    acc = hit_sum / denom
    # Perplexity from the cross-entropy alone (not the aux-regularized
    # loss), so MoE and dense perplexities are comparable.
    return loss, {"loss": loss, "accuracy": acc,
                  "perplexity": jnp.exp(jnp.minimum(ce, 20.0))}


def flops_per_token(cfg: GPTConfig) -> float:
    """Approximate training FLOPs/token (6N_active + attention quadratic
    term). For MoE, only the top-k routed experts do work per token, so the
    FFN share counts k experts, not all of them (MFU must not be inflated
    by inactive experts)."""
    n = cfg.num_params()
    if cfg.is_moe:
        d, f, L, E = cfg.d_model, cfg.d_ff, cfg.n_layers, cfg.n_experts
        K = min(cfg.expert_top_k, E)
        inactive_ffn = L * (E - K) * (2 * d * f + f)
        n -= inactive_ffn
    attn = 12 * cfg.n_layers * cfg.d_model * cfg.max_seq_len
    return 6.0 * n + attn
