"""Vision Transformer — image classification, TPU-first.

Same design stance as models/gpt.py: pure-pytree params, `lax.scan` over
stacked layers, bf16 matmuls with fp32 norm/softmax, logical-axis sharding
via ShardingRules. Patch embedding is a reshape + one big matmul (not a
conv) so the whole model is matmuls on the MXU.

Capability parity note: the reference's Train/AIR image benchmarks train
torchvision models (reference:
release/air_tests/air_benchmarks/workloads/torch_benchmark.py); this is
the rebuild's JAX vision model for those paths.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, replace
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

from ray_tpu.parallel.sharding import ShardingRules


@dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    n_channels: int = 3
    n_classes: int = 1000
    n_layers: int = 12
    d_model: int = 768
    n_heads: int = 12
    d_ff: int = 3072
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    layernorm_eps: float = 1e-6
    remat: bool = False
    pool: str = "cls"  # "cls" | "mean"

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def patch_dim(self) -> int:
        return self.n_channels * self.patch_size ** 2

    @property
    def seq_len(self) -> int:
        return self.n_patches + (1 if self.pool == "cls" else 0)

    def num_params(self) -> int:
        d, f, L = self.d_model, self.d_ff, self.n_layers
        per_layer = 4 * d * d + 2 * d * f + f + d + 4 * d
        return (self.patch_dim * d + d + self.seq_len * d
                + L * per_layer + 2 * d + d * self.n_classes
                + self.n_classes + (d if self.pool == "cls" else 0))


PRESETS: Dict[str, ViTConfig] = {
    "vit-b16": ViTConfig(),
    "vit-l16": ViTConfig(n_layers=24, d_model=1024, n_heads=16, d_ff=4096),
    "vit-s16": ViTConfig(n_layers=12, d_model=384, n_heads=6, d_ff=1536),
    # Test-size configs.
    "vit-tiny": ViTConfig(
        image_size=32, patch_size=8, n_classes=10, n_layers=2, d_model=64,
        n_heads=4, d_ff=128, dtype=jnp.float32),
}


def config(name: str, **overrides) -> ViTConfig:
    cfg = PRESETS[name]
    return replace(cfg, **overrides) if overrides else cfg


# -- init + sharding specs ----------------------------------------------

def init(cfg: ViTConfig, key: jax.Array) -> Dict[str, Any]:
    k_patch, k_pos, k_layers, k_head, k_cls = jax.random.split(key, 5)
    d, f, L = cfg.d_model, cfg.d_ff, cfg.n_layers
    h, hd = cfg.n_heads, cfg.head_dim
    pd = cfg.param_dtype
    std = 0.02
    out_std = std / math.sqrt(2 * L)

    def norm(k, shape, s=std):
        return (jax.random.normal(k, shape, jnp.float32) * s).astype(pd)

    ks = jax.random.split(k_layers, 6)

    def stack(k, shape, s=std):
        return norm(k, (L,) + shape, s)

    layers = {
        "ln1_scale": jnp.ones((L, d), pd),
        "ln1_bias": jnp.zeros((L, d), pd),
        "wq": stack(ks[0], (d, h, hd)),
        "wk": stack(ks[1], (d, h, hd)),
        "wv": stack(ks[2], (d, h, hd)),
        "wo": stack(ks[3], (h, hd, d), out_std),
        "ln2_scale": jnp.ones((L, d), pd),
        "ln2_bias": jnp.zeros((L, d), pd),
        "w_in": stack(ks[4], (d, f)),
        "b_in": jnp.zeros((L, f), pd),
        "w_out": stack(ks[5], (f, d), out_std),
        "b_out": jnp.zeros((L, d), pd),
    }
    params = {
        "patch_proj": norm(k_patch, (cfg.patch_dim, d)),
        "patch_bias": jnp.zeros((d,), pd),
        "pos_embed": norm(k_pos, (cfg.seq_len, d)),
        "layers": layers,
        "lnf_scale": jnp.ones((d,), pd),
        "lnf_bias": jnp.zeros((d,), pd),
        "head_w": norm(k_head, (d, cfg.n_classes)),
        "head_b": jnp.zeros((cfg.n_classes,), pd),
    }
    if cfg.pool == "cls":
        params["cls_token"] = norm(k_cls, (d,))
    return params


def param_specs(cfg: ViTConfig, rules: ShardingRules) -> Dict[str, Any]:
    r = rules
    layers = {
        "ln1_scale": r.spec("layers", "embed"),
        "ln1_bias": r.spec("layers", "embed"),
        "wq": r.spec("layers", "embed", "heads", "head_dim"),
        "wk": r.spec("layers", "embed", "heads", "head_dim"),
        "wv": r.spec("layers", "embed", "heads", "head_dim"),
        "wo": r.spec("layers", "heads", "head_dim", "embed"),
        "ln2_scale": r.spec("layers", "embed"),
        "ln2_bias": r.spec("layers", "embed"),
        "w_in": r.spec("layers", "embed", "mlp"),
        "b_in": r.spec("layers", "mlp"),
        "w_out": r.spec("layers", "mlp", "embed"),
        "b_out": r.spec("layers", "embed"),
    }
    specs = {
        "patch_proj": r.spec(None, "embed"),
        "patch_bias": r.spec("embed"),
        "pos_embed": r.spec(None, "embed"),
        "layers": layers,
        "lnf_scale": r.spec("embed"),
        "lnf_bias": r.spec("embed"),
        "head_w": r.spec("embed", "vocab"),
        "head_b": r.spec("vocab"),
    }
    if cfg.pool == "cls":
        specs["cls_token"] = r.spec("embed")
    return specs


def batch_spec(rules: ShardingRules) -> PartitionSpec:
    """Spec for image batches [B, H, W, C]."""
    return rules.spec("batch", None, None, None)


# -- forward ------------------------------------------------------------

def _layernorm(x, scale, bias, eps):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (y * scale.astype(jnp.float32)
            + bias.astype(jnp.float32)).astype(x.dtype)


def patchify(cfg: ViTConfig, images: jax.Array) -> jax.Array:
    """[B, H, W, C] → [B, n_patches, patch_dim] by pure reshape/transpose."""
    B, H, W, C = images.shape
    p = cfg.patch_size
    x = images.reshape(B, H // p, p, W // p, p, C)
    x = x.transpose(0, 1, 3, 2, 4, 5)  # [B, Hp, Wp, p, p, C]
    return x.reshape(B, (H // p) * (W // p), p * p * C)


def _block(cfg: ViTConfig, x, layer):
    dt = cfg.dtype
    h = _layernorm(x, layer["ln1_scale"], layer["ln1_bias"],
                   cfg.layernorm_eps)
    q = jnp.einsum("bsd,dhk->bshk", h, layer["wq"].astype(dt))
    k = jnp.einsum("bsd,dhk->bshk", h, layer["wk"].astype(dt))
    v = jnp.einsum("bsd,dhk->bshk", h, layer["wv"].astype(dt))
    scale = 1.0 / math.sqrt(cfg.head_dim)
    logits = (jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
              ).astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1).astype(dt)
    attn = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
    x = x + jnp.einsum("bshk,hkd->bsd", attn, layer["wo"].astype(dt))

    h = _layernorm(x, layer["ln2_scale"], layer["ln2_bias"],
                   cfg.layernorm_eps)
    ff = jnp.einsum("bsd,df->bsf", h, layer["w_in"].astype(dt))
    ff = jax.nn.gelu(ff + layer["b_in"].astype(dt))
    ff = jnp.einsum("bsf,fd->bsd", ff, layer["w_out"].astype(dt))
    return x + ff + layer["b_out"].astype(dt)


def forward(params: Dict[str, Any], cfg: ViTConfig,
            images: jax.Array) -> jax.Array:
    """images [B, H, W, C] float → logits [B, n_classes] (fp32)."""
    dt = cfg.dtype
    patches = patchify(cfg, images.astype(dt))
    x = (jnp.einsum("bpd,de->bpe", patches, params["patch_proj"].astype(dt))
         + params["patch_bias"].astype(dt))
    if cfg.pool == "cls":
        cls = jnp.broadcast_to(
            params["cls_token"].astype(dt), (x.shape[0], 1, cfg.d_model))
        x = jnp.concatenate([cls, x], axis=1)
    x = x + params["pos_embed"].astype(dt)

    block = partial(_block, cfg)
    if cfg.remat:
        block = jax.checkpoint(
            block, policy=jax.checkpoint_policies.nothing_saveable)

    def scan_body(x, layer):
        return block(x, layer), None

    x, _ = jax.lax.scan(scan_body, x, params["layers"])
    x = _layernorm(x, params["lnf_scale"], params["lnf_bias"],
                   cfg.layernorm_eps)
    pooled = x[:, 0] if cfg.pool == "cls" else x.mean(axis=1)
    logits = (jnp.einsum("bd,dc->bc", pooled, params["head_w"].astype(dt))
              + params["head_b"].astype(dt))
    return logits.astype(jnp.float32)


def loss_fn(params: Dict[str, Any], cfg: ViTConfig, images: jax.Array,
            labels: jax.Array) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Softmax cross-entropy classification loss (fp32)."""
    logits = forward(params, cfg, images)
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    tgt = jnp.take_along_axis(logits, labels[:, None], axis=-1)[:, 0]
    loss = (logz - tgt).mean()
    acc = (logits.argmax(-1) == labels).mean()
    return loss, {"loss": loss, "accuracy": acc}


def flops_per_image(cfg: ViTConfig) -> float:
    return 6.0 * cfg.num_params() * cfg.seq_len
