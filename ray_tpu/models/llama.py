"""Llama family — RMSNorm / SwiGLU / full-dim rotary / GQA, TPU-first.

Same design stance as models/gpt.py (pure-pytree params, `lax.scan` over
stacked layers, bf16 matmuls with fp32 norm/softmax, logical-axis sharding
via ShardingRules) but the Llama architecture: sequential pre-norm blocks,
RMSNorm without bias, SwiGLU FFN, rotary applied to the full head dim with
the half-rotation (non-interleaved) convention, grouped-query attention,
and no biases anywhere.

Capability parity note: the reference has no model zoo (models come from
torch/transformers; e.g. its Train examples fine-tune HF models —
reference: python/ray/train/huggingface/huggingface_trainer.py). This
module is the JAX equivalent of `transformers.LlamaForCausalLM` for the
rebuild's Train/Serve paths.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, replace
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

from ray_tpu.parallel.sharding import ShardingRules


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    n_layers: int = 32
    d_model: int = 4096
    n_heads: int = 32
    n_kv_heads: Optional[int] = None  # != n_heads → GQA
    d_ff: int = 11008
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5
    tie_embeddings: bool = False
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True
    attn_impl: str = "dot"  # "dot" | "flash" | "ring" | "ulysses"

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads or self.n_heads

    def num_params(self) -> int:
        d, f, v, L = self.d_model, self.d_ff, self.vocab_size, self.n_layers
        kvh = self.kv_heads * self.head_dim
        per_layer = (d * d + 2 * d * kvh + d * d  # q, k, v, o
                     + 3 * d * f                   # gate, up, down
                     + 2 * d)                      # two RMSNorm scales
        head = 0 if self.tie_embeddings else v * d
        return v * d + L * per_layer + d + head


PRESETS: Dict[str, LlamaConfig] = {
    "llama2-7b": LlamaConfig(),
    "llama3-8b": LlamaConfig(
        vocab_size=128256, n_layers=32, d_model=4096, n_heads=32,
        n_kv_heads=8, d_ff=14336, max_seq_len=8192, rope_theta=500000.0),
    "tinyllama-1b": LlamaConfig(
        vocab_size=32000, n_layers=22, d_model=2048, n_heads=32,
        n_kv_heads=4, d_ff=5632, max_seq_len=2048),
    # Test-size configs.
    "llama-tiny": LlamaConfig(
        vocab_size=256, n_layers=2, d_model=64, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=128, dtype=jnp.float32, remat=False),
    "llama-micro": LlamaConfig(
        vocab_size=512, n_layers=4, d_model=128, n_heads=8, n_kv_heads=4,
        d_ff=256, max_seq_len=256, dtype=jnp.float32, remat=False),
}


def config(name: str, **overrides) -> LlamaConfig:
    cfg = PRESETS[name]
    return replace(cfg, **overrides) if overrides else cfg


# -- init + sharding specs ----------------------------------------------

def init(cfg: LlamaConfig, key: jax.Array) -> Dict[str, Any]:
    k_embed, k_layers, k_head = jax.random.split(key, 3)
    d, f, v, L = cfg.d_model, cfg.d_ff, cfg.vocab_size, cfg.n_layers
    h, kvh, hd = cfg.n_heads, cfg.kv_heads, cfg.head_dim
    pd = cfg.param_dtype
    std = 0.02
    out_std = std / math.sqrt(2 * L)

    def norm(k, shape, s=std):
        return (jax.random.normal(k, shape, jnp.float32) * s).astype(pd)

    ks = jax.random.split(k_layers, 7)

    def stack(k, shape, s=std):
        return norm(k, (L,) + shape, s)

    layers = {
        "attn_norm": jnp.ones((L, d), pd),
        "wq": stack(ks[0], (d, h, hd)),
        "wk": stack(ks[1], (d, kvh, hd)),
        "wv": stack(ks[2], (d, kvh, hd)),
        "wo": stack(ks[3], (h, hd, d), out_std),
        "ffn_norm": jnp.ones((L, d), pd),
        "w_gate": stack(ks[4], (d, f)),
        "w_up": stack(ks[5], (d, f)),
        "w_down": stack(ks[6], (f, d), out_std),
    }
    params = {
        "wte": norm(k_embed, (v, d)),
        "layers": layers,
        "final_norm": jnp.ones((d,), pd),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = norm(k_head, (d, v))
    return params


def param_specs(cfg: LlamaConfig, rules: ShardingRules) -> Dict[str, Any]:
    r = rules
    layers = {
        "attn_norm": r.spec("layers", "embed"),
        "wq": r.spec("layers", "embed", "heads", "head_dim"),
        "wk": r.spec("layers", "embed", "kv_heads", "head_dim"),
        "wv": r.spec("layers", "embed", "kv_heads", "head_dim"),
        "wo": r.spec("layers", "heads", "head_dim", "embed"),
        "ffn_norm": r.spec("layers", "embed"),
        "w_gate": r.spec("layers", "embed", "mlp"),
        "w_up": r.spec("layers", "embed", "mlp"),
        "w_down": r.spec("layers", "mlp", "embed"),
    }
    specs = {
        "wte": r.spec("vocab", "embed"),
        "layers": layers,
        "final_norm": r.spec("embed"),
    }
    if not cfg.tie_embeddings:
        specs["lm_head"] = r.spec("embed", "vocab")
    return specs


def batch_spec(rules: ShardingRules) -> PartitionSpec:
    return rules.spec("batch", "sequence")


# -- forward ------------------------------------------------------------

def _rmsnorm(x, scale, eps):
    x32 = x.astype(jnp.float32)
    var = (x32 * x32).mean(-1, keepdims=True)
    y = x32 * jax.lax.rsqrt(var + eps)
    return (y * scale.astype(jnp.float32)).astype(x.dtype)


def _rotary(x, positions, theta):
    """Llama (half-rotation) rotary over the full head dim.
    x: [B, S, H, D], positions: [B, S]."""
    D = x.shape[-1]
    half = D // 2
    freqs = 1.0 / (theta ** (jnp.arange(half, dtype=jnp.float32) / half))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B, S, half]
    cos = jnp.cos(angles)[:, :, None, :].astype(x.dtype)
    sin = jnp.sin(angles)[:, :, None, :].astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)


def _dot_attention(q, k, v, cfg: LlamaConfig):
    B, S, H, D = q.shape
    kvh = k.shape[2]
    if kvh != H:
        rep = H // kvh
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scale = 1.0 / math.sqrt(D)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    logits = logits.astype(jnp.float32)
    qpos = jax.lax.broadcasted_iota(jnp.int32, (S, S), 0)
    kpos = jax.lax.broadcasted_iota(jnp.int32, (S, S), 1)
    logits = jnp.where((qpos >= kpos)[None, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _attention(q, k, v, cfg: LlamaConfig):
    if cfg.attn_impl == "dot":
        return _dot_attention(q, k, v, cfg)
    if cfg.attn_impl == "flash":
        from ray_tpu.ops.flash_attention import flash_attention
        return flash_attention(q, k, v, causal=True)
    if cfg.attn_impl in ("ring", "ulysses"):
        from ray_tpu.models import gpt as _gpt
        # Reuse GPT's mesh-aware dispatch; the semantics (causal,
        # [B, S, H, D] layout) are identical.
        proxy = _gpt.GPTConfig(attn_impl=cfg.attn_impl)
        return _gpt._attention(q, k, v, proxy)
    raise ValueError(f"Unknown attn_impl {cfg.attn_impl!r}")


def _block(cfg: LlamaConfig, x, layer, positions):
    dt = cfg.dtype
    h = _rmsnorm(x, layer["attn_norm"], cfg.rms_eps)
    q = jnp.einsum("bsd,dhk->bshk", h, layer["wq"].astype(dt))
    k = jnp.einsum("bsd,dhk->bshk", h, layer["wk"].astype(dt))
    v = jnp.einsum("bsd,dhk->bshk", h, layer["wv"].astype(dt))
    q = _rotary(q, positions, cfg.rope_theta)
    k = _rotary(k, positions, cfg.rope_theta)
    attn = _attention(q, k, v, cfg)
    x = x + jnp.einsum("bshk,hkd->bsd", attn, layer["wo"].astype(dt))

    h = _rmsnorm(x, layer["ffn_norm"], cfg.rms_eps)
    gate = jnp.einsum("bsd,df->bsf", h, layer["w_gate"].astype(dt))
    up = jnp.einsum("bsd,df->bsf", h, layer["w_up"].astype(dt))
    ff = jax.nn.silu(gate) * up
    return x + jnp.einsum("bsf,fd->bsd", ff, layer["w_down"].astype(dt))


def forward(params: Dict[str, Any], cfg: LlamaConfig, tokens: jax.Array,
            positions: Optional[jax.Array] = None) -> jax.Array:
    """tokens [B, S] int32 → logits [B, S, vocab] (compute dtype)."""
    B, S = tokens.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
    x = jnp.take(params["wte"], tokens, axis=0).astype(cfg.dtype)

    block = partial(_block, cfg)
    if cfg.remat:
        block = jax.checkpoint(
            block, policy=jax.checkpoint_policies.nothing_saveable)

    def scan_body(x, layer):
        return block(x, layer, positions), None

    x, _ = jax.lax.scan(scan_body, x, params["layers"])
    x = _rmsnorm(x, params["final_norm"], cfg.rms_eps)
    if cfg.tie_embeddings:
        return jnp.einsum("bsd,vd->bsv", x, params["wte"].astype(cfg.dtype))
    return jnp.einsum("bsd,dv->bsv", x, params["lm_head"].astype(cfg.dtype))


def loss_fn(params: Dict[str, Any], cfg: LlamaConfig, tokens: jax.Array,
            targets: jax.Array, mask: Optional[jax.Array] = None,
            z_loss: float = 0.0) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Next-token cross-entropy in fp32 (+ optional z-loss)."""
    logits = forward(params, cfg, tokens).astype(jnp.float32)
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    tgt_logit = jnp.take_along_axis(
        logits, targets[..., None], axis=-1)[..., 0]
    nll = logz - tgt_logit
    if z_loss:
        nll = nll + z_loss * logz ** 2
    if mask is None:
        mask = jnp.ones_like(nll)
    mask = mask.astype(jnp.float32)
    denom = jnp.maximum(mask.sum(), 1.0)
    loss = (nll * mask).sum() / denom
    acc = ((logits.argmax(-1) == targets) * mask).sum() / denom
    return loss, {"loss": loss, "accuracy": acc,
                  "perplexity": jnp.exp(jnp.minimum(loss, 20.0))}


def flops_per_token(cfg: LlamaConfig) -> float:
    attn = 12 * cfg.n_layers * cfg.d_model * cfg.max_seq_len
    return 6.0 * cfg.num_params() + attn
