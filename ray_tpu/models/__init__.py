from ray_tpu.models import diffusion, gpt, llama, vit

__all__ = ["diffusion", "gpt", "llama", "vit"]
