from ray_tpu.models import diffusion, gpt, llama, t5, vit

__all__ = ["diffusion", "gpt", "llama", "t5", "vit"]
