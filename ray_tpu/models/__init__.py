from ray_tpu.models import bert, diffusion, gpt, llama, t5, vit

__all__ = ["bert", "diffusion", "gpt", "llama", "t5", "vit"]
