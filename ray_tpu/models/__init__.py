from ray_tpu.models import gpt

__all__ = ["gpt"]
