"""T5-style encoder-decoder, TPU-first.

The seq2seq family of the model zoo (alongside the decoder-only GPT/Llama,
the ViT encoder, and the diffusion UNet): pre-RMSNorm blocks, relative
position bias buckets added to attention logits (no absolute positions),
a gated-GELU feed-forward, causal decoder self-attention plus
cross-attention over the encoder output, and a tied embedding with the
T5 d_model^-0.5 logit scaling.

Same TPU design rules as models/gpt.py: pure-pytree params with logical
axis names for GSPMD sharding, `lax.scan` over stacked layers (O(1)
compile), bf16 matmuls with fp32 softmax/norm accumulation, optional
per-block remat, static shapes throughout.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, replace
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

from ray_tpu.parallel.sharding import ShardingRules


@dataclass(frozen=True)
class T5Config:
    vocab_size: int = 32128
    d_model: int = 512
    n_heads: int = 8
    d_ff: int = 1024
    n_encoder_layers: int = 6
    n_decoder_layers: int = 6
    rel_pos_buckets: int = 32
    rel_pos_max_distance: int = 128
    layernorm_eps: float = 1e-6
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    def num_params(self) -> int:
        d, f, h = self.d_model, self.d_ff, self.n_heads
        attn = 4 * d * d
        ffn = 3 * d * f  # gated: wi_0, wi_1, wo
        enc = self.n_encoder_layers * (attn + ffn + 2 * d)
        dec = self.n_decoder_layers * (2 * attn + ffn + 3 * d)
        rel = 2 * self.rel_pos_buckets * h  # enc + dec bias tables
        return self.vocab_size * d + enc + dec + rel + 2 * d


PRESETS: Dict[str, T5Config] = {
    "t5-small": T5Config(),
    "t5-base": T5Config(d_model=768, n_heads=12, d_ff=2048,
                        n_encoder_layers=12, n_decoder_layers=12),
    "t5-tiny": T5Config(vocab_size=256, d_model=64, n_heads=4, d_ff=128,
                        n_encoder_layers=2, n_decoder_layers=2,
                        rel_pos_buckets=8, rel_pos_max_distance=32,
                        dtype=jnp.float32, remat=False),
}


def config(name: str, **overrides) -> T5Config:
    cfg = PRESETS[name]
    return replace(cfg, **overrides) if overrides else cfg


# -- init + sharding specs ----------------------------------------------

def _attn_params(key, d, h, hd, pd, std):
    ks = jax.random.split(key, 4)

    def norm(k, shape, s):
        return (jax.random.normal(k, shape, jnp.float32) * s).astype(pd)

    return {
        "wq": norm(ks[0], (d, h, hd), std),
        "wk": norm(ks[1], (d, h, hd), std),
        "wv": norm(ks[2], (d, h, hd), std),
        "wo": norm(ks[3], (h, hd, d), std),
    }


def init(cfg: T5Config, key: jax.Array) -> Dict[str, Any]:
    d, f, h, hd = cfg.d_model, cfg.d_ff, cfg.n_heads, cfg.head_dim
    pd = cfg.param_dtype
    std = 1.0 / math.sqrt(d)
    keys = jax.random.split(key, 8)

    def norm(k, shape, s=std):
        return (jax.random.normal(k, shape, jnp.float32) * s).astype(pd)

    def stack(k, n, builder):
        return jax.tree.map(
            lambda *xs: jnp.stack(xs),
            *[builder(sub) for sub in jax.random.split(k, n)])

    def enc_layer(k):
        k1, k2 = jax.random.split(k)
        layer = {"ln1": jnp.ones((d,), pd), "ln2": jnp.ones((d,), pd),
                 "attn": _attn_params(k1, d, h, hd, pd, std)}
        k_in0, k_in1, k_out = jax.random.split(k2, 3)
        layer["wi_0"] = norm(k_in0, (d, f))
        layer["wi_1"] = norm(k_in1, (d, f))
        layer["wo_ff"] = norm(k_out, (f, d))
        return layer

    def dec_layer(k):
        k1, k2, k3 = jax.random.split(k, 3)
        layer = enc_layer(k1)
        layer["ln3"] = jnp.ones((d,), pd)
        layer["cross"] = _attn_params(k3, d, h, hd, pd, std)
        return layer

    return {
        "wte": norm(keys[0], (cfg.vocab_size, d)),
        "enc_rel_bias": norm(keys[1], (cfg.rel_pos_buckets, h)),
        "dec_rel_bias": norm(keys[2], (cfg.rel_pos_buckets, h)),
        "encoder": stack(keys[3], cfg.n_encoder_layers, enc_layer),
        "decoder": stack(keys[4], cfg.n_decoder_layers, dec_layer),
        "enc_final_ln": jnp.ones((d,), pd),
        "dec_final_ln": jnp.ones((d,), pd),
    }


def _attn_specs(r: ShardingRules):
    return {
        "wq": r.spec("layers", "embed", "heads", "head_dim"),
        "wk": r.spec("layers", "embed", "heads", "head_dim"),
        "wv": r.spec("layers", "embed", "heads", "head_dim"),
        "wo": r.spec("layers", "heads", "head_dim", "embed"),
    }


def param_specs(cfg: T5Config, rules: ShardingRules) -> Dict[str, Any]:
    r = rules
    enc = {"ln1": r.spec("layers", "embed"), "ln2": r.spec("layers", "embed"),
           "attn": _attn_specs(r),
           "wi_0": r.spec("layers", "embed", "mlp"),
           "wi_1": r.spec("layers", "embed", "mlp"),
           "wo_ff": r.spec("layers", "mlp", "embed")}
    dec = dict(enc)
    dec["ln3"] = r.spec("layers", "embed")
    dec["cross"] = _attn_specs(r)
    return {
        "wte": r.spec("vocab", "embed"),
        "enc_rel_bias": r.spec(None, "heads"),
        "dec_rel_bias": r.spec(None, "heads"),
        "encoder": enc,
        "decoder": dec,
        "enc_final_ln": r.spec("embed"),
        "dec_final_ln": r.spec("embed"),
    }


def batch_spec(rules: ShardingRules) -> PartitionSpec:
    return rules.spec("batch", "sequence")


# -- forward ------------------------------------------------------------

def _rmsnorm(x, scale, eps):
    x32 = x.astype(jnp.float32)
    var = (x32 ** 2).mean(-1, keepdims=True)
    return (x32 * jax.lax.rsqrt(var + eps)
            * scale.astype(jnp.float32)).astype(x.dtype)


def _relative_buckets(rel_pos, bidirectional: bool, num_buckets: int,
                      max_distance: int):
    """T5's log-bucketed relative positions (t5x relative_position_bucket).
    ``rel_pos`` = q_pos - k_pos: positive = key in the past. Unidirectional
    buckets must grow with distance INTO THE PAST — the causally visible
    region — not the (masked) future."""
    ret = 0
    n = rel_pos
    if bidirectional:
        num_buckets //= 2
        ret = (n < 0).astype(jnp.int32) * num_buckets
        n = jnp.abs(n)
    else:
        n = jnp.maximum(n, 0)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    log_ratio = jnp.log(n.astype(jnp.float32) / max_exact + 1e-6) / \
        math.log(max_distance / max_exact)
    large = max_exact + (log_ratio * (num_buckets - max_exact)).astype(
        jnp.int32)
    large = jnp.minimum(large, num_buckets - 1)
    return ret + jnp.where(is_small, n, large)


def _rel_bias(table, q_len: int, k_len: int, bidirectional: bool,
              num_buckets: int, max_distance: int, dtype):
    q_pos = jnp.arange(q_len)[:, None]
    k_pos = jnp.arange(k_len)[None, :]
    buckets = _relative_buckets(q_pos - k_pos, bidirectional, num_buckets,
                                max_distance)
    bias = table[buckets]  # [Q, K, H]
    return bias.transpose(2, 0, 1)[None].astype(dtype)  # [1, H, Q, K]


def _mha(q_in, kv_in, attn_p, cfg: T5Config, bias=None, causal=False):
    dt = cfg.dtype
    q = jnp.einsum("bsd,dhk->bshk", q_in, attn_p["wq"].astype(dt))
    k = jnp.einsum("bsd,dhk->bshk", kv_in, attn_p["wk"].astype(dt))
    v = jnp.einsum("bsd,dhk->bshk", kv_in, attn_p["wv"].astype(dt))
    # Upstream T5 omits the 1/sqrt(head_dim) here by folding it into a
    # special wq init; with standard init we apply it explicitly (same
    # function, saner init story).
    scale = 1.0 / math.sqrt(q.shape[-1])
    logits = (jnp.einsum("bqhd,bkhd->bhqk", q, k)
              * scale).astype(jnp.float32)
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    if causal:
        Q, K = logits.shape[-2], logits.shape[-1]
        qpos = jax.lax.broadcasted_iota(jnp.int32, (Q, K), 0)
        kpos = jax.lax.broadcasted_iota(jnp.int32, (Q, K), 1)
        logits = jnp.where((qpos >= kpos)[None, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(dt)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
    return jnp.einsum("bshk,hkd->bsd", out, attn_p["wo"].astype(dt))


def _ffn(x, layer, cfg: T5Config):
    dt = cfg.dtype
    gate = jax.nn.gelu(
        jnp.einsum("bsd,df->bsf", x, layer["wi_0"].astype(dt)))
    up = jnp.einsum("bsd,df->bsf", x, layer["wi_1"].astype(dt))
    return jnp.einsum("bsf,fd->bsd", gate * up, layer["wo_ff"].astype(dt))


def encode(params, cfg: T5Config, tokens: jax.Array) -> jax.Array:
    """tokens [B, S] → encoder hidden [B, S, d]."""
    dt = cfg.dtype
    x = jnp.take(params["wte"], tokens, axis=0).astype(dt)
    S = tokens.shape[1]
    bias = _rel_bias(params["enc_rel_bias"], S, S, True,
                     cfg.rel_pos_buckets, cfg.rel_pos_max_distance, dt)

    def block(x, layer):
        h = _rmsnorm(x, layer["ln1"], cfg.layernorm_eps)
        x = x + _mha(h, h, layer["attn"], cfg, bias=bias)
        h = _rmsnorm(x, layer["ln2"], cfg.layernorm_eps)
        return x + _ffn(h, layer, cfg)

    if cfg.remat:
        block = jax.checkpoint(
            block, policy=jax.checkpoint_policies.nothing_saveable)
    x, _ = jax.lax.scan(lambda c, l: (block(c, l), None), x,
                        params["encoder"])
    return _rmsnorm(x, params["enc_final_ln"], cfg.layernorm_eps)


def decode(params, cfg: T5Config, enc_out: jax.Array,
           decoder_tokens: jax.Array) -> jax.Array:
    """enc_out [B, Se, d] + decoder_tokens [B, Sd] → logits [B, Sd, V]."""
    dt = cfg.dtype
    x = jnp.take(params["wte"], decoder_tokens, axis=0).astype(dt)
    Sd = decoder_tokens.shape[1]
    self_bias = _rel_bias(params["dec_rel_bias"], Sd, Sd, False,
                          cfg.rel_pos_buckets, cfg.rel_pos_max_distance, dt)

    def block(x, layer):
        h = _rmsnorm(x, layer["ln1"], cfg.layernorm_eps)
        x = x + _mha(h, h, layer["attn"], cfg, bias=self_bias, causal=True)
        h = _rmsnorm(x, layer["ln3"], cfg.layernorm_eps)
        x = x + _mha(h, enc_out, layer["cross"], cfg)
        h = _rmsnorm(x, layer["ln2"], cfg.layernorm_eps)
        return x + _ffn(h, layer, cfg)

    if cfg.remat:
        block = jax.checkpoint(
            block, policy=jax.checkpoint_policies.nothing_saveable)
    x, _ = jax.lax.scan(lambda c, l: (block(c, l), None), x,
                        params["decoder"])
    x = _rmsnorm(x, params["dec_final_ln"], cfg.layernorm_eps)
    # Tied embedding head with T5's rescale.
    x = x * (cfg.d_model ** -0.5)
    return jnp.einsum("bsd,vd->bsv", x, params["wte"].astype(dt))


def forward(params, cfg: T5Config, encoder_tokens: jax.Array,
            decoder_tokens: jax.Array) -> jax.Array:
    return decode(params, cfg, encode(params, cfg, encoder_tokens),
                  decoder_tokens)


def loss_fn(params, cfg: T5Config, encoder_tokens, decoder_tokens,
            targets, mask=None) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    logits = forward(params, cfg, encoder_tokens,
                     decoder_tokens).astype(jnp.float32)
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    tgt = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    nll = logz - tgt
    if mask is None:
        mask = jnp.ones_like(nll)
    mask = mask.astype(jnp.float32)
    denom = jnp.maximum(mask.sum(), 1.0)
    loss = (nll * mask).sum() / denom
    acc = ((logits.argmax(-1) == targets) * mask).sum() / denom
    return loss, {"loss": loss, "accuracy": acc}
