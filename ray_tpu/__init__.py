"""ray_tpu: a TPU-native distributed computing framework.

A ground-up rebuild of the reference framework's capabilities (tasks, actors,
distributed objects, Data/Train/Tune/Serve libraries) designed TPU-first:
TPU chips and ICI topology are first-class schedulable resources, training
parallelism is expressed as `jax.sharding` meshes compiled by XLA/GSPMD, and
collectives ride ICI — never NCCL.

Public API mirrors the reference's top-level surface
(python/ray/__init__.py): ``init, shutdown, remote, get, put, wait, kill,
cancel, get_actor, ...``.
"""

from ray_tpu import exceptions
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.worker import (ClientContext, available_resources,
                                     broadcast, cluster_usage,
                                     cancel, cluster_resources, free, get,
                                     get_actor, get_tpu_ids, init,
                                     is_initialized, kill, nodes, put,
                                     shutdown, start_head_server, wait)
from ray_tpu.actor import ActorClass, ActorHandle, method
from ray_tpu.remote_function import RemoteFunction, remote
from ray_tpu.runtime_context import get_runtime_context

__version__ = "0.1.0"

# GPU-era alias: the accelerator resource on this framework is the TPU.
get_gpu_ids = get_tpu_ids

__all__ = [
    "ActorClass",
    "method",
    "ActorHandle",
    "ClientContext",
    "ObjectRef",
    "RemoteFunction",
    "__version__",
    "available_resources",
    "broadcast",
    "cluster_usage",
    "cancel",
    "cluster_resources",
    "exceptions",
    "free",
    "get",
    "get_actor",
    "get_gpu_ids",
    "get_runtime_context",
    "get_tpu_ids",
    "init",
    "is_initialized",
    "kill",
    "nodes",
    "put",
    "remote",
    "shutdown",
    "start_head_server",
    "wait",
]
