from ray_tpu.parallel.mesh import (AXIS_ORDER, MeshConfig, build_mesh,
                                   single_device_mesh)
from ray_tpu.parallel.sharding import (ShardingRules, context_parallel_rules,
                                       dp_rules, fsdp_rules, named_sharding,
                                       shard_tree, tp_fsdp_rules,
                                       tree_shardings)

__all__ = [
    "AXIS_ORDER",
    "MeshConfig",
    "ShardingRules",
    "build_mesh",
    "context_parallel_rules",
    "dp_rules",
    "fsdp_rules",
    "named_sharding",
    "shard_tree",
    "single_device_mesh",
    "tp_fsdp_rules",
    "tree_shardings",
]
