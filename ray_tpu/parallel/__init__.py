from ray_tpu.parallel.mesh import (AXIS_ORDER, MeshConfig, build_mesh,
                                   single_device_mesh)
from ray_tpu.parallel.pipeline import (make_pipeline_fn, sequential_apply,
                                       stage_param_specs)
from ray_tpu.parallel.sharding import (ShardingRules, context_parallel_rules,
                                       dp_rules, fsdp_rules, named_sharding,
                                       shard_tree, tp_fsdp_rules,
                                       tree_shardings)

__all__ = [
    "AXIS_ORDER",
    "MeshConfig",
    "ShardingRules",
    "build_mesh",
    "context_parallel_rules",
    "dp_rules",
    "fsdp_rules",
    "make_pipeline_fn",
    "named_sharding",
    "sequential_apply",
    "shard_tree",
    "single_device_mesh",
    "stage_param_specs",
    "tp_fsdp_rules",
    "tree_shardings",
]
