"""Pipeline parallelism via shard_map + collective permute (GPipe schedule).

XLA has no native pipeline primitive (SURVEY.md §7 "hard parts"), so stages
are laid out the TPU way: stage parameters are stacked on a leading axis
sharded over the "pp" mesh axis, every device runs the SAME compiled tick
body, and activations flow stage→stage over ICI with `lax.ppermute`. A
microbatch enters stage 0 each tick; after `n_stages + n_micro - 1` ticks
every microbatch has drained through the last stage. Gradients flow through
ppermute's transpose (reverse permute), so `jax.grad` of a pipelined forward
is itself a pipelined backward.

The reference has NO pipeline parallelism at all (SURVEY.md §2.5 — only
actors + send/recv building blocks users could assemble); this module is a
new TPU-native capability.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ray_tpu._private.jax_compat import shard_map


def stage_param_specs(param_tree, axis: str = "pp"):
    """PartitionSpecs sharding the leading (stage) axis of every leaf."""
    return jax.tree.map(lambda _: P(axis), param_tree)


def make_pipeline_fn(stage_fn: Callable, n_stages: int, mesh,
                     axis: str = "pp") -> Callable:
    """Build pipelined_apply(stage_params, micro_inputs) -> outputs.

    * ``stage_fn(params_one_stage, x) -> y`` — one stage's computation;
      x and y must have identical shape/dtype (inter-stage activations).
    * ``stage_params`` — pytree whose leaves have leading dim ``n_stages``,
      sharded over the ``axis`` mesh dimension (see stage_param_specs).
    * ``micro_inputs`` — [n_micro, micro_batch, ...] microbatches.

    Returns [n_micro, micro_batch, ...] outputs (replicated). Differentiable.
    """
    if axis not in mesh.axis_names:
        raise ValueError(f"mesh has no {axis!r} axis: {mesh.axis_names}")
    axis_size = dict(zip(mesh.axis_names, mesh.devices.shape))[axis]
    if axis_size != n_stages:
        raise ValueError(
            f"n_stages={n_stages} must equal the {axis!r} mesh axis size "
            f"({axis_size}); one stage per mesh slice.")

    shift = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def per_device(stage_params, xs):
        # stage_params leaves: [1, ...] (this device's stage); xs replicated.
        local = jax.tree.map(lambda p: p[0], stage_params)
        stage = jax.lax.axis_index(axis)
        n_micro = xs.shape[0]
        n_ticks = n_micro + n_stages - 1

        def tick(carry, t):
            received, outputs = carry
            # Stage 0 pulls microbatch t from the input stream (clipped index
            # is harmless: the value is masked out-of-window by the output
            # collection below); later stages consume what the previous
            # stage sent last tick.
            x_t = jax.lax.dynamic_index_in_dim(
                xs, jnp.clip(t, 0, n_micro - 1), 0, keepdims=False)
            inp = jnp.where(stage == 0, x_t, received)
            y = stage_fn(local, inp)
            # Last stage emits microbatch t-(n_stages-1) this tick.
            out_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
            in_window = (t >= n_stages - 1) & (stage == n_stages - 1)
            updated = jax.lax.dynamic_update_index_in_dim(
                outputs, y, out_idx, 0)
            outputs = jnp.where(in_window, updated, outputs)
            received = jax.lax.ppermute(y, axis, shift)
            return (received, outputs), None

        zeros_out = jnp.zeros(xs.shape, xs.dtype)
        init = (jnp.zeros(xs.shape[1:], xs.dtype), zeros_out)
        (_, outputs), _ = jax.lax.scan(
            tick, init, jnp.arange(n_ticks))
        # Only the last stage holds real outputs; psum-mask to replicate.
        outputs = jnp.where(stage == n_stages - 1, outputs, 0)
        return jax.lax.psum(outputs, axis)

    def pipelined(stage_params, micro_inputs):
        in_param_specs = stage_param_specs(stage_params, axis)
        mapped = shard_map(
            per_device, mesh=mesh,
            in_specs=(in_param_specs, P()),
            out_specs=P(),
            check_vma=False)
        return mapped(stage_params, micro_inputs)

    return pipelined


def sequential_apply(stage_fn: Callable, stage_params, micro_inputs):
    """Reference semantics of make_pipeline_fn (no pipelining): apply the
    stage stack to every microbatch in order. Used by tests to check the
    pipelined schedule is numerically identical."""
    n_stages = jax.tree.leaves(stage_params)[0].shape[0]

    def apply_all(x):
        for s in range(n_stages):
            params_s = jax.tree.map(lambda p: p[s], stage_params)
            x = stage_fn(params_s, x)
        return x

    return jax.vmap(apply_all)(micro_inputs)
