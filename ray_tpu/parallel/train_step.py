"""Sharded training step: init + step builders over a device mesh.

The GSPMD successor to the reference's prepare_model/prepare_optimizer
wrappers (train/torch/train_loop_utils.py:51): instead of wrapping the model
in DDP/FSDP modules, we jit one functional train step whose inputs carry
NamedShardings; XLA inserts the gradient psums / param all-gathers over ICI.
Parameters are *initialized inside jit with out_shardings* so a 6B-param
model never materializes unsharded on any single host.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec

from ray_tpu.models import gpt
from ray_tpu.parallel.sharding import ShardingRules, tree_shardings


def default_optimizer(learning_rate=3e-4, weight_decay=0.1,
                      warmup_steps: int = 100,
                      total_steps: int = 10_000) -> optax.GradientTransformation:
    schedule = optax.warmup_cosine_decay_schedule(
        0.0, learning_rate, warmup_steps, max(total_steps, warmup_steps + 1))
    return optax.chain(
        optax.clip_by_global_norm(1.0),
        optax.adamw(schedule, b1=0.9, b2=0.95, eps=1e-8,
                    weight_decay=weight_decay),
    )


def memory_efficient_optimizer(learning_rate=1e-4,
                               warmup_steps: int = 100,
                               total_steps: int = 10_000
                               ) -> optax.GradientTransformation:
    """Adafactor: factored second moments, no first moment — optimizer
    state shrinks from 2 fp32 copies of the params (adam, ~8 bytes/param)
    to O(rows + cols) per matrix. The single-chip recipe for models
    whose adam state would blow HBM (gpt-1.3b on a 16GB chip: params
    2.6GB bf16 + grads 2.6GB + adam 10.4GB does not fit; with adafactor
    the whole train state does). The ZeRO-equivalent GSPMD path shards
    adam state across chips instead — this is the one-chip analog."""
    schedule = optax.warmup_cosine_decay_schedule(
        0.0, learning_rate, warmup_steps,
        max(total_steps, warmup_steps + 1))
    return optax.chain(
        optax.clip_by_global_norm(1.0),
        optax.adafactor(learning_rate=schedule, momentum=None),
    )


def init_train_state(cfg: gpt.GPTConfig, mesh,
                     rules: Optional[ShardingRules] = None,
                     optimizer: Optional[optax.GradientTransformation] = None,
                     seed: int = 0) -> Dict[str, Any]:
    """Build {params, opt_state, step}, created directly in sharded form."""
    rules = rules or ShardingRules()
    optimizer = optimizer or default_optimizer()
    pspecs = gpt.param_specs(cfg, rules)
    pshard = tree_shardings(mesh, pspecs)

    @partial(jax.jit, out_shardings=pshard)
    def _init_params(key):
        return gpt.init(cfg, key)

    params = _init_params(jax.random.PRNGKey(seed))
    # Optimizer state inherits param shardings through GSPMD propagation —
    # except leaves with no data dependence on params (e.g. adam's step
    # count), which XLA places on a single device; replicate those onto the
    # mesh so the train step sees one consistent device set.
    opt_state = jax.jit(optimizer.init)(params)

    def _ensure_on_mesh(x):
        sharding = getattr(x, "sharding", None)
        if sharding is not None and getattr(
                sharding, "num_devices", 1) == mesh.size:
            return x
        return jax.device_put(x, NamedSharding(mesh, PartitionSpec()))

    opt_state = jax.tree.map(_ensure_on_mesh, opt_state)
    step = jax.device_put(jnp.zeros((), jnp.int32),
                          NamedSharding(mesh, PartitionSpec()))
    return {"params": params, "opt_state": opt_state, "step": step}


def _with_mesh_registered(jitted, mesh):
    """Register ``mesh`` as the current mesh around every call, not once at
    build time: jit traces lazily (first call / new shapes), so the registry
    must hold THIS step's mesh whenever a trace may happen — two steps built
    over different meshes would otherwise trace against the wrong one."""
    import functools

    from ray_tpu.parallel import mesh as mesh_mod

    @functools.wraps(jitted)
    def wrapped(*args, **kwargs):
        mesh_mod.set_current_mesh(mesh)
        return jitted(*args, **kwargs)

    return wrapped


def make_train_step(cfg: gpt.GPTConfig, mesh,
                    rules: Optional[ShardingRules] = None,
                    optimizer: Optional[optax.GradientTransformation] = None,
                    accum_steps: int = 1) -> Callable:
    """Returns jitted step(state, batch) -> (state, metrics).

    batch = {"tokens": [B, S] int32, "targets": [B, S] int32,
             "mask": optional [B, S]}. With accum_steps > 1 the leading batch
    dim must be divisible by it; microbatches run in a lax.scan (the
    microbatching substrate pipeline parallelism reuses).
    """
    rules = rules or ShardingRules()
    optimizer = optimizer or default_optimizer()
    bspec = gpt.batch_spec(rules)

    def loss_for(params, micro):
        return gpt.loss_fn(params, cfg, micro["tokens"], micro["targets"],
                           micro.get("mask"))

    def step(state, batch):
        params = state["params"]
        batch = {
            k: jax.lax.with_sharding_constraint(
                v, NamedSharding(mesh, bspec))
            for k, v in batch.items()
        }
        grad_fn = jax.value_and_grad(loss_for, has_aux=True)
        if accum_steps == 1:
            (_, metrics), grads = grad_fn(params, batch)
        else:
            def micro_body(carry, micro):
                g_acc, m_acc = carry
                (_, m), g = grad_fn(params, micro)
                g_acc = jax.tree.map(jnp.add, g_acc, g)
                m_acc = jax.tree.map(jnp.add, m_acc, m)
                return (g_acc, m_acc), None

            micros = jax.tree.map(
                lambda x: x.reshape((accum_steps, -1) + x.shape[1:]), batch)
            zeros_g = jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params)
            zeros_m = {"loss": 0.0, "accuracy": 0.0, "perplexity": 0.0}
            zeros_m = jax.tree.map(jnp.float32, zeros_m)
            (grads, metrics), _ = jax.lax.scan(
                micro_body, (zeros_g, zeros_m), micros)
            grads = jax.tree.map(lambda g: g / accum_steps, grads)
            metrics = jax.tree.map(lambda m: m / accum_steps, metrics)
        updates, opt_state = optimizer.update(
            grads, state["opt_state"], params)
        params = optax.apply_updates(params, updates)
        return ({"params": params, "opt_state": opt_state,
                 "step": state["step"] + 1}, metrics)

    return _with_mesh_registered(jax.jit(step, donate_argnums=(0,)), mesh)


def make_eval_step(cfg: gpt.GPTConfig, mesh,
                   rules: Optional[ShardingRules] = None) -> Callable:
    rules = rules or ShardingRules()
    bspec = gpt.batch_spec(rules)

    def step(params, batch):
        batch = {
            k: jax.lax.with_sharding_constraint(
                v, NamedSharding(mesh, bspec))
            for k, v in batch.items()
        }
        _, metrics = gpt.loss_fn(params, cfg, batch["tokens"],
                                 batch["targets"], batch.get("mask"))
        return metrics

    return _with_mesh_registered(jax.jit(step), mesh)
