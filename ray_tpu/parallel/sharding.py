"""Logical-axis sharding rules → PartitionSpecs.

The GSPMD replacement for the reference's wrapper-based strategies
(torch DDP/FSDP in train/torch/train_loop_utils.py): models annotate each
parameter/activation dimension with a *logical* axis name; a ShardingRules
table maps logical names to mesh axes. Swapping DP↔FSDP↔TP↔SP is a rules
change — the model code never changes.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple, Union

import jax
from jax.sharding import NamedSharding, PartitionSpec

MeshAxes = Union[None, str, Tuple[str, ...]]


@dataclass(frozen=True)
class ShardingRules:
    """Maps logical dimension names to mesh axes (None = replicated)."""

    batch: MeshAxes = ("dp", "fsdp")
    sequence: MeshAxes = None  # set to "sp" for context parallelism
    embed: MeshAxes = "fsdp"  # weight-sharding axis (ZeRO-3 analog)
    heads: MeshAxes = "tp"
    kv_heads: MeshAxes = "tp"
    head_dim: MeshAxes = None
    mlp: MeshAxes = "tp"
    vocab: MeshAxes = "tp"
    expert: MeshAxes = "ep"
    layers: MeshAxes = None  # leading axis of scan-stacked params

    def spec(self, *logical_axes: Optional[str]) -> PartitionSpec:
        parts = []
        for name in logical_axes:
            if name is None:
                parts.append(None)
            else:
                parts.append(getattr(self, name))
        return PartitionSpec(*parts)


# Rules presets ---------------------------------------------------------

def dp_rules() -> ShardingRules:
    """Pure data parallelism: replicate weights, shard batch."""
    return ShardingRules(embed=None, heads=None, kv_heads=None, mlp=None,
                         vocab=None)


def fsdp_rules() -> ShardingRules:
    """Fully-sharded DP (ZeRO-3): weights sharded over fsdp, no TP."""
    return ShardingRules(heads=None, kv_heads=None, mlp=None, vocab=None)


def tp_fsdp_rules() -> ShardingRules:
    """2D: Megatron TP on heads/mlp/vocab + FSDP on the embed dim."""
    return ShardingRules()


def context_parallel_rules() -> ShardingRules:
    """TP+FSDP+sequence sharding (ring attention over sp)."""
    return ShardingRules(sequence="sp")


# Helpers ---------------------------------------------------------------

def named_sharding(mesh, spec: PartitionSpec) -> NamedSharding:
    return NamedSharding(mesh, spec)


def tree_shardings(mesh, spec_tree):
    """Map a pytree of PartitionSpecs to NamedShardings on `mesh`."""
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s), spec_tree,
        is_leaf=lambda x: isinstance(x, PartitionSpec))


def shard_tree(tree, mesh, spec_tree):
    """Device_put a pytree with the given specs (zero-copy when possible)."""
    shardings = tree_shardings(mesh, spec_tree)
    return jax.device_put(tree, shardings)
