"""Logical-axis sharding rules → PartitionSpecs.

The GSPMD replacement for the reference's wrapper-based strategies
(torch DDP/FSDP in train/torch/train_loop_utils.py): models annotate each
parameter/activation dimension with a *logical* axis name; a ShardingRules
table maps logical names to mesh axes. Swapping DP↔FSDP↔TP↔SP is a rules
change — the model code never changes.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple, Union

import jax
from jax.sharding import NamedSharding, PartitionSpec

MeshAxes = Union[None, str, Tuple[str, ...]]


@dataclass(frozen=True)
class ShardingRules:
    """Maps logical dimension names to mesh axes (None = replicated)."""

    batch: MeshAxes = ("dp", "fsdp")
    sequence: MeshAxes = None  # set to "sp" for context parallelism
    embed: MeshAxes = "fsdp"  # weight-sharding axis (ZeRO-3 analog)
    heads: MeshAxes = "tp"
    kv_heads: MeshAxes = "tp"
    head_dim: MeshAxes = None
    mlp: MeshAxes = "tp"
    vocab: MeshAxes = "tp"
    expert: MeshAxes = "ep"
    layers: MeshAxes = None  # leading axis of scan-stacked params

    def spec(self, *logical_axes: Optional[str]) -> PartitionSpec:
        parts = []
        for name in logical_axes:
            if name is None:
                parts.append(None)
            else:
                parts.append(getattr(self, name))
        return PartitionSpec(*parts)


# Rules presets ---------------------------------------------------------

def dp_rules() -> ShardingRules:
    """Pure data parallelism: replicate weights, shard batch."""
    return ShardingRules(embed=None, heads=None, kv_heads=None, mlp=None,
                         vocab=None)


def fsdp_rules() -> ShardingRules:
    """Fully-sharded DP (ZeRO-3): weights sharded over fsdp, no TP."""
    return ShardingRules(heads=None, kv_heads=None, mlp=None, vocab=None)


def tp_fsdp_rules() -> ShardingRules:
    """2D: Megatron TP on heads/mlp/vocab + FSDP on the embed dim."""
    return ShardingRules()


def context_parallel_rules() -> ShardingRules:
    """TP+FSDP+sequence sharding (ring attention over sp)."""
    return ShardingRules(sequence="sp")


# Shard-slice math (checkpoint resharding) ------------------------------
# Pure-index GSPMD block partitioning: given a parameter's global shape,
# a PartitionSpec-like spec, and a mesh described as ordered
# (axis, size) pairs, compute which index block one mesh coordinate
# owns. Balanced ``array_split`` boundaries (first ``S % N`` shards get
# one extra row) so a checkpoint saved on 8 ranks can be resharded onto
# 6 — elastic shrink/grow never requires divisibility.


def axis_split_bounds(dim_size: int, num_shards: int):
    """[(start, stop)] per shard along one dimension, balanced."""
    if num_shards <= 0:
        raise ValueError(f"num_shards must be positive, got {num_shards}")
    base, extra = divmod(dim_size, num_shards)
    bounds = []
    start = 0
    for i in range(num_shards):
        stop = start + base + (1 if i < extra else 0)
        bounds.append((start, stop))
        start = stop
    return bounds


def _spec_dim_axes(dim_spec) -> Tuple[str, ...]:
    """Normalize one dimension's spec entry to a tuple of mesh axes."""
    if dim_spec is None:
        return ()
    if isinstance(dim_spec, str):
        return (dim_spec,)
    return tuple(dim_spec)


def shard_slices(global_shape, spec, axes, coords) -> Tuple[slice, ...]:
    """The index block one mesh position owns under ``spec``.

    ``axes`` maps mesh axis name -> size; ``coords`` maps axis name ->
    this position's index on that axis. A dimension sharded over a
    tuple of axes composes them row-major (same ordering GSPMD uses).
    Dimensions with no spec entry (or None) are fully replicated.
    """
    out = []
    for d, size in enumerate(global_shape):
        dim_axes = _spec_dim_axes(spec[d]) if d < len(spec) else ()
        n = 1
        idx = 0
        for name in dim_axes:
            n *= int(axes[name])
            idx = idx * int(axes[name]) + int(coords[name])
        if n <= 1:
            out.append(slice(0, size))
        else:
            start, stop = axis_split_bounds(size, n)[idx]
            out.append(slice(start, stop))
    return tuple(out)


def slices_overlap(a, b):
    """Intersection of two same-rank slice tuples, or None if empty."""
    out = []
    for sa, sb in zip(a, b):
        start = max(sa.start, sb.start)
        stop = min(sa.stop, sb.stop)
        if start >= stop:
            return None
        out.append(slice(start, stop))
    return tuple(out)


# Helpers ---------------------------------------------------------------

def named_sharding(mesh, spec: PartitionSpec) -> NamedSharding:
    return NamedSharding(mesh, spec)


def tree_shardings(mesh, spec_tree):
    """Map a pytree of PartitionSpecs to NamedShardings on `mesh`."""
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s), spec_tree,
        is_leaf=lambda x: isinstance(x, PartitionSpec))


def shard_tree(tree, mesh, spec_tree):
    """Device_put a pytree with the given specs (zero-copy when possible)."""
    shardings = tree_shardings(mesh, spec_tree)
    return jax.device_put(tree, shardings)
