"""Device mesh construction: the TPU-native parallelism substrate.

Where the reference wires torch DDP/FSDP process groups over NCCL
(reference: python/ray/train/torch/train_loop_utils.py:51 prepare_model,
train/torch/config.py:113 init_process_group), this framework expresses ALL
intra-model parallelism as a `jax.sharding.Mesh` with named axes and lets
XLA/GSPMD insert the collectives over ICI/DCN:

* ``dp``   — pure data parallelism (gradient psum)
* ``fsdp`` — fully-sharded data parallelism (ZeRO-3-equivalent: params and
             optimizer state sharded over this axis, all-gathered per layer)
* ``tp``   — tensor (Megatron-style model) parallelism
* ``sp``   — sequence/context parallelism (ring attention / Ulysses)
* ``ep``   — expert parallelism for MoE layers
* ``pp``   — pipeline parallelism (GPipe schedule over shard_map +
             ppermute, parallel/pipeline.py)

Batch dimensions shard over (dp, fsdp); weights over (fsdp, tp); sequence
over sp; experts over ep; pipeline stages over pp.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional, Sequence, Tuple

import numpy as np

AXIS_ORDER = ("dp", "fsdp", "tp", "sp", "ep", "pp")
# Axes over which a batch is sharded.
BATCH_AXES = ("dp", "fsdp")


@dataclass(frozen=True)
class MeshConfig:
    """Sizes for each mesh axis; -1 means "fill with remaining devices".

    Axis order follows ICI-locality best practice: the innermost axes (tp,
    sp) get the most tightly coupled devices, dp/fsdp span slices/hosts (the
    scaling-book recipe: model axes ride ICI, data axes can ride DCN).
    """

    dp: int = 1
    fsdp: int = -1
    tp: int = 1
    sp: int = 1
    ep: int = 1
    pp: int = 1

    def resolve(self, n_devices: int) -> "MeshConfig":
        sizes = {a: getattr(self, a) for a in AXIS_ORDER}
        fills = [a for a, s in sizes.items() if s == -1]
        if len(fills) > 1:
            raise ValueError(f"Only one axis may be -1, got {fills}")
        known = math.prod(s for s in sizes.values() if s != -1)
        if fills:
            if n_devices % known != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes "
                    f"product {known}")
            sizes[fills[0]] = n_devices // known
        total = math.prod(sizes.values())
        if total != n_devices:
            raise ValueError(
                f"Mesh axes {sizes} use {total} devices but {n_devices} "
                "are available")
        return MeshConfig(**sizes)

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return AXIS_ORDER

    def shape(self) -> Tuple[int, ...]:
        return tuple(getattr(self, a) for a in AXIS_ORDER)

    @property
    def batch_shards(self) -> int:
        return self.dp * self.fsdp


def build_mesh(config: Optional[MeshConfig] = None,
               devices: Optional[Sequence] = None):
    """Build a `jax.sharding.Mesh` from a MeshConfig.

    Uses `mesh_utils.create_device_mesh` when the requested shape matches the
    platform topology (so tp/sp land on ICI neighbors); falls back to a plain
    reshape for virtual/CPU device sets.
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    config = (config or MeshConfig()).resolve(len(devices))
    shape = config.shape()
    try:
        from jax.experimental import mesh_utils
        dev_array = mesh_utils.create_device_mesh(
            shape, devices=list(devices))
    except Exception:  # noqa: BLE001 - virtual platforms may reject topology
        dev_array = np.array(list(devices)).reshape(shape)
    return Mesh(dev_array, AXIS_ORDER)


def single_device_mesh():
    """A 1-device mesh with all axes size 1 — lets the same sharded program
    run unmodified on one chip."""
    return build_mesh(MeshConfig(dp=1, fsdp=1, tp=1, sp=1, ep=1))


# -- current-mesh registry ----------------------------------------------
# Ops that need an explicit shard_map (ring attention) read the ambient
# mesh here; make_train_step / user code set it. A registry rather than a
# parameter because the mesh must be static at trace time while model code
# only receives (params, cfg, batch).

_CURRENT_MESH = None


def set_current_mesh(mesh) -> None:
    global _CURRENT_MESH
    _CURRENT_MESH = mesh


def current_mesh():
    return _CURRENT_MESH
