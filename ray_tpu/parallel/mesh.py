"""Device mesh construction: the TPU-native parallelism substrate.

Where the reference wires torch DDP/FSDP process groups over NCCL
(reference: python/ray/train/torch/train_loop_utils.py:51 prepare_model,
train/torch/config.py:113 init_process_group), this framework expresses ALL
intra-model parallelism as a `jax.sharding.Mesh` with named axes and lets
XLA/GSPMD insert the collectives over ICI/DCN:

* ``dp``   — pure data parallelism (gradient psum)
* ``fsdp`` — fully-sharded data parallelism (ZeRO-3-equivalent: params and
             optimizer state sharded over this axis, all-gathered per layer)
* ``tp``   — tensor (Megatron-style model) parallelism
* ``sp``   — sequence/context parallelism (ring attention / Ulysses)
* ``ep``   — expert parallelism for MoE layers
* ``pp``   — pipeline parallelism (GPipe schedule over shard_map +
             ppermute, parallel/pipeline.py)

Batch dimensions shard over (dp, fsdp); weights over (fsdp, tp); sequence
over sp; experts over ep; pipeline stages over pp.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional, Sequence, Tuple

import numpy as np

AXIS_ORDER = ("dp", "fsdp", "tp", "sp", "ep", "pp")
# Axes over which a batch is sharded.
BATCH_AXES = ("dp", "fsdp")


@dataclass(frozen=True)
class MeshConfig:
    """Sizes for each mesh axis; -1 means "fill with remaining devices".

    Axis order follows ICI-locality best practice: the innermost axes (tp,
    sp) get the most tightly coupled devices, dp/fsdp span slices/hosts (the
    scaling-book recipe: model axes ride ICI, data axes can ride DCN).
    """

    dp: int = 1
    fsdp: int = -1
    tp: int = 1
    sp: int = 1
    ep: int = 1
    pp: int = 1
    #: TPU pod slices joined over DCN (multi-slice training). The dp axis
    #: is the one that crosses the slice boundary — gradient psums ride
    #: DCN once per step while fsdp/tp/sp collectives stay on each
    #: slice's ICI (the scaling-book layering; SURVEY §2.4 "DCN-aware
    #: multi-slice meshes"). dp must be a multiple of `slices`.
    slices: int = 1

    def resolve(self, n_devices: int) -> "MeshConfig":
        sizes = {a: getattr(self, a) for a in AXIS_ORDER}
        fills = [a for a, s in sizes.items() if s == -1]
        if len(fills) > 1:
            raise ValueError(f"Only one axis may be -1, got {fills}")
        known = math.prod(s for s in sizes.values() if s != -1)
        if fills:
            if n_devices % known != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes "
                    f"product {known}")
            sizes[fills[0]] = n_devices // known
        total = math.prod(sizes.values())
        if total != n_devices:
            raise ValueError(
                f"Mesh axes {sizes} use {total} devices but {n_devices} "
                "are available")
        if self.slices > 1 and sizes["dp"] % self.slices != 0:
            raise ValueError(
                f"dp={sizes['dp']} must be a multiple of slices="
                f"{self.slices}: the dp axis is the one crossing the "
                "DCN slice boundary")
        return MeshConfig(**sizes, slices=self.slices)

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return AXIS_ORDER

    def shape(self) -> Tuple[int, ...]:
        return tuple(getattr(self, a) for a in AXIS_ORDER)

    @property
    def batch_shards(self) -> int:
        return self.dp * self.fsdp


def build_mesh(config: Optional[MeshConfig] = None,
               devices: Optional[Sequence] = None):
    """Build a `jax.sharding.Mesh` from a MeshConfig.

    Uses `mesh_utils.create_device_mesh` when the requested shape matches the
    platform topology (so tp/sp land on ICI neighbors); falls back to a plain
    reshape for virtual/CPU device sets.
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    config = (config or MeshConfig()).resolve(len(devices))
    shape = config.shape()
    if config.slices > 1:
        return _build_multi_slice_mesh(config, list(devices))
    try:
        from jax.experimental import mesh_utils
        dev_array = mesh_utils.create_device_mesh(
            shape, devices=list(devices))
    except Exception:  # noqa: BLE001 - virtual platforms may reject topology
        dev_array = np.array(list(devices)).reshape(shape)
    return Mesh(dev_array, AXIS_ORDER)


def _build_multi_slice_mesh(config: MeshConfig, devices: list):
    """Hybrid DCN x ICI mesh (the multi-slice analog of the reference's
    multi-node NCCL world): the OUTER positions of the dp axis enumerate
    slices, so only dp collectives (gradient psum) cross DCN; every
    fsdp/tp/sp/ep/pp collective stays inside one slice's ICI. Devices
    group by their hardware ``slice_index`` when the platform reports it
    (real multi-slice TPU), falling back to contiguous equal splits
    (virtual/CPU validation meshes)."""
    import jax
    from jax.sharding import Mesh

    n_slices = config.slices
    if len(devices) % n_slices != 0:
        raise ValueError(
            f"{len(devices)} devices not divisible into {n_slices} slices")
    per_slice = len(devices) // n_slices
    by_slice: dict = {}
    if all(getattr(d, "slice_index", None) is not None for d in devices):
        for d in devices:
            by_slice.setdefault(d.slice_index, []).append(d)
        if len(by_slice) != n_slices or any(
                len(v) != per_slice for v in by_slice.values()):
            raise ValueError(
                f"hardware reports {len(by_slice)} slices with sizes "
                f"{[len(v) for v in by_slice.values()]}, config wants "
                f"{n_slices} x {per_slice}")
        groups = [by_slice[k] for k in sorted(by_slice)]
    else:
        groups = [devices[i * per_slice:(i + 1) * per_slice]
                  for i in range(n_slices)]
    # Arrange each slice's devices over (dp_in, fsdp, tp, sp, ep, pp),
    # then stack slices as the OUTER dp positions.
    dp_in = config.dp // n_slices
    inner_shape = (dp_in, config.fsdp, config.tp, config.sp,
                   config.ep, config.pp)
    slabs = []
    for group in groups:
        try:
            from jax.experimental import mesh_utils
            slabs.append(mesh_utils.create_device_mesh(
                inner_shape, devices=group))
        except Exception:  # noqa: BLE001 - virtual platforms
            slabs.append(np.array(group).reshape(inner_shape))
    dev_array = np.stack(slabs, axis=0).reshape(config.shape())
    return Mesh(dev_array, AXIS_ORDER)


def single_device_mesh():
    """A 1-device mesh with all axes size 1 — lets the same sharded program
    run unmodified on one chip."""
    return build_mesh(MeshConfig(dp=1, fsdp=1, tp=1, sp=1, ep=1))


# -- current-mesh registry ----------------------------------------------
# Ops that need an explicit shard_map (ring attention) read the ambient
# mesh here; make_train_step / user code set it. A registry rather than a
# parameter because the mesh must be static at trace time while model code
# only receives (params, cfg, batch).

_CURRENT_MESH = None


def set_current_mesh(mesh) -> None:
    global _CURRENT_MESH
    _CURRENT_MESH = mesh


def current_mesh():
    return _CURRENT_MESH
