"""Multi-node-on-one-host test cluster.

Analog of the reference's ``ray.cluster_utils.Cluster``
(python/ray/cluster_utils.py:99, add_node :165, remove_node :238), which runs
multiple raylets as separate processes on one machine so scheduling,
failover, spilling, and reconstruction can be tested without a real cluster.
Here nodes are virtual members of the in-process cluster scheduler: each has
its own resource pool, TPU chip slots, and identity, and ``remove_node``
exercises the same failure paths real node death would (task retry, actor
restart, lineage reconstruction, PG rescheduling).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ray_tpu._private.ids import NodeID


class NodeHandle:
    """Returned by Cluster.add_node; identifies a virtual node."""

    def __init__(self, node_id: NodeID, resources: Dict[str, float]):
        self.node_id = node_id
        self.resources = dict(resources)

    @property
    def hex_id(self) -> str:
        return self.node_id.hex()

    def __repr__(self):
        return f"NodeHandle({self.node_id.hex()[:12]})"


class Cluster:
    def __init__(self, initialize_head: bool = True, connect: bool = True,
                 head_node_args: Optional[dict] = None):
        import ray_tpu
        self._nodes: List[NodeHandle] = []
        self.head_node: Optional[NodeHandle] = None
        head_node_args = dict(head_node_args or {})
        if initialize_head:
            if not ray_tpu.is_initialized():
                ray_tpu.init(**head_node_args)
            runtime = ray_tpu._private.worker.global_worker.runtime
            self._runtime = runtime
            head_state = runtime.scheduler.node(runtime.head_node_id)
            self.head_node = NodeHandle(runtime.head_node_id,
                                        head_state.resources)
            self._nodes.append(self.head_node)
        else:
            self._runtime = None

    @property
    def runtime(self):
        if self._runtime is None:
            import ray_tpu
            self._runtime = ray_tpu._private.worker.global_worker.runtime
        return self._runtime

    def add_node(self, *, num_cpus: float = 1.0, num_tpus: float = 0.0,
                 num_gpus: Optional[float] = None,
                 resources: Optional[Dict[str, float]] = None,
                 object_store_memory: Optional[int] = None,
                 **kwargs) -> NodeHandle:
        if num_gpus is not None:
            num_tpus = num_gpus  # accelerator-option compatibility
        node_resources: Dict[str, float] = {"CPU": float(num_cpus)}
        if num_tpus:
            node_resources["TPU"] = float(num_tpus)
        if resources:
            node_resources.update(resources)
        node_resources.setdefault(
            "memory", float(object_store_memory or 1 << 30))
        node_id = self.runtime.add_node(node_resources)
        handle = NodeHandle(node_id, node_resources)
        self._nodes.append(handle)
        return handle

    def remove_node(self, node: NodeHandle,
                    allow_graceful: bool = True) -> None:
        self.runtime.remove_node(node.node_id)
        if node in self._nodes:
            self._nodes.remove(node)

    def list_all_nodes(self) -> List[NodeHandle]:
        return list(self._nodes)

    def wait_for_nodes(self, timeout: float = 30.0) -> None:
        # Virtual nodes join synchronously; nothing to wait for.
        return

    def shutdown(self) -> None:
        import ray_tpu
        ray_tpu.shutdown()
        self._runtime = None
        self._nodes.clear()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
