"""Blockwise (online-softmax) attention in pure jnp.

The memory-efficient attention recurrence (Rabe & Staats / FlashAttention):
iterate over KV chunks with running (max, sum, out) accumulators so the full
[S, S] score matrix never materializes. O(S) memory instead of O(S^2), fully
differentiable through `lax.scan`, runs on any backend — it is both the
fallback for the Pallas kernel's backward pass and the per-step compute of
ring attention (ring_attention.py).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def _repeat_kv(k, v, n_heads):
    kvh = k.shape[2]
    if kvh != n_heads:
        rep = n_heads // kvh
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    return k, v


def attention_chunk(q, k, v, m, l, o, q_pos, k_pos, causal: bool,
                    scale: float):
    """One online-softmax update. q: [B,H,Sq,D]; k,v: [B,H,Sk,D];
    m,l: [B,H,Sq]; o: [B,H,Sq,D] (fp32 accumulators). Returns updated
    (m, l, o)."""
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        mask = q_pos[:, None] >= k_pos[None, :]
        logits = jnp.where(mask[None, None], logits, _NEG_INF)
    m_new = jnp.maximum(m, logits.max(-1))
    # Rows with every key masked keep m == _NEG_INF; correction stays finite.
    correction = jnp.exp(m - m_new)
    p = jnp.exp(logits - m_new[..., None])
    if causal:
        p = jnp.where(mask[None, None], p, 0.0)
    l_new = l * correction + p.sum(-1)
    o_new = o * correction[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v, preferred_element_type=jnp.float32)
    return m_new, l_new, o_new


@partial(jax.jit, static_argnames=("causal", "chunk_size"))
def blockwise_attention(q, k, v, causal: bool = True,
                        chunk_size: int = 512,
                        q_offset: int = 0, kv_offset: int = 0) -> jax.Array:
    """Causal attention over KV chunks. q,k,v: [B, S, H|KVH, D] →
    [B, S, H, D]. ``q_offset``/``kv_offset`` shift global positions (used by
    ring attention when q and kv live on different sequence shards)."""
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    k, v = _repeat_kv(k, v, H)
    scale = 1.0 / math.sqrt(D)
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    q_pos = q_offset + jnp.arange(Sq)
    chunk = min(chunk_size, Sk)
    n_chunks = (Sk + chunk - 1) // chunk
    pad = n_chunks * chunk - Sk
    if pad:
        kt = jnp.pad(kt, ((0, 0), (0, 0), (0, pad), (0, 0)))
        vt = jnp.pad(vt, ((0, 0), (0, 0), (0, pad), (0, 0)))
    kt = kt.reshape(B, H, n_chunks, chunk, D).transpose(2, 0, 1, 3, 4)
    vt = vt.reshape(B, H, n_chunks, chunk, D).transpose(2, 0, 1, 3, 4)

    m0 = jnp.full((B, H, Sq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Sq), jnp.float32)
    o0 = jnp.zeros((B, H, Sq, D), jnp.float32)

    def body(carry, inputs):
        m, l, o = carry
        idx, kc, vc = inputs
        k_pos = kv_offset + idx * chunk + jnp.arange(chunk)
        # Padded keys sit past the real sequence; mask them via position.
        valid = (idx * chunk + jnp.arange(chunk)) < Sk
        k_pos = jnp.where(valid, k_pos, q_offset + Sq + 10**9)
        m, l, o = attention_chunk(qt, kc, vc, m, l, o, q_pos, k_pos,
                                  True, scale)
        return (m, l, o), None

    if causal:
        (m, l, o), _ = jax.lax.scan(
            body, (m0, l0, o0), (jnp.arange(n_chunks), kt, vt))
    else:
        # Non-causal: same loop, mask only padding.
        def body_nc(carry, inputs):
            m, l, o = carry
            idx, kc, vc = inputs
            k_pos = jnp.where(
                (idx * chunk + jnp.arange(chunk)) < Sk,
                jnp.zeros((chunk,), jnp.int32), q_offset + Sq + 10**9)
            q_pos_nc = jnp.full((Sq,), 10**9)  # q >= k always (no mask)
            m, l, o = attention_chunk(qt, kc, vc, m, l, o, q_pos_nc, k_pos,
                                      True, scale)
            return (m, l, o), None

        (m, l, o), _ = jax.lax.scan(
            body_nc, (m0, l0, o0), (jnp.arange(n_chunks), kt, vt))
    out = o / jnp.maximum(l[..., None], 1e-30)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)
