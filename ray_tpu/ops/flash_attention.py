"""Flash attention as Pallas TPU kernels (forward + backward).

Forward tiles Q over the grid and streams KV blocks through VMEM with the
online-softmax recurrence, keeping the MXU fed with [blk_q, D] x [D, blk_k]
matmuls (pallas_guide.md: grid/BlockSpec + fori_loop pattern), and emits the
per-row logsumexp needed by the backward pass.

Backward is the standard two-kernel FlashAttention scheme: a dQ kernel
(grid over Q blocks, streaming KV) and a dK/dV kernel (grid over KV blocks,
streaming Q), both recomputing probabilities from q, k and the saved
logsumexp — O(S) memory, no S x S tensor ever materializes in HBM. This is
what lets the GPT train step run "selective" rematerialisation instead of
full-block recompute (models/gpt.py GPTConfig.remat_policy).

On non-TPU backends the kernels run in interpreter mode so the same code
path is testable on the CPU mesh (SURVEY.md §4: fake-TPU strategy).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ray_tpu.ops.blockwise_attention import blockwise_attention

_NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, blk_q: int,
                      blk_k: int, seq_len: int, causal: bool, scale: float):
    """Grid: (batch*heads, num_q_blocks). q_ref: [blk_q, D] tile;
    k_ref/v_ref: [S, D] for this (b, h); o_ref: [blk_q, D];
    lse_ref: [1, blk_q]."""
    qi = pl.program_id(1)
    q = q_ref[...].astype(jnp.float32) * scale
    D = q.shape[-1]

    q_pos = qi * blk_q + jax.lax.broadcasted_iota(
        jnp.int32, (blk_q, blk_k), 0)

    n_k = seq_len // blk_k

    def body(kb, carry):
        m, l, o = carry
        k_blk = k_ref[pl.ds(kb * blk_k, blk_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(kb * blk_k, blk_k), :].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if causal:
            k_pos = kb * blk_k + jax.lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 1)
            mask = q_pos >= k_pos
            logits = jnp.where(mask, logits, _NEG_INF)
        m_new = jnp.maximum(m, logits.max(-1))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(logits - m_new[:, None])
        if causal:
            p = jnp.where(mask, p, 0.0)
        l_new = l * corr + p.sum(-1)
        o_new = o * corr[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, o_new

    m0 = jnp.full((blk_q,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((blk_q,), jnp.float32)
    o0 = jnp.zeros((blk_q, D), jnp.float32)
    if causal:
        # Only KV blocks at or before this Q block's last row contribute.
        n_iter = jnp.minimum(pl.cdiv((qi + 1) * blk_q, blk_k), n_k)
    else:
        n_iter = n_k
    m, l, o = jax.lax.fori_loop(0, n_iter, body, (m0, l0, o0))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[...] = (o / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[...] = (m + jnp.log(l_safe))[None, :]


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                         dq_ref, *, blk_q: int, blk_k: int, seq_len: int,
                         causal: bool, scale: float):
    """Grid: (batch*heads, num_q_blocks). dq for one Q tile, streaming KV."""
    qi = pl.program_id(1)
    q = q_ref[...].astype(jnp.float32) * scale
    g = g_ref[...].astype(jnp.float32)
    lse = lse_ref[0, :]
    delta = delta_ref[0, :]
    D = q.shape[-1]

    q_pos = qi * blk_q + jax.lax.broadcasted_iota(
        jnp.int32, (blk_q, blk_k), 0)
    n_k = seq_len // blk_k

    def body(kb, dq):
        k_blk = k_ref[pl.ds(kb * blk_k, blk_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(kb * blk_k, blk_k), :].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        p = jnp.exp(logits - lse[:, None])
        if causal:
            k_pos = kb * blk_k + jax.lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 1)
            p = jnp.where(q_pos >= k_pos, p, 0.0)
        dp = jax.lax.dot_general(
            g, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        return dq + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        n_iter = jnp.minimum(pl.cdiv((qi + 1) * blk_q, blk_k), n_k)
    else:
        n_iter = n_k
    dq = jax.lax.fori_loop(
        0, n_iter, body, jnp.zeros((blk_q, D), jnp.float32))
    dq_ref[...] = (dq * scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, *, blk_q: int, blk_k: int,
                          seq_len: int, causal: bool, scale: float):
    """Grid: (batch*heads, num_k_blocks). dk/dv for one KV tile, streaming
    Q blocks (only those at or after the diagonal when causal)."""
    ki = pl.program_id(1)
    k = k_ref[...].astype(jnp.float32)
    v = v_ref[...].astype(jnp.float32)
    D = k.shape[-1]

    k_pos = ki * blk_k + jax.lax.broadcasted_iota(
        jnp.int32, (blk_q, blk_k), 1)
    n_q = seq_len // blk_q

    def body(qb, carry):
        dk, dv = carry
        q_blk = q_ref[pl.ds(qb * blk_q, blk_q), :].astype(
            jnp.float32) * scale
        g_blk = g_ref[pl.ds(qb * blk_q, blk_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(qb * blk_q, blk_q)]
        delta = delta_ref[0, pl.ds(qb * blk_q, blk_q)]
        logits = jax.lax.dot_general(
            q_blk, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        p = jnp.exp(logits - lse[:, None])
        if causal:
            q_pos = qb * blk_q + jax.lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 0)
            p = jnp.where(q_pos >= k_pos, p, 0.0)
        dv = dv + jax.lax.dot_general(
            p, g_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            g_blk, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        dk = dk + jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk, dv

    if causal:
        # Q blocks strictly before this KV block's first row see none of it.
        qb_start = (ki * blk_k) // blk_q
    else:
        qb_start = 0
    dk, dv = jax.lax.fori_loop(
        qb_start, n_q, body,
        (jnp.zeros((blk_k, D), jnp.float32),
         jnp.zeros((blk_k, D), jnp.float32)))
    # dk already includes one factor of scale via q_blk; that IS d(logits)^T
    # @ q * scale, which equals scale * ds^T @ q — correct as accumulated.
    dk_ref[...] = dk.astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


def _repeat_heads(k, v, n_heads):
    kvh = k.shape[2]
    if kvh != n_heads:
        rep = n_heads // kvh
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    return k, v


def _to_bh(x):
    B, S, H, D = x.shape
    return x.transpose(0, 2, 1, 3).reshape(B * H, S, D)


def _from_bh(x, B, H):
    BH, S, D = x.shape
    return x.reshape(B, H, S, D).transpose(0, 2, 1, 3)


def _pick_block(S: int, want: int) -> int:
    """Largest lane-aligned block <= want that divides S (0 if none)."""
    b = min(want, S)
    b -= b % 128
    while b >= 128 and S % b:
        b -= 128
    return b


def _flash_forward(q, k, v, causal: bool, blk_q: int, blk_k: int):
    B, S, H, D = q.shape
    k, v = _repeat_heads(k, v, H)
    scale = 1.0 / math.sqrt(D)
    blk_q = _pick_block(S, blk_q)
    blk_k = _pick_block(S, blk_k)
    if blk_q < 128 or blk_k < 128:
        # Ragged sequence (not a multiple of 128): fall back to the jnp
        # blockwise path (no lse output — the custom VJP then differentiates
        # the blockwise recurrence instead of running the Pallas backward).
        return blockwise_attention(q, k, v, causal=causal), None
    qf, kf, vf = _to_bh(q), _to_bh(k), _to_bh(v)

    kernel = functools.partial(
        _flash_fwd_kernel, blk_q=blk_q, blk_k=blk_k, seq_len=S,
        causal=causal, scale=scale)
    out, lse = pl.pallas_call(
        kernel,
        grid=(B * H, S // blk_q),
        in_specs=[
            pl.BlockSpec((None, blk_q, D), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, S, D), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, S, D), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, blk_q, D), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, 1, blk_q), lambda i, j: (i, 0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, 1, S), jnp.float32),
        ],
        interpret=_interpret(),
    )(qf, kf, vf)
    return _from_bh(out, B, H), lse


def _flash_backward(q, k, v, out, lse, g, causal: bool, blk_q: int,
                    blk_k: int):
    B, S, H, D = q.shape
    kvh = k.shape[2]
    k_rep, v_rep = _repeat_heads(k, v, H)
    scale = 1.0 / math.sqrt(D)
    qf, kf, vf = _to_bh(q), _to_bh(k_rep), _to_bh(v_rep)
    gf, of = _to_bh(g), _to_bh(out)
    delta = jnp.sum(gf.astype(jnp.float32) * of.astype(jnp.float32),
                    axis=-1)[:, None, :]  # [BH, 1, S]

    common = dict(blk_q=blk_q, blk_k=blk_k, seq_len=S, causal=causal,
                  scale=scale)
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, **common),
        grid=(B * H, S // blk_q),
        in_specs=[
            pl.BlockSpec((None, blk_q, D), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, S, D), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, S, D), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, blk_q, D), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, 1, blk_q), lambda i, j: (i, 0, j)),
            pl.BlockSpec((None, 1, blk_q), lambda i, j: (i, 0, j)),
        ],
        out_specs=pl.BlockSpec((None, blk_q, D), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
        interpret=_interpret(),
    )(qf, kf, vf, gf, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, **common),
        grid=(B * H, S // blk_k),
        in_specs=[
            pl.BlockSpec((None, S, D), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, blk_k, D), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, blk_k, D), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, S, D), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, 1, S), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, 1, S), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, blk_k, D), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, blk_k, D), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, S, D), k.dtype),
            jax.ShapeDtypeStruct((B * H, S, D), v.dtype),
        ],
        interpret=_interpret(),
    )(qf, kf, vf, gf, lse, delta)

    dq = _from_bh(dq, B, H)
    dk = _from_bh(dk, B, H)
    dv = _from_bh(dv, B, H)
    if kvh != H:
        # GQA: fold gradients of the repeated heads back onto the KV heads.
        rep = H // kvh
        dk = dk.reshape(B, S, kvh, rep, D).sum(axis=3)
        dv = dv.reshape(B, S, kvh, rep, D).sum(axis=3)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q, k, v, causal: bool = True, blk_q: int = 1024,
                    blk_k: int = 1024):
    """q: [B, S, H, D], k/v: [B, S, KVH, D] → [B, S, H, D]."""
    return _flash_forward(q, k, v, causal, blk_q, blk_k)[0]


def _fwd(q, k, v, causal, blk_q, blk_k):
    out, lse = _flash_forward(q, k, v, causal, blk_q, blk_k)
    if lse is None:
        # Ragged fallback: differentiate the jnp blockwise recurrence.
        return out, (q, k, v, None, None)
    return out, (q, k, v, out, lse)


def _bwd(causal, blk_q, blk_k, residuals, g):
    q, k, v, out, lse = residuals
    if lse is None:
        _, vjp = jax.vjp(
            lambda q_, k_, v_: blockwise_attention(q_, k_, v_, causal=causal),
            q, k, v)
        return vjp(g)
    S = q.shape[1]
    return _flash_backward(q, k, v, out, lse, g, causal,
                           _pick_block(S, blk_q), _pick_block(S, blk_k))


flash_attention.defvjp(_fwd, _bwd)
