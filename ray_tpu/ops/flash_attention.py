"""Flash attention as a Pallas TPU kernel.

Forward pass tiles Q over the grid and streams KV blocks through VMEM with
the online-softmax recurrence, keeping the MXU fed with [blk_q, D] x
[D, blk_k] matmuls (pallas_guide.md: grid/BlockSpec + fori_loop pattern).
Backward pass is a custom VJP that recomputes attention blockwise in jnp
(blockwise_attention.py) — O(S) memory, no saved probability matrix.

On non-TPU backends the kernel runs in interpreter mode so the same code
path is testable on the CPU mesh (SURVEY.md §4: fake-TPU strategy).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ray_tpu.ops.blockwise_attention import blockwise_attention

_NEG_INF = -1e30


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, blk_q: int, blk_k: int,
                      seq_len: int, causal: bool, scale: float):
    """Grid: (batch*heads, num_q_blocks). q_ref: [blk_q, D] tile;
    k_ref/v_ref: [S, D] for this (b, h); o_ref: [blk_q, D]."""
    qi = pl.program_id(1)
    q = q_ref[...].astype(jnp.float32) * scale
    D = q.shape[-1]

    q_pos = qi * blk_q + jax.lax.broadcasted_iota(
        jnp.int32, (blk_q, blk_k), 0)

    n_k = seq_len // blk_k

    def body(kb, carry):
        m, l, o = carry
        k_blk = k_ref[pl.ds(kb * blk_k, blk_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(kb * blk_k, blk_k), :].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if causal:
            k_pos = kb * blk_k + jax.lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 1)
            mask = q_pos >= k_pos
            logits = jnp.where(mask, logits, _NEG_INF)
        m_new = jnp.maximum(m, logits.max(-1))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(logits - m_new[:, None])
        if causal:
            p = jnp.where(mask, p, 0.0)
        l_new = l * corr + p.sum(-1)
        o_new = o * corr[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, o_new

    m0 = jnp.full((blk_q,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((blk_q,), jnp.float32)
    o0 = jnp.zeros((blk_q, D), jnp.float32)
    if causal:
        # Only KV blocks at or before this Q block's last row contribute.
        n_iter = jnp.minimum(pl.cdiv((qi + 1) * blk_q, blk_k), n_k)
    else:
        n_iter = n_k
    m, l, o = jax.lax.fori_loop(0, n_iter, body, (m0, l0, o0))
    o_ref[...] = (o / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


def _flash_forward(q, k, v, causal: bool, blk_q: int, blk_k: int):
    B, S, H, D = q.shape
    kvh = k.shape[2]
    if kvh != H:
        rep = H // kvh
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scale = 1.0 / math.sqrt(D)
    blk_q = min(blk_q, S)
    blk_k = min(blk_k, S)
    if S % blk_q or S % blk_k:
        # Ragged tail: fall back to the jnp blockwise path.
        return blockwise_attention(q, k, v, causal=causal)
    qf = q.transpose(0, 2, 1, 3).reshape(B * H, S, D)
    kf = k.transpose(0, 2, 1, 3).reshape(B * H, S, D)
    vf = v.transpose(0, 2, 1, 3).reshape(B * H, S, D)

    kernel = functools.partial(
        _flash_fwd_kernel, blk_q=blk_q, blk_k=blk_k, seq_len=S,
        causal=causal, scale=scale)
    interpret = jax.default_backend() != "tpu"
    out = pl.pallas_call(
        kernel,
        grid=(B * H, S // blk_q),
        in_specs=[
            pl.BlockSpec((None, blk_q, D), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, S, D), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, S, D), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, blk_q, D), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(B, H, S, D).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q, k, v, causal: bool = True, blk_q: int = 128,
                    blk_k: int = 128):
    """q: [B, S, H, D], k/v: [B, S, KVH, D] → [B, S, H, D]."""
    return _flash_forward(q, k, v, causal, blk_q, blk_k)


def _fwd(q, k, v, causal, blk_q, blk_k):
    out = _flash_forward(q, k, v, causal, blk_q, blk_k)
    return out, (q, k, v)


def _bwd(causal, blk_q, blk_k, residuals, g):
    q, k, v = residuals
    # Recompute through the O(S)-memory jnp recurrence; its VJP is exact.
    _, vjp = jax.vjp(
        lambda q_, k_, v_: blockwise_attention(q_, k_, v_, causal=causal),
        q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)
