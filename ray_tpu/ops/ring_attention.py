"""Ring attention: exact causal attention over a sequence-parallel mesh axis.

Green-field capability (SURVEY.md §2.5/§5: the reference has no sequence/
context parallelism). Each device holds a contiguous sequence shard of
Q/K/V. KV shards rotate around the ring with `jax.lax.ppermute` — which XLA
lowers to ICI neighbor transfers on TPU — while every device folds each
arriving KV shard into its online-softmax accumulators
(blockwise_attention.attention_chunk). The score matrix never exceeds
[B, H, S/n, S/n]; sequence length scales linearly with ring size.

Must run inside a `shard_map` (or pmap) that binds ``axis_name``; the
parallel train step wires this under the `sp` mesh axis
(parallel/train_step.py).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from ray_tpu.ops.blockwise_attention import _repeat_kv, attention_chunk


def ring_attention(q, k, v, axis_name: str = "sp", causal: bool = True
                   ) -> jax.Array:
    """q,k,v: local shards [B, S_local, H|KVH, D] → [B, S_local, H, D].

    The KV pair travels the ring; step i processes the shard originally
    owned by device (my_index + i) mod n. Causality is enforced with global
    positions, so fully-future shards contribute nothing (their probability
    mass underflows to zero) and the result is exactly the unsharded causal
    attention.
    """
    B, Sl, H, D = q.shape
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    k, v = _repeat_kv(k, v, H)
    scale = 1.0 / math.sqrt(D)

    qt = q.transpose(0, 2, 1, 3).astype(jnp.float32)
    kt = k.transpose(0, 2, 1, 3).astype(jnp.float32)
    vt = v.transpose(0, 2, 1, 3).astype(jnp.float32)

    q_pos = my * Sl + jnp.arange(Sl)
    m0 = jnp.full((B, H, Sl), -1e30, jnp.float32)
    l0 = jnp.zeros((B, H, Sl), jnp.float32)
    o0 = jnp.zeros((B, H, Sl, D), jnp.float32)

    # Receive from the right neighbor so step i holds shard (my + i) % n.
    perm = [(j, (j - 1) % n) for j in range(n)]

    def body(carry, i):
        m, l, o, kc, vc = carry
        src = (my + i) % n
        k_pos = src * Sl + jnp.arange(Sl)
        m, l, o = attention_chunk(qt, kc, vc, m, l, o, q_pos, k_pos,
                                  causal, scale)
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        return (m, l, o, kc, vc), None

    (m, l, o, _, _), _ = jax.lax.scan(
        body, (m0, l0, o0, kt, vt), jnp.arange(n))
    out = o / jnp.maximum(l[..., None], 1e-30)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def make_ring_attention(mesh, axis_name: str = "sp", causal: bool = True,
                        q_spec=None, kv_spec=None):
    """shard_map wrapper: full arrays in, full arrays out. By default only
    the sequence dimension is sharded (over ``axis_name``); callers running
    under a larger mesh pass explicit ``q_spec``/``kv_spec`` for the
    batch/head axes (e.g. the model's ring path, models/gpt.py)."""
    from jax.sharding import PartitionSpec as P

    from ray_tpu._private.jax_compat import shard_map

    if q_spec is None:
        q_spec = P(None, axis_name, None, None)
    if kv_spec is None:
        kv_spec = q_spec

    @partial(shard_map, mesh=mesh, in_specs=(q_spec, kv_spec, kv_spec),
             out_specs=q_spec, check_vma=False)
    def fn(q, k, v):
        return ring_attention(q, k, v, axis_name=axis_name, causal=causal)

    return fn
