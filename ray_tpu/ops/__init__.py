"""ray_tpu.ops: TPU kernels (Pallas) and memory-efficient attention.

Green-field capability relative to the reference (SURVEY.md §2.5: no
sequence/context parallelism exists in-tree): blockwise attention, a Pallas
flash-attention kernel for the MXU, and ring attention over the ICI ring
(sequence-parallel mesh axis).
"""

from ray_tpu.ops.blockwise_attention import blockwise_attention
from ray_tpu.ops.flash_attention import flash_attention
from ray_tpu.ops.ring_attention import ring_attention

__all__ = ["blockwise_attention", "flash_attention", "ring_attention"]
