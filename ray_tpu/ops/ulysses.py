"""Ulysses-style sequence parallelism: all-to-all head/sequence re-sharding.

The second long-context strategy next to ring attention (ops/ring_attention
.py). Ring keeps the sequence sharded and rotates KV blocks around the ICI
ring; Ulysses instead re-shards twice per attention call with all-to-all:

    [B, S/sp, H, D]  --all_to_all-->  [B, S, H/sp, D]
    (sequence-sharded activations)     (full sequence, head-sharded)

so each device runs *exact* full-sequence attention for its head group, then
the inverse all-to-all restores sequence sharding for the MLP. Preferable to
ring when n_heads >= sp and sequence lengths are moderate (two all-to-alls
cost less than a full ring pass of KV blocks); ring wins at extreme lengths.
The reference has no sequence parallelism of any kind (SURVEY.md §2.5).
"""

from __future__ import annotations

import math
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ray_tpu._private.jax_compat import shard_map


def _full_causal_attention(q, k, v):
    """Exact fp32-softmax causal attention on full sequences.
    q,k,v: [B, S, H, D] (H = local head group)."""
    B, S, H, D = q.shape
    kvh = k.shape[2]
    if kvh != H:
        rep = H // kvh
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scale = 1.0 / math.sqrt(D)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    qpos = jax.lax.broadcasted_iota(jnp.int32, (S, S), 0)
    kpos = jax.lax.broadcasted_iota(jnp.int32, (S, S), 1)
    logits = jnp.where((qpos >= kpos)[None, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def make_ulysses_attention(mesh, seq_axis: str = "sp",
                           batch_spec=("dp", "fsdp"),
                           inner: Optional[Callable] = None) -> Callable:
    """Returns attention(q, k, v) over sequence-sharded [B, S, H, D] inputs.

    Requires n_heads (and kv_heads) divisible by the seq_axis size. ``inner``
    defaults to exact causal attention; pass a flash kernel for long-S.
    """
    inner = inner or _full_causal_attention
    sp = dict(zip(mesh.axis_names, mesh.devices.shape))[seq_axis]
    spec = P(batch_spec, seq_axis, None, None)

    def per_shard(q, k, v):
        # local: [B, S/sp, H, D] → [B, S, H/sp, D]
        def scatter_heads(x):
            return jax.lax.all_to_all(
                x, seq_axis, split_axis=2, concat_axis=1, tiled=True)

        def gather_seq(x):
            return jax.lax.all_to_all(
                x, seq_axis, split_axis=1, concat_axis=2, tiled=True)

        o = inner(scatter_heads(q), scatter_heads(k), scatter_heads(v))
        return gather_seq(o)

    mapped = shard_map(per_shard, mesh=mesh,
                       in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)

    def attention(q, k, v):
        if q.shape[2] % sp or k.shape[2] % sp:
            raise ValueError(
                f"Ulysses needs n_heads divisible by {seq_axis} size {sp}; "
                f"got q heads {q.shape[2]}, kv heads {k.shape[2]}")
        return mapped(q, k, v)

    return attention
