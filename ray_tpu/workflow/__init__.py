"""ray_tpu.workflow: durable DAG execution.

Analog of the reference's python/ray/workflow (workflow_executor.py,
workflow_storage.py, workflow_state_from_dag.py): a Ray DAG
(ray_tpu/dag) runs with every task's result checkpointed to storage; a
crashed/cancelled workflow resumes from the last completed task instead of
recomputing. Task identity is the node's position in the DAG (stable
topological naming), so resume replays structure, not uuids.
"""

from __future__ import annotations

import os
import pickle
import time
from typing import Any, Dict, List, Optional, Tuple

import ray_tpu
from ray_tpu.dag import DAGNode, FunctionNode, InputAttributeNode, InputNode

__all__ = ["init", "run", "run_async", "resume", "get_output", "get_status",
           "list_all", "delete", "cancel"]

_storage_dir: Optional[str] = None

# Workflow statuses (reference: workflow/common.py WorkflowStatus).
RUNNING = "RUNNING"
SUCCESSFUL = "SUCCESSFUL"
FAILED = "FAILED"
CANCELED = "CANCELED"
RESUMABLE = "RESUMABLE"


def init(storage: Optional[str] = None) -> None:
    global _storage_dir
    if storage is None:
        storage = os.path.join(
            os.environ.get("TMPDIR", "/tmp"), "ray_tpu_workflows")
    _storage_dir = storage
    os.makedirs(storage, exist_ok=True)


def _storage() -> str:
    if _storage_dir is None:
        init()
    return _storage_dir


def _wf_dir(workflow_id: str) -> str:
    return os.path.join(_storage(), workflow_id)


def _task_key(node: DAGNode, counter: Dict[str, int]) -> str:
    """Stable name: class name + topological visit index."""
    base = type(node).__name__
    if isinstance(node, FunctionNode):
        base = node.fn._function.__name__
    idx = counter.get(base, 0)
    counter[base] = idx + 1
    return f"{base}_{idx}"


class _WorkflowStorage:
    def __init__(self, workflow_id: str):
        self.workflow_id = workflow_id
        self.dir = _wf_dir(workflow_id)
        os.makedirs(os.path.join(self.dir, "tasks"), exist_ok=True)

    def save_dag(self, dag: DAGNode, input_value: Any) -> None:
        import cloudpickle
        with open(os.path.join(self.dir, "dag.pkl"), "wb") as f:
            cloudpickle.dump({"dag": dag, "input": input_value}, f)

    def load_dag(self) -> Tuple[DAGNode, Any]:
        import cloudpickle
        with open(os.path.join(self.dir, "dag.pkl"), "rb") as f:
            data = cloudpickle.load(f)
        return data["dag"], data["input"]

    def set_status(self, status: str) -> None:
        with open(os.path.join(self.dir, "status"), "w") as f:
            f.write(status)

    def get_status(self) -> Optional[str]:
        try:
            with open(os.path.join(self.dir, "status")) as f:
                return f.read().strip()
        except FileNotFoundError:
            return None

    def has_task(self, key: str) -> bool:
        return os.path.exists(
            os.path.join(self.dir, "tasks", key + ".pkl"))

    def save_task(self, key: str, value: Any) -> None:
        path = os.path.join(self.dir, "tasks", key + ".pkl")
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(value, f)
        os.replace(tmp, path)  # atomic: no partial checkpoints on crash

    def load_task(self, key: str) -> Any:
        with open(os.path.join(self.dir, "tasks", key + ".pkl"),
                  "rb") as f:
            return pickle.load(f)

    def save_output(self, value: Any) -> None:
        self.save_task("__output__", value)

    def load_output(self) -> Any:
        return self.load_task("__output__")


def _execute_node(node: DAGNode, storage: _WorkflowStorage,
                  counter: Dict[str, int], cache: Dict[str, Any],
                  input_value: Any) -> Any:
    """Post-order execution with per-task checkpointing. Returns the node's
    *value* (checkpointing forces materialization at each step, matching
    the reference's per-task durability)."""
    if node._stable_uuid in cache:
        return cache[node._stable_uuid]
    if isinstance(node, InputNode):
        return input_value
    if isinstance(node, InputAttributeNode):
        value = _execute_node(node._parent, storage, counter, cache,
                              input_value)
        out = value[node._key] if node._is_item else getattr(
            value, node._key)
        cache[node._stable_uuid] = out
        return out
    if not isinstance(node, FunctionNode):
        raise TypeError(
            f"Workflows support function DAGs; got {type(node).__name__} "
            "(actor nodes are not durable)")
    key = _task_key(node, counter)
    # Resolve children first so their keys are assigned deterministically
    # even on the resume path.
    args = [
        _execute_node(a, storage, counter, cache, input_value)
        if isinstance(a, DAGNode) else a for a in node.bound_args]
    kwargs = {
        k: _execute_node(v, storage, counter, cache, input_value)
        if isinstance(v, DAGNode) else v
        for k, v in node.bound_kwargs.items()}
    if storage.has_task(key):
        result = storage.load_task(key)
    else:
        result = ray_tpu.get(node.fn.remote(*args, **kwargs))
        storage.save_task(key, result)
    cache[node._stable_uuid] = result
    return result


def run(dag: DAGNode, *, workflow_id: Optional[str] = None,
        input_value: Any = None) -> Any:
    """Execute a DAG durably; returns the output (reference:
    workflow.run)."""
    return ray_tpu.get(run_async(dag, workflow_id=workflow_id,
                                 input_value=input_value))


def run_async(dag: DAGNode, *, workflow_id: Optional[str] = None,
              input_value: Any = None):
    """Returns an ObjectRef of the workflow output."""
    import uuid as uuid_mod
    workflow_id = workflow_id or f"workflow-{uuid_mod.uuid4().hex[:8]}"
    storage = _WorkflowStorage(workflow_id)
    storage.save_dag(dag, input_value)
    storage.set_status(RUNNING)

    @ray_tpu.remote
    def _driver(wf_id: str):
        st = _WorkflowStorage(wf_id)
        dag, input_value = st.load_dag()
        try:
            out = _execute_node(dag, st, {}, {}, input_value)
            st.save_output(out)
            st.set_status(SUCCESSFUL)
            return out
        except BaseException:
            st.set_status(FAILED)
            raise

    return _driver.remote(workflow_id)


def resume(workflow_id: str) -> Any:
    """Re-run from storage; completed tasks load from checkpoints."""
    storage = _WorkflowStorage(workflow_id)
    dag, input_value = storage.load_dag()
    storage.set_status(RUNNING)
    try:
        out = _execute_node(dag, storage, {}, {}, input_value)
        storage.save_output(out)
        storage.set_status(SUCCESSFUL)
        return out
    except BaseException:
        storage.set_status(FAILED)
        raise


def get_output(workflow_id: str) -> Any:
    return _WorkflowStorage(workflow_id).load_output()


def get_status(workflow_id: str) -> Optional[str]:
    return _WorkflowStorage(workflow_id).get_status()


def list_all(status_filter: Optional[str] = None
             ) -> List[Tuple[str, Optional[str]]]:
    out = []
    root = _storage()
    for wf_id in sorted(os.listdir(root)):
        if not os.path.isdir(os.path.join(root, wf_id)):
            continue
        status = _WorkflowStorage(wf_id).get_status()
        if status_filter is None or status == status_filter:
            out.append((wf_id, status))
    return out


def delete(workflow_id: str) -> None:
    import shutil
    shutil.rmtree(_wf_dir(workflow_id), ignore_errors=True)


def cancel(workflow_id: str) -> None:
    _WorkflowStorage(workflow_id).set_status(CANCELED)
