"""ray_tpu.workflow: durable DAG execution.

Analog of the reference's python/ray/workflow (workflow_executor.py,
workflow_storage.py, workflow_state_from_dag.py): a Ray DAG
(ray_tpu/dag) runs with every task's result checkpointed to storage; a
crashed/cancelled workflow resumes from the last completed task instead of
recomputing. Task identity is the node's position in the DAG (stable
topological naming), so resume replays structure, not uuids.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import time
from typing import Any, Dict, List, Optional, Tuple

import ray_tpu
from ray_tpu.dag import DAGNode, FunctionNode, InputAttributeNode, InputNode

__all__ = ["init", "run", "run_async", "resume", "get_output", "get_status",
           "list_all", "delete", "cancel", "continuation",
           "wait_for_event", "trigger_event"]

_storage_dir: Optional[str] = None

# Workflow statuses (reference: workflow/common.py WorkflowStatus).
RUNNING = "RUNNING"
SUCCESSFUL = "SUCCESSFUL"
FAILED = "FAILED"
CANCELED = "CANCELED"
RESUMABLE = "RESUMABLE"


def init(storage: Optional[str] = None) -> None:
    global _storage_dir
    if storage is None:
        storage = os.path.join(
            os.environ.get("TMPDIR", "/tmp"), "ray_tpu_workflows")
    _storage_dir = storage
    os.makedirs(storage, exist_ok=True)


def _storage() -> str:
    if _storage_dir is None:
        init()
    return _storage_dir


def _wf_dir(workflow_id: str) -> str:
    return os.path.join(_storage(), workflow_id)


def _task_key(node: DAGNode, counter: Dict[str, int]) -> str:
    """Stable name: class name + topological visit index."""
    base = type(node).__name__
    if isinstance(node, FunctionNode):
        base = node.fn._function.__name__
    idx = counter.get(base, 0)
    counter[base] = idx + 1
    return f"{base}_{idx}"


class _WorkflowStorage:
    def __init__(self, workflow_id: str):
        self.workflow_id = workflow_id
        self.dir = _wf_dir(workflow_id)
        os.makedirs(os.path.join(self.dir, "tasks"), exist_ok=True)

    def save_dag(self, dag: DAGNode, input_value: Any) -> None:
        import cloudpickle
        with open(os.path.join(self.dir, "dag.pkl"), "wb") as f:
            cloudpickle.dump({"dag": dag, "input": input_value}, f)

    def load_dag(self) -> Tuple[DAGNode, Any]:
        import cloudpickle
        with open(os.path.join(self.dir, "dag.pkl"), "rb") as f:
            data = cloudpickle.load(f)
        return data["dag"], data["input"]

    def set_status(self, status: str) -> None:
        with open(os.path.join(self.dir, "status"), "w") as f:
            f.write(status)

    def get_status(self) -> Optional[str]:
        try:
            with open(os.path.join(self.dir, "status")) as f:
                return f.read().strip()
        except FileNotFoundError:
            return None

    def has_task(self, key: str) -> bool:
        return os.path.exists(
            os.path.join(self.dir, "tasks", key + ".pkl"))

    def save_task(self, key: str, value: Any) -> None:
        import cloudpickle  # checkpoints may hold DAGs (continuations)
        path = os.path.join(self.dir, "tasks", key + ".pkl")
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            cloudpickle.dump(value, f)
        os.replace(tmp, path)  # atomic: no partial checkpoints on crash

    def load_task(self, key: str) -> Any:
        with open(os.path.join(self.dir, "tasks", key + ".pkl"),
                  "rb") as f:
            return pickle.load(f)

    def save_output(self, value: Any) -> None:
        self.save_task("__output__", value)

    def load_output(self) -> Any:
        return self.load_task("__output__")


def _execute_node(node: DAGNode, storage: _WorkflowStorage,
                  counter: Dict[str, int], cache: Dict[str, Any],
                  input_value: Any, resolve_continuations: bool = True
                  ) -> Any:
    """Post-order execution with per-task checkpointing. Returns the node's
    *value* (checkpointing forces materialization at each step, matching
    the reference's per-task durability). With
    ``resolve_continuations=False`` the RAW result may be a DAG node —
    the caller's continuation loop drives it (keeps tail-recursive
    continuation chains iterative: constant Python stack however long
    the chain)."""
    if node._stable_uuid in cache:
        return cache[node._stable_uuid]
    if isinstance(node, InputNode):
        return input_value
    if isinstance(node, InputAttributeNode):
        value = _execute_node(node._parent, storage, counter, cache,
                              input_value)
        out = value[node._key] if node._is_item else getattr(
            value, node._key)
        cache[node._stable_uuid] = out
        return out
    if not isinstance(node, FunctionNode):
        raise TypeError(
            f"Workflows support function DAGs; got {type(node).__name__} "
            "(actor nodes are not durable)")
    key = _task_key(node, counter)
    # Resolve children first so their keys are assigned deterministically
    # even on the resume path.
    args = [
        _execute_node(a, storage, counter, cache, input_value)
        if isinstance(a, DAGNode) else a for a in node.bound_args]
    kwargs = {
        k: _execute_node(v, storage, counter, cache, input_value)
        if isinstance(v, DAGNode) else v
        for k, v in node.bound_kwargs.items()}
    if storage.has_task(key):
        result = storage.load_task(key)
    else:
        result = ray_tpu.get(node.fn.remote(*args, **kwargs))
        # Checkpoint FIRST — for a continuation this makes the DECISION
        # to continue durable before any continuation task runs, so a
        # crash mid-continuation resumes into the sub-DAG, never
        # re-runs this task.
        storage.save_task(key, result)
    if resolve_continuations:
        result = _run_continuations(result, storage, key, input_value)
    cache[node._stable_uuid] = result
    return result


def _run_continuations(result: Any, storage: "_WorkflowStorage",
                       parent_key: str, input_value: Any) -> Any:
    """Dynamic workflows (reference: workflow_executor.py continuation
    handling + workflow_state_from_dag.py): a task that RETURNS a DAG
    node continues the workflow with that sub-DAG. The sub-DAG's tasks
    checkpoint under a namespace derived from the parent task and the
    continuation depth, so a resumed workflow replays structure —
    loading every completed task from its checkpoint. THIS loop is the
    only place a returned sub-DAG executes (the sub-DAG's own root runs
    with resolve_continuations=False), so an arbitrarily long
    tail-recursive continuation chain iterates at constant Python
    stack depth; only static DAG nesting recurses."""
    depth = 0
    while isinstance(result, DAGNode):
        sub = _NamespacedStorage(storage, f"{parent_key}.c{depth}")
        result = _execute_node(result, sub, {}, {}, input_value,
                               resolve_continuations=False)
        depth += 1
    return result


class _NamespacedStorage:
    """Task-checkpoint view whose keys live under a continuation
    namespace; everything else delegates to the workflow's storage.
    The namespace is a short digest of the full continuation path —
    deterministic across resume, and immune to filename-length limits
    however deep the chain (a literal prefix chain hits the 255-byte
    filename cap within ~20 continuations)."""

    def __init__(self, base, prefix: str):
        # Flatten nested namespaces: base may itself be namespaced.
        self._base = getattr(base, "_base", base)
        if isinstance(base, _NamespacedStorage):
            path = f"{base._path}.{prefix}"
        else:
            path = prefix
        self._path = path
        self._prefix = hashlib.sha1(path.encode()).hexdigest()[:16]

    def _k(self, key: str) -> str:
        return f"{self._prefix}.{key}"

    def has_task(self, key: str) -> bool:
        return self._base.has_task(self._k(key))

    def save_task(self, key: str, value: Any) -> None:
        self._base.save_task(self._k(key), value)

    def load_task(self, key: str) -> Any:
        return self._base.load_task(self._k(key))


def continuation(dag: DAGNode) -> DAGNode:
    """Mark a DAG returned by a workflow task as the workflow's
    continuation (reference: workflow.continuation). Returning the
    bound DAG node itself is equivalent; this wrapper documents intent
    and validates the type at the return site."""
    if not isinstance(dag, DAGNode):
        raise TypeError(
            f"workflow.continuation expects a bound DAG node, got "
            f"{type(dag).__name__}")
    return dag


def run(dag: DAGNode, *, workflow_id: Optional[str] = None,
        input_value: Any = None) -> Any:
    """Execute a DAG durably; returns the output (reference:
    workflow.run)."""
    return ray_tpu.get(run_async(dag, workflow_id=workflow_id,
                                 input_value=input_value))


def run_async(dag: DAGNode, *, workflow_id: Optional[str] = None,
              input_value: Any = None):
    """Returns an ObjectRef of the workflow output."""
    import uuid as uuid_mod
    workflow_id = workflow_id or f"workflow-{uuid_mod.uuid4().hex[:8]}"
    storage = _WorkflowStorage(workflow_id)
    storage.save_dag(dag, input_value)
    storage.set_status(RUNNING)

    @ray_tpu.remote
    def _driver(wf_id: str):
        st = _WorkflowStorage(wf_id)
        dag, input_value = st.load_dag()
        try:
            out = _execute_node(dag, st, {}, {}, input_value)
            st.save_output(out)
            st.set_status(SUCCESSFUL)
            return out
        except BaseException:
            st.set_status(FAILED)
            raise

    return _driver.remote(workflow_id)


def resume(workflow_id: str) -> Any:
    """Re-run from storage; completed tasks load from checkpoints."""
    storage = _WorkflowStorage(workflow_id)
    dag, input_value = storage.load_dag()
    storage.set_status(RUNNING)
    try:
        out = _execute_node(dag, storage, {}, {}, input_value)
        storage.save_output(out)
        storage.set_status(SUCCESSFUL)
        return out
    except BaseException:
        storage.set_status(FAILED)
        raise


def get_output(workflow_id: str) -> Any:
    return _WorkflowStorage(workflow_id).load_output()


def get_status(workflow_id: str) -> Optional[str]:
    return _WorkflowStorage(workflow_id).get_status()


def list_all(status_filter: Optional[str] = None
             ) -> List[Tuple[str, Optional[str]]]:
    out = []
    root = _storage()
    for wf_id in sorted(os.listdir(root)):
        if not os.path.isdir(os.path.join(root, wf_id)):
            continue
        status = _WorkflowStorage(wf_id).get_status()
        if status_filter is None or status == status_filter:
            out.append((wf_id, status))
    return out


def delete(workflow_id: str) -> None:
    import shutil
    shutil.rmtree(_wf_dir(workflow_id), ignore_errors=True)


def cancel(workflow_id: str) -> None:
    _WorkflowStorage(workflow_id).set_status(CANCELED)


# -- events ----------------------------------------------------------------
# Analog of the reference's workflow event system (workflow/
# http_event_provider.py + workflow.wait_for_event): a workflow task can
# block on an external event; `trigger_event` (callable from anywhere in
# the cluster, including the dashboard's HTTP surface) releases it. Events
# ride the runtime's pubsub hub and are checkpointed like any other task —
# a resumed workflow does not re-wait for an event it already consumed.


def _validate_event_key(event_key: str) -> None:
    if not isinstance(event_key, str) or not event_key:
        raise ValueError(f"event_key must be a non-empty string, got "
                         f"{event_key!r}")
    if "|" in event_key:
        # '|' is the native pubsub wire separator.
        raise ValueError(
            f"Invalid event_key {event_key!r}: must not contain '|'")


def _event_latch(runtime) -> Dict[str, Any]:
    latch = getattr(runtime, "_workflow_event_latch", None)
    if latch is None:
        latch = runtime._workflow_event_latch = {}
    return latch


def wait_for_event(event_key: str, timeout: Optional[float] = None):
    """A DAG node that resolves to the event's payload once
    ``trigger_event(event_key, payload)`` fires. Events LATCH: a trigger
    that arrives before the waiter subscribes (or while a workflow is
    down pre-resume) is retained and delivered immediately; a later
    trigger for the same key overwrites the latch."""
    _validate_event_key(event_key)
    from ray_tpu.remote_function import remote

    # num_cpus=0: an event wait is parked I/O, not compute — it must not
    # hold a worker CPU slot for a possibly unbounded time.
    @remote(num_cpus=0)
    def _wait_for_event(_key: str = event_key, _timeout=timeout):
        import uuid as _uuid

        from ray_tpu._private.worker import global_worker
        runtime = global_worker.runtime
        hub = runtime.pubsub
        sub_id = f"workflow-event-{_uuid.uuid4().hex[:8]}"
        hub.subscribe(sub_id, "workflow_events", _key)
        try:
            import time as _time
            latch = _event_latch(runtime)
            deadline = (None if _timeout is None
                        else _time.monotonic() + _timeout)
            while True:
                # Latched (possibly pre-subscription) event wins.
                if _key in latch:
                    return latch[_key]
                remaining = 1.0
                if deadline is not None:
                    remaining = min(1.0, deadline - _time.monotonic())
                    if remaining <= 0:
                        raise TimeoutError(
                            f"workflow event {_key!r} did not arrive "
                            f"within {_timeout}s")
                msg = hub.poll(sub_id, timeout=remaining)
                if msg is not None:
                    import pickle as _pickle
                    return _pickle.loads(bytes.fromhex(msg[2]))
        finally:
            hub.drop_subscriber(sub_id)

    return _wait_for_event.bind()


def trigger_event(event_key: str, payload: Any = None) -> int:
    """Deliver an event to workflow tasks waiting on ``event_key`` (and
    latch it for waiters that haven't subscribed yet). Returns the number
    of currently-parked waiters it reached directly."""
    import pickle as _pickle

    from ray_tpu._private.worker import global_worker
    _validate_event_key(event_key)
    runtime = global_worker.runtime
    _event_latch(runtime)[event_key] = payload
    return runtime.pubsub.publish(
        "workflow_events", event_key, _pickle.dumps(payload).hex())
