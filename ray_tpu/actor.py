"""Actors: @remote classes, ActorClass, ActorHandle, ActorMethod.

Analog of the reference's python/ray/actor.py: ``Cls.remote(...)`` creates the
actor and returns a handle; ``handle.method.remote(...)`` submits ordered
actor tasks. Handles are picklable (they travel as actor IDs and re-bind to
the actor on deserialization).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ray_tpu._private import task_spec as ts
from ray_tpu._private.ids import ActorID, TaskID
from ray_tpu._private.task_spec import TaskKind, TaskSpec, validate_options
from ray_tpu._private.worker import global_worker


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str,
                 num_returns: int = 1,
                 concurrency_group: Optional[str] = None):
        self._handle = handle
        self._method_name = method_name
        self._num_returns = num_returns
        self._concurrency_group = concurrency_group

    def remote(self, *args, **kwargs):
        return self._handle._actor_method_call(
            self._method_name, args, kwargs,
            num_returns=self._num_returns,
            concurrency_group=self._concurrency_group)

    def options(self, num_returns: Optional[int] = None, name: str = "",
                concurrency_group: Optional[str] = None, **_ignored):
        return ActorMethod(
            self._handle, self._method_name,
            self._num_returns if num_returns is None else num_returns,
            concurrency_group=(concurrency_group or
                               self._concurrency_group))

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor method {self._method_name!r} cannot be called directly; "
            "use .remote().")


class ActorHandle:
    def __init__(self, actor_id: ActorID, cls: Optional[type] = None,
                 name: str = "", class_name: str = ""):
        import itertools
        import uuid
        self._actor_id = actor_id
        self._cls = cls
        self._name = name
        # Task-naming fallback when the class itself isn't importable
        # (client sessions rebind handles by id; the actor_info op streams
        # the class name so tasks still read "Cls.method", not
        # "Actor.method").
        self._class_name = class_name or (cls.__name__ if cls else "")
        # Per-handle ordering state (each handle instance gets its own
        # sequence, matching the reference's per-handle call ordering).
        # itertools.count.__next__ is atomic, so concurrent .remote() calls
        # from multiple threads sharing this handle get unique seq numbers.
        self._handle_id = uuid.uuid4().hex
        self._seq_counter = itertools.count(1)

    def __getattr__(self, item):
        if item.startswith("_"):
            raise AttributeError(item)
        # @ray_tpu.method tags on the class set per-method defaults.
        tag = getattr(getattr(self._cls, item, None),
                      "__ray_tpu_method__", None) if self._cls else None
        if tag:
            return ActorMethod(
                self, item,
                num_returns=tag.get("num_returns", 1),
                concurrency_group=tag.get("concurrency_group"))
        return ActorMethod(self, item)

    def _actor_method_call(self, method_name, args, kwargs, num_returns=1,
                           concurrency_group=None):
        runtime = global_worker.runtime
        seq = next(self._seq_counter)
        state = runtime.actor_state(self._actor_id)
        spec = TaskSpec(
            task_id=TaskID.for_actor_task(self._actor_id),
            kind=TaskKind.ACTOR_TASK,
            function_id=(state.creation_spec.function_id
                         if state is not None else b""),
            args=tuple(args),
            kwargs=dict(kwargs),
            resources={},
            num_returns=num_returns,
            name=f"{self._class_name or 'Actor'}.{method_name}",
            max_retries=0,
            actor_id=self._actor_id,
            method_name=method_name,
            sequence_number=seq,
            caller_handle_id=self._handle_id,
            concurrency_group=concurrency_group,
        )
        refs = runtime.submit_actor_task(spec)
        if num_returns == 0:
            return None
        if num_returns == 1:
            return refs[0]
        return refs

    def __reduce__(self):
        return (_rebind_actor_handle, (self._actor_id, self._name))

    def __repr__(self):
        cls_name = self._class_name or "Actor"
        return f"ActorHandle({cls_name}, {self._actor_id.hex()})"

    def _ray_kill(self, no_restart: bool = True):
        global_worker.runtime.kill_actor(self._actor_id, no_restart)


def _rebind_actor_handle(actor_id: ActorID, name: str) -> ActorHandle:
    runtime = global_worker.runtime
    state = runtime.actor_state(actor_id)
    cls = None
    class_name = ""
    if state is not None:
        try:
            cls = runtime.functions.load(state.creation_spec.function_id)
        except KeyError:
            cls = None
        class_name = getattr(state, "class_name", "")
    return ActorHandle(actor_id, cls, name, class_name=class_name)


class ActorClass:
    def __init__(self, cls: type, options: Dict[str, Any]):
        self._cls = cls
        self._default_options = validate_options(options, for_actor=True)
        self._exported: tuple = ("", None)
        self.__name__ = cls.__name__
        self.__qualname__ = getattr(cls, "__qualname__", cls.__name__)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class {self._cls.__name__!r} cannot be instantiated "
            "directly. Use Cls.remote() instead.")

    def options(self, **options) -> "ActorClass":
        merged = {**self._default_options, **options}
        clone = ActorClass(self._cls, merged)
        clone._exported = self._exported
        return clone

    def remote(self, *args, **kwargs) -> ActorHandle:
        return self._remote(args, kwargs, self._default_options)

    def bind(self, *args, **kwargs):
        """Build a ClassNode DAG node (reference: python/ray/dag/)."""
        from ray_tpu.dag import ClassNode
        return ClassNode(self, args, kwargs)

    def _remote(self, args, kwargs, options) -> ActorHandle:
        runtime = global_worker.runtime
        session, function_id = self._exported
        if session != runtime.session_id:
            function_id = runtime.register_function(self._cls)
            self._exported = (runtime.session_id, function_id)
        actor_id = ActorID.of(runtime.job_id)
        name = options.get("name") or ""
        namespace = options.get("namespace") or global_worker.namespace
        get_if_exists = bool(options.get("get_if_exists"))
        from ray_tpu.util.scheduling_strategies import strategy_from_options
        strategy = strategy_from_options(options)
        spec = TaskSpec(
            task_id=TaskID.for_actor_creation(actor_id),
            kind=TaskKind.ACTOR_CREATION,
            function_id=function_id,
            args=tuple(args),
            kwargs=dict(kwargs),
            resources=ts.resources_from_options(options, for_actor=True),
            num_returns=1,
            name=f"{self._cls.__name__}.__init__",
            max_retries=0,
            actor_id=actor_id,
            scheduling_strategy=strategy,
            runtime_env=options.get("runtime_env"),
        )
        actual_id = runtime.create_actor(
            spec,
            max_restarts=options.get("max_restarts", 0),
            max_concurrency=options.get("max_concurrency", 1),
            name=name,
            namespace=namespace,
            get_if_exists=get_if_exists,
            concurrency_groups=options.get("concurrency_groups"),
            lifetime=options.get("lifetime"),
        )
        return ActorHandle(actual_id, self._cls, name)


def method(*, num_returns: int = 1,
           concurrency_group: Optional[str] = None):
    """Per-method defaults on actor classes (reference: ray.method):

        @ray_tpu.remote(concurrency_groups={"io": 2, "compute": 4})
        class A:
            @ray_tpu.method(concurrency_group="io")
            def fetch(self): ...

    Handle calls route to the tagged group without per-call
    ``.options(concurrency_group=...)``."""
    def decorate(fn):
        fn.__ray_tpu_method__ = {"num_returns": num_returns,
                                 "concurrency_group": concurrency_group}
        return fn
    return decorate
