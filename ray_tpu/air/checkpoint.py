"""AIR Checkpoint: dict / directory / sharded-array forms.

Analog of the reference's python/ray/air/checkpoint.py:63 (Checkpoint with
to_dict/from_dict/to_directory/from_directory/uri conversions). The TPU-native
addition is first-class **sharded jax pytrees** via orbax — a 6B-param state
sharded over a mesh round-trips without ever being gathered onto one host
(`from_sharded_state` / `restore_sharded_state`).
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
import tempfile
import uuid
from typing import Any, Dict, Optional

_DICT_FILE = "checkpoint_dict.pkl"
_METADATA_FILE = "ckpt_metadata.json"
_SHARDED_DIR = "sharded_state"


class Checkpoint:
    def __init__(self, data: Optional[Dict[str, Any]] = None,
                 directory: Optional[str] = None):
        if (data is None) == (directory is None):
            raise ValueError(
                "Provide exactly one of data= or directory= "
                "(use from_dict/from_directory)")
        self._data = data
        self._directory = directory
        self.id = uuid.uuid4().hex[:8]

    # -- constructors -----------------------------------------------------

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        return cls(data=dict(data))

    @classmethod
    def from_directory(cls, directory: str) -> "Checkpoint":
        return cls(directory=str(directory))

    @classmethod
    def from_sharded_state(cls, state: Any, directory: str,
                           extra: Optional[Dict[str, Any]] = None
                           ) -> "Checkpoint":
        """Write a (possibly mesh-sharded) jax pytree with orbax and return a
        directory checkpoint. Each host writes only its shards."""
        import logging
        logging.getLogger("absl").setLevel(logging.WARNING)
        import orbax.checkpoint as ocp

        directory = os.path.abspath(directory)
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, _SHARDED_DIR)
        if os.path.exists(path):
            shutil.rmtree(path)
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(path, state)
        ckptr.wait_until_finished()
        meta = {"format": "orbax", "extra": extra or {}}
        with open(os.path.join(directory, _METADATA_FILE), "w") as f:
            json.dump(meta, f)
        return cls.from_directory(directory)

    # -- accessors --------------------------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        if self._data is not None:
            return dict(self._data)
        path = os.path.join(self._directory, _DICT_FILE)
        if os.path.exists(path):
            with open(path, "rb") as f:
                return pickle.load(f)
        raise ValueError(
            f"Checkpoint at {self._directory} has no dict form "
            f"(missing {_DICT_FILE}); use restore_sharded_state for orbax "
            "checkpoints.")

    def to_directory(self, path: Optional[str] = None) -> str:
        if self._directory is not None:
            if path and os.path.abspath(path) != self._directory:
                shutil.copytree(self._directory, path, dirs_exist_ok=True)
                return path
            return self._directory
        path = path or tempfile.mkdtemp(prefix="ray_tpu_ckpt_")
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, _DICT_FILE), "wb") as f:
            pickle.dump(self._data, f)
        return path

    def restore_sharded_state(self, target: Any) -> Any:
        """Restore an orbax checkpoint into the sharding layout of `target`
        (an abstract or concrete pytree with shardings)."""
        import logging
        logging.getLogger("absl").setLevel(logging.WARNING)
        import orbax.checkpoint as ocp

        if self._directory is None:
            raise ValueError("Sharded restore requires a directory checkpoint")
        path = os.path.join(self._directory, _SHARDED_DIR)
        ckptr = ocp.StandardCheckpointer()
        return ckptr.restore(path, target)

    @property
    def extra_metadata(self) -> Dict[str, Any]:
        if self._directory is None:
            return {}
        path = os.path.join(self._directory, _METADATA_FILE)
        if os.path.exists(path):
            with open(path) as f:
                return json.load(f).get("extra", {})
        return {}

    def __repr__(self):
        src = self._directory if self._directory else "<dict>"
        return f"Checkpoint(id={self.id}, source={src})"
