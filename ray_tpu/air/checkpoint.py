"""AIR Checkpoint: dict / directory / sharded-array / URI forms.

Analog of the reference's python/ray/air/checkpoint.py:63 (Checkpoint with
to_dict/from_dict/to_directory/from_directory/uri conversions). The TPU-native
addition is first-class **sharded jax pytrees** via orbax — a 6B-param state
sharded over a mesh round-trips without ever being gathered onto one host
(`from_sharded_state` / `restore_sharded_state`).

URI checkpoints (``to_uri``/``from_uri``) persist the payload through the
pluggable spill backends (``file://`` / ``session://`` / ``mock-s3://``,
_private/spill.py) with crash-safe atomic writes, so a gang restart can
restore from a location that survives the reporting node's death. A
``from_uri`` checkpoint is lazy: nothing is fetched until the first
``to_dict``/``to_directory``/``restore_sharded_state``, so handing one to
every rank of a restarted gang costs one small pickle, not one payload
copy per rank.
"""

from __future__ import annotations

import io
import json
import os
import pickle
import shutil
import tarfile
import tempfile
import uuid
from typing import Any, Dict, Optional

_DICT_FILE = "checkpoint_dict.pkl"
_METADATA_FILE = "ckpt_metadata.json"
_SHARDED_DIR = "sharded_state"

# URI-payload envelope versioning (one pickled dict per checkpoint file).
_PAYLOAD_KIND_DICT = "dict"
_PAYLOAD_KIND_DIR = "directory"


class Checkpoint:
    def __init__(self, data: Optional[Dict[str, Any]] = None,
                 directory: Optional[str] = None,
                 uri: Optional[str] = None):
        if sum(x is not None for x in (data, directory, uri)) != 1:
            raise ValueError(
                "Provide exactly one of data=, directory= or uri= "
                "(use from_dict/from_directory/from_uri)")
        self._data = data
        self._directory = directory
        self._uri = uri
        self.id = uuid.uuid4().hex[:8]

    # -- constructors -----------------------------------------------------

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        return cls(data=dict(data))

    @classmethod
    def from_directory(cls, directory: str) -> "Checkpoint":
        return cls(directory=str(directory))

    @classmethod
    def from_uri(cls, uri: str) -> "Checkpoint":
        """A lazy handle on a checkpoint persisted at a spill URI
        (``to_uri``'s return value). The payload is fetched on first
        access, from any process that can resolve the URI's backend."""
        return cls(uri=str(uri))

    @classmethod
    def from_sharded_state(cls, state: Any, directory: str,
                           extra: Optional[Dict[str, Any]] = None
                           ) -> "Checkpoint":
        """Write a (possibly mesh-sharded) jax pytree with orbax and return a
        directory checkpoint. Each host writes only its shards."""
        import logging
        logging.getLogger("absl").setLevel(logging.WARNING)
        import orbax.checkpoint as ocp

        directory = os.path.abspath(directory)
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, _SHARDED_DIR)
        if os.path.exists(path):
            shutil.rmtree(path)
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(path, state)
        ckptr.wait_until_finished()
        meta = {"format": "orbax", "extra": extra or {}}
        with open(os.path.join(directory, _METADATA_FILE), "w") as f:
            json.dump(meta, f)
        return cls.from_directory(directory)

    # -- URI persistence (durable checkpoints) ----------------------------

    @property
    def uri(self) -> Optional[str]:
        """The spill URI this checkpoint was persisted at/loaded from."""
        return self._uri

    def _payload_bytes(self) -> bytes:
        """One self-describing pickle: dict checkpoints carry the dict,
        directory checkpoints carry a tar of the tree (orbax sharded
        state included)."""
        self._hydrate()
        if self._data is not None:
            return pickle.dumps(
                {"kind": _PAYLOAD_KIND_DICT, "data": self._data},
                protocol=pickle.HIGHEST_PROTOCOL)
        buf = io.BytesIO()
        with tarfile.open(fileobj=buf, mode="w") as tar:
            tar.add(self._directory, arcname=".")
        return pickle.dumps(
            {"kind": _PAYLOAD_KIND_DIR, "tar": buf.getvalue()},
            protocol=pickle.HIGHEST_PROTOCOL)

    def to_uri(self, uri: str) -> str:
        """Persist this checkpoint's payload at a spill URI (crash-safe
        atomic write through the URI's backend: ``file://`` /
        ``session://`` / ``mock-s3://`` or any registered scheme).
        Returns the canonical URI — feed it to :meth:`from_uri` on any
        node that can resolve the backend."""
        from ray_tpu._private import spill
        backend = spill.reader_for_uri(uri)
        if backend is None:
            raise ValueError(f"no spill backend can write {uri!r}")
        _, rest = uri.partition("://")[::2]
        filename = os.path.basename(rest.rstrip("/"))
        if not filename:
            raise ValueError(f"checkpoint URI needs a filename: {uri!r}")
        out = backend.write(filename, self._payload_bytes())
        self._uri = out
        return out

    def _hydrate(self) -> None:
        """Materialize a lazy URI checkpoint into dict/directory form."""
        if self._data is not None or self._directory is not None:
            return
        from ray_tpu._private import spill
        payload = spill.read_uri(self._uri)
        if payload is None:
            raise ValueError(
                f"Checkpoint payload at {self._uri} is missing or "
                "unreadable (storage lost after the run that wrote it?)")
        envelope = pickle.loads(payload)
        if envelope.get("kind") == _PAYLOAD_KIND_DICT:
            self._data = envelope["data"]
            return
        directory = tempfile.mkdtemp(prefix="ray_tpu_ckpt_uri_")
        with tarfile.open(fileobj=io.BytesIO(envelope["tar"])) as tar:
            tar.extractall(directory)
        self._directory = directory

    # -- accessors --------------------------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        self._hydrate()
        if self._data is not None:
            return dict(self._data)
        path = os.path.join(self._directory, _DICT_FILE)
        if os.path.exists(path):
            with open(path, "rb") as f:
                return pickle.load(f)
        raise ValueError(
            f"Checkpoint at {self._directory} has no dict form "
            f"(missing {_DICT_FILE}); use restore_sharded_state for orbax "
            "checkpoints.")

    def to_directory(self, path: Optional[str] = None) -> str:
        self._hydrate()
        if self._directory is not None:
            if path and os.path.abspath(path) != self._directory:
                shutil.copytree(self._directory, path, dirs_exist_ok=True)
                return path
            return self._directory
        path = path or tempfile.mkdtemp(prefix="ray_tpu_ckpt_")
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, _DICT_FILE), "wb") as f:
            pickle.dump(self._data, f)
        return path

    def restore_sharded_state(self, target: Any) -> Any:
        """Restore an orbax checkpoint into the sharding layout of `target`
        (an abstract or concrete pytree with shardings)."""
        import logging
        logging.getLogger("absl").setLevel(logging.WARNING)
        import orbax.checkpoint as ocp

        self._hydrate()
        if self._directory is None:
            raise ValueError("Sharded restore requires a directory checkpoint")
        path = os.path.join(self._directory, _SHARDED_DIR)
        ckptr = ocp.StandardCheckpointer()
        return ckptr.restore(path, target)

    @property
    def extra_metadata(self) -> Dict[str, Any]:
        if self._uri is not None:
            self._hydrate()
        if self._directory is None:
            return {}
        path = os.path.join(self._directory, _METADATA_FILE)
        if os.path.exists(path):
            with open(path) as f:
                return json.load(f).get("extra", {})
        return {}

    def __repr__(self):
        src = self._uri or self._directory or "<dict>"
        return f"Checkpoint(id={self.id}, source={src})"
