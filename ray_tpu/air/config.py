"""AIR configs: ScalingConfig / RunConfig / FailureConfig / CheckpointConfig.

Analog of the reference's python/ray/air/config.py. The TPU-native
ScalingConfig speaks chips and mesh axes instead of GPUs: ``use_tpu`` +
``tpus_per_worker`` reserve chips, and ``mesh`` carries the parallelism
layout the trainer should build (one worker per TPU host; in-worker
parallelism is the mesh's job, not the worker count's).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class ScalingConfig:
    """How many train workers and what each reserves.

    reference: python/ray/air/config.py ScalingConfig (num_workers,
    use_gpu, resources_per_worker, trainer_resources).
    """

    num_workers: int = 1
    # Elastic lower bound: after a failure, the gang may restart with
    # fewer workers (down to this) when the cluster shrank and the full
    # complement cannot be re-placed within RAY_TPU_train_restart_wait_s.
    # None -> no elasticity (restart always needs num_workers).
    min_workers: Optional[int] = None
    use_tpu: bool = False
    tpus_per_worker: Optional[float] = None
    resources_per_worker: Optional[Dict[str, float]] = None
    trainer_resources: Optional[Dict[str, float]] = None
    placement_strategy: str = "PACK"
    # TPU-native: the mesh each worker should build over its chips
    # (a parallel.MeshConfig); None -> pure DP over workers.
    mesh: Optional[Any] = None
    # Runtime env for each train worker actor. {"worker_process": True}
    # puts every rank in its own OS process — required for true
    # multi-controller jax.distributed training on one host.
    runtime_env: Optional[Dict[str, Any]] = None

    @property
    def use_gpu(self) -> bool:  # reference-compat alias
        return self.use_tpu

    def worker_resources(self) -> Dict[str, float]:
        resources = dict(self.resources_per_worker or {})
        resources.setdefault("CPU", 1.0)
        if self.use_tpu:
            resources.setdefault(
                "TPU", self.tpus_per_worker
                if self.tpus_per_worker is not None else 1.0)
        return resources

    def as_placement_group_bundles(self):
        return [self.worker_resources() for _ in range(self.num_workers)]


@dataclass
class FailureConfig:
    """reference: air/config.py FailureConfig (max_failures).

    ``max_failures`` bounds gang restarts: 0 fails fast on the first
    failure (the original cause stays chained on the raised
    ``TrainingFailedError``), N allows N restarts, and -1 retries
    forever (reference semantics). Every restart resumes from the
    newest checkpoint reported so far — the durable one persisted under
    ``RunConfig.storage_path`` when configured, else the in-memory
    latest."""

    max_failures: int = 0


@dataclass
class CheckpointConfig:
    """reference: air/config.py CheckpointConfig."""

    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"
    checkpoint_frequency: int = 0
    checkpoint_at_end: bool = False


@dataclass
class RunConfig:
    """reference: air/config.py RunConfig."""

    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: Optional[FailureConfig] = None
    checkpoint_config: Optional[CheckpointConfig] = None
    stop: Optional[Dict[str, Any]] = None
    verbose: int = 1
    # Tune Callback instances (reference: air/config.py RunConfig.callbacks)
    callbacks: Optional[list] = None
