"""Train/Tune session: the in-loop API (report, world rank, checkpoint).

Analog of the reference's python/ray/air/session.py:41 (session.report) and
train/_internal/session.py (_TrainSession's bounded result queue). Each train
worker / trial has a _Session bound to its execution context; ``report``
blocks on a size-1 queue until the driver consumes the result — exactly the
reference's backpressure semantics.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Dict, Optional

from ray_tpu.air.checkpoint import Checkpoint


class StopSession(BaseException):
    """Raised inside report() when the driver stopped this worker/trial
    (e.g. an early-stopping scheduler). Inherits BaseException so user
    ``except Exception`` blocks don't swallow it."""


class _Session:
    def __init__(self, world_rank: int = 0, world_size: int = 1,
                 local_rank: int = 0, trial_id: str = "",
                 trial_name: str = "", config: Optional[dict] = None,
                 checkpoint: Optional[Checkpoint] = None,
                 dataset_shards: Optional[dict] = None):
        self.world_rank = world_rank
        self.world_size = world_size
        self.local_rank = local_rank
        self.trial_id = trial_id
        self.trial_name = trial_name
        self.config = config or {}
        self.loaded_checkpoint = checkpoint
        self.dataset_shards = dataset_shards or {}
        # Size-1 queue: the worker blocks in report() until the driver drains
        # (reference: train/_internal/session.py:63 queue.Queue(1)).
        self.result_queue: "queue.Queue" = queue.Queue(1)
        self.continue_event = threading.Event()
        self.stop_requested = False
        self.finished = False

    def report(self, metrics: Dict[str, Any],
               checkpoint: Optional[Checkpoint] = None) -> None:
        if self.stop_requested:
            raise StopSession()
        self.result_queue.put({"metrics": dict(metrics),
                               "checkpoint": checkpoint})
        self.continue_event.wait()
        self.continue_event.clear()
        if self.stop_requested:
            raise StopSession()


# One session per OS thread: train workers are actor threads, so
# thread-local storage gives each worker its own session.
_local = threading.local()


def _set_session(session: Optional[_Session]) -> None:
    _local.session = session


def _get_session() -> Optional[_Session]:
    return getattr(_local, "session", None)


def _require_session() -> _Session:
    s = _get_session()
    if s is None:
        raise RuntimeError(
            "No session active: this API must be called inside a train loop "
            "or Tune trainable run by JaxTrainer/Tuner.")
    return s


# -- public API (reference: air/session.py) ------------------------------

def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    _require_session().report(metrics, checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    return _require_session().loaded_checkpoint


def get_world_rank() -> int:
    return _require_session().world_rank


def get_world_size() -> int:
    return _require_session().world_size


def get_local_rank() -> int:
    return _require_session().local_rank


def get_trial_id() -> str:
    return _require_session().trial_id


def get_trial_name() -> str:
    return _require_session().trial_name


def get_config() -> dict:
    return dict(_require_session().config)


def get_dataset_shard(name: str = "train"):
    shard = _require_session().dataset_shards.get(name)
    if shard is None:
        raise KeyError(
            f"No dataset shard named {name!r} was passed to the trainer "
            f"(available: {list(_require_session().dataset_shards)})")
    return shard
