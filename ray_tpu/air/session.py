"""Train/Tune session: the in-loop API (report, world rank, checkpoint).

Analog of the reference's python/ray/air/session.py:41 (session.report) and
train/_internal/session.py (_TrainSession's bounded result queue). Each train
worker / trial has a _Session bound to its execution context; ``report``
blocks on a size-1 queue until the driver consumes the result — exactly the
reference's backpressure semantics.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Dict, Optional

from ray_tpu.air.checkpoint import Checkpoint


class StopSession(BaseException):
    """Raised inside report() when the driver stopped this worker/trial
    (e.g. an early-stopping scheduler). Inherits BaseException so user
    ``except Exception`` blocks don't swallow it."""


class _Session:
    def __init__(self, world_rank: int = 0, world_size: int = 1,
                 local_rank: int = 0, trial_id: str = "",
                 trial_name: str = "", config: Optional[dict] = None,
                 checkpoint: Optional[Checkpoint] = None,
                 dataset_shards: Optional[dict] = None,
                 ckpt_ctx: Optional[dict] = None):
        self.world_rank = world_rank
        self.world_size = world_size
        self.local_rank = local_rank
        self.trial_id = trial_id
        self.trial_name = trial_name
        self.config = config or {}
        self.loaded_checkpoint = checkpoint
        self.dataset_shards = dataset_shards or {}
        # Sharded-checkpoint context from the BackendExecutor: the run
        # name, storage URI, and agreed seq base every rank writes its
        # shard files under (see report_sharded).
        self.ckpt_ctx = ckpt_ctx
        # Set by the TrainWorker so a chaos kill fired inside a shard
        # write makes the whole rank play dead, not just the one call.
        self.on_chaos_kill = None
        self._shard_reports = 0
        self._shard_backend = None
        # Size-1 queue: the worker blocks in report() until the driver drains
        # (reference: train/_internal/session.py:63 queue.Queue(1)).
        self.result_queue: "queue.Queue" = queue.Queue(1)
        self.continue_event = threading.Event()
        self.stop_requested = False
        self.finished = False

    def report(self, metrics: Dict[str, Any],
               checkpoint: Optional[Checkpoint] = None,
               shard: Optional[dict] = None) -> None:
        if self.stop_requested:
            raise StopSession()
        result = {"metrics": dict(metrics), "checkpoint": checkpoint}
        if shard is not None:
            result["shard"] = shard
        self.result_queue.put(result)
        self.continue_event.wait()
        self.continue_event.clear()
        if self.stop_requested:
            raise StopSession()

    def report_sharded(self, metrics: Dict[str, Any], state: Any,
                       specs: Optional[dict] = None,
                       axes_items=None,
                       extra: Optional[Dict[str, Any]] = None) -> None:
        """Report metrics plus THIS RANK's checkpoint shard.

        Phase one of the two-phase sharded save: the rank extracts its
        local parameter blocks from ``state`` (per ``specs``; default:
        dim 0 of every array over an ``fsdp`` axis of ``world_size``)
        and writes one ``.shard-<rank>`` file through the run's spill
        backend. The shard record rides the ordinary result payload to
        the driver as the write's ack; the driver commits the manifest
        only once every rank acked. A failed write reports
        ``{"error": ...}`` instead — the driver fails that save attempt
        cleanly and training continues from the previous checkpoint.
        """
        from ray_tpu._private import chaos, spill
        from ray_tpu.train._internal import sharded_checkpoint as sc
        ctx = self.ckpt_ctx
        if ctx is None:
            raise RuntimeError(
                "report_sharded needs a sharded-checkpoint context: run "
                "under a trainer with RunConfig.storage_path set")
        if self._shard_backend is None:
            self._shard_backend = spill.backend_for_uri(
                ctx["storage_uri"], session_id=ctx.get("session_id", ""))
        seq = int(ctx["seq_base"]) + self._shard_reports
        self._shard_reports += 1
        if axes_items is None:
            axes_items = [("fsdp", self.world_size)]
        flat, structure = sc.flatten_tree(state)
        if specs is None:
            specs = sc.default_specs(flat, axis=axes_items[0][0])
        try:
            local = sc.extract_local_shard(flat, specs, axes_items,
                                           self.world_rank)
            record = sc.write_shard(self._shard_backend, ctx["run"], seq,
                                    self.world_rank, local)
        except chaos.ChaosKill:
            if self.on_chaos_kill is not None:
                self.on_chaos_kill()
            raise
        except spill.SpillFailure as exc:
            record = {"seq": seq, "rank": self.world_rank,
                      "error": str(exc)}
        if self.world_rank == 0 and "error" not in record:
            record["tree_meta"] = sc.build_tree_meta(
                flat, structure, specs, axes_items, extra)
        self.report(metrics, shard=record)


# One session per OS thread: train workers are actor threads, so
# thread-local storage gives each worker its own session.
_local = threading.local()


def _set_session(session: Optional[_Session]) -> None:
    _local.session = session


def _get_session() -> Optional[_Session]:
    return getattr(_local, "session", None)


def _require_session() -> _Session:
    s = _get_session()
    if s is None:
        raise RuntimeError(
            "No session active: this API must be called inside a train loop "
            "or Tune trainable run by JaxTrainer/Tuner.")
    return s


# -- public API (reference: air/session.py) ------------------------------

def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    _require_session().report(metrics, checkpoint)


def report_sharded(metrics: Dict[str, Any], state: Any,
                   specs: Optional[dict] = None, axes_items=None,
                   extra: Optional[Dict[str, Any]] = None) -> None:
    """Report metrics + this rank's shard of ``state`` (per-rank sharded
    checkpointing; commits when every rank of the round has reported)."""
    _require_session().report_sharded(metrics, state, specs=specs,
                                      axes_items=axes_items, extra=extra)


def get_checkpoint() -> Optional[Checkpoint]:
    return _require_session().loaded_checkpoint


def get_world_rank() -> int:
    return _require_session().world_rank


def get_world_size() -> int:
    return _require_session().world_size


def get_local_rank() -> int:
    return _require_session().local_rank


def get_trial_id() -> str:
    return _require_session().trial_id


def get_trial_name() -> str:
    return _require_session().trial_name


def get_config() -> dict:
    return dict(_require_session().config)


def get_dataset_shard(name: str = "train"):
    shard = _require_session().dataset_shards.get(name)
    if shard is None:
        raise KeyError(
            f"No dataset shard named {name!r} was passed to the trainer "
            f"(available: {list(_require_session().dataset_shards)})")
    return shard
