"""Ray DAG API: lazily-bound task/actor call graphs.

Analog of the reference's python/ray/dag/ (FunctionNode, ClassNode,
InputNode, dag_node.py execute): ``fn.bind(*args)`` builds a DAG node
instead of submitting; ``node.execute(input)`` walks the graph, submits
every bound call as a task with parent ObjectRefs as arguments, and returns
the root's ObjectRef. Workflows compile these DAGs into durable executions
(ray_tpu/workflow).
"""

from __future__ import annotations

import uuid
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["DAGNode", "FunctionNode", "InputNode", "ClassNode",
           "ClassMethodNode", "InputAttributeNode"]


class DAGNode:
    def __init__(self):
        self._stable_uuid = uuid.uuid4().hex

    def execute(self, *args, **kwargs):
        """Execute the DAG rooted here; returns ObjectRef (or value for
        InputNode)."""
        cache: Dict[str, Any] = {}
        input_value = args[0] if args else None
        return self._execute_recursive(cache, input_value)

    def _execute_recursive(self, cache: Dict[str, Any], input_value):
        raise NotImplementedError

    def _resolve_arg(self, arg, cache, input_value):
        if isinstance(arg, DAGNode):
            return arg._execute_recursive(cache, input_value)
        return arg


class InputNode(DAGNode):
    """Placeholder for the runtime input (reference: dag/input_node.py).
    Supports context-manager style: ``with InputNode() as inp:``."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def __getattr__(self, item):
        if item.startswith("_"):
            raise AttributeError(item)
        return InputAttributeNode(self, item)

    def __getitem__(self, key):
        return InputAttributeNode(self, key, is_item=True)

    def _execute_recursive(self, cache, input_value):
        return input_value


class InputAttributeNode(DAGNode):
    def __init__(self, parent: InputNode, key, is_item: bool = False):
        super().__init__()
        self._parent = parent
        self._key = key
        self._is_item = is_item

    def _execute_recursive(self, cache, input_value):
        value = self._parent._execute_recursive(cache, input_value)
        if self._is_item:
            return value[self._key]
        return getattr(value, self._key)


class FunctionNode(DAGNode):
    def __init__(self, remote_function, args: Tuple, kwargs: Dict):
        super().__init__()
        self._remote_function = remote_function
        self._bound_args = args
        self._bound_kwargs = kwargs

    def _execute_recursive(self, cache, input_value):
        if self._stable_uuid in cache:
            return cache[self._stable_uuid]
        args = [self._resolve_arg(a, cache, input_value)
                for a in self._bound_args]
        kwargs = {k: self._resolve_arg(v, cache, input_value)
                  for k, v in self._bound_kwargs.items()}
        ref = self._remote_function.remote(*args, **kwargs)
        cache[self._stable_uuid] = ref
        return ref

    # -- workflow compilation hooks -------------------------------------

    @property
    def fn(self):
        return self._remote_function

    @property
    def bound_args(self):
        return self._bound_args

    @property
    def bound_kwargs(self):
        return self._bound_kwargs

    def get_options(self) -> dict:
        return dict(self._remote_function._default_options)


class ClassNode(DAGNode):
    """A bound actor constructor; method calls on it create
    ClassMethodNodes (reference: dag/class_node.py)."""

    def __init__(self, actor_class, args: Tuple, kwargs: Dict):
        super().__init__()
        self._actor_class = actor_class
        self._bound_args = args
        self._bound_kwargs = kwargs

    def __getattr__(self, item):
        if item.startswith("_"):
            raise AttributeError(item)
        return _UnboundMethod(self, item)

    def _execute_recursive(self, cache, input_value):
        if self._stable_uuid in cache:
            return cache[self._stable_uuid]
        args = [self._resolve_arg(a, cache, input_value)
                for a in self._bound_args]
        kwargs = {k: self._resolve_arg(v, cache, input_value)
                  for k, v in self._bound_kwargs.items()}
        handle = self._actor_class.remote(*args, **kwargs)
        cache[self._stable_uuid] = handle
        return handle


class _UnboundMethod:
    def __init__(self, class_node: ClassNode, method_name: str):
        self._class_node = class_node
        self._method_name = method_name

    def bind(self, *args, **kwargs) -> "ClassMethodNode":
        return ClassMethodNode(self._class_node, self._method_name, args,
                               kwargs)


class ClassMethodNode(DAGNode):
    def __init__(self, class_node: ClassNode, method_name: str,
                 args: Tuple, kwargs: Dict):
        super().__init__()
        self._class_node = class_node
        self._method_name = method_name
        self._bound_args = args
        self._bound_kwargs = kwargs

    def _execute_recursive(self, cache, input_value):
        if self._stable_uuid in cache:
            return cache[self._stable_uuid]
        handle = self._class_node._execute_recursive(cache, input_value)
        args = [self._resolve_arg(a, cache, input_value)
                for a in self._bound_args]
        kwargs = {k: self._resolve_arg(v, cache, input_value)
                  for k, v in self._bound_kwargs.items()}
        ref = getattr(handle, self._method_name).remote(*args, **kwargs)
        cache[self._stable_uuid] = ref
        return ref
