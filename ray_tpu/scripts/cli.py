"""ray-tpu CLI.

Analog of the reference's python/ray/scripts/scripts.py subset
(`ray status/memory/timeline/list`, scripts.py:529,2390-2403) plus
`bench`. argparse instead of click (no extra deps); single-node commands
initialize a local runtime on demand.
"""

from __future__ import annotations

import argparse
import json
import sys


def _ensure_init():
    import ray_tpu
    if not ray_tpu.is_initialized():
        ray_tpu.init()


def cmd_status(args) -> int:
    _ensure_init()
    from ray_tpu._private.state import status_summary
    print(status_summary())
    return 0


def cmd_memory(args) -> int:
    _ensure_init()
    from ray_tpu._private.state import memory_summary
    print(memory_summary())
    return 0


def _fmt_bytes(n) -> str:
    if n is None:
        return "-"
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if n < 1024 or unit == "TiB":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024.0
    return f"{n:.1f}TiB"


def _render_top_frame(snap: dict) -> str:
    """One `ray-tpu top` frame from runtime.top_snapshot(): every number
    is a windowed derivation from the head's time-series store."""
    lines = []
    tasks = snap.get("tasks", {})
    objects = snap.get("objects", {})
    ts_meta = snap.get("timeseries", {})
    lines.append(
        f"ray-tpu top — window {snap.get('window_s', 0):g}s — "
        f"{len(snap.get('nodes', []))} node(s) — "
        f"{ts_meta.get('series', 0)} series "
        f"({ts_meta.get('dropped_series', 0)} dropped)")
    alerts = snap.get("alerts", {})
    if alerts.get("firing_count"):
        rules = ", ".join(sorted(set(alerts.get("rules", []))))
        lines.append(f"ALERTS FIRING: {alerts['firing_count']} ({rules})")
    lines.append(
        f"tasks/s  submitted {tasks.get('submitted_per_s', 0.0):.2f}  "
        f"finished {tasks.get('finished_per_s', 0.0):.2f}  "
        f"failed {tasks.get('failed_per_s', 0.0):.2f}")
    lines.append(
        f"objects  store {_fmt_bytes(objects.get('store_bytes'))}  "
        f"spill/s {_fmt_bytes(objects.get('spill_bytes_per_s'))}  "
        f"restores/s {objects.get('restores_per_s', 0.0):.2f}")
    xfer = snap.get("transfer") or {}
    if xfer.get("links_active"):
        top_link = xfer.get("top_link") or {}
        line = (f"transfer {xfer.get('mbps_total', 0.0):.2f}MB/s over "
                f"{xfer['links_active']} link(s)")
        if top_link:
            line += (f"  top {top_link.get('src', '')[:12]}->"
                     f"{top_link.get('dst', '')[:12]} "
                     f"{top_link.get('mbps', 0.0):.2f}MB/s")
        hot = xfer.get("max_fanout") or {}
        if hot:
            line += (f"  fanout {hot.get('key', '')[:16]} x"
                     f"{hot.get('fanout', 0)}")
        lines.append(line)
    loops = snap.get("loops", {})
    if loops:
        lines.append("loop lag  " + "  ".join(
            f"{name} {lag * 1000:.1f}ms"
            for name, lag in sorted(loops.items())))
    nodes = snap.get("nodes", [])
    if nodes:
        lines.append("")
        rows = []
        for n in nodes:
            cpu = n.get("resources", {}).get("CPU", 0)
            rows.append((
                n.get("node_id", "")[:12],
                "yes" if n.get("alive") else "NO",
                "-" if n.get("epoch") is None else str(n["epoch"]),
                "-" if n.get("phi") is None else f"{n['phi']:.2f}",
                "-" if n.get("last_heartbeat_age_s") is None
                else f"{n['last_heartbeat_age_s']:.1f}s",
                f"{cpu:g}",
                _fmt_bytes(n.get("rss_bytes")),
                f"{n.get('tasks_submitted_per_s', 0.0):.2f}",
                f"{n.get('tasks_finished_per_s', 0.0):.2f}",
            ))
        hdr = ("NODE", "ALIVE", "EPOCH", "PHI", "HB_AGE", "CPU",
               "RSS", "SUB/S", "FIN/S")
        widths = [max(len(hdr[i]), *(len(r[i]) for r in rows))
                  for i in range(len(hdr))]
        fmt = "  ".join(f"{{:<{w}}}" for w in widths)
        lines.append(fmt.format(*hdr))
        for r in rows:
            lines.append(fmt.format(*r))
    serve = snap.get("serve", {})
    if serve:
        lines.append("")
        rows = []
        for name in sorted(serve):
            d = serve[name]
            target = d.get("target_replicas")
            rows.append((
                name,
                str(d.get("replicas", 0)),
                "-" if target is None else str(target),
                f"{d.get('qps', 0.0):.2f}",
                f"{d.get('p50_s', 0.0) * 1000:.1f}ms",
                f"{d.get('p95_s', 0.0) * 1000:.1f}ms",
                f"{d.get('mean_queue_depth', 0.0):.1f}",
            ))
        hdr = ("DEPLOYMENT", "REPLICAS", "TARGET", "QPS", "P50", "P95",
               "QUEUE")
        widths = [max(len(hdr[i]), *(len(r[i]) for r in rows))
                  for i in range(len(hdr))]
        fmt = "  ".join(f"{{:<{w}}}" for w in widths)
        lines.append(fmt.format(*hdr))
        for r in rows:
            lines.append(fmt.format(*r))
    return "\n".join(lines)


def cmd_top(args) -> int:
    """`ray-tpu top [--once] [--interval S] [--window S] [--json]` —
    live cluster view rendered entirely from the head's windowed
    time-series store: per-node usage/epoch/suspicion + task rates,
    object-store bytes and spill rate, per-deployment qps/p95/queue,
    control-loop lag."""
    import time as _time

    _ensure_init()
    from ray_tpu._private.worker import global_worker
    rt = global_worker.runtime
    while True:
        snap = rt.top_snapshot(window=args.window)
        if args.json:
            print(json.dumps(snap, indent=2, default=str))
        else:
            if not args.once:
                print("\x1b[2J\x1b[H", end="")  # clear + home
            print(_render_top_frame(snap))
        if args.once:
            return 0
        try:
            _time.sleep(max(0.2, args.interval))
        except KeyboardInterrupt:
            return 0


def cmd_timeline(args) -> int:
    _ensure_init()
    from ray_tpu._private.state import timeline
    out = args.output or "timeline.json"
    events = timeline(out)
    print(f"Wrote {len(events)} events to {out}")
    return 0


def cmd_trace(args) -> int:
    """`ray-tpu trace [--id TRACE_ID | --tail N | --summary]
    [--perfetto out.json]` — inspect assembled distributed traces (the
    head merges spans shipped on metrics frames per trace_id; see
    /api/traces). Default lists recent traces; --id shows one trace's
    span tree + stage breakdown; --summary prints the cluster-level
    critical-path attribution; --perfetto writes Chrome-trace JSON with
    cross-process flow arrows for ui.perfetto.dev."""
    _ensure_init()
    from ray_tpu._private.worker import global_worker
    rt = global_worker.runtime

    def _fmt_s(sec):
        return f"{sec * 1000:.2f}ms" if sec < 1.0 else f"{sec:.3f}s"

    if args.perfetto:
        events = rt.trace_perfetto(args.id)
        if not events:
            print("no matching trace spans" if args.id
                  else "no trace spans assembled yet")
            return 1
        with open(args.perfetto, "w") as f:
            json.dump({"traceEvents": events}, f)
        print(f"Wrote {len(events)} events to {args.perfetto} "
              "(open in ui.perfetto.dev)")
        return 0
    if args.summary:
        summary = rt.trace_summary()
        print(f"traces assembled: {summary['traces']}")
        stages = summary["stages"]
        if not stages:
            return 0
        hdr = ("STAGE", "COUNT", "TOTAL", "SHARE", "P50", "P95")
        rows = [(stage, str(s["count"]), _fmt_s(s["total_s"]),
                 f"{s['share'] * 100:.1f}%", _fmt_s(s["p50_s"]),
                 _fmt_s(s["p95_s"]))
                for stage, s in sorted(stages.items(),
                                       key=lambda kv: -kv[1]["total_s"])]
        widths = [max(len(hdr[i]), *(len(r[i]) for r in rows))
                  for i in range(len(hdr))]
        fmt = "  ".join(f"{{:<{w}}}" for w in widths)
        print(fmt.format(*hdr))
        for r in rows:
            print(fmt.format(*r))
        return 0
    if args.id:
        trace = rt.trace_get(args.id)
        if trace is None:
            print(f"no trace {args.id!r}")
            return 1
        print(f"trace {trace['trace_id']}: {trace['span_count']} spans, "
              f"{_fmt_s(trace['duration_s'])} across "
              f"{len(trace['origins'])} origin(s)")
        for stage, s in sorted(trace["stages"].items(),
                               key=lambda kv: -kv[1]["total_s"]):
            print(f"  {stage:<14} x{s['count']:<4} "
                  f"{_fmt_s(s['total_s']):>10}  "
                  f"{s['share'] * 100:5.1f}%")
        # Indent each span under its parent (the cross-process chain).
        by_id = {s["span_id"]: s for s in trace["spans"]}
        t0 = trace["start_time"]

        def depth(span):
            d, seen = 0, set()
            while span.get("parent_id") in by_id:
                if span["span_id"] in seen:
                    break
                seen.add(span["span_id"])
                span = by_id[span["parent_id"]]
                d += 1
            return d
        for s in trace["spans"]:
            dur = s.get("duration") or 0.0
            origin = (f"{(s.get('node_id') or 'head')[:8]}/"
                      f"{s.get('component', '?')}-{s.get('pid', 0)}")
            print(f"  {'  ' * depth(s)}{s['name']} "
                  f"[+{_fmt_s(max(0.0, s['start_time'] - t0))} "
                  f"{_fmt_s(dur)}] @{origin}")
        return 0
    rows = rt.trace_list(args.tail)
    if not rows:
        print("no traces assembled yet (is tracing enabled and sampled?)")
        return 0
    for r in rows:
        print(f"{r['trace_id']}  {r['root']:<28} "
              f"{r['span_count']:>3} spans  "
              f"{_fmt_s(r['duration_s']):>10}  "
              f"origins={len(r['origins'])}")
    return 0


def cmd_list(args) -> int:
    _ensure_init()
    from ray_tpu.experimental.state import api
    fn = {
        "actors": api.list_actors,
        "tasks": api.list_tasks,
        "objects": api.list_objects,
        "nodes": api.list_nodes,
        "placement-groups": api.list_placement_groups,
    }[args.resource]
    print(json.dumps(fn(), indent=2, default=str))
    return 0


def cmd_actors(args) -> int:
    """`ray-tpu actors [--detached]` — list actors with lifetime;
    --detached shows only GCS-owned survivors (the ones an operator
    must `ray_tpu.kill()` explicitly post-mortem)."""
    _ensure_init()
    from ray_tpu.experimental.state import api
    filters = [("lifetime", "=", "detached")] if args.detached else None
    rows = api.list_actors(filters=filters)
    if args.json:
        print(json.dumps(rows, indent=2, default=str))
        return 0
    if not rows:
        print("no matching actors")
        return 0
    hdr = ("ACTOR_ID", "CLASS", "NAME", "NAMESPACE", "LIFETIME",
           "STATE", "RESTARTS")
    widths = [max(len(hdr[i]), *(len(str(r[k])) for r in rows))
              for i, k in enumerate(("actor_id", "class_name", "name",
                                     "namespace", "lifetime", "state",
                                     "num_restarts"))]
    fmt = "  ".join(f"{{:<{w}}}" for w in widths)
    print(fmt.format(*hdr))
    for r in rows:
        print(fmt.format(r["actor_id"], r["class_name"], r["name"],
                         r["namespace"], r["lifetime"], r["state"],
                         r["num_restarts"]))
    return 0


def cmd_summary(args) -> int:
    _ensure_init()
    from ray_tpu.experimental.state import api
    fn = {"tasks": api.summarize_tasks,
          "objects": api.summarize_objects}[args.resource]
    print(json.dumps(fn(), indent=2, default=str))
    return 0


def cmd_metrics(args) -> int:
    if getattr(args, "grafana", False):
        from ray_tpu.dashboard.grafana import generate_dashboard
        print(json.dumps(generate_dashboard(), indent=2))
        return 0
    _ensure_init()
    from ray_tpu._private.worker import global_worker
    runtime = getattr(global_worker, "_runtime", None)
    text_fn = getattr(runtime, "cluster_metrics_text", None)
    if text_fn is not None:
        # Cluster-wide exposition: every node/worker's series with
        # node_id/pid/component labels (what /metrics serves).
        print(text_fn())
    else:
        from ray_tpu.util.metrics import export_prometheus
        print(export_prometheus())
    return 0


def cmd_devices(args) -> int:
    import jax
    for d in jax.devices():
        print(f"{d.id}: {d.device_kind} (process {d.process_index}, "
              f"platform {d.platform})")
    return 0


def cmd_job(args) -> int:
    """`ray-tpu job submit/status/logs/stop/list` (analog of the reference's
    `ray job` CLI, dashboard/modules/job/cli.py)."""
    from ray_tpu.job_submission import JobSubmissionClient
    client = JobSubmissionClient(getattr(args, "address", None))
    if args.job_command == "submit":
        runtime_env = None
        if args.working_dir:
            runtime_env = {"working_dir": args.working_dir}
        import shlex
        entrypoint = list(args.entrypoint)
        if entrypoint and entrypoint[0] == "--":
            entrypoint = entrypoint[1:]
        job_id = client.submit_job(
            entrypoint=" ".join(shlex.quote(t) for t in entrypoint),
            runtime_env=runtime_env,
            submission_id=args.submission_id)
        print(job_id)
        if args.wait:
            for chunk in client.tail_job_logs(job_id, timeout=args.timeout):
                sys.stdout.write(chunk)
            status = client.get_job_status(job_id)
            print(f"Job {job_id} finished: {status.value}")
            return 0 if status.value == "SUCCEEDED" else 1
        return 0
    if args.job_command == "status":
        print(client.get_job_status(args.job_id).value)
        return 0
    if args.job_command == "logs":
        print(client.get_job_logs(args.job_id), end="")
        return 0
    if args.job_command == "stop":
        stopped = client.stop_job(args.job_id)
        print("stopped" if stopped else "already terminal")
        return 0
    if args.job_command == "list":
        for j in client.list_jobs():
            print(f"{j.submission_id}\t{j.status.value}\t{j.entrypoint}")
        return 0
    return 1


def cmd_logs(args) -> int:
    """`ray-tpu logs [filename] [--node/--pid/--tail/--follow]` —
    read the session's captured per-process logs from disk (reference:
    `ray logs`, scripts/scripts.py:2390). Deliberately does NOT
    initialize a runtime: it reads the CURRENT session when run inside
    a driver, else the newest ``session_latest`` on disk."""
    import time

    from ray_tpu.experimental.state import api
    kwargs = dict(filename=args.filename, node_id=args.node,
                  pid=args.pid)
    try:
        if args.list:
            for row in api.list_logs(node_id=args.node):
                print(f"{row['node']}\t{row['size_bytes']}\t"
                      f"{row['filename']}")
            return 0
        lines = api.get_log(tail=args.tail, **kwargs)
    except FileNotFoundError as exc:
        print(str(exc), file=sys.stderr)
        return 1
    for line in lines:
        print(line)
    if not args.follow:
        return 0
    seen = len(api.get_log(tail=-1, **kwargs))
    try:
        while True:
            time.sleep(1.0)
            all_lines = api.get_log(tail=-1, **kwargs)
            for line in all_lines[seen:]:
                print(line)
            seen = max(seen, len(all_lines))
    except KeyboardInterrupt:
        return 0


def cmd_alerts(args) -> int:
    """`ray-tpu alerts [--history] [--json]` — active alert instances
    (firing → pending → resolved) and the rule table from the head's
    alert engine; every number comes from the time-series store."""
    _ensure_init()
    from ray_tpu._private.worker import global_worker
    snap = global_worker.runtime.alerts_snapshot()
    if args.json:
        print(json.dumps(snap, indent=2, default=str))
        return 0
    print(f"alerting {'enabled' if snap.get('enabled') else 'DISABLED'} — "
          f"eval period {snap.get('period_s', 0):g}s — "
          f"{len(snap.get('rules', []))} rule(s) — "
          f"{len(snap.get('firing', []))} firing")
    alerts = snap.get("alerts", [])
    if alerts:
        rows = [(a.get("state", "").upper(), a.get("rule", ""),
                 a.get("key") or "-", a.get("severity", ""),
                 f"{a.get('value', 0):.4g}", f"{a.get('since_s', 0):.0f}s")
                for a in alerts]
        hdr = ("STATE", "RULE", "KEY", "SEVERITY", "VALUE", "SINCE")
        widths = [max(len(hdr[i]), *(len(r[i]) for r in rows))
                  for i in range(len(hdr))]
        fmt = "  ".join(f"{{:<{w}}}" for w in widths)
        print(fmt.format(*hdr))
        for r in rows:
            print(fmt.format(*r))
    else:
        print("no active alert instances")
    if args.history:
        for h in snap.get("history", []):
            print(f"{h.get('since_s', 0):>8.1f}s ago  "
                  f"{h.get('state', ''):<9} {h.get('rule', '')}"
                  f"[{h.get('key') or '-'}] value={h.get('value', 0):.4g}")
    return 0


def cmd_xfer(args) -> int:
    """`ray-tpu xfer [--links|--objects|--tree] [--window S] [--json]`
    — the dataplane flow plane: per-link transfer matrix (windowed
    MB/s, p95 latency, failovers/errors per src->dst node pair), the
    per-object pull fan-out table (broadcast amplification), and the
    last broadcast's spanning tree with per-edge MB/s."""
    _ensure_init()
    from ray_tpu._private.worker import global_worker
    snap = global_worker.runtime.flows_snapshot(window=args.window)
    if args.json:
        print(json.dumps(snap, indent=2, default=str))
        return 0
    if args.tree:
        bc = snap.get("broadcast")
        if not bc:
            print("no broadcast recorded")
            return 0
        print(f"last broadcast — key {bc.get('key', '?')[:32]}, "
              f"{_fmt_bytes(bc.get('size'))} to {bc.get('nodes', 0)} "
              f"node(s), fanout {bc.get('fanout', '?')}, depth "
              f"{bc.get('depth', 0)}, {bc.get('age_s', 0.0):.0f}s ago")
        children: dict = {}
        for e in bc.get("edges", []):
            children.setdefault(e.get("src", "?"), []).append(e)

        def _edge_line(e) -> str:
            secs = e.get("secs")
            rate = (f"{e.get('bytes', 0) / secs / 1e6:.1f} MB/s"
                    if secs else "-")
            line = f"{e.get('dst', '?')[:12]}  " \
                   f"[{'ok' if e.get('ok') else 'FAILED'}, {rate}"
            if e.get("failovers"):
                line += f", {e['failovers']} failover(s)"
            return line + "]"

        def _walk(src: str, prefix: str) -> None:
            kids = children.get(src, [])
            for i, e in enumerate(kids):
                last = i == len(kids) - 1
                print(prefix + ("`-- " if last else "|-- ")
                      + _edge_line(e))
                _walk(e.get("dst", ""),
                      prefix + ("    " if last else "|   "))

        root = bc.get("root", "head")
        print(root if root == "head" else root[:12])
        _walk(root, "")
        return 0
    stats = snap.get("stats", {})
    print(f"transfer ledger — window {snap.get('window_s', 0):g}s — "
          f"{stats.get('links', 0)} link(s), "
          f"{stats.get('objects', 0)} object(s), "
          f"{stats.get('records', 0)} record(s) merged")
    show_links = not args.objects
    show_objects = not args.links
    links = snap.get("links", [])
    if show_links:
        if links:
            rows = [(lk.get("src", "")[:12] or "-",
                     lk.get("dst", "")[:12] or "-",
                     f"{lk.get('mbps', 0.0):.2f}",
                     _fmt_bytes(lk.get("window_bytes")),
                     _fmt_bytes(lk.get("bytes_total")),
                     str(lk.get("records", 0)),
                     f"{lk.get('p95_s', 0.0) * 1000:.1f}ms",
                     str(lk.get("failovers", 0)),
                     str(lk.get("errors", 0)))
                    for lk in links]
            hdr = ("SRC", "DST", "MB/S", "WINDOW", "TOTAL", "PULLS",
                   "P95", "FAILOVER", "ERR")
            widths = [max(len(hdr[i]), *(len(r[i]) for r in rows))
                      for i in range(len(hdr))]
            fmt = "  ".join(f"{{:<{w}}}" for w in widths)
            print(fmt.format(*hdr))
            for r in rows:
                print(fmt.format(*r))
        else:
            print("no transfer links recorded")
    objects = snap.get("objects", [])
    if show_objects:
        if show_links:
            print()
        if objects:
            rows = [(o.get("key", "")[:24],
                     str(o.get("fanout", 0)),
                     str(len(o.get("nodes", []))),
                     _fmt_bytes(o.get("bytes_total")),
                     str(o.get("pulls", 0)))
                    for o in objects]
            hdr = ("OBJECT", "FANOUT", "NODES", "BYTES", "PULLS")
            widths = [max(len(hdr[i]), *(len(r[i]) for r in rows))
                      for i in range(len(hdr))]
            fmt = "  ".join(f"{{:<{w}}}" for w in widths)
            print(fmt.format(*hdr))
            for r in rows:
                print(fmt.format(*r))
        else:
            print("no object fan-out recorded")
    return 0


def cmd_events(args) -> int:
    """`ray-tpu events [--severity S] [--source S] [--node N]
    [--limit N] [--follow] [--json]` — the head's cluster event
    journal (membership, serve, train, spill, alert transitions)."""
    import time as _time

    _ensure_init()
    from ray_tpu._private.worker import global_worker
    rt = global_worker.runtime

    def _print(rows) -> None:
        for ev in rows:
            if args.json:
                print(json.dumps(ev, default=str))
                continue
            labels = ev.get("labels") or {}
            extra = " ".join(f"{k}={v}"
                             for k, v in sorted(labels.items()))
            node = (ev.get("node_id") or "")[:12] or "-"
            print(f"{ev.get('seq', 0):>6}  {ev.get('age_s', 0):>7.1f}s  "
                  f"{ev.get('severity', ''):<8} "
                  f"{ev.get('source', ''):<14} {node:<12}  "
                  f"{ev.get('message', '')}"
                  + (f"  [{extra}]" if extra else ""))

    try:
        rows = rt.cluster_events(severity=args.severity,
                                 source=args.source, node_id=args.node,
                                 limit=args.limit)
    except ValueError as exc:
        print(str(exc), file=sys.stderr)
        return 1
    _print(rows)
    if not args.follow:
        return 0
    last_seq = rows[-1]["seq"] if rows else 0
    try:
        while True:
            _time.sleep(1.0)
            fresh = rt.cluster_events(severity=args.severity,
                                      source=args.source,
                                      node_id=args.node,
                                      since_seq=last_seq)
            _print(fresh)
            if fresh:
                last_seq = fresh[-1]["seq"]
    except KeyboardInterrupt:
        return 0


def cmd_profile(args) -> int:
    """CPU profiles, four ways: this driver process (default), a node
    daemon (--node), any cluster worker by pid (--pid, cooperative —
    resolved through the owning daemon, no py-spy needed), or the whole
    cluster at once (--cluster, synchronized burst fanned to every live
    daemon + the head, merged). --report instead prints the loop-lag
    flight recorder's incidents. Writes a speedscope JSON (open at
    speedscope.app) or collapsed flamegraph stacks."""
    _ensure_init()
    import json as _json

    from ray_tpu._private.profiling import profile_self
    from ray_tpu._private.worker import global_worker
    runtime = global_worker.runtime
    if args.report:
        incidents = runtime.profile_incidents()
        if not incidents:
            print("no loop-lag incidents recorded")
            return 0
        for inc in incidents:
            print(f"loop={inc['loop']} lag={inc['lag_s']:.3f}s "
                  f"(threshold {inc['threshold_s']:.3f}s) "
                  f"component={inc['component'] or '?'} "
                  f"node={inc['node_id'][:8] or 'head'} "
                  f"pid={inc['pid']} scope={inc['scope']} "
                  f"{inc['age_s']:.0f}s ago")
            for stack, weight in inc["top_stacks"][:10]:
                print(f"  {weight:>8}  {stack}")
        return 0
    fmt = "speedscope" if args.output.endswith(".json") else "folded"
    if args.cluster:
        result = runtime.profile_cluster(args.duration, args.hz, fmt)
    elif args.pid is not None:
        try:
            result = runtime.profile_pid(args.pid, args.duration,
                                         args.hz, fmt)
        except ValueError as exc:
            print(exc)
            return 1
    elif args.node:
        conn = None
        for nid, c in runtime._remote_nodes.items():
            if nid.hex().startswith(args.node):
                conn = c
                break
        if conn is None:
            print(f"no live node matches {args.node!r}")
            return 1
        result = conn.profile(args.duration, args.hz, fmt)
    else:
        result = profile_self(args.duration, args.hz, fmt)
    with open(args.output, "w") as f:
        if fmt == "speedscope":
            _json.dump(result, f)
        else:
            f.write(result)
    print(f"Wrote {fmt} profile to {args.output}")
    return 0


def cmd_grafana(args) -> int:
    from ray_tpu.dashboard.grafana import write_dashboards
    for path in write_dashboards(args.out):
        print(f"Wrote {path}")
    return 0


def cmd_microbenchmark(args) -> int:
    """`ray-tpu microbenchmark` — the core ops/s suite (reference:
    release/microbenchmark/run_microbenchmark.py)."""
    from ray_tpu._private.ray_perf import main as perf_main
    perf_main(duration=args.duration)
    return 0


def cmd_start(args) -> int:
    """`ray-tpu start` — join (or head) a multi-process cluster
    (reference: `ray start --head/--address`, scripts/scripts.py:529)."""
    import json
    import time

    if args.head:
        import ray_tpu
        if not ray_tpu.is_initialized():
            ray_tpu.init(num_cpus=args.num_cpus, num_tpus=args.num_tpus,
                         _memory=args.memory,
                         resources=(json.loads(args.resources)
                                    if args.resources else None))
        host, port = ray_tpu.start_head_server(port=args.port,
                                               host=args.host)
        print(f"Head node listening for node daemons on {host}:{port}")
        print(f"Join with: ray-tpu start --address <this-host>:{port}")
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            ray_tpu.shutdown()
        return 0
    if not args.address:
        print("start requires --head or --address host:port",
              file=sys.stderr)
        return 1
    from ray_tpu._private.multinode import run_node
    run_node(args.address, num_cpus=args.num_cpus,
             num_tpus=args.num_tpus, memory=args.memory,
             resources=json.loads(args.resources) if args.resources
             else None,
             labels=json.loads(args.labels) if args.labels else None)
    return 0


def cmd_up(args) -> int:
    """`ray-tpu up cluster.yaml` (reference: `ray up`,
    scripts/scripts.py:1216)."""
    from ray_tpu.autoscaler.launcher import up
    out = up(args.config_file, no_head=args.no_head)
    print(f"cluster {out['cluster_name']}: created "
          f"{out['created']['head']} head, "
          f"{out['created']['workers']} workers; nodes now: "
          f"{out['nodes']}")
    return 0


def cmd_down(args) -> int:
    """`ray-tpu down cluster.yaml` (reference: `ray down`)."""
    from ray_tpu.autoscaler.launcher import down
    nodes = down(args.config_file)
    print(f"terminated {len(nodes)} nodes: {nodes}")
    return 0


def cmd_dashboard(args) -> int:
    """`ray-tpu dashboard` — run the HTTP observability endpoint."""
    import time

    import ray_tpu
    from ray_tpu.dashboard import start_dashboard
    if not ray_tpu.is_initialized():
        ray_tpu.init()
    head = start_dashboard(args.host, args.port)
    print(f"Dashboard listening on http://{args.host}:{head.bound_port}")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        head.stop()
    return 0


def cmd_serve(args) -> int:
    """`ray-tpu serve deploy/status/shutdown` (analog of the reference's
    `serve` CLI, serve/scripts.py)."""
    import json

    from ray_tpu import serve
    if args.serve_command == "deploy":
        from ray_tpu.serve.schema import apply_config
        with open(args.config_file) as f:
            config = json.load(f)
        apply_config(config)
        print("deployed")
        return 0
    if args.serve_command == "status":
        print(json.dumps(serve.status(), indent=2))
        return 0
    if args.serve_command == "shutdown":
        serve.shutdown()
        print("shut down")
        return 0
    return 1


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="ray-tpu",
        description="TPU-native distributed computing framework CLI")
    sub = parser.add_subparsers(dest="command", required=True)

    sub.add_parser("status", help="cluster resource + task summary")
    p = sub.add_parser("top", help="live cluster view from the head's "
                                   "windowed time-series store")
    p.add_argument("--once", action="store_true",
                   help="print one frame and exit")
    p.add_argument("--interval", type=float, default=2.0,
                   help="refresh period in seconds (default 2)")
    p.add_argument("--window", type=float, default=None,
                   help="derivation window in seconds (default 30)")
    p.add_argument("--json", action="store_true",
                   help="emit the raw snapshot as JSON")
    sub.add_parser("memory", help="object store summary")
    p = sub.add_parser("timeline", help="dump chrome://tracing JSON")
    p.add_argument("-o", "--output", default=None)
    p = sub.add_parser("trace", help="inspect assembled distributed "
                                     "traces (cross-process spans)")
    p.add_argument("--id", default=None,
                   help="show one trace's span tree + stage breakdown")
    p.add_argument("--tail", type=int, default=20,
                   help="list the N most recent traces (default 20)")
    p.add_argument("--summary", action="store_true",
                   help="cluster-level per-stage critical-path breakdown")
    p.add_argument("--perfetto", default=None, metavar="OUT_JSON",
                   help="write Chrome-trace JSON (slices + flow arrows); "
                        "combine with --id for a single trace")
    p = sub.add_parser("list", help="list cluster state")
    p.add_argument("resource", choices=["actors", "tasks", "objects",
                                        "nodes", "placement-groups"])
    p = sub.add_parser("actors", help="list actors (lifetime-aware)")
    p.add_argument("--detached", action="store_true",
                   help="only GCS-owned detached actors")
    p.add_argument("--json", action="store_true",
                   help="machine-readable output")
    p = sub.add_parser("summary", help="summarize cluster state")
    p.add_argument("resource", choices=["tasks", "objects"])
    p = sub.add_parser("metrics",
                       help="print cluster-wide Prometheus metrics")
    p.add_argument("--grafana", action="store_true",
                   help="print the generated Grafana dashboard JSON "
                        "instead of the exposition")
    sub.add_parser("devices", help="list visible accelerator devices")

    p = sub.add_parser("job", help="submit and manage jobs")
    jsub = p.add_subparsers(dest="job_command", required=True)
    ps = jsub.add_parser("submit", help="run an entrypoint as a job")
    ps.add_argument("--submission-id", default=None)
    ps.add_argument("--working-dir", default=None)
    ps.add_argument("--wait", action="store_true",
                    help="stream logs until the job finishes")
    ps.add_argument("--timeout", type=float, default=3600.0)
    ps.add_argument("entrypoint", nargs=argparse.REMAINDER)
    for name in ("status", "logs", "stop"):
        pj = jsub.add_parser(name)
        pj.add_argument("job_id")
    jsub.add_parser("list")

    p = sub.add_parser("logs", help="read captured session logs "
                                    "(worker/daemon stdout+stderr)")
    p.add_argument("filename", nargs="?", default=None,
                   help="exact log filename (default: all capture "
                        "files)")
    p.add_argument("--node", default=None,
                   help="node id prefix (or 'head') to read")
    p.add_argument("--pid", type=int, default=None,
                   help="only files of this process id")
    p.add_argument("--tail", type=int, default=1000,
                   help="last N lines (-1 for everything)")
    p.add_argument("--follow", "-f", action="store_true",
                   help="keep polling for new lines")
    p.add_argument("--list", action="store_true",
                   help="list the session's log files instead")

    p = sub.add_parser("alerts", help="active alerts + rule table from "
                                      "the head's alert engine")
    p.add_argument("--history", action="store_true",
                   help="also print the bounded transition history")
    p.add_argument("--json", action="store_true",
                   help="emit the raw snapshot as JSON")
    p = sub.add_parser("xfer", help="dataplane flow plane: per-link "
                                    "transfer matrix + object fan-out")
    p.add_argument("--links", action="store_true",
                   help="only the per-link MB/s matrix")
    p.add_argument("--objects", action="store_true",
                   help="only the per-object fan-out table")
    p.add_argument("--tree", action="store_true",
                   help="render the last broadcast's spanning tree "
                        "with per-edge MB/s")
    p.add_argument("--window", type=float, default=None,
                   help="MB/s window in seconds (clamped to the store's)")
    p.add_argument("--json", action="store_true",
                   help="emit the raw snapshot as JSON")
    p = sub.add_parser("events", help="cluster event journal "
                                      "(membership, serve, train, "
                                      "spill, alert transitions)")
    p.add_argument("--severity", default=None,
                   help="minimum severity (info/warning/error/critical)")
    p.add_argument("--source", default=None,
                   help="only events from this subsystem")
    p.add_argument("--node", default=None,
                   help="only events stamped with this node id")
    p.add_argument("--limit", type=int, default=None,
                   help="last N matching events")
    p.add_argument("--follow", "-f", action="store_true",
                   help="keep polling for new events (by seq)")
    p.add_argument("--json", action="store_true",
                   help="one JSON object per line")

    p = sub.add_parser("profile", help="sample CPU stacks on demand "
                                       "(driver, --node <id>, --pid, "
                                       "--cluster) or --report the "
                                       "loop-lag flight recorder")
    p.add_argument("--node", default=None,
                   help="node id prefix to profile (default: this "
                        "process)")
    p.add_argument("--pid", type=int, default=None,
                   help="profile a cluster worker by pid, resolved "
                        "through its owning daemon (no py-spy needed)")
    p.add_argument("--cluster", action="store_true",
                   help="synchronized burst: every live daemon + the "
                        "head sample together, merged into one graph")
    p.add_argument("--report", action="store_true",
                   help="print the loop-lag flight recorder's "
                        "incidents instead of sampling")
    p.add_argument("--duration", type=float, default=5.0)
    p.add_argument("--hz", type=int, default=100)
    p.add_argument("--output", default="profile.speedscope.json",
                   help=".json -> speedscope, anything else -> "
                        "collapsed stacks")
    p = sub.add_parser("grafana-dashboards",
                       help="generate Grafana dashboard JSON for the "
                            "cluster's Prometheus metrics")
    p.add_argument("--out", default="grafana_dashboards")
    p = sub.add_parser("microbenchmark",
                       help="core ops/s suite (tasks, actors, put/get)")
    p.add_argument("--duration", type=float, default=2.0)

    p = sub.add_parser("start", help="start a head or join as a node")
    p.add_argument("--head", action="store_true")
    p.add_argument("--host", default="0.0.0.0",
                   help="head bind address (the control plane is "
                        "unauthenticated: expose only on trusted networks)")
    p.add_argument("--address", default=None,
                   help="head host:port to join as a node daemon")
    p.add_argument("--port", type=int, default=6380)
    p.add_argument("--num-cpus", type=float, default=1.0)
    p.add_argument("--num-tpus", type=float, default=0.0)
    p.add_argument("--memory", type=float, default=float(1 << 30))
    p.add_argument("--resources", default=None,
                   help="extra resources as JSON")
    p.add_argument("--labels", default=None,
                   help="node labels as JSON (cloud providers tag their "
                        "nodes here, e.g. provider_node_id)")

    p = sub.add_parser("up", help="create a cluster from a YAML config")
    p.add_argument("config_file")
    p.add_argument("--no-head", action="store_true",
                   help="only create workers (head runs elsewhere)")

    p = sub.add_parser("down", help="terminate a cluster's nodes")
    p.add_argument("config_file")

    p = sub.add_parser("dashboard", help="run the HTTP dashboard")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8265)

    p = sub.add_parser("serve", help="deploy and inspect Serve apps")
    ssub = p.add_subparsers(dest="serve_command", required=True)
    pd = ssub.add_parser("deploy", help="deploy from a JSON config file")
    pd.add_argument("config_file")
    ssub.add_parser("status")
    ssub.add_parser("shutdown")

    args = parser.parse_args(argv)
    handler = {
        "status": cmd_status,
        "top": cmd_top,
        "memory": cmd_memory,
        "timeline": cmd_timeline,
        "trace": cmd_trace,
        "list": cmd_list,
        "actors": cmd_actors,
        "summary": cmd_summary,
        "metrics": cmd_metrics,
        "devices": cmd_devices,
        "job": cmd_job,
        "logs": cmd_logs,
        "serve": cmd_serve,
        "dashboard": cmd_dashboard,
        "start": cmd_start,
        "up": cmd_up,
        "down": cmd_down,
        "microbenchmark": cmd_microbenchmark,
        "profile": cmd_profile,
        "grafana-dashboards": cmd_grafana,
        "alerts": cmd_alerts,
        "xfer": cmd_xfer,
        "events": cmd_events,
    }[args.command]
    return handler(args)


if __name__ == "__main__":
    sys.exit(main())
