"""Runtime context (analog of python/ray/runtime_context.py)."""

from __future__ import annotations

from typing import Optional

from ray_tpu._private.runtime import current_task_spec
from ray_tpu._private.worker import global_worker


class RuntimeContext:
    @property
    def job_id(self):
        return global_worker.job_id

    @property
    def node_id(self) -> str:
        return self.get_node_id()

    def get_job_id(self) -> str:
        return global_worker.job_id.hex() if global_worker.job_id else ""

    def get_node_id(self) -> str:
        """Node the current task runs on (driver: the head node)."""
        spec = current_task_spec()
        rt = global_worker.runtime
        node_id = getattr(spec, "_node_id", None) if spec else None
        if node_id is None and spec is not None and spec.actor_id is not None:
            state = rt.actor_state(spec.actor_id)
            if state is not None:
                node_id = getattr(state.creation_spec, "_node_id", None)
        if node_id is None:
            node_id = rt.head_node_id
        return node_id.hex()

    def get_task_id(self) -> Optional[str]:
        spec = current_task_spec()
        return spec.task_id.hex() if spec else None

    def get_actor_id(self) -> Optional[str]:
        spec = current_task_spec()
        if spec is not None and spec.actor_id is not None:
            return spec.actor_id.hex()
        return None

    @property
    def was_current_actor_reconstructed(self) -> bool:
        spec = current_task_spec()
        if spec is None or spec.actor_id is None:
            return False
        state = global_worker.runtime.actor_state(spec.actor_id)
        return bool(state and state.num_restarts > 0)

    def get_assigned_resources(self) -> dict:
        spec = current_task_spec()
        return dict(spec.resources) if spec else {}

    def get_runtime_env_string(self) -> str:
        return "{}"


_runtime_context = RuntimeContext()


def get_runtime_context() -> RuntimeContext:
    return _runtime_context
