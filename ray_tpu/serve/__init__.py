"""ray_tpu.serve: model serving on the actor runtime.

Analog of the reference's python/ray/serve (SURVEY.md §2.6): a controller
actor reconciles deployment replicas; handles route requests with
power-of-two replica picking; @serve.batch coalesces concurrent requests
into one call (on TPU: one pjit batch); an aiohttp proxy provides HTTP
ingress; autoscaling follows ongoing-request load. TPU-first difference:
replicas typically hold a compiled pjit program + sharded params, so
`num_replicas` maps to chips/slices, and batching targets MXU-shaped
batches.
"""

from __future__ import annotations

import uuid
from typing import Any, Callable, Dict, List, Optional, Union

import ray_tpu
from ray_tpu.exceptions import BackPressureError
from ray_tpu.serve.batching import batch
from ray_tpu.serve.continuous_batching import ContinuousBatcher
from ray_tpu.serve.handle import DeploymentHandle

__all__ = ["Application", "BackPressureError", "ContinuousBatcher",
           "Deployment", "DeploymentHandle", "batch", "delete",
           "deployment", "get_app_handle", "get_deployment_handle",
           "ingress", "run", "shutdown", "status", "start"]


class Deployment:
    """Produced by @serve.deployment; immutable config + .bind()/.deploy().

    Reference: serve/deployment.py Deployment (options: num_replicas,
    ray_actor_options, max_concurrent_queries, autoscaling_config,
    route_prefix, user_config)."""

    def __init__(self, func_or_class, name: str, config: Dict[str, Any]):
        self._func_or_class = func_or_class
        self.name = name
        self._config = dict(config)

    def options(self, **kwargs) -> "Deployment":
        cfg = {**self._config, **kwargs}
        name = cfg.pop("name", self.name)
        return Deployment(self._func_or_class, name, cfg)

    def bind(self, *args, **kwargs) -> "Application":
        return Application(self, args, kwargs)

    @property
    def num_replicas(self) -> int:
        return self._config.get("num_replicas") or 1

    @property
    def route_prefix(self) -> Optional[str]:
        rp = self._config.get("route_prefix", "/" + self.name)
        return rp

    def _deploy(self, init_args, init_kwargs, controller,
                route_prefix: Optional[str] = "__unset__") -> None:
        import cloudpickle
        cfg = self._config
        autoscaling = cfg.get("autoscaling_config")
        num_replicas = cfg.get("num_replicas")
        if autoscaling and num_replicas is None:
            num_replicas = autoscaling.get("min_replicas", 1)
        rp = self.route_prefix if route_prefix == "__unset__" else \
            route_prefix
        version = cfg.get("version") or uuid.uuid4().hex
        ray_tpu.get(controller.deploy.remote(
            self.name,
            cloudpickle.dumps(self._func_or_class),
            init_args, init_kwargs,
            num_replicas or 1,
            cfg.get("ray_actor_options") or {},
            rp,
            cfg.get("max_concurrent_queries", 100),
            autoscaling,
            version,
            cfg.get("user_config"),
            cfg.get("max_queued_requests", -1),
        ))


class Application:
    """A bound deployment DAG node (reference: serve DAG API
    deployment_graph.py / Application). Bound arguments may themselves be
    Applications — they deploy first and are replaced by handles."""

    def __init__(self, deployment: Deployment, args, kwargs):
        self.deployment = deployment
        self.args = args
        self.kwargs = kwargs


def deployment(_func_or_class=None, *, name: Optional[str] = None,
               num_replicas: Optional[int] = None,
               ray_actor_options: Optional[dict] = None,
               max_concurrent_queries: int = 100,
               max_queued_requests: int = -1,
               autoscaling_config: Optional[dict] = None,
               route_prefix: Optional[str] = "__default__",
               user_config: Any = None,
               version: Optional[str] = None,
               **_ignored):
    """``@serve.deployment`` / ``@serve.deployment(num_replicas=...)``.

    ``max_queued_requests`` caps router-side queueing: once a
    deployment has that many requests outstanding beyond its replicas'
    concurrent capacity, further requests fast-fail with
    :class:`BackPressureError` (-1 = unlimited, the default)."""

    def decorate(target):
        dep_name = name or target.__name__
        cfg = {
            "num_replicas": num_replicas,
            "ray_actor_options": ray_actor_options,
            "max_concurrent_queries": max_concurrent_queries,
            "max_queued_requests": max_queued_requests,
            "autoscaling_config": autoscaling_config,
            "user_config": user_config,
            "version": version,
        }
        if route_prefix != "__default__":
            cfg["route_prefix"] = route_prefix
        return Deployment(target, dep_name, cfg)

    if _func_or_class is not None:
        return decorate(_func_or_class)
    return decorate


def ingress(_app=None, **_kwargs):
    """FastAPI-style ingress shim: returns the class unchanged (the aiohttp
    proxy handles raw HTTP; FastAPI integration is out of scope — the
    reference's @serve.ingress(app) wraps a FastAPI app)."""

    def decorate(cls):
        return cls

    return decorate if _app is None else decorate(_app)


def _deploy_application(app: Application, controller,
                        route_prefix="__unset__") -> DeploymentHandle:
    """Deploy bottom-up: bound Application args become handles (recursing
    into dict/list args, so e.g. DAGDriver's {route: app} map works)."""
    def resolve(v):
        if isinstance(v, Application):
            return _deploy_application(v, controller, route_prefix=None)
        if isinstance(v, dict):
            return {k: resolve(x) for k, x in v.items()}
        if isinstance(v, (list, tuple)):
            return type(v)(resolve(x) for x in v)
        return v

    args = tuple(resolve(a) for a in app.args)
    kwargs = {k: resolve(v) for k, v in app.kwargs.items()}
    app.deployment._deploy(args, kwargs, controller,
                           route_prefix=route_prefix)
    return DeploymentHandle(app.deployment.name, controller)


def run(target: Union[Application, Deployment], *,
        host: str = "127.0.0.1", port: Optional[int] = None,
        route_prefix: str = "__unset__", name: str = "default",
        _blocking: bool = False) -> DeploymentHandle:
    """Deploy an application; returns its entry handle (reference:
    serve/api.py:455 serve.run). Pass ``port`` to also start HTTP ingress
    (port=0 picks an ephemeral port; see http_port())."""
    from ray_tpu.serve._private.controller import get_or_create_controller
    if not ray_tpu.is_initialized():
        ray_tpu.init()
    controller = get_or_create_controller()
    if isinstance(target, Deployment):
        target = target.bind()
    handle = _deploy_application(target, controller,
                                 route_prefix=route_prefix)
    if port is not None:
        start(host=host, port=port)
    if _proxy is not None:
        # serve.run returns only once the app is REACHABLE (reference:
        # serve.run blocks until the application is RUNNING): the proxy
        # refreshes its route table via long-poll, so without this wait
        # a request issued right after a second run() 404s against the
        # previous table.
        prefix = target.deployment.route_prefix \
            if route_prefix == "__unset__" else route_prefix
        if prefix:
            import time as _time
            deadline = _time.monotonic() + 15
            while _time.monotonic() < deadline:
                if ray_tpu.get(_proxy.has_route.remote(prefix),
                               timeout=15):
                    break
                _time.sleep(0.05)
    return handle


_proxy = None
_proxy_port: Optional[int] = None


def start(detached: bool = False, host: str = "127.0.0.1",
          port: int = 8000, **_ignored):
    """Start the HTTP proxy (reference: serve.start / http_options).

    ``detached=True`` gives the proxy actor the GCS-owned detached
    lifetime: it survives the starting driver's exit (and a head
    restart with ``gcs_store_path``) and is torn down only by
    ``ray_tpu.kill(proxy, no_restart=True)``."""
    global _proxy, _proxy_port
    if _proxy is not None:
        return _proxy
    from ray_tpu.serve._private.http_proxy import HTTPProxyActor
    cls = ray_tpu.remote(HTTPProxyActor)
    opts = {"name": "_serve_http_proxy", "get_if_exists": True}
    if detached:
        opts["lifetime"] = "detached"
    _proxy = cls.options(**opts).remote(host, port)
    _proxy_port = ray_tpu.get(_proxy.ready.remote())
    return _proxy


def http_port() -> Optional[int]:
    """The bound ingress port (useful with port=0)."""
    return _proxy_port


def get_deployment_handle(deployment_name: str,
                          app_name: str = "default") -> DeploymentHandle:
    return DeploymentHandle(deployment_name)


def get_app_handle(name: str = "default") -> DeploymentHandle:
    return DeploymentHandle(name)


def status() -> Dict[str, Any]:
    from ray_tpu.serve._private.controller import get_or_create_controller
    controller = get_or_create_controller()
    return ray_tpu.get(controller.list_deployments.remote())


def delete(name: str) -> None:
    from ray_tpu.serve._private.controller import get_or_create_controller
    controller = get_or_create_controller()
    ray_tpu.get(controller.delete_deployment.remote(name))


def _redeploy_from_records(records: Dict[str, dict]) -> int:
    """Replay persisted deployment records against a fresh controller.

    Head-failover rehydration (runtime._rehydrate_serve calls this on a
    background thread after init): each record is the FULL deploy
    payload the old head's controller persisted, so the replay is an
    ordinary ``deploy`` — replicas needing resources from daemons that
    have not re-registered yet simply ride the controller's bounded
    startup retries. Returns how many deployments replayed."""
    import logging

    import cloudpickle

    from ray_tpu.serve._private.controller import get_or_create_controller
    logger = logging.getLogger("ray_tpu.serve")
    controller = get_or_create_controller()
    n = 0
    for name, rec in sorted(records.items()):
        try:
            init_args, init_kwargs = cloudpickle.loads(
                rec["init_payload"])
            ray_tpu.get(controller.deploy.remote(
                name,
                rec["deployment_def_bytes"],
                init_args, init_kwargs,
                rec.get("num_replicas") or 1,
                rec.get("ray_actor_options") or {},
                rec.get("route_prefix"),
                rec.get("max_concurrent_queries", 100),
                rec.get("autoscaling_config"),
                rec.get("version") or uuid.uuid4().hex,
                rec.get("user_config"),
                rec.get("max_queued_requests", -1),
            ))
            n += 1
        except Exception:  # noqa: BLE001 - best effort per deployment;
            # the record stays in the store for the next head life.
            logger.exception("could not rehydrate deployment %r", name)
    return n


def shutdown() -> None:
    global _proxy, _proxy_port
    from ray_tpu.serve._private.controller import (CONTROLLER_NAME,
                                                   get_or_create_controller)
    if not ray_tpu.is_initialized():
        return
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        controller = None
    if controller is not None:
        try:
            ray_tpu.get(controller.shutdown.remote())
        except Exception:  # noqa: BLE001 - controller already dead
            pass
        try:
            ray_tpu.kill(controller)
        except Exception:  # noqa: BLE001
            pass
    if _proxy is not None:
        try:
            ray_tpu.get(_proxy.shutdown.remote())
        except Exception:  # noqa: BLE001
            pass
        ray_tpu.kill(_proxy)
        _proxy = None
        _proxy_port = None
