"""Continuous (iteration-level) batching for autoregressive decode loops.

The LLM-serving engine the paper's TPU focus begs for (Orca, OSDI '22;
reference points: vLLM's scheduler, Ray Serve's ``@serve.batch`` which
only batches at *request* granularity): an autoregressive model decodes
one token per iteration, so batching whole requests leaves the batch
ragged — a 10-token completion holds its slot while a 500-token neighbor
finishes. :class:`ContinuousBatcher` instead admits **new requests into a
running decode batch at iteration boundaries**: the batch shape stays
fixed (``num_slots`` — one compiled ``pjit`` program, no retracing), each
slot carries an independent sequence, finished sequences free their slot
mid-flight, and freed slots are refilled from the queue before the next
step.

The engine is deliberately model-agnostic: the caller owns an opaque
``state`` (on TPU: the KV cache + current-token arrays, sharded however
the mesh wants) and supplies two callables —

``prefill_fn(state, slot, prompt) -> state``
    Write ``prompt`` into slot ``slot`` (on TPU: ``jax.jit``-ed
    ``at[slot].set`` updates of the fixed-shape cache; pad the prompt to
    the cache's prompt axis — the engine never inspects prompts).

``step_fn(state, active_mask) -> (state, tokens)``
    One decode iteration over ALL slots. ``active_mask`` is a
    ``num_slots``-length tuple of bools — inactive (padding) slots must
    be masked out of attention/sampling but stay in the batch, keeping
    the call shape fixed. ``tokens`` is indexable per slot (list, numpy
    or JAX array); inactive slots' tokens are ignored.

Per-sequence completion is engine-side: a sequence finishes when it
emits ``eos_token`` or reaches its ``max_new_tokens``. ``submit()`` is
the whole client API — it parks on an asyncio future, so a replica can
drive the engine from plain async handlers (and ``num_ongoing`` keeps
counting in-flight sequences for the controller's drain poll: draining a
replica lets live decodes run out before the replica dies).

The decode step runs in a worker thread (``asyncio.to_thread``) so a
multi-ms pjit dispatch never stalls the replica's event loop.
"""

from __future__ import annotations

import asyncio
import itertools
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from ray_tpu._private import builtin_metrics

_engine_ids = itertools.count(1)


def _as_py(token: Any) -> Any:
    """Host-side view of a per-slot token (JAX/numpy scalar → Python)."""
    item = getattr(token, "item", None)
    return item() if callable(item) else token


class _Sequence:
    __slots__ = ("prompt", "max_new_tokens", "future", "tokens",
                 "admitted_at_iter", "t_submit")

    def __init__(self, prompt, max_new_tokens: int, future):
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.future = future
        self.tokens: List[Any] = []
        self.admitted_at_iter: Optional[int] = None
        self.t_submit = time.monotonic()


class ContinuousBatcher:
    """Slot-based continuous batching over a fixed-shape decode step.

    ::

        engine = ContinuousBatcher(
            state=init_cache(num_slots=8),
            prefill_fn=prefill, step_fn=decode_step,
            num_slots=8, eos_token=EOS)
        tokens = await engine.submit(prompt, max_new_tokens=64)
    """

    def __init__(self, *, state: Any,
                 prefill_fn: Callable[[Any, int, Any], Any],
                 step_fn: Callable[[Any, Tuple[bool, ...]],
                                   Tuple[Any, Any]],
                 num_slots: int, eos_token: Any = None,
                 max_new_tokens: int = 128,
                 max_queued: Optional[int] = None,
                 name: Optional[str] = None):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        self._state = state
        self._prefill = prefill_fn
        self._step = step_fn
        self._num_slots = num_slots
        self._eos = eos_token
        self._default_max_new = max_new_tokens
        self._max_queued = max_queued
        self._name = name or f"decode{next(_engine_ids)}"
        self._slots: List[Optional[_Sequence]] = [None] * num_slots
        self._pending: Deque[_Sequence] = deque()
        self._wake: Optional[asyncio.Event] = None
        self._loop_task: Optional[asyncio.Task] = None
        self._iteration = 0
        self._completed = 0
        self._admitted_running = 0  # joined a live batch mid-decode
        self._admitted_fresh = 0    # admitted while the loop was idle
        self._steps_with_admission = 0

    # -- client API ------------------------------------------------------

    async def submit(self, prompt: Any, *,
                     max_new_tokens: Optional[int] = None) -> List[Any]:
        """Queue one sequence; resolves to its generated tokens (EOS
        excluded) once it completes. Admission happens at the next
        iteration boundary — possibly into a batch that is already
        decoding other sequences."""
        if self._max_queued is not None and \
                len(self._pending) >= self._max_queued:
            raise RuntimeError(
                f"ContinuousBatcher {self._name!r} admission queue is "
                f"full ({self._max_queued} pending)")
        self._ensure_loop()
        seq = _Sequence(prompt,
                        max_new_tokens or self._default_max_new,
                        asyncio.get_event_loop().create_future())
        self._pending.append(seq)
        self._wake.set()
        return await seq.future

    def stats(self) -> Dict[str, Any]:
        active = sum(1 for s in self._slots if s is not None)
        return {
            "name": self._name,
            "num_slots": self._num_slots,
            "active_slots": active,
            "pending": len(self._pending),
            "iterations": self._iteration,
            "completed": self._completed,
            "admitted_running": self._admitted_running,
            "admitted_fresh": self._admitted_fresh,
            "steps_with_admission": self._steps_with_admission,
        }

    # -- decode loop -----------------------------------------------------

    def _ensure_loop(self) -> None:
        if self._wake is None:
            self._wake = asyncio.Event()
        if self._loop_task is None or self._loop_task.done():
            self._loop_task = asyncio.get_event_loop().create_task(
                self._decode_loop())

    def _admit(self) -> None:
        """Fill free slots from the queue — the iteration-boundary
        admission step. Prefill happens here, slot by slot, so a newly
        admitted sequence decodes its first token in the very next
        step."""
        was_running = any(s is not None for s in self._slots)
        admitted = 0
        for slot in range(self._num_slots):
            if self._slots[slot] is not None or not self._pending:
                continue
            seq = self._pending.popleft()
            try:
                self._state = self._prefill(self._state, slot, seq.prompt)
            except BaseException as exc:  # noqa: BLE001 - per-sequence
                if not seq.future.done():
                    seq.future.set_exception(exc)
                continue
            seq.admitted_at_iter = self._iteration
            self._slots[slot] = seq
            admitted += 1
            if was_running:
                self._admitted_running += 1
            else:
                self._admitted_fresh += 1
        if admitted and was_running:
            self._steps_with_admission += 1
        if admitted:
            builtin_metrics.serve_decode_admitted().inc(
                admitted, tags={"engine": self._name,
                                "kind": ("running" if was_running
                                         else "fresh")})

    def _finish(self, slot: int, *, error: Optional[BaseException] = None
                ) -> None:
        seq = self._slots[slot]
        self._slots[slot] = None
        if seq is None or seq.future.done():
            return
        if error is not None:
            seq.future.set_exception(error)
        else:
            self._completed += 1
            seq.future.set_result(seq.tokens)

    async def _decode_loop(self) -> None:
        while True:
            self._admit()
            active_mask = tuple(s is not None for s in self._slots)
            n_active = sum(active_mask)
            builtin_metrics.serve_decode_active_slots().set(
                n_active, tags={"engine": self._name})
            if not n_active:
                # Idle: park until a submit wakes us (no spin).
                self._wake.clear()
                await self._wake.wait()
                continue
            try:
                # The fixed-shape step (one pjit dispatch) runs off the
                # event loop; this task is its only state toucher.
                self._state, tokens = await asyncio.to_thread(
                    self._step, self._state, active_mask)
            except BaseException as exc:  # noqa: BLE001 - fail the batch
                for slot, live in enumerate(active_mask):
                    if live:
                        self._finish(slot, error=exc)
                continue
            self._iteration += 1
            for slot, live in enumerate(active_mask):
                if not live:
                    continue
                seq = self._slots[slot]
                tok = _as_py(tokens[slot])
                done = False
                if self._eos is not None and tok == self._eos:
                    done = True  # EOS excluded from the result
                else:
                    seq.tokens.append(tok)
                    done = len(seq.tokens) >= seq.max_new_tokens
                if done:
                    self._finish(slot)
