"""DeploymentHandle: Python-side entry point to a deployment.

Analog of the reference's serve/handle.py RayServeHandle:
``handle.remote(*args)`` routes to a replica and returns an ObjectRef;
``handle.method.remote(...)`` targets a specific method. Handles pickle by
name and re-bind through the controller, so they can be passed into other
deployments (DAG composition) or tasks.
"""

from __future__ import annotations

from typing import Any, Optional

from ray_tpu.serve._private.router import Router


class _MethodCaller:
    def __init__(self, handle: "DeploymentHandle", method_name: str):
        self._handle = handle
        self._method_name = method_name

    def remote(self, *args, **kwargs):
        return self._handle._router.assign_request(
            self._method_name, args, kwargs)


class DeploymentHandle:
    def __init__(self, deployment_name: str, controller=None):
        from ray_tpu.serve._private.controller import \
            get_or_create_controller
        self.deployment_name = deployment_name
        self._controller = controller or get_or_create_controller()
        self._router = Router(self._controller, deployment_name)

    def remote(self, *args, **kwargs):
        return self._router.assign_request("__call__", args, kwargs)

    def __getattr__(self, item):
        if item.startswith("_"):
            raise AttributeError(item)
        return _MethodCaller(self, item)

    def options(self, **_kwargs) -> "DeploymentHandle":
        return self

    def __reduce__(self):
        return (DeploymentHandle, (self.deployment_name,))

    def __repr__(self):
        return f"DeploymentHandle({self.deployment_name!r})"
