"""DeploymentHandle: Python-side entry point to a deployment.

Analog of the reference's serve/handle.py RayServeHandle:
``handle.remote(*args)`` routes to a replica and returns an ObjectRef;
``handle.method.remote(...)`` targets a specific method. Handles pickle by
name and re-bind through the controller, so they can be passed into other
deployments (DAG composition) or tasks.

``handle.options(timeout_s=..., max_retries=...)`` returns a configured
handle sharing the same router (reference: handle.options): timeout_s
arms a per-request deadline (expiry raises GetTimeoutError at get),
max_retries caps transparent failover re-dispatches for requests issued
through that handle.
"""

from __future__ import annotations

from typing import Any, Optional

from ray_tpu.serve._private.router import Router

_HANDLE_OPTIONS = ("timeout_s", "max_retries")


class _MethodCaller:
    def __init__(self, handle: "DeploymentHandle", method_name: str):
        self._handle = handle
        self._method_name = method_name

    def remote(self, *args, **kwargs):
        h = self._handle
        return h._router.assign_request(
            self._method_name, args, kwargs,
            timeout_s=h._timeout_s, max_retries=h._max_retries)


class DeploymentHandle:
    def __init__(self, deployment_name: str, controller=None):
        from ray_tpu.serve._private.controller import \
            get_or_create_controller
        self.deployment_name = deployment_name
        self._controller = controller or get_or_create_controller()
        self._router = Router(self._controller, deployment_name)
        self._timeout_s: Optional[float] = None
        self._max_retries: Optional[int] = None

    def remote(self, *args, **kwargs):
        return self._router.assign_request(
            "__call__", args, kwargs,
            timeout_s=self._timeout_s, max_retries=self._max_retries)

    def __getattr__(self, item):
        if item.startswith("_"):
            raise AttributeError(item)
        return _MethodCaller(self, item)

    def options(self, **kwargs) -> "DeploymentHandle":
        """A configured copy SHARING this handle's router (and therefore
        its membership long-poll and load table) — options never spawn
        new control-plane traffic. Unknown keys raise TypeError instead
        of being silently dropped."""
        unknown = set(kwargs) - set(_HANDLE_OPTIONS)
        if unknown:
            raise TypeError(
                f"Unknown DeploymentHandle options {sorted(unknown)}; "
                f"supported: {list(_HANDLE_OPTIONS)}")
        clone = DeploymentHandle.__new__(DeploymentHandle)
        clone.deployment_name = self.deployment_name
        clone._controller = self._controller
        clone._router = self._router
        clone._timeout_s = kwargs.get("timeout_s", self._timeout_s)
        clone._max_retries = kwargs.get("max_retries", self._max_retries)
        return clone

    @classmethod
    def _rebuild(cls, deployment_name: str, timeout_s, max_retries):
        handle = cls(deployment_name)
        handle._timeout_s = timeout_s
        handle._max_retries = max_retries
        return handle

    def __reduce__(self):
        return (DeploymentHandle._rebuild,
                (self.deployment_name, self._timeout_s, self._max_retries))

    def __repr__(self):
        return f"DeploymentHandle({self.deployment_name!r})"
