"""DAGDriver: HTTP entry deployment routing paths to bound applications.

Analog of the reference's serve/drivers.py DAGDriver (the deployment-graph
ingress, serve/deployment_graph.py): bind it with either a single
application or a {route: application} dict; requests fan out to the bound
handles.
"""

from __future__ import annotations

from typing import Any, Dict, Union

from ray_tpu import serve


@serve.deployment(name="DAGDriver")
class DAGDriver:
    def __init__(self, dags: Union[Any, Dict[str, Any]]):
        # Bound Applications arrive as DeploymentHandles after deploy.
        if isinstance(dags, dict):
            self._routes = dict(dags)
            self._single = None
        else:
            self._routes = {}
            self._single = dags

    async def __call__(self, request) -> Any:
        """HTTP entry: route on path for dict DAGs; pass the JSON body to
        the target handle. Direct handle calls pass their argument through
        unchanged (it has no .json())."""
        if hasattr(request, "json"):
            try:
                payload = request.json()
            except Exception:  # noqa: BLE001 - non-JSON body
                payload = getattr(request, "body", None)
        else:
            payload = request
        if self._single is not None:
            return serve_get(self._single.remote(payload))
        path = getattr(request, "path", "/")
        handle = self._routes.get(path)
        if handle is None:
            raise ValueError(f"No route for {path!r}; routes: "
                             f"{sorted(self._routes)}")
        return serve_get(handle.remote(payload))

    def predict(self, payload) -> Any:
        """Python-side entry (handle.predict.remote(x))."""
        if self._single is not None:
            return serve_get(self._single.remote(payload))
        raise ValueError("predict() requires a single-dag driver")

    def predict_with_route(self, route: str, payload) -> Any:
        handle = self._routes.get(route)
        if handle is None:
            raise ValueError(f"No route {route!r}")
        return serve_get(handle.remote(payload))


def serve_get(ref):
    """Resolve a handle call result (ObjectRef) inside a replica."""
    import ray_tpu
    return ray_tpu.get(ref)
