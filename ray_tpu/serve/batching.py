"""@serve.batch: transparent dynamic request batching, adaptively tuned.

Analog of the reference's serve/batching.py: an async method decorated
with ``@serve.batch`` receives a *list* of inputs; concurrent callers are
coalesced until the batch fills or the wait timeout elapses, then the
underlying function runs once and each caller gets its element of the
returned list. The core TPU win: replicas batch independent HTTP/handle
requests into one MXU-sized ``pjit`` call.

**Adaptive micro-batching** (this module's throughput engine): with a
latency budget — ``@serve.batch(target_latency_s=...)`` or the
``RAY_TPU_serve_batch_target_latency_ms`` flag — the queue tunes its own
operating point online instead of serving the static knobs. Each
request's queue+execute latency feeds a sliding window; every
``_ADJUST_EVERY`` batches the observed p95 is compared to the budget and
the live ``(max_batch_size, wait_timeout)`` pair moves AIMD-style:

* p95 over budget → multiplicative decrease (halve the batch-size cap,
  halve the wait) — under light traffic the wait timeout dominates
  latency, so shedding it restores the budget immediately;
* p95 under ``_HEADROOM`` of budget → additive increase (cap +1, wait
  ×1.5 toward the configured maxima) — under saturating traffic batches
  fill before the timeout and the cap climbs back to the MXU-sized
  batch that maximizes throughput.

The decorated knobs are *ceilings*; adaptation only moves inside
``[1, max_batch_size]`` × ``[min_wait, batch_wait_timeout_s]``. The live
operating point is observable: ``ray_tpu_serve_batch_size`` (last
executed batch) and ``ray_tpu_serve_batch_size_limit`` (current cap)
gauges, and ``wrapper.batch_stats()`` for tests/CLI.
"""

from __future__ import annotations

import asyncio
import functools
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from ray_tpu._private import builtin_metrics

# Adaptation cadence and shape. Not config flags: these are internal
# loop-stability constants, not operator-facing knobs.
_ADJUST_EVERY = 8        # batches between AIMD adjustments
_LATENCY_WINDOW = 256    # per-request latency samples kept
_HEADROOM = 0.7          # grow only while p95 < _HEADROOM * budget
_MIN_WAIT_S = 0.0005     # wait floor: never spin at zero under load


def _percentile(samples: List[float], q: float) -> float:
    if not samples:
        return 0.0
    ordered = sorted(samples)
    idx = min(len(ordered) - 1, int(q * len(ordered)))
    return ordered[idx]


class _BatchQueue:
    def __init__(self, fn, max_batch_size: int, timeout_s: float,
                 target_latency_s: Optional[float], name: str):
        self._fn = fn
        self._max = max_batch_size          # ceiling (decorator knob)
        self._timeout = timeout_s           # ceiling (decorator knob)
        self._target = target_latency_s     # None = fixed batching
        self._name = name
        # Live operating point (== ceilings when not adaptive).
        self.cur_max = max_batch_size
        self.cur_timeout = timeout_s
        self._queue: Optional[asyncio.Queue] = None
        self._loop_task = None
        # Adaptation state.
        self._latencies: deque = deque(maxlen=_LATENCY_WINDOW)
        self._batches = 0
        self._items = 0
        self._last_batch_size = 0
        self._shrinks = 0
        self._grows = 0

    def _ensure_loop(self):
        if self._queue is None:
            self._queue = asyncio.Queue()
            self._loop_task = asyncio.get_event_loop().create_task(
                self._batch_loop())

    async def _batch_loop(self):
        while True:
            first = await self._queue.get()
            batch = [first]
            loop = asyncio.get_event_loop()
            deadline = loop.time() + self.cur_timeout
            while len(batch) < self.cur_max:
                remaining = deadline - loop.time()
                if remaining <= 0:
                    break
                try:
                    item = await asyncio.wait_for(self._queue.get(),
                                                  remaining)
                    batch.append(item)
                except asyncio.TimeoutError:
                    break
            args = [item[0] for item in batch]
            futures = [item[1] for item in batch]
            enqueue_times = [item[2] for item in batch]
            try:
                results = await self._fn(args)
                if len(results) != len(batch):
                    raise ValueError(
                        f"@serve.batch function returned {len(results)} "
                        f"results for a batch of {len(batch)}")
                for fut, res in zip(futures, results):
                    if not fut.done():
                        fut.set_result(res)
            except Exception as e:  # noqa: BLE001 - propagate per caller
                for fut in futures:
                    if not fut.done():
                        fut.set_exception(e)
            self._observe(len(batch), enqueue_times)

    def _observe(self, batch_size: int, enqueue_times: List[float]) -> None:
        """Feed one executed batch into the adaptation state + gauges."""
        self._batches += 1
        self._items += batch_size
        self._last_batch_size = batch_size
        builtin_metrics.serve_batch_size().set(
            batch_size, tags={"fn": self._name})
        if self._target is None:
            return
        done = time.monotonic()
        self._latencies.extend(done - t for t in enqueue_times)
        if self._batches % _ADJUST_EVERY == 0:
            self._adjust()

    def _adjust(self) -> None:
        """One AIMD step of the (cap, wait) operating point against the
        observed request-latency p95."""
        p95 = _percentile(list(self._latencies), 0.95)
        if p95 > self._target:
            self.cur_max = max(1, self.cur_max // 2)
            self.cur_timeout = max(_MIN_WAIT_S, self.cur_timeout / 2)
            self._shrinks += 1
        elif p95 < _HEADROOM * self._target:
            if self.cur_max < self._max:
                self.cur_max += 1
                self._grows += 1
            if self.cur_timeout < self._timeout:
                self.cur_timeout = min(self._timeout,
                                       self.cur_timeout * 1.5)
        builtin_metrics.serve_batch_size_limit().set(
            self.cur_max, tags={"fn": self._name})

    async def submit(self, arg):
        self._ensure_loop()
        fut = asyncio.get_event_loop().create_future()
        await self._queue.put((arg, fut, time.monotonic()))
        return await fut

    def stats(self) -> Dict[str, Any]:
        return {
            "adaptive": self._target is not None,
            "target_latency_s": self._target,
            "max_batch_size": self._max,
            "cur_max_batch_size": self.cur_max,
            "batch_wait_timeout_s": self._timeout,
            "cur_wait_timeout_s": self.cur_timeout,
            "batches": self._batches,
            "items": self._items,
            "last_batch_size": self._last_batch_size,
            "mean_batch_size": (self._items / self._batches
                                if self._batches else 0.0),
            "p95_latency_s": _percentile(list(self._latencies), 0.95),
            "shrinks": self._shrinks,
            "grows": self._grows,
        }


def _default_target_latency_s() -> Optional[float]:
    """Cluster-level latency budget for queues that don't declare one:
    RAY_TPU_serve_batch_target_latency_ms (0 = fixed batching)."""
    from ray_tpu.serve._private.common import serve_config
    ms = serve_config("serve_batch_target_latency_ms", 0.0)
    return (ms / 1000.0) if ms and ms > 0 else None


def batch(_fn: Optional[Callable] = None, *, max_batch_size: int = 10,
          batch_wait_timeout_s: float = 0.01,
          target_latency_s: Optional[float] = None):
    """``@serve.batch`` / ``@serve.batch(max_batch_size=…)``.

    With ``target_latency_s`` (or the cluster flag
    ``RAY_TPU_serve_batch_target_latency_ms``) the queue adapts its
    batch size and wait timeout online against that p95 budget; the
    decorator knobs become ceilings. Without either, batching is fixed
    at the declared knobs (the original behavior)."""

    def decorator(fn):
        queues: Dict[Any, _BatchQueue] = {}  # per-instance (or one for
        # free functions)

        if not asyncio.iscoroutinefunction(fn):
            raise TypeError(
                f"@serve.batch requires an async (``async def``) "
                f"function; {getattr(fn, '__name__', fn)!r} is "
                f"synchronous. Batched callers park on an asyncio "
                f"future, so a sync handler would deadlock the "
                f"replica's event loop.")

        @functools.wraps(fn)
        async def wrapper(*args, **kwargs):
            # Accepted shapes: fn(item) / fn(item=…) for free
            # functions, method(self, item) / method(self, item=…) for
            # methods. The single request argument may arrive
            # positionally or as a keyword — kwargs used to be silently
            # dropped here, stalling the caller forever.
            if len(args) + len(kwargs) == 2 and len(args) >= 1:
                owner = args[0]
                arg = args[1] if len(args) == 2 else \
                    next(iter(kwargs.values()))
                key = id(owner)
                bound = functools.partial(fn, owner)
            elif len(args) + len(kwargs) == 1:
                owner = None
                arg = args[0] if args else next(iter(kwargs.values()))
                key = None
                bound = fn
            else:
                raise TypeError(
                    "@serve.batch functions take exactly one request "
                    "argument (positional or keyword); got "
                    f"{len(args)} positional and {len(kwargs)} keyword "
                    "arguments")
            q = queues.get(key)
            if q is None:
                target = target_latency_s
                if target is None:
                    target = _default_target_latency_s()
                q = _BatchQueue(bound, max_batch_size,
                                batch_wait_timeout_s, target,
                                getattr(fn, "__qualname__",
                                        getattr(fn, "__name__", "batch")))
                queues[key] = q
            return await q.submit(arg)

        def batch_stats(instance: Any = None) -> Optional[Dict[str, Any]]:
            """Live stats of the batch queue bound to ``instance``
            (None for a free function)."""
            q = queues.get(None if instance is None else id(instance))
            return q.stats() if q is not None else None

        wrapper.batch_stats = batch_stats
        wrapper._batch_queues = queues
        return wrapper

    if _fn is not None:
        return decorator(_fn)
    return decorator
