"""@serve.batch: transparent dynamic request batching.

Analog of the reference's serve/batching.py: an async method decorated with
``@serve.batch`` receives a *list* of inputs; concurrent callers are
coalesced until ``max_batch_size`` requests are queued or
``batch_wait_timeout_s`` elapses, then the underlying function runs once
and each caller gets its element of the returned list. The core TPU win:
replicas batch independent HTTP/handle requests into one MXU-sized
``pjit`` call.
"""

from __future__ import annotations

import asyncio
import functools
from typing import Any, Callable, List, Optional


class _BatchQueue:
    def __init__(self, fn, max_batch_size: int, timeout_s: float):
        self._fn = fn
        self._max = max_batch_size
        self._timeout = timeout_s
        self._queue: Optional[asyncio.Queue] = None
        self._loop_task = None

    def _ensure_loop(self):
        if self._queue is None:
            self._queue = asyncio.Queue()
            self._loop_task = asyncio.get_event_loop().create_task(
                self._batch_loop())

    async def _batch_loop(self):
        while True:
            first = await self._queue.get()
            batch = [first]
            deadline = asyncio.get_event_loop().time() + self._timeout
            while len(batch) < self._max:
                remaining = deadline - asyncio.get_event_loop().time()
                if remaining <= 0:
                    break
                try:
                    item = await asyncio.wait_for(self._queue.get(),
                                                  remaining)
                    batch.append(item)
                except asyncio.TimeoutError:
                    break
            args = [item[0] for item in batch]
            futures = [item[1] for item in batch]
            try:
                results = await self._fn(args)
                if len(results) != len(batch):
                    raise ValueError(
                        f"@serve.batch function returned {len(results)} "
                        f"results for a batch of {len(batch)}")
                for fut, res in zip(futures, results):
                    if not fut.done():
                        fut.set_result(res)
            except Exception as e:  # noqa: BLE001 - propagate per caller
                for fut in futures:
                    if not fut.done():
                        fut.set_exception(e)

    async def submit(self, arg):
        self._ensure_loop()
        fut = asyncio.get_event_loop().create_future()
        await self._queue.put((arg, fut))
        return await fut


def batch(_fn: Optional[Callable] = None, *, max_batch_size: int = 10,
          batch_wait_timeout_s: float = 0.01):
    """``@serve.batch`` / ``@serve.batch(max_batch_size=…)``."""

    def decorator(fn):
        queues = {}  # per-instance (or one for free functions)

        if not asyncio.iscoroutinefunction(fn):
            raise TypeError("@serve.batch requires an async function")

        @functools.wraps(fn)
        async def wrapper(*args):
            # Method: (self, item); function: (item,)
            if len(args) == 2:
                owner, arg = args
                key = id(owner)
                bound = functools.partial(fn, owner)
            elif len(args) == 1:
                owner, arg = None, args[0]
                key = None
                bound = fn
            else:
                raise TypeError(
                    "@serve.batch functions take exactly one request "
                    "argument")
            q = queues.get(key)
            if q is None:
                q = _BatchQueue(bound, max_batch_size, batch_wait_timeout_s)
                queues[key] = q
            return await q.submit(arg)

        return wrapper

    if _fn is not None:
        return decorator(_fn)
    return decorator
