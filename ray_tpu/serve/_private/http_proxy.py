"""HTTP proxy: aiohttp ingress routing to deployment replicas.

Analog of the reference's serve/_private/http_proxy.py:218 HTTPProxy (there
uvicorn/starlette; aiohttp here — starlette is not in this image). One
proxy actor binds the port, matches the longest route prefix, and awaits
the replica response off the event loop thread. The controller stays
off-path (routes refresh only when membership changes).
"""

from __future__ import annotations

import asyncio
import threading
from typing import Any, Dict, Optional


class Request:
    """What a deployment callable receives for an HTTP request (the
    starlette-Request stand-in)."""

    def __init__(self, method: str, path: str, query_params: Dict[str, str],
                 body: bytes, headers: Dict[str, str]):
        self.method = method
        self.path = path
        self.query_params = query_params
        self.body = body
        self.headers = headers

    def json(self):
        import json
        return json.loads(self.body) if self.body else None


class HTTPProxyActor:
    def __init__(self, host: str, port: int):
        self._host = host
        self._port = port
        self._routes: Dict[str, Any] = {}  # prefix -> DeploymentHandle
        self._version = -1
        self._runner = None
        self._started = asyncio.Event()

    async def ready(self) -> int:
        """Start the server; returns the bound port."""
        from aiohttp import web

        from ray_tpu.serve._private.controller import \
            get_or_create_controller
        self._controller = get_or_create_controller()

        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self._handle)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self._host, self._port)
        await site.start()
        # Resolve the actual port (0 = ephemeral).
        for sock in site._server.sockets:  # noqa: SLF001
            self._port = sock.getsockname()[1]
            break
        # Route-table push (reference: LongPollClient in the proxy): a
        # background task parks in the controller and refreshes the
        # local table on change — the request path reads it locally.
        self._route_poll = asyncio.ensure_future(self._poll_routes())
        self._started.set()
        return self._port

    async def _poll_routes(self):
        import ray_tpu
        from ray_tpu.serve.handle import DeploymentHandle
        handles = {}  # name -> DeploymentHandle (stable across versions)
        while True:
            try:
                version, routes = await asyncio.to_thread(
                    lambda: ray_tpu.get(
                        self._controller.listen_for_change.remote(
                            "routes", self._version), timeout=90))
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 - controller restarting
                await asyncio.sleep(0.2)
                continue
            for name in list(handles):
                if name not in routes.values():
                    del handles[name]
            self._routes = {
                prefix: handles.setdefault(
                    name, DeploymentHandle(name, self._controller))
                for prefix, name in routes.items()}
            self._version = version

    async def has_route(self, prefix: str) -> bool:
        """serve.run's readiness probe: has the long-poll delivered this
        prefix to the local table yet?"""
        return prefix in self._routes

    async def _wait_for_routes(self, timeout: float = 15.0) -> None:
        # Wait only for the FIRST membership push (a request racing proxy
        # startup): once a push has arrived (version >= 0), an empty
        # table is authoritative — e.g. after deleting the last
        # deployment — and must 404 immediately, not stall here.
        deadline = asyncio.get_event_loop().time() + timeout
        while self._version < 0 and \
                asyncio.get_event_loop().time() < deadline:
            await asyncio.sleep(0.05)

    async def _handle(self, request):
        import ray_tpu
        from aiohttp import web
        if not self._routes:
            await self._wait_for_routes()
        path = "/" + request.match_info["tail"]
        # Longest matching prefix wins (reference: route table matching).
        match = None
        for prefix in sorted(self._routes, key=len, reverse=True):
            if path == prefix or path.startswith(
                    prefix.rstrip("/") + "/") or prefix == "/":
                match = prefix
                break
        if match is None:
            return web.json_response(
                {"error": f"no deployment at {path}"}, status=404)
        handle = self._routes[match]
        body = await request.read()
        req = Request(request.method, path, dict(request.query),
                      body, dict(request.headers))
        from ray_tpu.exceptions import BackPressureError, GetTimeoutError
        try:
            ref = handle.remote(req)
            result = await asyncio.to_thread(
                lambda: ray_tpu.get([ref], timeout=60)[0])
        except BackPressureError as e:
            # Overload sheds, it doesn't error: clients should back off
            # and retry (reference: serve proxy 503 on BackPressureError).
            return web.json_response({"error": str(e)}, status=503,
                                     headers={"Retry-After": "1"})
        except GetTimeoutError as e:
            # A handle.options(timeout_s=...) deadline (or the proxy's
            # own 60s cap) expired before a replica answered.
            return web.json_response({"error": str(e)}, status=504)
        except ValueError as e:
            # Router-side "deployment does not exist": the route table
            # is mid-refresh after a delete. Application ValueErrors
            # arrive wrapped in TaskError, so this is unambiguous.
            return web.json_response({"error": str(e)}, status=404)
        except Exception as e:  # noqa: BLE001 - surface as 500
            return web.json_response({"error": str(e)}, status=500)
        if isinstance(result, bytes):
            return web.Response(body=result)
        if isinstance(result, str):
            return web.Response(text=result)
        return web.json_response(result)

    async def shutdown(self) -> bool:
        poll = getattr(self, "_route_poll", None)
        if poll is not None:
            poll.cancel()
        if self._runner is not None:
            await self._runner.cleanup()
        return True
