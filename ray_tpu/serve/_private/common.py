"""Shared serve-internal helpers: replica lifecycle states, the
system-failure classification that gates router failover, and config
access (analog of the reference's serve/_private/common.py).
"""

from __future__ import annotations

import os
from typing import Any

from ray_tpu.exceptions import (ActorError, NodeDiedError, ObjectLostError,
                                WorkerCrashedError)

# Replica lifecycle (reference: serve/_private/common.py ReplicaState):
# STARTING -> RUNNING -> DRAINING -> STOPPED. Only RUNNING replicas are
# published to routers; DRAINING replicas finish in-flight work, then die.
STARTING = "STARTING"
RUNNING = "RUNNING"
DRAINING = "DRAINING"
STOPPED = "STOPPED"

# What counts as "the infrastructure failed" (retry elsewhere) versus
# "the application raised" (surface to the caller unchanged). TaskError
# wraps application exceptions and is deliberately NOT here.
_SYSTEM_FAILURES = (ActorError, ObjectLostError, NodeDiedError,
                    WorkerCrashedError)


def is_system_failure(exc: BaseException) -> bool:
    if isinstance(exc, _SYSTEM_FAILURES):
        return True
    # A replica that REFUSES work (draining, chaos-dead) raises
    # ActorDiedError from inside the method body; the actor executor
    # wraps in-method exceptions in TaskError, so classify the cause too.
    return isinstance(getattr(exc, "cause", None), _SYSTEM_FAILURES)


def serve_config(name: str, default: Any) -> Any:
    """Read a serve flag with the standard precedence: runtime config
    (native/python flag table, already env-overridden) when a runtime is
    up, else the raw ``RAY_TPU_<name>`` env var, else the default."""
    try:
        from ray_tpu._private.worker import global_worker
        runtime = global_worker._runtime
        cfg = getattr(runtime, "config", None)
        if cfg is not None:
            return cfg.get(name)
    except Exception:  # noqa: BLE001 - fall back to the env var
        pass
    env = os.environ.get(f"RAY_TPU_{name}")
    if env is None:
        return default
    if isinstance(default, bool):
        return env.lower() in ("1", "true", "yes", "on")
    if isinstance(default, int):
        try:
            return int(float(env))
        except ValueError:
            return default
    if isinstance(default, float):
        try:
            return float(env)
        except ValueError:
            return default
    return env
