"""Shared serve-internal helpers: replica lifecycle states and config
access (analog of the reference's serve/_private/common.py). The
system-failure classification that gates router failover moved to
``ray_tpu.exceptions.is_system_failure`` so train gang recovery shares
it; it is re-exported here for existing importers.
"""

from __future__ import annotations

from typing import Any

# Re-exported for serve-internal callers: the classification itself
# lives in ray_tpu.exceptions so train gang recovery and serve failover
# share one definition.
from ray_tpu.exceptions import is_system_failure  # noqa: F401

# Replica lifecycle (reference: serve/_private/common.py ReplicaState):
# STARTING -> RUNNING -> DRAINING -> STOPPED. Only RUNNING replicas are
# published to routers; DRAINING replicas finish in-flight work, then die.
STARTING = "STARTING"
RUNNING = "RUNNING"
DRAINING = "DRAINING"
STOPPED = "STOPPED"


def serve_config(name: str, default: Any) -> Any:
    """Read a serve flag with the standard precedence: runtime config
    (native/python flag table, already env-overridden) when a runtime is
    up, else the raw ``RAY_TPU_<name>`` env var, else the default.
    Thin alias over the shared ``runtime_config_value`` (the same
    precedence train's fault-tolerance knobs use)."""
    from ray_tpu._private.ray_config import runtime_config_value
    return runtime_config_value(name, default)
