"""Serve autoscaler: the actuation half of the signal plane.

Analog of the reference's serve/_private/autoscaling_policy.py (replicas
sized to ongoing-requests / target) split the same way the reference
splits it: a PURE decision engine (:class:`AutoscalePolicy`, unit-testable
with injected clocks and stats) and a thin actuation pass the controller's
control loop runs on the ``serve_autoscale_interval_s`` cadence.

Inputs per deployment, all windowed from the head's time-series store
(``controller.deployment_stats()`` → ``runtime.serve_stats``):

* **queue depth** — mean outstanding requests across routers (in-flight +
  queued), the primary load signal: ``desired = ceil(load / target)``.
* **p95 burn** — if the deployment declares ``target_p95_ms`` and the
  windowed p95 exceeds it under traffic, the policy forces at least one
  step up even when the queue-depth math says "enough".
* **scale hints** — typed ``scale_hint`` alerts (e.g. ``serve_p95_burn``)
  recorded by the controller: a firing "up" hint forces at least one step
  up and blocks scale-down entirely. Hints are TTL-aged
  (``serve_scale_hint_ttl_s``) so a dead alert engine cannot pin a
  deployment's hint forever.

Stability comes from hysteresis and cooldown, not smoothing: scale-up is
immediate after ``upscale_delay_s`` of cooldown (default 0 — saturating
traffic must not wait), scale-down requires the downscale verdict to hold
*continuously* for ``downscale_delay_s`` AND that long since the last
action, so a traffic dip between bursts never drops replicas. Targets are
always clamped to the deployment's ``[min_replicas, max_replicas]``.

Actuation goes through the ordinary reconcile path: scale-up starts
STARTING replicas, scale-down marks victims DRAINING (in-flight requests
finish, bounded by ``serve_drain_timeout_s``) — the autoscaler never drops
a request. Every decision is journaled (``source="autoscale"``) and
counted in ``ray_tpu_serve_autoscale_decisions_total{direction}``; the
per-deployment target lands in the ``ray_tpu_serve_target_replicas``
gauge so target-vs-actual is one Grafana panel.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Dict, Optional

# autoscaling_config keys a deployment may declare. Unknown keys are a
# config error (schema.validate + normalize both enforce it): a typo'd
# "max_replica" silently defaulting is how autoscalers run away.
KNOWN_CONFIG_KEYS = frozenset({
    "min_replicas", "max_replicas",
    "target_ongoing_requests",
    # Reference-Ray spelling, kept as an alias.
    "target_num_ongoing_requests_per_replica",
    "target_p95_ms",
    "upscale_delay_s", "downscale_delay_s",
})


def normalize_config(cfg: Dict[str, Any], *,
                     current_replicas: int = 1,
                     default_upscale_delay_s: float = 0.0,
                     default_downscale_delay_s: float = 10.0
                     ) -> Dict[str, Any]:
    """Validate + fill an ``autoscaling_config`` dict. Raises ValueError
    on unknown keys or inconsistent bounds. Pure."""
    unknown = set(cfg) - KNOWN_CONFIG_KEYS
    if unknown:
        raise ValueError(
            f"Unknown autoscaling_config keys {sorted(unknown)}; "
            f"supported: {sorted(KNOWN_CONFIG_KEYS)}")
    min_r = int(cfg.get("min_replicas", 1))
    max_r = int(cfg.get("max_replicas", max(current_replicas, min_r, 1)))
    if min_r < 1:
        raise ValueError(f"min_replicas must be >= 1, got {min_r}")
    if min_r > max_r:
        raise ValueError(
            f"min_replicas ({min_r}) > max_replicas ({max_r})")
    target = cfg.get("target_ongoing_requests",
                     cfg.get("target_num_ongoing_requests_per_replica", 2))
    target = float(target)
    if target <= 0:
        raise ValueError(
            f"target_ongoing_requests must be > 0, got {target}")
    p95 = cfg.get("target_p95_ms")
    if p95 is not None and float(p95) <= 0:
        raise ValueError(f"target_p95_ms must be > 0, got {p95}")
    up_delay = float(cfg.get("upscale_delay_s", default_upscale_delay_s))
    down_delay = float(cfg.get("downscale_delay_s",
                               default_downscale_delay_s))
    if up_delay < 0 or down_delay < 0:
        raise ValueError("autoscaling delays must be >= 0")
    return {
        "min_replicas": min_r,
        "max_replicas": max_r,
        "target_ongoing_requests": target,
        "target_p95_ms": None if p95 is None else float(p95),
        "upscale_delay_s": up_delay,
        "downscale_delay_s": down_delay,
    }


@dataclass
class Decision:
    """One autoscaling verdict for one deployment."""

    target: int
    direction: str  # "up" | "down" | "none"
    reason: str

    @property
    def changed(self) -> bool:
        return self.direction != "none"


class _DeploymentScaleState:
    """Per-deployment hysteresis memory (pure-policy side)."""

    __slots__ = ("last_scale_t", "down_since")

    def __init__(self):
        self.last_scale_t: Optional[float] = None  # None = never scaled
        self.down_since: Optional[float] = None


class AutoscalePolicy:
    """Pure decision engine: no clocks, no RPCs, no metrics — callers
    inject ``now`` and windowed ``stats``, making every branch a unit
    test (target computation, hysteresis, cooldown, clamps, hint
    override)."""

    def __init__(self):
        self._state: Dict[str, _DeploymentScaleState] = {}

    def forget(self, name: str) -> None:
        """Drop hysteresis state for a deleted deployment."""
        self._state.pop(name, None)

    def desired_replicas(self, cfg: Dict[str, Any], current: int,
                         stats: Optional[Dict[str, Any]],
                         hint: Optional[Dict[str, Any]]) -> tuple:
        """The raw (pre-hysteresis) target: ``ceil(load / target)``
        with the p95-burn and scale-hint floors, clamped to bounds.
        Returns (desired, reason). Pure and stateless."""
        min_r, max_r = cfg["min_replicas"], cfg["max_replicas"]
        stats = stats or {}
        load = float(stats.get("mean_queue_depth", 0.0) or 0.0)
        qps = float(stats.get("qps", 0.0) or 0.0)
        desired = math.ceil(load / cfg["target_ongoing_requests"])
        reason = (f"queue_depth={load:.2f} "
                  f"target={cfg['target_ongoing_requests']:g}")
        # p95 burn: latency over budget under live traffic forces at
        # least one step up even if the queue math is satisfied.
        p95_budget = cfg.get("target_p95_ms")
        if p95_budget and qps > 0:
            p95_ms = float(stats.get("p95_s", 0.0) or 0.0) * 1000.0
            if p95_ms > p95_budget and desired <= current:
                desired = current + 1
                reason = (f"p95_burn {p95_ms:.1f}ms > "
                          f"{p95_budget:g}ms budget")
        # Scale-hint override: a firing "up" hint (alert plane) floors
        # the target at one step up; resolution/TTL clears it.
        if hint is not None and hint.get("direction", "up") == "up":
            if desired <= current:
                desired = current + 1
                reason = f"scale_hint:{hint.get('rule', '?')}"
        return max(min_r, min(max_r, desired)), reason

    def decide(self, name: str, *, current: int, cfg: Dict[str, Any],
               stats: Optional[Dict[str, Any]],
               hint: Optional[Dict[str, Any]], now: float) -> Decision:
        """Full decision: raw target + hysteresis/cooldown. ``cfg`` must
        be :func:`normalize_config` output; ``current`` is the DESIRED
        replica count (actuation-in-progress must not double-trigger)."""
        st = self._state.setdefault(name, _DeploymentScaleState())
        desired, reason = self.desired_replicas(cfg, current, stats, hint)
        since_scale = (math.inf if st.last_scale_t is None
                       else now - st.last_scale_t)
        if desired > current:
            st.down_since = None
            if since_scale < cfg["upscale_delay_s"]:
                return Decision(current, "none",
                                f"cooldown ({reason})")
            st.last_scale_t = now
            return Decision(desired, "up", reason)
        if desired < current:
            # Hint in force = never down (even a "down" raw verdict):
            # the alert plane says this deployment is burning.
            if hint is not None and hint.get("direction", "up") == "up":
                st.down_since = None
                return Decision(current, "none", "scale_hint holds")
            if st.down_since is None:
                st.down_since = now
            held = now - st.down_since
            if held < cfg["downscale_delay_s"] or \
                    since_scale < cfg["downscale_delay_s"]:
                return Decision(current, "none",
                                f"downscale held {held:.1f}s ({reason})")
            st.down_since = None
            st.last_scale_t = now
            return Decision(desired, "down", reason)
        st.down_since = None
        return Decision(current, "none", reason)
