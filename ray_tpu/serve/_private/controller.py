"""ServeController: the deployment control plane.

Analog of the reference's serve/controller.py:64 ServeController +
_private/deployment_state.py: a singleton async actor that owns desired
state (deployments, replica counts), reconciles actual replica actors
toward it, restarts failed replicas, and serves membership (with a version
counter standing in for the reference's LongPollHost push channel,
_private/long_poll.py:68 — routers poll the version and refresh on change).
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.serve._private.replica import ReplicaActor

logger = logging.getLogger("ray_tpu.serve")

CONTROLLER_NAME = "_serve_controller"


class DeploymentInfo:
    def __init__(self, name: str, deployment_def_bytes: bytes,
                 init_args, init_kwargs, num_replicas: int,
                 ray_actor_options: dict, route_prefix: Optional[str],
                 max_concurrent_queries: int,
                 autoscaling_config: Optional[dict], version: str,
                 user_config: Optional[Any] = None):
        self.name = name
        self.deployment_def_bytes = deployment_def_bytes
        self.init_args = init_args
        self.init_kwargs = init_kwargs
        self.num_replicas = num_replicas
        self.ray_actor_options = ray_actor_options or {}
        self.route_prefix = route_prefix
        self.max_concurrent_queries = max_concurrent_queries
        self.autoscaling_config = autoscaling_config
        self.version = version
        self.user_config = user_config
        self.replicas: List[Any] = []  # live ActorHandles


class ServeController:
    """deploy/delete mutate desired state; a reconcile pass runs after every
    mutation and periodically from the autoscale tick."""

    def __init__(self):
        self._deployments: Dict[str, DeploymentInfo] = {}
        self._membership_version = 0
        self._replica_seq = 0
        # Long-poll wakeup (reference: _private/long_poll.py:68
        # LongPollHost): created lazily inside the actor's event loop;
        # replaced on every bump so each change wakes ALL parked waiters.
        self._changed = None

    def _bump_membership(self) -> None:
        self._membership_version += 1
        ev = self._changed
        self._changed = None
        if ev is not None:
            ev.set()

    # -- desired state ---------------------------------------------------

    async def deploy(self, name: str, deployment_def_bytes: bytes,
                     init_args, init_kwargs, num_replicas: int,
                     ray_actor_options: dict, route_prefix: Optional[str],
                     max_concurrent_queries: int,
                     autoscaling_config: Optional[dict],
                     version: str, user_config: Optional[Any] = None) -> bool:
        existing = self._deployments.get(name)
        info = DeploymentInfo(name, deployment_def_bytes, init_args,
                              init_kwargs, num_replicas, ray_actor_options,
                              route_prefix, max_concurrent_queries,
                              autoscaling_config, version,
                              user_config=user_config)
        if existing is not None:
            if existing.version == version and \
                    existing.num_replicas == num_replicas:
                if existing.user_config != user_config:
                    # Same code/scale, new user_config: deliver it via
                    # reconfigure() without replica churn.
                    existing.user_config = user_config
                    if user_config is not None:
                        ray_tpu.get([r.reconfigure.remote(user_config)
                                     for r in existing.replicas])
                    return True
                return False
            # Code/config change: replace replicas (simple rolling=all).
            info.replicas = [] if existing.version != version else \
                existing.replicas
            if existing.version != version:
                for r in existing.replicas:
                    ray_tpu.kill(r)
        self._deployments[name] = info
        await self._reconcile(name)
        return True

    async def delete_deployment(self, name: str) -> bool:
        info = self._deployments.pop(name, None)
        if info is None:
            return False
        for r in info.replicas:
            ray_tpu.kill(r)
        self._bump_membership()
        return True

    async def shutdown(self) -> bool:
        for name in list(self._deployments):
            await self.delete_deployment(name)
        return True

    # -- reconciliation --------------------------------------------------

    async def _reconcile(self, name: str) -> None:
        info = self._deployments.get(name)
        if info is None:
            return
        new_replicas = []
        while len(info.replicas) < info.num_replicas:
            self._replica_seq += 1
            cls = ray_tpu.remote(ReplicaActor)
            opts = dict(info.ray_actor_options)
            opts.setdefault("max_concurrency", info.max_concurrent_queries)
            opts["name"] = f"_serve_replica::{name}::{self._replica_seq}"
            opts["max_restarts"] = 3
            replica = cls.options(**opts).remote(
                name, info.deployment_def_bytes, info.init_args,
                info.init_kwargs)
            info.replicas.append(replica)
            new_replicas.append(replica)
        while len(info.replicas) > info.num_replicas:
            victim = info.replicas.pop()
            ray_tpu.kill(victim)
        self._bump_membership()
        # Wait for replicas to become ready so run() returns a usable app.
        for r in info.replicas:
            ray_tpu.get(r.ready.remote())
        if info.user_config is not None and new_replicas:
            # user_config reaches NEW replicas via reconfigure(); existing
            # ones already have it (re-sending on every health tick would
            # re-run potentially expensive reloads).
            ray_tpu.get([r.reconfigure.remote(info.user_config)
                         for r in new_replicas])

    async def check_health(self, name: str) -> int:
        """Probe replicas; restart any that died. Returns live count
        (reference: deployment_state health-check loop)."""
        info = self._deployments.get(name)
        if info is None:
            return 0
        live = []
        for r in info.replicas:
            try:
                ray_tpu.get([r.ready.remote()], timeout=5)
                live.append(r)
            except Exception:  # noqa: BLE001 - dead replica
                logger.warning("Replica of %s failed health check", name)
        info.replicas = live
        await self._reconcile(name)
        return len(live)

    # -- membership / routing -------------------------------------------

    async def membership_version(self) -> int:
        return self._membership_version

    async def get_replicas(self, name: str):
        info = self._deployments.get(name)
        if info is None:
            raise ValueError(f"Deployment {name!r} does not exist")
        return (self._membership_version, info.replicas,
                info.max_concurrent_queries)

    async def listen_for_change(self, key, last_version: int,
                                timeout_s: float = 30.0):
        """Long-poll (reference: LongPollHost.listen_for_change): parks
        until the membership version moves past ``last_version`` (or the
        keepalive timeout), then returns the current snapshot for
        ``key`` — ("replicas", name) or "routes". Routers/proxies call
        this from a background thread; the REQUEST path never does."""
        import asyncio
        loop = asyncio.get_event_loop()
        deadline = loop.time() + timeout_s
        while self._membership_version <= last_version:
            remaining = deadline - loop.time()
            if remaining <= 0:
                break
            if self._changed is None:
                self._changed = asyncio.Event()
            try:
                await asyncio.wait_for(self._changed.wait(), remaining)
            except asyncio.TimeoutError:
                break
        if key == "routes":
            return (self._membership_version, await self.get_routes())
        name = key[1]
        info = self._deployments.get(name)
        if info is None:
            # None (not []) = "no such deployment": routers fail requests
            # fast instead of waiting out the replica-appearance window.
            return (self._membership_version, None, 1)
        return (self._membership_version, list(info.replicas),
                info.max_concurrent_queries)

    async def list_deployments(self) -> Dict[str, dict]:
        return {
            name: {
                "num_replicas": info.num_replicas,
                "live_replicas": len(info.replicas),
                "route_prefix": info.route_prefix,
                "version": info.version,
                "autoscaling_config": info.autoscaling_config,
            }
            for name, info in self._deployments.items()
        }

    async def get_routes(self) -> Dict[str, str]:
        return {info.route_prefix: name
                for name, info in self._deployments.items()
                if info.route_prefix}

    # -- autoscaling -----------------------------------------------------

    async def autoscale_tick(self) -> Dict[str, int]:
        """One autoscaling pass (reference: _private/autoscaling_policy.py:
        replicas sized to ongoing-requests / target). Called periodically by
        the proxy or tests."""
        decisions = {}
        for name, info in self._deployments.items():
            cfg = info.autoscaling_config
            if not cfg:
                continue
            target = cfg.get("target_num_ongoing_requests_per_replica", 1)
            min_r = cfg.get("min_replicas", 1)
            max_r = cfg.get("max_replicas", max(info.num_replicas, 1))
            total_ongoing = 0
            for r in info.replicas:
                try:
                    total_ongoing += ray_tpu.get(
                        [r.num_ongoing.remote()], timeout=5)[0]
                except Exception:  # noqa: BLE001
                    pass
            desired = max(min_r, min(max_r, round(total_ongoing / target)
                                     if target else min_r))
            if desired != info.num_replicas:
                info.num_replicas = desired
                await self._reconcile(name)
            decisions[name] = info.num_replicas
        return decisions


def get_or_create_controller():
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        cls = ray_tpu.remote(ServeController)
        # Concurrency covers one parked long-poll per router/proxy on
        # top of the control operations.
        return cls.options(name=CONTROLLER_NAME, get_if_exists=True,
                           max_concurrency=128).remote()
