"""ServeController: the deployment control plane.

Analog of the reference's serve/controller.py:64 ServeController +
_private/deployment_state.py: a singleton async actor that owns desired
state (deployments, replica counts), reconciles actual replica actors
toward it, restarts failed replicas, and serves membership (with a version
counter standing in for the reference's LongPollHost push channel,
_private/long_poll.py:68 — routers poll the version and refresh on change).

Replica lifecycle (reference: deployment_state.py ReplicaState):
STARTING -> RUNNING -> DRAINING -> STOPPED. Only RUNNING replicas are
published to routers. Scale-down and redeploy never hard-kill a serving
replica: victims are marked DRAINING (they refuse new work, routers drop
them on the membership push), the control loop polls ``num_ongoing`` down
to zero bounded by ``serve_drain_timeout_s``, and only then kills.
Rolling redeploy starts the new generation first and retires the old one
once the replacements are RUNNING. Replica startup is bounded by
``serve_startup_timeout_s`` and retried against ``serve_start_budget``;
health checks probe the user-overridable ``check_health()`` in parallel
every ``serve_health_check_period_s`` and replace replicas after
``serve_health_failure_threshold`` consecutive failures.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu._private import builtin_metrics, events
from ray_tpu.serve._private import autoscaler as autoscaler_mod
from ray_tpu.serve._private.common import (DRAINING, RUNNING, STARTING,
                                           STOPPED, is_system_failure,
                                           serve_config)
from ray_tpu.serve._private.replica import ReplicaActor

logger = logging.getLogger("ray_tpu.serve")

CONTROLLER_NAME = "_serve_controller"


class ReplicaState:
    """One replica actor's lifecycle record."""

    __slots__ = ("handle", "name", "state", "version", "health_failures",
                 "drain_deadline")

    def __init__(self, handle, name: str, version: str):
        self.handle = handle
        self.name = name  # runtime actor name (get_actor-able)
        self.state = STARTING
        self.version = version
        self.health_failures = 0
        self.drain_deadline: Optional[float] = None  # loop.time(), DRAINING

    def snapshot(self) -> dict:
        return {"name": self.name, "state": self.state,
                "version": self.version,
                "health_failures": self.health_failures}


class DeploymentInfo:
    def __init__(self, name: str, deployment_def_bytes: bytes,
                 init_args, init_kwargs, num_replicas: int,
                 ray_actor_options: dict, route_prefix: Optional[str],
                 max_concurrent_queries: int,
                 autoscaling_config: Optional[dict], version: str,
                 user_config: Optional[Any] = None,
                 max_queued_requests: int = -1):
        self.name = name
        self.deployment_def_bytes = deployment_def_bytes
        self.init_args = init_args
        self.init_kwargs = init_kwargs
        self.num_replicas = num_replicas
        self.ray_actor_options = ray_actor_options or {}
        self.route_prefix = route_prefix
        self.max_concurrent_queries = max_concurrent_queries
        self.autoscaling_config = autoscaling_config
        self.version = version
        self.user_config = user_config
        self.max_queued_requests = max_queued_requests
        self.replicas: List[ReplicaState] = []

    def running(self) -> List[ReplicaState]:
        return [r for r in self.replicas if r.state == RUNNING]


async def _get_async(refs, timeout):
    """Await a blocking ray_tpu.get off the controller's event loop (a
    single loop serves every long-poll; it must never block)."""
    return await asyncio.to_thread(ray_tpu.get, refs, timeout=timeout)


class ServeController:
    """deploy/delete mutate desired state; a reconcile pass runs after every
    mutation; a background control loop runs health checks and drains."""

    def __init__(self):
        self._deployments: Dict[str, DeploymentInfo] = {}
        self._membership_version = 0
        self._replica_seq = 0
        # Long-poll wakeup (reference: _private/long_poll.py:68
        # LongPollHost): created lazily inside the actor's event loop;
        # replaced on every bump so each change wakes ALL parked waiters.
        self._changed = None
        self._reconcile_lock: Optional[asyncio.Lock] = None
        self._control_task = None
        # Node-death push (membership subsystem): a declared node death
        # wakes the control loop for an immediate health pass instead
        # of waiting out the rest of the period — replicas on the dead
        # node are replaced in push-latency, not poll-latency.
        self._node_event: Optional[asyncio.Event] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._membership_subscribed = False
        # Scale hints pushed by the alerting plane (typed scale_hint
        # alerts, e.g. serve_p95_burn): latest firing hint per
        # deployment. Cleared on alert resolve AND TTL-aged
        # (serve_scale_hint_ttl_s) so a dead alert engine cannot pin a
        # deployment's hint forever. Input to the autoscaler policy.
        self._scale_hints: Dict[str, dict] = {}
        self._alerts_subscribed = False
        # Autoscaler (serve/_private/autoscaler.py): pure policy state
        # plus the control-loop cadence marker. Decisions actuate
        # through the ordinary reconcile path (STARTING replicas on the
        # way up, DRAINING on the way down).
        self._autoscale_policy = autoscaler_mod.AutoscalePolicy()
        self._next_autoscale_t = 0.0

    def _bump_membership(self) -> None:
        self._membership_version += 1
        ev = self._changed
        self._changed = None
        if ev is not None:
            ev.set()

    def _ensure_background(self) -> None:
        """Start the health/drain control loop (lazily: __init__ may run
        before the actor's event loop owns this coroutine context)."""
        if self._reconcile_lock is None:
            self._reconcile_lock = asyncio.Lock()
        if self._node_event is None:
            self._node_event = asyncio.Event()
            self._loop = asyncio.get_event_loop()
        if not self._membership_subscribed:
            self._membership_subscribed = True
            self._subscribe_membership()
        if not self._alerts_subscribed:
            self._alerts_subscribed = True
            self._subscribe_alerts()
        if self._control_task is None or self._control_task.done():
            self._control_task = asyncio.ensure_future(self._control_loop())

    # -- durable desired state (head failover) ---------------------------

    def _gcs_store(self):
        """The head runtime's gcs_store, when reachable in-process (the
        controller is a head-resident actor). None = persistence off."""
        try:
            from ray_tpu._private.worker import global_worker
            return getattr(global_worker._runtime, "gcs_store", None)
        except Exception:  # noqa: BLE001 - no in-process runtime
            return None

    def _persist_deployment(self, info: "DeploymentInfo") -> None:
        """Write the FULL deploy payload to the gcs_store so a head
        reborn on the same store can replay the deploy against a fresh
        controller (reference: serve checkpointing its desired state
        into the GCS KV). Best-effort: unpicklable init args degrade to
        in-memory-only desired state, logged once per deploy."""
        store = self._gcs_store()
        if store is None:
            return
        import cloudpickle
        try:
            payload = cloudpickle.dumps((info.init_args,
                                         info.init_kwargs))
        except Exception:  # noqa: BLE001 - user args may not pickle
            logger.warning(
                "deployment %r has unpicklable init args; it will NOT "
                "survive a head restart", info.name)
            return
        try:
            store.record_serve_deployment(info.name, {
                "name": info.name,
                "deployment_def_bytes": info.deployment_def_bytes,
                "init_payload": payload,
                "num_replicas": info.num_replicas,
                "ray_actor_options": dict(info.ray_actor_options or {}),
                "route_prefix": info.route_prefix,
                "max_concurrent_queries": info.max_concurrent_queries,
                "autoscaling_config": info.autoscaling_config,
                "version": info.version,
                "user_config": info.user_config,
                "max_queued_requests": info.max_queued_requests,
                "recorded_at": time.time(),
            })
        except OSError:
            logger.exception("could not persist deployment %r",
                             info.name)

    def _unpersist_deployment(self, name: str) -> None:
        store = self._gcs_store()
        if store is None:
            return
        try:
            store.remove_serve_deployment(name)
        except OSError:
            logger.exception("could not remove deployment record %r",
                             name)

    def _subscribe_membership(self) -> None:
        """Subscribe to the head runtime's membership table when it is
        reachable in-process (the controller is a head-resident actor).
        Best effort: without it the control loop still catches node
        death on its next periodic pass."""
        try:
            from ray_tpu._private.worker import global_worker
            membership = getattr(global_worker._runtime, "membership",
                                 None)
        except Exception:  # noqa: BLE001 - no in-process runtime
            membership = None
        if membership is not None:
            membership.subscribe(self._on_membership_event)

    def _subscribe_alerts(self) -> None:
        """Subscribe to the head alert engine for typed scale_hint
        alerts (same in-process best-effort reach as membership)."""
        try:
            from ray_tpu._private.worker import global_worker
            global_worker._runtime.subscribe_alerts(self._on_alert)
        except Exception:  # noqa: BLE001 - no in-process alert plane
            pass

    def _on_alert(self, alert: dict) -> None:
        """Runs on the head metrics-update thread: record/clear the
        latest scale hint per deployment. No replica churn here — the
        hint is advisory input for the autoscaler, not a command."""
        hint = alert.get("scale_hint")
        if not isinstance(hint, dict):
            return
        deployment = str(hint.get("deployment")
                         or (alert.get("labels") or {}).get("deployment")
                         or alert.get("key") or "")
        if not deployment:
            return
        if alert.get("state") == "firing":
            self._scale_hints[deployment] = {
                "direction": hint.get("direction", "up"),
                "rule": alert.get("rule"),
                "value": alert.get("value"),
                "t": time.monotonic(),
            }
        elif alert.get("state") == "resolved":
            self._scale_hints.pop(deployment, None)

    def _live_scale_hints(self) -> Dict[str, dict]:
        """Firing scale hints younger than serve_scale_hint_ttl_s;
        expired ones are dropped on read (a crashed alert engine never
        delivers the resolve, so age is the backstop)."""
        ttl = serve_config("serve_scale_hint_ttl_s", 120.0)
        now = time.monotonic()
        for name in [n for n, h in self._scale_hints.items()
                     if ttl > 0 and now - h.get("t", now) > ttl]:
            hint = self._scale_hints.pop(name)
            events.emit("autoscale",
                        f"scale hint for {name} expired after {ttl:g}s "
                        f"(rule {hint.get('rule')})",
                        labels={"deployment": name,
                                "rule": str(hint.get("rule"))})
        return dict(self._scale_hints)

    def scale_hints(self) -> Dict[str, dict]:
        """Latest firing (unexpired) scale hints, keyed by deployment."""
        return self._live_scale_hints()

    def _on_membership_event(self, event: dict) -> None:
        """Runs on the DECLARER's thread (membership fan-out): hop to
        the controller's event loop and wake the control loop."""
        if event.get("event") != "dead":
            return
        loop, ev = self._loop, self._node_event
        if loop is not None and ev is not None:
            try:
                loop.call_soon_threadsafe(ev.set)
            except RuntimeError:
                pass  # loop already closed (controller shutting down)

    # -- desired state ---------------------------------------------------

    async def deploy(self, name: str, deployment_def_bytes: bytes,
                     init_args, init_kwargs, num_replicas: int,
                     ray_actor_options: dict, route_prefix: Optional[str],
                     max_concurrent_queries: int,
                     autoscaling_config: Optional[dict],
                     version: str, user_config: Optional[Any] = None,
                     max_queued_requests: int = -1) -> bool:
        self._ensure_background()
        if autoscaling_config:
            # Fail the deploy fast on a bad config (unknown key, bad
            # bounds) instead of skipping silent autoscale passes.
            autoscaler_mod.normalize_config(
                autoscaling_config, current_replicas=num_replicas)
        existing = self._deployments.get(name)
        info = DeploymentInfo(name, deployment_def_bytes, init_args,
                              init_kwargs, num_replicas, ray_actor_options,
                              route_prefix, max_concurrent_queries,
                              autoscaling_config, version,
                              user_config=user_config,
                              max_queued_requests=max_queued_requests)
        if existing is not None:
            if existing.version == version and \
                    existing.num_replicas == num_replicas:
                if existing.max_queued_requests != max_queued_requests:
                    existing.max_queued_requests = max_queued_requests
                    self._bump_membership()
                if existing.user_config != user_config:
                    # Same code/scale, new user_config: deliver it via
                    # reconfigure() without replica churn.
                    existing.user_config = user_config
                    if user_config is not None:
                        await _get_async(
                            [r.handle.reconfigure.remote(user_config)
                             for r in existing.replicas
                             if r.state in (STARTING, RUNNING)], None)
                    self._persist_deployment(existing)
                    return True
                self._persist_deployment(existing)
                return False
            # Code or scale changed: adopt the existing replica set and
            # reconcile — the rolling path starts the new generation
            # before draining the old one (never a hard kill).
            info.replicas = existing.replicas
        self._deployments[name] = info
        self._persist_deployment(info)
        await self._reconcile(name)
        return True

    async def delete_deployment(self, name: str) -> bool:
        info = self._deployments.pop(name, None)
        if info is None:
            return False
        # An explicit delete (or serve.shutdown) retires the durable
        # record too — only a CRASHED head leaves records to replay.
        self._unpersist_deployment(name)
        self._autoscale_policy.forget(name)
        # Unpublish first (routers and the proxy drop it on the push),
        # then drain in-flight work bounded by the drain window.
        self._bump_membership()
        victims = [r for r in info.replicas if r.state != STOPPED]
        for rs in victims:
            self._begin_drain(rs)
        await self._drain_and_stop(victims)
        return True

    async def shutdown(self) -> bool:
        if self._control_task is not None:
            self._control_task.cancel()
            self._control_task = None
        for name in list(self._deployments):
            await self.delete_deployment(name)
        return True

    # -- replica lifecycle ------------------------------------------------

    def _start_replica(self, info: DeploymentInfo) -> ReplicaState:
        self._replica_seq += 1
        cls = ray_tpu.remote(ReplicaActor)
        opts = dict(info.ray_actor_options)
        opts.setdefault("max_concurrency", info.max_concurrent_queries)
        actor_name = f"_serve_replica::{info.name}::{self._replica_seq}"
        opts["name"] = actor_name
        opts["max_restarts"] = 3
        handle = cls.options(**opts).remote(
            info.name, info.deployment_def_bytes, info.init_args,
            info.init_kwargs)
        rs = ReplicaState(handle, actor_name, info.version)
        info.replicas.append(rs)
        events.emit("serve", f"replica {actor_name} starting",
                    labels={"deployment": info.name, "replica": actor_name,
                            "version": info.version})
        return rs

    def _stop_replica(self, info: DeploymentInfo, rs: ReplicaState) -> None:
        rs.state = STOPPED
        try:
            ray_tpu.kill(rs.handle, no_restart=True)
        except Exception:  # noqa: BLE001 - already dead
            pass
        if rs in info.replicas:
            info.replicas.remove(rs)
        events.emit("serve", f"replica {rs.name} stopped",
                    severity="warning",
                    labels={"deployment": info.name, "replica": rs.name})

    def _begin_drain(self, rs: ReplicaState) -> None:
        """DRAINING: refuse new requests (in-flight ones finish), wait
        for num_ongoing to hit zero, then die — bounded by the window."""
        if rs.state == DRAINING:
            return
        rs.state = DRAINING
        rs.drain_deadline = asyncio.get_event_loop().time() + \
            serve_config("serve_drain_timeout_s", 30.0)
        events.emit("serve", f"replica {rs.name} draining",
                    labels={"replica": rs.name})
        try:
            rs.handle.set_draining.remote()  # push; poll loop re-pushes
        except Exception:  # noqa: BLE001 - replica already gone
            pass

    async def _drain_outcome(self, rs: ReplicaState) -> Optional[str]:
        """None = still draining; else the serve_drained outcome tag."""
        try:
            n = (await _get_async([rs.handle.num_ongoing.remote()], 5))[0]
        except Exception:  # noqa: BLE001 - died while draining
            return "dead"
        if n == 0:
            return "clean"
        if asyncio.get_event_loop().time() >= (rs.drain_deadline or 0):
            return "timeout"
        return None

    async def _drain_and_stop(self, victims: List[ReplicaState]) -> None:
        """Inline drain (delete/shutdown path): bounded by each victim's
        drain deadline, immediate when idle."""
        remaining = [r for r in victims if r.state == DRAINING]
        while remaining:
            still = []
            for rs in remaining:
                outcome = await self._drain_outcome(rs)
                if outcome is None:
                    still.append(rs)
                    continue
                self._finish_drain(None, rs, outcome)
            if not still:
                return
            remaining = still
            await asyncio.sleep(0.05)

    def _finish_drain(self, info: Optional[DeploymentInfo],
                      rs: ReplicaState, outcome: str) -> None:
        rs.state = STOPPED
        try:
            ray_tpu.kill(rs.handle, no_restart=True)
        except Exception:  # noqa: BLE001
            pass
        if info is not None and rs in info.replicas:
            info.replicas.remove(rs)
        builtin_metrics.serve_drained().inc(tags={"outcome": outcome})
        events.emit("serve", f"replica {rs.name} drained ({outcome})",
                    severity="info" if outcome == "clean" else "warning",
                    labels={"replica": rs.name, "outcome": outcome})

    # -- reconciliation --------------------------------------------------

    async def _reconcile(self, name: str) -> None:
        self._ensure_background()
        async with self._reconcile_lock:
            await self._reconcile_locked(name)

    async def _reconcile_locked(self, name: str) -> None:
        info = self._deployments.get(name)
        if info is None:
            return
        # 1. Start missing current-generation replicas (rolling: the old
        #    generation keeps serving while these come up).
        current = [r for r in info.replicas
                   if r.version == info.version
                   and r.state in (STARTING, RUNNING)]
        for _ in range(max(0, info.num_replicas - len(current))):
            self._start_replica(info)
        # 2. Bounded parallel startup wait (raises on exhausted budget).
        new_running = await self._wait_for_startup(info)
        # 3. Retire old-generation and excess replicas via draining.
        victims = [r for r in info.replicas
                   if r.state in (STARTING, RUNNING)
                   and r.version != info.version]
        current_running = [r for r in info.replicas
                           if r.version == info.version
                           and r.state == RUNNING]
        excess = len(current_running) - info.num_replicas
        if excess > 0:
            # Newest first: the longest-lived replicas keep serving.
            victims.extend(current_running[-excess:])
        for rs in victims:
            self._begin_drain(rs)
        # 4. Publish the new membership in one push.
        self._bump_membership()
        # 5. user_config reaches NEW replicas via reconfigure(); existing
        #    ones already have it (re-sending on every pass would re-run
        #    potentially expensive reloads).
        if info.user_config is not None and new_running:
            await _get_async(
                [r.handle.reconfigure.remote(info.user_config)
                 for r in new_running if r.state == RUNNING], None)

    async def _wait_for_startup(self, info: DeploymentInfo
                                ) -> List[ReplicaState]:
        """Wait (in parallel) for STARTING replicas of the current
        version; kill-and-recreate failures against the start budget.
        Returns the replicas that newly reached RUNNING."""
        timeout = serve_config("serve_startup_timeout_s", 30.0)
        budget = serve_config("serve_start_budget", 3)
        became_running: List[ReplicaState] = []
        last_error: Optional[BaseException] = None
        while True:
            starting = [r for r in info.replicas
                        if r.state == STARTING
                        and r.version == info.version]
            if not starting:
                return became_running

            async def _ready(rs: ReplicaState) -> Optional[BaseException]:
                try:
                    await _get_async([rs.handle.ready.remote()], timeout)
                    return None
                except Exception as exc:  # noqa: BLE001 - hung/crashed
                    return exc

            results = await asyncio.gather(*[_ready(r) for r in starting])
            failed = []
            for rs, exc in zip(starting, results):
                if exc is None:
                    rs.state = RUNNING
                    became_running.append(rs)
                else:
                    last_error = exc
                    failed.append(rs)
            if not failed:
                continue
            for rs in failed:
                logger.warning(
                    "Replica %s of %s failed to start (%s); killing and "
                    "recreating.", rs.name, info.name, last_error)
                self._stop_replica(info, rs)
            if budget < len(failed):
                raise RuntimeError(
                    f"Deployment {info.name!r} failed to start: replicas "
                    f"did not become ready within "
                    f"serve_startup_timeout_s={timeout}s and the "
                    f"serve_start_budget of retries is exhausted. Last "
                    f"error: {type(last_error).__name__}: {last_error}")
            budget -= len(failed)
            for _ in failed:
                self._start_replica(info)

    # -- health / drain control loop --------------------------------------

    async def _control_loop(self) -> None:
        while True:
            # Period-bounded wait that a membership death push cuts
            # short: replicas on a declared-dead node are probed (and
            # replaced) immediately.
            try:
                await asyncio.wait_for(
                    self._node_event.wait(),
                    timeout=serve_config(
                        "serve_health_check_period_s", 1.0))
            except asyncio.TimeoutError:
                pass
            self._node_event.clear()
            try:
                await self._health_pass()
                await self._drain_pass()
                await self._maybe_autoscale()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 - the loop must survive
                logger.exception("serve control loop pass failed")

    async def _probe(self, rs: ReplicaState,
                     timeout: float) -> Optional[BaseException]:
        try:
            await _get_async([rs.handle.check_health.remote()], timeout)
            return None
        except Exception as exc:  # noqa: BLE001 - classified by caller
            return exc

    async def _health_pass(self) -> None:
        timeout = serve_config("serve_health_check_timeout_s", 5.0)
        threshold = serve_config("serve_health_failure_threshold", 3)
        for name in list(self._deployments):
            info = self._deployments.get(name)
            if info is None:
                continue
            running = info.running()
            if not running:
                continue
            results = await asyncio.gather(
                *[self._probe(rs, timeout) for rs in running])
            changed = False
            for rs, exc in zip(running, results):
                if exc is None:
                    rs.health_failures = 0
                    continue
                rs.health_failures += 1
                builtin_metrics.serve_health_check_failures().inc()
                logger.warning(
                    "Replica %s of %s failed health check (%d/%d): %s",
                    rs.name, name, rs.health_failures, threshold, exc)
                if is_system_failure(exc):
                    # The actor itself is gone — draining is pointless.
                    self._stop_replica(info, rs)
                    changed = True
                elif rs.health_failures >= threshold:
                    self._begin_drain(rs)
                    changed = True
            if changed:
                self._bump_membership()
                await self._reconcile(name)  # start replacements now

    async def _drain_pass(self) -> None:
        for name in list(self._deployments):
            info = self._deployments.get(name)
            if info is None:
                continue
            for rs in [r for r in info.replicas if r.state == DRAINING]:
                outcome = await self._drain_outcome(rs)
                if outcome is not None:
                    self._finish_drain(info, rs, outcome)

    # -- membership / routing -------------------------------------------

    async def membership_version(self) -> int:
        return self._membership_version

    def _membership(self, info: DeploymentInfo):
        return (self._membership_version,
                [r.handle for r in info.replicas if r.state == RUNNING],
                info.max_concurrent_queries, info.max_queued_requests)

    async def get_replicas(self, name: str):
        info = self._deployments.get(name)
        if info is None:
            raise ValueError(f"Deployment {name!r} does not exist")
        return self._membership(info)

    async def replica_states(self, name: str) -> List[dict]:
        """Lifecycle introspection (tests, chaos benches: find real
        replica actor names to kill)."""
        info = self._deployments.get(name)
        if info is None:
            return []
        return [r.snapshot() for r in info.replicas]

    async def listen_for_change(self, key, last_version: int,
                                timeout_s: float = 30.0):
        """Long-poll (reference: LongPollHost.listen_for_change): parks
        until the membership version moves past ``last_version`` (or the
        keepalive timeout), then returns the current snapshot for
        ``key`` — ("replicas", name) or "routes". Routers/proxies call
        this from a background thread; the REQUEST path never does."""
        loop = asyncio.get_event_loop()
        deadline = loop.time() + timeout_s
        while self._membership_version <= last_version:
            remaining = deadline - loop.time()
            if remaining <= 0:
                break
            if self._changed is None:
                self._changed = asyncio.Event()
            try:
                await asyncio.wait_for(self._changed.wait(), remaining)
            except asyncio.TimeoutError:
                break
        if key == "routes":
            return (self._membership_version, await self.get_routes())
        name = key[1]
        info = self._deployments.get(name)
        if info is None:
            # None (not []) = "no such deployment": routers fail requests
            # fast instead of waiting out the replica-appearance window.
            return (self._membership_version, None, 1, -1)
        return self._membership(info)

    async def list_deployments(self) -> Dict[str, dict]:
        return {
            name: {
                "num_replicas": info.num_replicas,
                "live_replicas": len(info.running()),
                "route_prefix": info.route_prefix,
                "version": info.version,
                "autoscaling_config": info.autoscaling_config,
                "autoscaled": bool(info.autoscaling_config),
            }
            for name, info in self._deployments.items()
        }

    async def get_routes(self) -> Dict[str, str]:
        return {info.route_prefix: name
                for name, info in self._deployments.items()
                if info.route_prefix}

    async def deployment_stats(self, window: float = 30.0) -> dict:
        """Windowed per-deployment traffic rollup (qps, p50/p95, mean
        queue depth, replica count) from the head's time-series store —
        the signal a metrics-driven autoscaling policy polls instead of
        fanning RPCs out to every replica."""
        from ray_tpu._private.worker import global_worker
        runtime = getattr(global_worker, "_runtime", None)
        stats_fn = getattr(runtime, "serve_stats", None)
        if stats_fn is None:
            return {"window_s": window, "deployments": {}}
        return stats_fn(window=window)

    # -- autoscaling -----------------------------------------------------

    async def _maybe_autoscale(self) -> None:
        """Cadence gate for the autoscaling pass inside the control
        loop (the health/drain loop runs every
        serve_health_check_period_s; autoscaling on its own, slower,
        serve_autoscale_interval_s clock; <= 0 disables it)."""
        interval = serve_config("serve_autoscale_interval_s", 2.0)
        if interval <= 0:
            return
        now = asyncio.get_event_loop().time()
        if now < self._next_autoscale_t:
            return
        self._next_autoscale_t = now + interval
        await self._autoscale_pass()

    def _apply_autoscale_decision(self, info: DeploymentInfo,
                                  decision) -> None:
        """Record one actuated decision: counter + journal row. The
        target gauge is set unconditionally by the caller so
        target-vs-actual graphs exist even at steady state."""
        direction = decision.direction
        old = info.num_replicas
        info.num_replicas = decision.target
        # The autoscaler target is desired state too: persist it so a
        # reborn head resumes at the scaled target, not the deploy-time
        # replica count.
        self._persist_deployment(info)
        builtin_metrics.serve_autoscale_decisions().inc(
            tags={"deployment": info.name, "direction": direction})
        events.emit(
            "autoscale",
            f"deployment {info.name}: {old} -> {decision.target} "
            f"replicas ({decision.reason})",
            labels={"deployment": info.name, "direction": direction,
                    "from": str(old), "to": str(decision.target),
                    "reason": decision.reason[:120]})
        logger.info("Autoscaling %s: %d -> %d replicas (%s)",
                    info.name, old, decision.target, decision.reason)

    async def _autoscale_pass(self) -> Dict[str, int]:
        """One pass of the closed loop: windowed deployment stats +
        live scale hints -> pure policy -> reconcile. Scale-down goes
        through DRAINING (in-flight requests finish); scale-up starts
        replicas through the bounded-startup path."""
        window = serve_config("serve_autoscale_window_s", 15.0)
        try:
            stats = (await self.deployment_stats(window=window)).get(
                "deployments", {})
        except Exception:  # noqa: BLE001 - no signal plane: skip pass
            stats = {}
        hints = self._live_scale_hints()
        now = asyncio.get_event_loop().time()
        targets: Dict[str, int] = {}
        for name, info in list(self._deployments.items()):
            if not info.autoscaling_config:
                continue
            try:
                cfg = autoscaler_mod.normalize_config(
                    info.autoscaling_config,
                    current_replicas=info.num_replicas,
                    default_upscale_delay_s=serve_config(
                        "serve_autoscale_upscale_delay_s", 0.0),
                    default_downscale_delay_s=serve_config(
                        "serve_autoscale_downscale_delay_s", 10.0))
            except ValueError:
                logger.exception("Invalid autoscaling_config on %s; "
                                 "skipping", name)
                continue
            decision = self._autoscale_policy.decide(
                name, current=info.num_replicas, cfg=cfg,
                stats=stats.get(name), hint=hints.get(name), now=now)
            targets[name] = decision.target
            builtin_metrics.serve_target_replicas().set(
                decision.target, tags={"deployment": name})
            if decision.changed:
                self._apply_autoscale_decision(info, decision)
                await self._reconcile(name)
        return targets

    async def autoscale_status(self) -> Dict[str, dict]:
        """Target-vs-actual per autoscaled deployment (status/top
        surfaces): desired target, RUNNING count, bounds, live hint."""
        hints = self._live_scale_hints()
        out = {}
        for name, info in self._deployments.items():
            cfg = info.autoscaling_config
            if not cfg:
                continue
            out[name] = {
                "target": info.num_replicas,
                "running": len(info.running()),
                "min_replicas": cfg.get("min_replicas", 1),
                "max_replicas": cfg.get("max_replicas",
                                        info.num_replicas),
                "scale_hint": hints.get(name),
            }
        return out

    async def autoscale_tick(self) -> Dict[str, int]:
        """One autoscaling pass (reference: _private/autoscaling_policy.py:
        replicas sized to ongoing-requests / target). Called periodically by
        the proxy or tests."""
        decisions = {}
        for name, info in list(self._deployments.items()):
            cfg = info.autoscaling_config
            if not cfg:
                continue
            target = cfg.get("target_num_ongoing_requests_per_replica", 1)
            min_r = cfg.get("min_replicas", 1)
            max_r = cfg.get("max_replicas", max(info.num_replicas, 1))

            async def _ongoing(rs: ReplicaState) -> int:
                try:
                    return (await _get_async(
                        [rs.handle.num_ongoing.remote()], 5))[0]
                except Exception:  # noqa: BLE001
                    return 0

            counts = await asyncio.gather(
                *[_ongoing(r) for r in info.running()])
            total_ongoing = sum(counts)
            desired = max(min_r, min(max_r, round(total_ongoing / target)
                                     if target else min_r))
            builtin_metrics.serve_target_replicas().set(
                desired, tags={"deployment": name})
            if desired != info.num_replicas:
                self._apply_autoscale_decision(
                    info, autoscaler_mod.Decision(
                        desired,
                        "up" if desired > info.num_replicas else "down",
                        f"manual tick: ongoing={total_ongoing} "
                        f"target={target:g}"))
                await self._reconcile(name)
            decisions[name] = info.num_replicas
        return decisions


def get_or_create_controller():
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        cls = ray_tpu.remote(ServeController)
        # Concurrency covers one parked long-poll per router/proxy on
        # top of the control operations.
        return cls.options(name=CONTROLLER_NAME, get_if_exists=True,
                           max_concurrency=128).remote()
