"""Replica actor: hosts one copy of a deployment.

Analog of the reference's serve/_private/replica.py:260 RayServeReplica:
unwraps the deployment definition (class or function), constructs it once
(handles to other deployments arrive through init args — the DAG
composition path), then serves `handle_request` calls. Async methods are
awaited; `@serve.batch` methods batch transparently (serve/batching.py).

Lifecycle hooks (reference: replica.py check_health + drain protocol):
``check_health`` probes the user's ``check_health()`` when the deployment
defines one; ``set_draining`` flips the replica into drain mode — new
requests are refused with ActorDiedError (a SYSTEM failure, so routers
transparently fail them over to the new generation) while in-flight ones
run to completion and ``num_ongoing`` counts them down for the
controller's drain poll.

Chaos sites ``serve.replica_kill`` / ``serve.replica_delay_ms`` are
evaluated at the top of every ``handle_request``: a ``kill`` op makes
the replica play dead (every subsequent call raises ActorDiedError, the
same signal a genuinely killed actor produces), a ``delay_ms`` op
stalls the event loop — a whole-replica slowdown, the "slow replica"
failure mode.
"""

from __future__ import annotations

import asyncio
import inspect
from typing import Any

from ray_tpu._private import chaos
from ray_tpu.exceptions import ActorDiedError
from ray_tpu.util import tracing


class ReplicaActor:
    def __init__(self, deployment_name: str, deployment_def_bytes: bytes,
                 init_args, init_kwargs):
        import cloudpickle
        self._deployment_name = deployment_name
        deployment_def = cloudpickle.loads(deployment_def_bytes)
        self._is_function = inspect.isfunction(deployment_def)
        if self._is_function:
            self._callable = deployment_def
        else:
            self._callable = deployment_def(*(init_args or ()),
                                            **(init_kwargs or {}))
        self._ongoing = 0
        self._draining = False
        self._chaos_dead = False

    def _refuse(self, why: str) -> ActorDiedError:
        # The router classifies ActorDiedError (directly, or as the cause
        # inside the executor's TaskError wrapper) as a SYSTEM failure
        # and transparently fails the request over to another replica.
        return ActorDiedError(
            message=f"Replica of {self._deployment_name} is {why}.")

    async def ready(self) -> bool:
        if self._chaos_dead:
            raise self._refuse("dead (chaos kill)")
        return True

    async def num_ongoing(self) -> int:
        return self._ongoing

    async def check_health(self) -> bool:
        """Controller health probe: defers to the deployment's own
        ``check_health()`` when defined (sync or async); raising (or a
        chaos kill) marks the probe failed."""
        if self._chaos_dead:
            raise self._refuse("dead (chaos kill)")
        fn = getattr(self._callable, "check_health", None)
        if fn is not None:
            result = fn()
            if inspect.iscoroutine(result):
                await result
        return True

    async def set_draining(self) -> int:
        """Enter drain mode; returns the in-flight count at that moment
        (the controller polls num_ongoing until it reaches zero)."""
        self._draining = True
        return self._ongoing

    async def reconfigure(self, user_config: Any) -> bool:
        fn = getattr(self._callable, "reconfigure", None)
        if fn is not None:
            result = fn(user_config)
            if inspect.iscoroutine(result):
                await result
        return True

    async def handle_request(self, method_name: str, args, kwargs):
        if chaos.ACTIVE:
            chaos.maybe_inject("serve.replica_delay_ms")
            try:
                chaos.maybe_inject("serve.replica_kill")
            except chaos.ChaosKill:
                self._chaos_dead = True
        if self._chaos_dead:
            raise self._refuse("dead (chaos kill)")
        if self._draining:
            raise self._refuse("draining")
        self._ongoing += 1
        try:
            if self._is_function:
                fn = self._callable
            else:
                fn = getattr(self._callable, method_name or "__call__")
            # Parent is the actor-task execute span the runtime opened for
            # this handle_request call; untraced requests see no parent and
            # the child_span is a no-op.
            with tracing.child_span(
                    "serve::replica_handler",
                    {"stage": "serve_handle",
                     "deployment": self._deployment_name,
                     "method": method_name or "__call__"}):
                if inspect.iscoroutinefunction(fn):
                    return await fn(*args, **kwargs)
                # Sync handlers run off the event loop so concurrent
                # requests overlap and num_ongoing reflects true load
                # (reference: replica.py runs sync callables in a thread
                # pool).
                result = await asyncio.to_thread(fn, *args, **kwargs)
                if inspect.iscoroutine(result):
                    result = await result
                return result
        finally:
            self._ongoing -= 1
