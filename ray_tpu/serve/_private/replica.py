"""Replica actor: hosts one copy of a deployment.

Analog of the reference's serve/_private/replica.py:260 RayServeReplica:
unwraps the deployment definition (class or function), constructs it once
(handles to other deployments arrive through init args — the DAG
composition path), then serves `handle_request` calls. Async methods are
awaited; `@serve.batch` methods batch transparently (serve/batching.py).
"""

from __future__ import annotations

import asyncio
import inspect
from typing import Any


class ReplicaActor:
    def __init__(self, deployment_name: str, deployment_def_bytes: bytes,
                 init_args, init_kwargs):
        import cloudpickle
        self._deployment_name = deployment_name
        deployment_def = cloudpickle.loads(deployment_def_bytes)
        self._is_function = inspect.isfunction(deployment_def)
        if self._is_function:
            self._callable = deployment_def
        else:
            self._callable = deployment_def(*(init_args or ()),
                                            **(init_kwargs or {}))
        self._ongoing = 0

    async def ready(self) -> bool:
        return True

    async def num_ongoing(self) -> int:
        return self._ongoing

    async def reconfigure(self, user_config: Any) -> bool:
        fn = getattr(self._callable, "reconfigure", None)
        if fn is not None:
            result = fn(user_config)
            if inspect.iscoroutine(result):
                await result
        return True

    async def handle_request(self, method_name: str, args, kwargs):
        self._ongoing += 1
        try:
            if self._is_function:
                fn = self._callable
            else:
                fn = getattr(self._callable, method_name or "__call__")
            if inspect.iscoroutinefunction(fn):
                return await fn(*args, **kwargs)
            # Sync handlers run off the event loop so concurrent requests
            # overlap and num_ongoing reflects true load (reference:
            # replica.py runs sync callables in a thread pool).
            result = await asyncio.to_thread(fn, *args, **kwargs)
            if inspect.iscoroutine(result):
                result = await result
            return result
        finally:
            self._ongoing -= 1
