"""Router: replica selection for a deployment.

Analog of the reference's serve/_private/router.py:261 (assign_request
:298): keeps a cached replica list refreshed when the controller's
membership version moves (the pull flavor of the reference's long-poll
push), and picks the less-loaded of two random replicas (power-of-two
choices) using each replica's last-known ongoing count.
"""

from __future__ import annotations

import random
import threading
from typing import Any, List, Optional

import ray_tpu


class Router:
    def __init__(self, controller, deployment_name: str):
        self._controller = controller
        self._name = deployment_name
        self._version = -1
        self._replicas: List[Any] = []
        self._max_queries = 1
        self._lock = threading.Lock()
        self._rr = 0

    def _refresh(self) -> None:
        current = ray_tpu.get(self._controller.membership_version.remote())
        with self._lock:
            if current == self._version and self._replicas:
                return
        version, replicas, max_q = ray_tpu.get(
            self._controller.get_replicas.remote(self._name))
        with self._lock:
            self._version = version
            self._replicas = list(replicas)
            self._max_queries = max_q

    def pick_replica(self):
        self._refresh()
        with self._lock:
            replicas = list(self._replicas)
            self._rr += 1
            rr = self._rr
        if not replicas:
            raise RuntimeError(
                f"Deployment {self._name!r} has no live replicas")
        if len(replicas) == 1:
            return replicas[0]
        # Power-of-two choices on sampled ongoing counts.
        a, b = random.sample(replicas, 2)
        try:
            qa, qb = ray_tpu.get([a.num_ongoing.remote(),
                                  b.num_ongoing.remote()], timeout=5)
        except Exception:  # noqa: BLE001 - fall back to round robin
            return replicas[rr % len(replicas)]
        return a if qa <= qb else b

    def assign_request(self, method_name: str, args, kwargs):
        """Returns an ObjectRef of the replica call."""
        replica = self.pick_replica()
        return replica.handle_request.remote(method_name, args, kwargs)
