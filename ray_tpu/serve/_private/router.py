"""Router: replica selection for a deployment — controller OFF the
request path.

Analog of the reference's serve/_private/router.py:261 (assign_request
:298) + _private/long_poll.py:68 LongPollClient: membership is PUSHED to
the router through a controller long-poll running on a background thread,
and per-replica load is tracked ROUTER-LOCALLY (incremented at assignment,
decremented when the assigned call completes). The request path does
zero controller RPCs: pick the less-loaded of two random replicas
(power-of-two choices) from the local table and call it.

Resilience (reference: router retry_exception_types + serve's
max_queued_requests cap):

* **Transparent failover** — ``assign_request`` returns a router-minted
  PROMISE ref, not the raw replica-call ref. The router remembers
  ``(method, args, kwargs)`` per outstanding request; when the replica
  call seals with a SYSTEM failure (actor death / object loss — never an
  application exception) the request is re-dispatched to another live
  replica under a per-request retry budget, and the caller's ref simply
  resolves later. Completion is event-driven via the object store's
  seal callbacks — no polling thread, nothing added to the hot path.
* **Deadlines** — ``handle.options(timeout_s=...)`` arms a lazy timer;
  expiry settles the promise with GetTimeoutError, best-effort cancels
  the in-flight replica call, and drains the load-table charge.
* **Backpressure** — with ``max_queued_requests`` set on the deployment,
  requests beyond (replicas x max_concurrent_queries) + cap fast-fail
  with BackPressureError instead of queueing unboundedly.
"""

from __future__ import annotations

import heapq
import logging
import random
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu._private import builtin_metrics
from ray_tpu.exceptions import (BackPressureError, GetTimeoutError,
                                is_system_failure)
from ray_tpu.serve._private.common import serve_config

logger = logging.getLogger("ray_tpu.serve")

# How long an evicted-by-failure replica stays unpickable while the
# (possibly stale) membership table still lists it.
_SUSPECT_TTL_S = 5.0


class _PendingRequest:
    __slots__ = ("req_id", "method", "args", "kwargs", "promise", "inner",
                 "replica_hex", "retries_left", "deadline", "trace_ctx",
                 "t_start")

    def __init__(self, req_id: int, method: str, args, kwargs, promise,
                 retries_left: int, deadline: Optional[float],
                 trace_ctx: Optional[dict] = None):
        self.req_id = req_id
        self.t_start = time.monotonic()  # feeds the latency histogram
        self.method = method
        self.args = args
        self.kwargs = kwargs
        self.promise = promise
        self.inner = None  # ObjectRef of the current replica-call attempt
        self.replica_hex: Optional[str] = None  # charged replica
        self.retries_left = retries_left
        self.deadline = deadline  # monotonic, None = no deadline
        # Trace context captured at assignment; rides the request so a
        # failover re-dispatch stays in the same trace.
        self.trace_ctx = trace_ctx


class Router:
    def __init__(self, controller, deployment_name: str):
        self._controller = controller
        self._name = deployment_name
        self._version = -1
        self._replicas: List[Any] = []
        self._max_queries = 1
        self._max_queued = -1  # -1 = unlimited (no shedding)
        self._lock = threading.Lock()
        # actor_id hex -> requests assigned by THIS router still in
        # flight (reference: router-local num_ongoing, no replica RPCs).
        self._ongoing: Dict[str, int] = {}
        # req_id -> _PendingRequest: every accepted, unsettled request.
        self._requests: Dict[int, _PendingRequest] = {}
        self._req_seq = 0
        # Replicas evicted after a system failure: hex -> monotonic
        # expiry. Keeps a dead replica unpickable while the membership
        # table is stale (the controller needs a health tick to notice).
        self._suspect: Dict[str, float] = {}
        self._have_replicas = threading.Event()
        self._polled = threading.Event()  # first membership answer seen
        self._known = True  # deployment exists, per last poll
        self._stop = False
        self._threads_started = False
        # Failover re-dispatch queue: seal callbacks run on whatever
        # thread sealed the result and must not block in pick_replica's
        # membership waits, so a dedicated worker re-dispatches.
        self._retry_queue: deque = deque()
        self._retry_wake = threading.Event()
        self._retry_thread_started = False
        # Deadline timer (lazy: only requests with timeout_s pay for it).
        self._timer_heap: List[tuple] = []  # (deadline, req_id)
        self._timer_cond = threading.Condition(self._lock)
        self._timer_thread_started = False

    @staticmethod
    def _runtime():
        from ray_tpu._private.worker import global_worker
        return global_worker.runtime

    # -- background membership tracking ----------------------------------

    def _ensure_threads(self) -> None:
        if self._threads_started:
            return
        with self._lock:
            if self._threads_started:
                return
            self._threads_started = True
        threading.Thread(target=self._poll_loop, daemon=True,
                         name=f"serve-router-poll-{self._name}").start()

    def _poll_loop(self) -> None:
        """Long-poll membership (reference: LongPollClient): blocks in
        the controller until the version moves, then refreshes the local
        replica table. Never touched by the request path."""
        from ray_tpu.exceptions import ActorError
        while not self._stop:
            try:
                ver, replicas, max_q, max_queued = ray_tpu.get(
                    self._controller.listen_for_change.remote(
                        ("replicas", self._name), self._version),
                    timeout=90)
            except ActorError:
                break  # controller is gone: serve shut down
            except Exception:  # noqa: BLE001 - transient: retry
                time.sleep(0.2)
                continue
            with self._lock:
                self._version = ver
                self._known = replicas is not None
                self._replicas = list(replicas or ())
                self._max_queries = max_q
                self._max_queued = max_queued
                live = set()
                for r in self._replicas:
                    hexid = r._actor_id.hex()
                    live.add(hexid)
                    self._ongoing.setdefault(hexid, 0)
                # Prune stale charges AND stale suspicions for replicas
                # that left membership (long-lived routers must not bias
                # power-of-two picks on ghosts).
                for gone in set(self._ongoing) - live:
                    del self._ongoing[gone]
                for gone in set(self._suspect) - live:
                    del self._suspect[gone]
            builtin_metrics.serve_replicas().set(
                len(self._replicas), tags={"deployment": self._name})
            if self._replicas:
                self._have_replicas.set()
            else:
                self._have_replicas.clear()
            self._polled.set()

    def _ensure_retry_thread(self) -> None:
        if self._retry_thread_started:
            return
        with self._lock:
            if self._retry_thread_started:
                return
            self._retry_thread_started = True
        threading.Thread(target=self._retry_loop, daemon=True,
                         name=f"serve-router-retry-{self._name}").start()

    def _retry_loop(self) -> None:
        while not self._stop:
            if not self._retry_queue:
                self._retry_wake.wait(timeout=1.0)
                self._retry_wake.clear()
                continue
            try:
                pending = self._retry_queue.popleft()
            except IndexError:
                continue
            try:
                self._dispatch(pending)
            except Exception as exc:  # noqa: BLE001 - no replica to take it
                self._settle(pending.req_id, exception=exc)

    def _ensure_timer_thread(self) -> None:
        if self._timer_thread_started:
            return
        with self._lock:
            if self._timer_thread_started:
                return
            self._timer_thread_started = True
        threading.Thread(target=self._timer_loop, daemon=True,
                         name=f"serve-router-timer-{self._name}").start()

    def _timer_loop(self) -> None:
        while not self._stop:
            with self._timer_cond:
                while self._timer_heap and \
                        self._timer_heap[0][0] <= time.monotonic():
                    _, req_id = heapq.heappop(self._timer_heap)
                    pending = self._requests.get(req_id)
                    if pending is None:
                        continue
                    # Settle outside the lock (fulfill + cancel).
                    threading.Thread(
                        target=self._expire, args=(req_id,),
                        daemon=True).start()
                wait = 1.0
                if self._timer_heap:
                    wait = max(0.0,
                               self._timer_heap[0][0] - time.monotonic())
                self._timer_cond.wait(timeout=min(wait, 1.0))

    def _expire(self, req_id: int) -> None:
        with self._lock:
            pending = self._requests.get(req_id)
            inner = pending.inner if pending is not None else None
        if pending is None:
            return
        self._settle(req_id, exception=GetTimeoutError(
            f"Serve request to {self._name!r} did not complete within "
            f"its timeout_s deadline."))
        if inner is not None:
            try:  # best-effort: free the replica slot early
                ray_tpu.cancel(inner)
            except Exception:  # noqa: BLE001
                pass

    def stop(self) -> None:
        self._stop = True
        self._retry_wake.set()
        with self._timer_cond:
            self._timer_cond.notify_all()

    # -- completion / settlement -----------------------------------------

    def _uncharge(self, hexid: Optional[str]) -> None:
        """Caller holds self._lock."""
        if hexid is not None and hexid in self._ongoing:
            self._ongoing[hexid] = max(0, self._ongoing[hexid] - 1)

    def _settle(self, req_id: int, *, alias=None, exception=None) -> None:
        """Resolve the caller-visible promise and drop the request from
        the load table. Idempotent: first settle wins (the store's
        first-write-wins seal backs this up for racing settles)."""
        with self._lock:
            pending = self._requests.pop(req_id, None)
            if pending is None:
                return
            self._uncharge(pending.replica_hex)
            pending.replica_hex = None
            depth = len(self._requests)
        tags = {"deployment": self._name}
        builtin_metrics.serve_requests().inc(tags=tags)
        builtin_metrics.serve_request_latency().observe(
            time.monotonic() - pending.t_start, tags=tags)
        builtin_metrics.serve_queue_depth().set(depth, tags=tags)
        self._runtime().fulfill_promise(pending.promise, alias=alias,
                                        exception=exception)

    def _on_sealed(self, req_id: int, ref) -> None:
        """Seal callback for one replica-call attempt: runs on whatever
        thread sealed the result. Classifies the outcome; system
        failures re-dispatch (failover), everything else resolves the
        caller's promise by aliasing the attempt's ref."""
        with self._lock:
            pending = self._requests.get(req_id)
            if pending is None or pending.inner is not ref:
                return  # settled/superseded: accounting already done
            hexid = pending.replica_hex
            self._uncharge(hexid)
            pending.replica_hex = None
        try:
            exc = self._runtime().store.get_if_exception(ref.object_id())
        except Exception:  # noqa: BLE001 - undeserializable error payload
            exc = None
        if exc is not None and is_system_failure(exc) \
                and pending.retries_left > 0 and not self._stop:
            with self._lock:
                pending.retries_left -= 1
                if hexid is not None:
                    # Keep the dead replica unpickable while membership
                    # is stale; the poll loop clears it on refresh.
                    self._suspect[hexid] = time.monotonic() + _SUSPECT_TTL_S
            builtin_metrics.serve_failovers().inc()
            logger.info("Failing over a request to %s after: %s",
                        self._name, exc)
            self._ensure_retry_thread()
            self._retry_queue.append(pending)
            self._retry_wake.set()
            return
        self._settle(req_id, alias=ref)

    # -- request path (zero controller RPCs) -----------------------------

    def pick_replica(self):
        self._ensure_threads()
        if not self._have_replicas.is_set():
            # Fail fast on a deployment the controller does not know
            # (the old direct get_replicas raised ValueError at once);
            # wait out only the replica-appearance window for real ones.
            if self._polled.wait(timeout=10) and not self._known:
                raise ValueError(
                    f"Deployment {self._name!r} does not exist")
            if not self._have_replicas.wait(timeout=30):
                raise RuntimeError(
                    f"Deployment {self._name!r} has no live replicas")
        with self._lock:
            replicas = self._replicas
            if not replicas:
                raise RuntimeError(
                    f"Deployment {self._name!r} has no live replicas")
            if self._suspect:
                now = time.monotonic()
                healthy = [r for r in replicas
                           if self._suspect.get(r._actor_id.hex(), 0) <= now]
                # A fully-suspect table still dispatches (a retry against
                # a suspect beats failing the request outright).
                if healthy:
                    replicas = healthy
            if len(replicas) == 1:
                choice = replicas[0]
            else:
                # Power-of-two choices on LOCAL ongoing counts.
                a, b = random.sample(replicas, 2)
                qa = self._ongoing.get(a._actor_id.hex(), 0)
                qb = self._ongoing.get(b._actor_id.hex(), 0)
                choice = a if qa <= qb else b
            hexid = choice._actor_id.hex()
            self._ongoing[hexid] = self._ongoing.get(hexid, 0) + 1
        return choice

    def _dispatch(self, pending: _PendingRequest) -> None:
        """Charge a replica, submit the call, subscribe to completion.
        Used for both first dispatch and failover re-dispatch."""
        replica = self.pick_replica()
        hexid = replica._actor_id.hex()
        try:
            # The dispatch span makes the actor submit inside it inherit
            # the request's trace: the replica-side handler span parents
            # here across the hop (also on failover re-dispatches).
            from ray_tpu.util import tracing
            with tracing.continue_context(
                    pending.trace_ctx, "serve::router_dispatch",
                    {"stage": "serve_dispatch", "deployment": self._name,
                     "replica": hexid[:8]}):
                ref = replica.handle_request.remote(
                    pending.method, pending.args, pending.kwargs)
        except BaseException:
            # The pick already charged this replica; a failed submit has
            # no completing ref to drain the charge back.
            with self._lock:
                self._uncharge(hexid)
            raise
        with self._lock:
            live = self._requests.get(pending.req_id)
            if live is not pending:
                # Expired/settled while we were picking: the settle path
                # already drained the OLD charge; drain the one we just
                # took and abandon the attempt.
                self._uncharge(hexid)
                return
            pending.inner = ref
            pending.replica_hex = hexid
        self._runtime().store.on_sealed(
            ref.object_id(),
            lambda _oid, rid=pending.req_id, r=ref: self._on_sealed(rid, r))

    def assign_request(self, method_name: str, args, kwargs,
                       timeout_s: Optional[float] = None,
                       max_retries: Optional[int] = None):
        """Returns a promise ObjectRef that resolves to the request's
        result — across failover re-dispatches if needed."""
        self._ensure_threads()
        with self._lock:
            max_queued = self._max_queued
            if max_queued is not None and max_queued >= 0 \
                    and self._replicas:
                capacity = len(self._replicas) * max(1, self._max_queries)
                outstanding = len(self._requests)
                if outstanding >= capacity + max_queued:
                    shed = BackPressureError(
                        num_queued=outstanding - capacity,
                        max_queued=max_queued, deployment=self._name)
                else:
                    shed = None
            else:
                shed = None
        if shed is not None:
            builtin_metrics.serve_shed().inc()
            raise shed
        if max_retries is None:
            max_retries = serve_config("serve_failover_retries", 3)
        runtime = self._runtime()
        promise = runtime.create_promise()
        deadline = None
        if timeout_s is not None:
            deadline = time.monotonic() + timeout_s
        # Head-of-trace sampling for serve traffic: each request roots
        # (or joins) a trace here; unsampled requests carry None and the
        # whole serve path stays bare.
        from ray_tpu.util import tracing
        trace_ctx = (tracing.inject_context()
                     if tracing.is_tracing_enabled() else None)
        with self._lock:
            self._req_seq += 1
            pending = _PendingRequest(self._req_seq, method_name, args,
                                      kwargs, promise, max_retries,
                                      deadline, trace_ctx)
            self._requests[pending.req_id] = pending
            depth = len(self._requests)
        builtin_metrics.serve_queue_depth().set(
            depth, tags={"deployment": self._name})
        try:
            self._dispatch(pending)
        except BaseException:
            with self._lock:
                self._requests.pop(pending.req_id, None)
            raise
        if deadline is not None:
            self._ensure_timer_thread()
            with self._timer_cond:
                heapq.heappush(self._timer_heap,
                               (deadline, pending.req_id))
                self._timer_cond.notify_all()
        return promise
