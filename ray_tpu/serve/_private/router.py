"""Router: replica selection for a deployment — controller OFF the
request path.

Analog of the reference's serve/_private/router.py:261 (assign_request
:298) + _private/long_poll.py:68 LongPollClient: membership is PUSHED to
the router through a controller long-poll running on a background thread,
and per-replica load is tracked ROUTER-LOCALLY (incremented at assignment,
decremented when the assigned ObjectRef completes). The request path does
zero controller RPCs: pick the less-loaded of two random replicas
(power-of-two choices) from the local table and call it.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import Any, Dict, List

import ray_tpu

logger = logging.getLogger("ray_tpu.serve")


class Router:
    def __init__(self, controller, deployment_name: str):
        self._controller = controller
        self._name = deployment_name
        self._version = -1
        self._replicas: List[Any] = []
        self._max_queries = 1
        self._lock = threading.Lock()
        # actor_id hex -> requests assigned by THIS router still in
        # flight (reference: router-local num_ongoing, no replica RPCs).
        self._ongoing: Dict[str, int] = {}
        self._outstanding: Dict[Any, str] = {}  # ObjectRef -> actor hex
        self._have_work = threading.Event()
        self._have_replicas = threading.Event()
        self._polled = threading.Event()  # first membership answer seen
        self._known = True  # deployment exists, per last poll
        self._stop = False
        self._threads_started = False

    # -- background membership + completion tracking --------------------

    def _ensure_threads(self) -> None:
        if self._threads_started:
            return
        with self._lock:
            if self._threads_started:
                return
            self._threads_started = True
        threading.Thread(target=self._poll_loop, daemon=True,
                         name=f"serve-router-poll-{self._name}").start()
        threading.Thread(target=self._drain_loop, daemon=True,
                         name=f"serve-router-drain-{self._name}").start()

    def _poll_loop(self) -> None:
        """Long-poll membership (reference: LongPollClient): blocks in
        the controller until the version moves, then refreshes the local
        replica table. Never touched by the request path."""
        from ray_tpu.exceptions import ActorError
        while not self._stop:
            try:
                ver, replicas, max_q = ray_tpu.get(
                    self._controller.listen_for_change.remote(
                        ("replicas", self._name), self._version),
                    timeout=90)
            except ActorError:
                break  # controller is gone: serve shut down
            except Exception:  # noqa: BLE001 - transient: retry
                time.sleep(0.2)
                continue
            with self._lock:
                self._version = ver
                self._known = replicas is not None
                self._replicas = list(replicas or ())
                live = set()
                for r in self._replicas:
                    hexid = r._actor_id.hex()
                    live.add(hexid)
                    self._ongoing.setdefault(hexid, 0)
                for gone in set(self._ongoing) - live:
                    del self._ongoing[gone]
                self._max_queries = max_q
            if self._replicas:
                self._have_replicas.set()
            else:
                self._have_replicas.clear()
            self._polled.set()

    def _drain_loop(self) -> None:
        """Decrement router-local load as assigned calls complete (the
        thread owns the waiting; the request path never blocks)."""
        while not self._stop:
            with self._lock:
                refs = list(self._outstanding)
            if not refs:
                self._have_work.wait(timeout=0.5)
                self._have_work.clear()
                continue
            try:
                # BLOCK for the first completion (condition-wait inside
                # the runtime, not a 50ms poll — a router per deployment
                # must not burn constant CPU), then scoop every other
                # already-done ref in one non-blocking sweep.
                done, _ = ray_tpu.wait(refs, num_returns=1, timeout=0.5)
                if done and len(refs) > 1:
                    done, _ = ray_tpu.wait(refs, num_returns=len(refs),
                                           timeout=0)
            except Exception:  # noqa: BLE001 - shutdown window
                time.sleep(0.05)
                continue
            if not done:
                continue
            with self._lock:
                for ref in done:
                    hexid = self._outstanding.pop(ref, None)
                    if hexid is not None and hexid in self._ongoing:
                        self._ongoing[hexid] = max(
                            0, self._ongoing[hexid] - 1)

    def stop(self) -> None:
        self._stop = True
        self._have_work.set()

    # -- request path (zero controller RPCs) -----------------------------

    def pick_replica(self):
        self._ensure_threads()
        if not self._have_replicas.is_set():
            # Fail fast on a deployment the controller does not know
            # (the old direct get_replicas raised ValueError at once);
            # wait out only the replica-appearance window for real ones.
            if self._polled.wait(timeout=10) and not self._known:
                raise ValueError(
                    f"Deployment {self._name!r} does not exist")
            if not self._have_replicas.wait(timeout=30):
                raise RuntimeError(
                    f"Deployment {self._name!r} has no live replicas")
        with self._lock:
            replicas = self._replicas
            if not replicas:
                raise RuntimeError(
                    f"Deployment {self._name!r} has no live replicas")
            if len(replicas) == 1:
                choice = replicas[0]
            else:
                # Power-of-two choices on LOCAL ongoing counts.
                a, b = random.sample(replicas, 2)
                qa = self._ongoing.get(a._actor_id.hex(), 0)
                qb = self._ongoing.get(b._actor_id.hex(), 0)
                choice = a if qa <= qb else b
            hexid = choice._actor_id.hex()
            self._ongoing[hexid] = self._ongoing.get(hexid, 0) + 1
        return choice

    def assign_request(self, method_name: str, args, kwargs):
        """Returns an ObjectRef of the replica call."""
        replica = self.pick_replica()
        try:
            ref = replica.handle_request.remote(method_name, args, kwargs)
        except BaseException:
            # The pick already charged this replica; a failed submit has
            # no completing ref to drain the charge back.
            with self._lock:
                hexid = replica._actor_id.hex()
                if hexid in self._ongoing:
                    self._ongoing[hexid] = max(0, self._ongoing[hexid] - 1)
            raise
        with self._lock:
            self._outstanding[ref] = replica._actor_id.hex()
        self._have_work.set()
        return ref
