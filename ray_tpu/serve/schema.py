"""Declarative Serve config schemas + apply.

Analog of the reference's serve/schema.py (pydantic ServeApplicationSchema
consumed by `serve deploy` / the REST API): dataclass schemas with
validation, a loader that resolves ``import_path`` strings, and
``apply_config`` which reconciles a running Serve instance to the declared
state.
"""

from __future__ import annotations

import importlib
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class DeploymentSchema:
    # None = "not set in the config": apply_config only overrides fields the
    # operator actually declared (the code-declared value wins otherwise).
    name: str
    num_replicas: Optional[int] = None
    max_concurrent_queries: Optional[int] = None
    max_queued_requests: Optional[int] = None
    ray_actor_options: Dict[str, Any] = field(default_factory=dict)
    autoscaling_config: Optional[Dict[str, Any]] = None
    user_config: Optional[Dict[str, Any]] = None

    def validate(self) -> None:
        if not self.name:
            raise ValueError("Deployment name must be non-empty")
        if self.num_replicas is not None and self.num_replicas < 0:
            raise ValueError(
                f"num_replicas must be >= 0, got {self.num_replicas}")
        if self.max_queued_requests is not None \
                and self.max_queued_requests < -1:
            raise ValueError(
                f"max_queued_requests must be >= -1 (-1 = unlimited), "
                f"got {self.max_queued_requests}")
        if self.autoscaling_config:
            # Full validation (unknown keys, bounds, targets, delays)
            # lives in the autoscaler policy module; failing here keeps
            # `serve deploy` errors at config-parse time.
            from ray_tpu.serve._private import autoscaler
            autoscaler.normalize_config(
                self.autoscaling_config,
                current_replicas=self.num_replicas or 1)


@dataclass
class ServeApplicationSchema:
    import_path: str
    name: str = "default"
    route_prefix: str = "/"
    runtime_env: Dict[str, Any] = field(default_factory=dict)
    deployments: List[DeploymentSchema] = field(default_factory=list)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "ServeApplicationSchema":
        deployments = [
            DeploymentSchema(**dep) if not isinstance(dep, DeploymentSchema)
            else dep
            for dep in d.get("deployments", [])]
        schema = ServeApplicationSchema(
            import_path=d["import_path"],
            name=d.get("name", "default"),
            route_prefix=d.get("route_prefix", "/"),
            runtime_env=d.get("runtime_env", {}),
            deployments=deployments)
        schema.validate()
        return schema

    def validate(self) -> None:
        if ":" not in self.import_path:
            raise ValueError(
                f"import_path must look like 'module:attribute', got "
                f"{self.import_path!r}")
        for dep in self.deployments:
            dep.validate()

    def to_dict(self) -> Dict[str, Any]:
        return asdict(self)


def _load_target(import_path: str):
    module_name, _, attr = import_path.partition(":")
    module = importlib.import_module(module_name)
    target = module
    for part in attr.split("."):
        target = getattr(target, part)
    return target


def apply_config(config: Dict[str, Any]):
    """Deploy the application declared by a config dict (the body of the
    reference's `serve deploy config.yaml` / REST PUT /api/serve/applications).
    Per-deployment overrides in ``deployments`` are applied over the bound
    application before deploy. Returns the entry handle."""
    import copy

    from ray_tpu import serve
    schema = ServeApplicationSchema.from_dict(config)
    target = _load_target(schema.import_path)
    overrides = {d.name: d for d in schema.deployments}

    app = target
    if isinstance(app, serve.Deployment):
        app = app.bind()
    # Deep-copy the bound graph: module-level Applications are shared, and
    # overrides must not leak into later, unrelated serve.run() calls.
    app = copy.deepcopy(app)

    # Walk the bound application graph, applying per-deployment overrides
    # (only fields the config actually set).
    def override(application):
        dep = application.deployment
        o = overrides.get(dep.name)
        if o is not None:
            dep._config = dict(dep._config)
            if o.num_replicas is not None:
                dep._config["num_replicas"] = o.num_replicas
            if o.max_concurrent_queries is not None:
                dep._config["max_concurrent_queries"] = \
                    o.max_concurrent_queries
            if o.max_queued_requests is not None:
                dep._config["max_queued_requests"] = o.max_queued_requests
            if o.autoscaling_config is not None:
                dep._config["autoscaling_config"] = o.autoscaling_config
            if o.ray_actor_options:
                dep._config["ray_actor_options"] = o.ray_actor_options
            if o.user_config is not None:
                dep._config["user_config"] = o.user_config
        def walk(v):
            if isinstance(v, serve.Application):
                override(v)
            elif isinstance(v, dict):
                for x in v.values():
                    walk(x)
            elif isinstance(v, (list, tuple)):
                for x in v:
                    walk(x)

        for a in list(application.args) + list(application.kwargs.values()):
            walk(a)

    override(app)
    return serve.run(app, route_prefix=schema.route_prefix, port=None)
