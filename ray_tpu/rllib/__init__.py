"""ray_tpu.rllib: reinforcement learning on the actor runtime, JAX-first.

The RL stack of the framework (reference: rllib, SURVEY.md §2.6):
Algorithm/AlgorithmConfig driver, WorkerSet rollout actors (CPU envs),
JAX policies compiled by XLA, 22 algorithms (PPO/APPO/DQN/APEX-DQN/
Rainbow/R2D2/QMIX/SimpleQ/SAC/TD3/DDPG/CQL/A2C/A3C/IMPALA/PG/BC/MARWIL/
ES/ARS/BanditLinUCB/BanditLinTS — incl. distributional C51 + noisy
nets, recurrent sequence replay with burn-in, monotonic multi-agent
value factorization, and closed-form contextual bandits),
multi-agent training (MultiAgentEnv + policy maps), the new-stack
core/ (RLModule/Learner/LearnerGroup — SPMD pjit or remote-actor
data-parallel learners), connectors, offline JSON IO, replay buffers
(prioritized + n-step), and the model catalog. Every learner update is a
jitted functional step.
"""

from ray_tpu.rllib.algorithms.a2c import A2C, A2CConfig
from ray_tpu.rllib.algorithms.a3c import A3C, A3CConfig
from ray_tpu.rllib.algorithms.apex_dqn import ApexDQN, ApexDQNConfig
from ray_tpu.rllib.algorithms.apex_ddpg import (ApexDDPG,
                                                ApexDDPGConfig)
from ray_tpu.rllib.algorithms.alpha_star import (AlphaStar,
                                                 AlphaStarConfig)
from ray_tpu.rllib.algorithms.alpha_zero import (AlphaZero,
                                                 AlphaZeroConfig)
from ray_tpu.rllib.algorithms.appo import APPO, APPOConfig
from ray_tpu.rllib.algorithms.ars import ARS, ARSConfig
from ray_tpu.rllib.algorithms.bandit import (BanditConfig, BanditLinTS,
                                             BanditLinTSConfig,
                                             BanditLinUCB,
                                             BanditLinUCBConfig)
from ray_tpu.rllib.algorithms.bc import BC, BCConfig
from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.algorithms.cql import CQL, CQLConfig
from ray_tpu.rllib.algorithms.crr import CRR, CRRConfig
from ray_tpu.rllib.algorithms.ddpg import DDPG, DDPGConfig
from ray_tpu.rllib.algorithms.ddppo import DDPPO, DDPPOConfig
from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig
from ray_tpu.rllib.algorithms.dreamer import (Dreamer,
                                               DreamerConfig)
from ray_tpu.rllib.algorithms.dt import DT, DTConfig
from ray_tpu.rllib.algorithms.es import ES, ESConfig
from ray_tpu.rllib.algorithms.impala import Impala, ImpalaConfig
from ray_tpu.rllib.algorithms.maddpg import MADDPG, MADDPGConfig
from ray_tpu.rllib.algorithms.maml import MAML, MAMLConfig
from ray_tpu.rllib.algorithms.marwil import MARWIL, MARWILConfig
from ray_tpu.rllib.algorithms.mbmpo import MBMPO, MBMPOConfig
from ray_tpu.rllib.algorithms.pg import PG, PGConfig
from ray_tpu.rllib.algorithms.ppo import PPO, PPOConfig
from ray_tpu.rllib.algorithms.qmix import QMix, QMixConfig
from ray_tpu.rllib.algorithms.r2d2 import R2D2, R2D2Config
from ray_tpu.rllib.algorithms.random_agent import (RandomAgent,
                                                   RandomAgentConfig)
from ray_tpu.rllib.algorithms.rainbow import Rainbow, RainbowConfig
from ray_tpu.rllib.algorithms.registry import get_algorithm_class
from ray_tpu.rllib.algorithms.sac import SAC, SACConfig
from ray_tpu.rllib.algorithms.simple_q import SimpleQ, SimpleQConfig
from ray_tpu.rllib.algorithms.slateq import SlateQ, SlateQConfig
from ray_tpu.rllib.algorithms.td3 import TD3, TD3Config
from ray_tpu.rllib.env import MultiAgentEnv
from ray_tpu.rllib.evaluation.multi_agent_worker import (
    MultiAgentRolloutWorker)
from ray_tpu.rllib.evaluation.rollout_worker import RolloutWorker
from ray_tpu.rllib.evaluation.worker_set import WorkerSet
from ray_tpu.rllib.models.catalog import ModelCatalog
from ray_tpu.rllib.offline import JsonReader, JsonWriter
from ray_tpu.rllib.policy.jax_policy import JAXPolicy, compute_gae
from ray_tpu.rllib.policy.q_policy import QPolicy
from ray_tpu.rllib.policy.sac_policy import SACPolicy
from ray_tpu.rllib.policy.sample_batch import MultiAgentBatch, SampleBatch
from ray_tpu.rllib.utils.replay_buffers import (PrioritizedReplayBuffer,
                                                ReplayBuffer)

__all__ = ["A2C", "A2CConfig", "A3C", "A3CConfig", "APPO", "APPOConfig",
           "BanditConfig", "BanditLinTS", "BanditLinTSConfig",
           "BanditLinUCB", "BanditLinUCBConfig",
           "ApexDQN", "ApexDQNConfig", "ApexDDPG", "ApexDDPGConfig",
           "RandomAgent", "RandomAgentConfig",
           "AlphaStar", "AlphaStarConfig",
           "AlphaZero", "AlphaZeroConfig", "CRR", "CRRConfig",
           "DDPPO", "DDPPOConfig", "Dreamer", "DreamerConfig", "MAML", "MAMLConfig", "MBMPO", "MBMPOConfig",
           "ARS", "ARSConfig", "Algorithm", "AlgorithmConfig", "BC",
           "BCConfig", "CQL", "CQLConfig", "DDPG", "DDPGConfig", "DQN",
           "DQNConfig", "DT", "DTConfig", "ES", "ESConfig", "Impala", "ImpalaConfig",
           "JAXPolicy", "JsonReader", "MultiAgentBatch", "MultiAgentEnv",
           "MultiAgentRolloutWorker",
           "JsonWriter", "MARWIL", "MARWILConfig", "ModelCatalog", "PG",
           "QMix", "QMixConfig", "MADDPG", "MADDPGConfig",
           "SlateQ", "SlateQConfig",
           "R2D2", "R2D2Config", "Rainbow", "RainbowConfig",
           "PGConfig", "PPO", "PPOConfig", "QPolicy",
           "PrioritizedReplayBuffer", "ReplayBuffer", "RolloutWorker",
           "SAC", "SACConfig", "SACPolicy", "SampleBatch", "SimpleQ",
           "SimpleQConfig", "TD3",
           "TD3Config", "WorkerSet",
           "compute_gae", "get_algorithm_class"]
