"""LearnerGroup: the scale-out wrapper around Learners.

Analog of the reference's rllib/core/rl_trainer/trainer_runner.py
(TrainerRunner), which data-parallelizes RLTrainer actors over GPUs. Two
TPU-native modes:

- **SPMD** (default, ``num_remote_learners=0``): ONE Learner whose jitted
  update is sharded over the ``dp`` axis of a device mesh — within a host
  the gradient all-reduce is a GSPMD psum over ICI, which is how
  multi-learner should look on TPU (no actor per chip).
- **remote**: N learner actors on the ray_tpu runtime, each computing
  gradients on its batch shard; the group tree-averages the gradients and
  has every actor apply the same averaged update (synchronous DP across
  hosts, the reference's allreduce semantics made explicit).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.rllib.core.learner import LearnerConfig
from ray_tpu.rllib.core.rl_module import RLModuleSpec


class _RemoteLearner:
    """Actor body: a built Learner driven over the runtime."""

    def __init__(self, learner_class, module_spec, config):
        self.learner = learner_class(module_spec, config).build()

    def compute_gradients(self, batch):
        return self.learner.compute_gradients(batch)

    def apply_gradients(self, grads):
        self.learner.apply_gradients(grads)
        return True

    def get_weights(self):
        return self.learner.get_weights()

    def set_weights(self, weights):
        self.learner.set_weights(weights)
        return True


class LearnerGroup:
    def __init__(self, learner_class, module_spec: RLModuleSpec,
                 config: Optional[LearnerConfig] = None,
                 num_remote_learners: int = 0, mesh=None):
        self.config = config or LearnerConfig()
        self._remote = num_remote_learners > 0
        if self._remote:
            import ray_tpu
            actor_cls = ray_tpu.remote(_RemoteLearner)
            self._learners = [
                actor_cls.remote(learner_class, module_spec, self.config)
                for _ in range(num_remote_learners)]
        else:
            if mesh is None:
                import jax
                from ray_tpu.parallel.mesh import MeshConfig, build_mesh
                mesh = build_mesh(
                    MeshConfig(dp=len(jax.devices()), fsdp=1))
            self._learner = learner_class(module_spec, self.config,
                                          mesh=mesh).build()
            self.mesh = mesh

    @property
    def is_remote(self) -> bool:
        return self._remote

    def update(self, batch: Dict[str, Any]) -> Dict[str, float]:
        """One synchronous data-parallel update on the global batch."""
        if not self._remote:
            return self._learner.update(batch)
        import ray_tpu
        size = len(next(iter(batch.values())))
        # Never hand a learner an empty shard (mean over zero rows is NaN
        # and would poison the averaged gradients); cover every row.
        n = min(len(self._learners), size)
        bounds = np.array_split(np.arange(size), n)
        shards = [
            {k: np.asarray(v)[idx[0]:idx[-1] + 1
                              ] for k, v in batch.items()}
            for idx in bounds]
        results = ray_tpu.get([
            lr.compute_gradients.remote(s)
            for lr, s in zip(self._learners, shards)])
        import jax
        # Shards can differ by one row: weight each gradient by its share
        # of the global batch so the average equals the full-batch grad.
        weights = np.asarray([len(idx) / size for idx in bounds],
                             np.float64)
        grads = jax.tree.map(
            lambda *g: np.tensordot(weights, np.stack(g), axes=1).astype(
                np.asarray(g[0]).dtype),
            *[g for g, _ in results])
        ray_tpu.get([lr.apply_gradients.remote(grads)
                     for lr in self._learners])
        metrics_list = [m for _, m in results]
        return {k: float(np.mean([m[k] for m in metrics_list]))
                for k in metrics_list[0]}

    def get_weights(self):
        if not self._remote:
            return self._learner.get_weights()
        import ray_tpu
        return ray_tpu.get(self._learners[0].get_weights.remote())

    def set_weights(self, weights) -> None:
        if not self._remote:
            self._learner.set_weights(weights)
            return
        import ray_tpu
        ray_tpu.get([lr.set_weights.remote(weights)
                     for lr in self._learners])

    def stop(self) -> None:
        if self._remote:
            import ray_tpu
            for lr in self._learners:
                ray_tpu.kill(lr)
