"""RLModule: the functional network unit of the new stack.

Analog of the reference's rllib/core/rl_module/rl_module.py — the
framework-agnostic module with forward_train / forward_exploration /
forward_inference entry points — made JAX-idiomatic: a module is a pair of
pure functions (``init(key) -> params``, forwards taking ``params``
explicitly) so the Learner can jit/pjit them and rollout workers can run
the identical apply with device_put weights.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional


class RLModule:
    """Base class. Subclasses define the param init and the three
    forwards; all are pure (params in, tensors out)."""

    def init(self, key) -> Any:
        raise NotImplementedError

    def forward_train(self, params, batch: Dict[str, Any]
                      ) -> Dict[str, Any]:
        """Outputs needed by the loss (logits, values, logps, ...)."""
        raise NotImplementedError

    def forward_exploration(self, params, obs, key):
        """Stochastic actions for rollouts → (actions, extras dict)."""
        raise NotImplementedError

    def forward_inference(self, params, obs):
        """Deterministic actions for serving/eval."""
        raise NotImplementedError


@dataclass
class RLModuleSpec:
    """Analog of the reference's SingleAgentRLModuleSpec: everything
    needed to construct the module on any process."""

    module_class: type
    observation_space: Any = None
    action_space: Any = None
    model_config: Dict[str, Any] = field(default_factory=dict)

    def build(self) -> "RLModule":
        return self.module_class(self.observation_space,
                                 self.action_space, self.model_config)


class MLPActorCriticModule(RLModule):
    """The catalog MLP actor-critic as an RLModule (discrete or Box)."""

    def __init__(self, observation_space, action_space,
                 model_config: Optional[Dict[str, Any]] = None):
        import gymnasium as gym
        import numpy as np

        model_config = model_config or {}
        self.hiddens = tuple(model_config.get("fcnet_hiddens", (64, 64)))
        self.obs_dim = int(np.prod(observation_space.shape))
        self.discrete = isinstance(action_space, gym.spaces.Discrete)
        self.act_dim = (int(action_space.n) if self.discrete
                        else int(np.prod(action_space.shape)))

    def init(self, key):
        import jax
        import jax.numpy as jnp

        from ray_tpu.rllib.models.catalog import mlp_init
        k_pi, k_vf = jax.random.split(key)
        params = {
            "pi": mlp_init(k_pi, [self.obs_dim, *self.hiddens,
                                  self.act_dim]),
            "vf": mlp_init(k_vf, [self.obs_dim, *self.hiddens, 1]),
        }
        if not self.discrete:
            params["log_std"] = jnp.zeros((self.act_dim,))
        return params

    # -- distribution helpers -------------------------------------------

    def _logits(self, params, obs):
        from ray_tpu.rllib.models.catalog import mlp_apply
        return mlp_apply(params["pi"], obs)

    def _values(self, params, obs):
        from ray_tpu.rllib.models.catalog import mlp_apply
        return mlp_apply(params["vf"], obs)[..., 0]

    def _logp(self, params, obs, actions):
        import jax
        import jax.numpy as jnp
        logits = self._logits(params, obs)
        if self.discrete:
            logp_all = jax.nn.log_softmax(logits)
            return jnp.take_along_axis(
                logp_all, actions[..., None].astype(jnp.int32), -1)[..., 0]
        log_std = params["log_std"]
        var = jnp.exp(2 * log_std)
        return (-0.5 * (((actions - logits) ** 2) / var
                        + 2 * log_std + jnp.log(2 * jnp.pi))).sum(-1)

    def _entropy(self, params, obs):
        import jax
        import jax.numpy as jnp
        logits = self._logits(params, obs)
        if self.discrete:
            p = jax.nn.softmax(logits)
            return -(p * jax.nn.log_softmax(logits)).sum(-1)
        return (params["log_std"]
                + 0.5 * jnp.log(2 * jnp.pi * jnp.e)).sum()

    # -- RLModule API ----------------------------------------------------

    def forward_train(self, params, batch):
        obs = batch["obs"]
        return {
            "logits": self._logits(params, obs),
            "values": self._values(params, obs),
            "logp": self._logp(params, obs, batch["actions"]),
            "entropy": self._entropy(params, obs),
        }

    def forward_exploration(self, params, obs, key):
        import jax
        import jax.numpy as jnp
        logits = self._logits(params, obs)
        if self.discrete:
            actions = jax.random.categorical(key, logits)
        else:
            std = jnp.exp(params["log_std"])
            actions = logits + std * jax.random.normal(key, logits.shape)
        return actions, {"values": self._values(params, obs)}

    def forward_inference(self, params, obs):
        logits = self._logits(params, obs)
        return logits.argmax(-1) if self.discrete else logits
