"""Learner: one gradient engine over an RLModule.

Analog of the reference's rllib/core/rl_trainer (RLTrainer): owns module
params + optimizer state and exposes update(batch). Subclasses implement
``compute_loss(params, batch) -> (loss, metrics)``; the base class builds
a single jitted update from it. For SPMD scale-out the update can be
compiled with explicit shardings (params replicated, batch split on the
mesh's ``dp`` axis) so GSPMD inserts the gradient psum over ICI — the
TPU-native form of the reference's multi-GPU data-parallel learner.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

from ray_tpu.rllib.core.rl_module import RLModuleSpec


@dataclass
class LearnerConfig:
    lr: float = 5e-4
    grad_clip: float = 40.0
    seed: int = 0
    # PPO-family hyperparameters (used by PPOLearner).
    clip_param: float = 0.2
    vf_loss_coeff: float = 0.5
    entropy_coeff: float = 0.0
    extra: Dict[str, Any] = field(default_factory=dict)


class Learner:
    def __init__(self, module_spec: RLModuleSpec,
                 config: Optional[LearnerConfig] = None, mesh=None):
        self.module_spec = module_spec
        self.config = config or LearnerConfig()
        self.module = module_spec.build()
        self._mesh = mesh
        self._built = False

    # -- to be implemented by algorithm learners ------------------------

    def compute_loss(self, params, batch) -> Tuple[Any, Dict[str, Any]]:
        raise NotImplementedError

    # -- engine ----------------------------------------------------------

    def build(self) -> "Learner":
        import jax
        import optax

        if self._built:
            return self
        config = self.config
        self.params = self.module.init(
            jax.random.PRNGKey(config.seed))
        self._optimizer = optax.chain(
            optax.clip_by_global_norm(config.grad_clip),
            optax.adam(config.lr))
        self.opt_state = self._optimizer.init(self.params)

        def update_fn(params, opt_state, batch):
            (loss, metrics), grads = jax.value_and_grad(
                self.compute_loss, has_aux=True)(params, batch)
            updates, opt_state = self._optimizer.update(grads, opt_state,
                                                        params)
            params = optax.apply_updates(params, updates)
            metrics["total_loss"] = loss
            return params, opt_state, metrics

        def grads_fn(params, batch):
            (loss, metrics), grads = jax.value_and_grad(
                self.compute_loss, has_aux=True)(params, batch)
            metrics["total_loss"] = loss
            return grads, metrics

        def apply_fn(params, opt_state, grads):
            updates, opt_state = self._optimizer.update(grads, opt_state,
                                                        params)
            return optax.apply_updates(params, updates), opt_state

        if self._mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            replicated = NamedSharding(self._mesh, P())
            batch_sharded = NamedSharding(self._mesh, P("dp"))
            self._batch_sharding = batch_sharded
            self._update_jit = jax.jit(
                update_fn,
                in_shardings=(replicated, replicated, batch_sharded),
                out_shardings=(replicated, replicated, replicated))
        else:
            self._batch_sharding = None
            self._update_jit = jax.jit(update_fn)
        self._grads_jit = jax.jit(grads_fn)
        self._apply_jit = jax.jit(apply_fn)
        self._built = True
        return self

    def _device_batch(self, batch):
        import jax
        import jax.numpy as jnp
        batch = {k: jnp.asarray(v) for k, v in batch.items()}
        if self._batch_sharding is not None:
            ndp = self._mesh.shape["dp"]
            size = next(iter(batch.values())).shape[0]
            if size < ndp:
                raise ValueError(
                    f"Batch of {size} rows cannot be sharded over dp={ndp} "
                    "devices; grow the batch or shrink the mesh")
            if size % ndp:
                # device_put requires equal shards; drop the remainder
                # (< ndp rows) rather than crash on ragged batches.
                self.last_dropped_rows = size % ndp
                batch = {k: v[:size - size % ndp] for k, v in batch.items()}
            else:
                self.last_dropped_rows = 0
            batch = jax.device_put(batch, self._batch_sharding)
        return batch

    def update(self, batch) -> Dict[str, float]:
        """One synchronous gradient step on ``batch`` (globally sharded
        over the mesh's dp axis in SPMD mode)."""
        self.params, self.opt_state, metrics = self._update_jit(
            self.params, self.opt_state, self._device_batch(batch))
        return {k: float(v) for k, v in metrics.items()}

    def compute_gradients(self, batch) -> Tuple[Any, Dict[str, float]]:
        """Gradients only (remote-learner mode: the group averages)."""
        import jax
        import numpy as np
        grads, metrics = self._grads_jit(self.params,
                                         self._device_batch(batch))
        return (jax.tree.map(np.asarray, grads),
                {k: float(v) for k, v in metrics.items()})

    def apply_gradients(self, grads) -> None:
        self.params, self.opt_state = self._apply_jit(
            self.params, self.opt_state, grads)

    def get_weights(self):
        import jax
        import numpy as np
        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights) -> None:
        import jax
        import jax.numpy as jnp
        self.params = jax.tree.map(jnp.asarray, weights)


class PPOLearner(Learner):
    """PPO's clipped-surrogate loss on any RLModule exposing logp /
    values / entropy through forward_train (the new-stack twin of
    algorithms/ppo.py)."""

    def compute_loss(self, params, batch):
        import jax.numpy as jnp

        config = self.config
        out = self.module.forward_train(params, batch)
        ratio = jnp.exp(out["logp"] - batch["logp_old"])
        adv = batch["advantages"]
        surrogate = jnp.minimum(
            ratio * adv,
            jnp.clip(ratio, 1 - config.clip_param,
                     1 + config.clip_param) * adv)
        pi_loss = -surrogate.mean()
        vf_loss = ((out["values"] - batch["value_targets"]) ** 2).mean()
        entropy = out["entropy"].mean()
        total = (pi_loss + config.vf_loss_coeff * vf_loss
                 - config.entropy_coeff * entropy)
        return total, {"policy_loss": pi_loss, "vf_loss": vf_loss,
                       "entropy": entropy}
