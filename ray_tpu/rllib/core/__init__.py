"""ray_tpu.rllib.core: the RLlib "new stack".

Analog of the reference's embryonic rllib/core (SURVEY.md §2.6:
rl_module/rl_module.py, rl_trainer/trainer_runner.py), redesigned
TPU-first: RLModule is a *functional* network description (pure init/apply
over pytree params), Learner owns one jitted update built from a
compute_loss, and LearnerGroup is the TrainerRunner analog with two
scale-out modes — SPMD (one pjit update sharded over the device mesh's
``dp`` axis; gradients ride ICI via GSPMD-inserted psums) and remote
(learner actors computing gradients that the group averages), covering the
reference's multi-GPU-learner capability on TPU.
"""

from ray_tpu.rllib.core.learner import Learner, LearnerConfig, PPOLearner
from ray_tpu.rllib.core.learner_group import LearnerGroup
from ray_tpu.rllib.core.rl_module import (MLPActorCriticModule, RLModule,
                                          RLModuleSpec)

__all__ = ["Learner", "LearnerConfig", "LearnerGroup",
           "MLPActorCriticModule", "PPOLearner", "RLModule", "RLModuleSpec"]
