"""Policy registry: maps the ``policy_class`` name in a policy config to
an implementation (the analog of the reference's per-framework policy
classes resolved in rllib/algorithms/*/: torch_policy vs tf_policy — here
they are all JAX)."""

from __future__ import annotations

from typing import Any, Dict

import numpy as np


def make_policy(policy_config: Dict[str, Any], obs_space, action_space,
                seed: int = 0):
    """Instantiate the policy named by policy_config['policy_class']."""
    import gymnasium as gym
    if isinstance(obs_space, gym.spaces.Dict) and "obs" in obs_space.spaces:
        # The {"obs", "action_mask"} dict convention (AlphaZero envs,
        # reference parametric-action envs): policies encode the inner
        # observation; masks are the algorithm's concern.
        obs_space = obs_space.spaces["obs"]
    name = policy_config.get("policy_class", "actor_critic")
    model_config = {
        "fcnet_hiddens": policy_config.get("fcnet_hiddens", (64, 64)),
        "conv_filters": policy_config.get("conv_filters"),
        "post_fcnet_dim": policy_config.get("post_fcnet_dim", 256),
        "dueling": policy_config.get("dueling", False),
        "noisy": policy_config.get("noisy", True),
        "num_atoms": policy_config.get("num_atoms", 51),
        "lstm_cell_size": policy_config.get("lstm_cell_size", 64),
        "v_min": policy_config.get("v_min", -10.0),
        "v_max": policy_config.get("v_max", 10.0),
    }
    if name == "actor_critic":
        from ray_tpu.rllib.policy.jax_policy import JAXPolicy
        return JAXPolicy(
            obs_dim=int(np.prod(obs_space.shape)),
            action_space=action_space,
            hiddens=tuple(model_config["fcnet_hiddens"]),
            seed=seed,
            obs_space=obs_space,
            model_config=model_config)
    if name == "q":
        from ray_tpu.rllib.policy.q_policy import QPolicy
        return QPolicy(obs_space, action_space, model_config, seed=seed)
    if name == "sac":
        from ray_tpu.rllib.policy.sac_policy import SACPolicy
        return SACPolicy(obs_space, action_space, model_config, seed=seed)
    if name == "r2d2":
        from ray_tpu.rllib.policy.r2d2_policy import R2D2Policy
        return R2D2Policy(obs_space, action_space, model_config,
                          seed=seed)
    if name == "rainbow":
        from ray_tpu.rllib.policy.rainbow_policy import RainbowPolicy
        return RainbowPolicy(obs_space, action_space, model_config,
                             seed=seed)
    if name == "td3":
        from ray_tpu.rllib.policy.sac_policy import TD3Policy
        return TD3Policy(obs_space, action_space, model_config, seed=seed)
    raise ValueError(f"Unknown policy_class {name!r}")
