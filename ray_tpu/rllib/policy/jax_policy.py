"""JAXPolicy: actor-critic policy as pure pytree params + jitted functions.

The TPU-native replacement for the reference's rllib/policy/torch_policy_v2
(SURVEY.md §2.6: "JAX policy + learner"): an MLP torso with policy and value
heads, categorical (Discrete) or diagonal-gaussian (Box) action
distributions, fully functional (params in, actions/losses out) so the
learner jits/pjits the update and rollout workers run the same apply
function with device_put weights.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.policy.sample_batch import SampleBatch


def _mlp_init(key, sizes: Sequence[int]):
    params = []
    for i in range(len(sizes) - 1):
        key, k1, k2 = jax.random.split(key, 3)
        w = jax.random.normal(k1, (sizes[i], sizes[i + 1])) * jnp.sqrt(
            2.0 / sizes[i])
        b = jnp.zeros((sizes[i + 1],))
        params.append({"w": w, "b": b})
    return params


def _mlp_apply(params, x, activate_last=False):
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1 or activate_last:
            x = jnp.tanh(x)
    return x


class JAXPolicy:
    """Holds params + jitted fns. Not itself an actor — rollout workers and
    the learner each own one."""

    def __init__(self, obs_dim: int, action_space: Any,
                 hiddens: Sequence[int] = (64, 64), seed: int = 0,
                 obs_space: Any = None,
                 model_config: Optional[Dict[str, Any]] = None):
        import gymnasium as gym
        self.obs_dim = obs_dim
        self.action_space = action_space
        self.discrete = isinstance(action_space, gym.spaces.Discrete)
        self.act_dim = (int(action_space.n) if self.discrete
                        else int(np.prod(action_space.shape)))
        key = jax.random.PRNGKey(seed)
        k_enc, k_pi, k_vf, k_logstd = jax.random.split(key, 4)
        out = self.act_dim
        # Image observations get the catalog CNN as a SHARED torso with
        # linear pi/vf heads (the standard Atari actor-critic shape —
        # reference: models/catalog.py vision nets feeding both heads);
        # vector observations keep the per-head MLP torsos.
        self._enc_apply = None
        from ray_tpu.rllib.models.catalog import ModelCatalog
        if obs_space is not None and ModelCatalog.is_image_space(obs_space):
            enc_init, self._enc_apply, feat = ModelCatalog.get_encoder(
                obs_space, model_config or {})
            self.params = {
                "enc": enc_init(k_enc),
                "pi": _mlp_init(k_pi, [feat, out]),
                "vf": _mlp_init(k_vf, [feat, 1]),
            }
        else:
            self.params = {
                "pi": _mlp_init(k_pi, [obs_dim, *hiddens, out]),
                "vf": _mlp_init(k_vf, [obs_dim, *hiddens, 1]),
            }
        if not self.discrete:
            self.params["log_std"] = jnp.zeros((self.act_dim,))
        self._sample_jit = jax.jit(self._sample)
        self._value_jit = jax.jit(self._value)

        def _sample_step(params, obs, key):
            key, sub = jax.random.split(key)
            a, logp, v = self._sample(params, obs, sub)
            return a, logp, v, key

        # One fused dispatch per env step: the key split runs INSIDE
        # the jit (a Python-side jax.random.split costs a whole extra
        # dispatch per step — ~25% of head-path sampling time on CPU).
        self._sample_step_jit = jax.jit(_sample_step)

    # -- functional core -------------------------------------------------

    def _torso(self, params, obs):
        if self._enc_apply is not None:
            return self._enc_apply(params["enc"], obs)
        return obs

    def logits(self, params, obs):
        return _mlp_apply(params["pi"], self._torso(params, obs))

    def _value(self, params, obs):
        return _mlp_apply(params["vf"], self._torso(params, obs))[..., 0]

    def logp(self, params, obs, actions):
        logits = self.logits(params, obs)
        if self.discrete:
            logp_all = jax.nn.log_softmax(logits)
            return jnp.take_along_axis(
                logp_all, actions[..., None].astype(jnp.int32), -1)[..., 0]
        log_std = params["log_std"]
        var = jnp.exp(2 * log_std)
        return (-0.5 * (((actions - logits) ** 2) / var
                        + 2 * log_std + jnp.log(2 * jnp.pi))).sum(-1)

    def entropy(self, params, obs):
        logits = self.logits(params, obs)
        if self.discrete:
            p = jax.nn.softmax(logits)
            return -(p * jax.nn.log_softmax(logits)).sum(-1)
        return (params["log_std"] + 0.5 * jnp.log(2 * jnp.pi * jnp.e)).sum()

    def _sample(self, params, obs, key):
        logits = self.logits(params, obs)
        value = self._value(params, obs)
        if self.discrete:
            action = jax.random.categorical(key, logits)
            logp = jax.nn.log_softmax(logits)[
                jnp.arange(obs.shape[0]), action]
            return action, logp, value
        std = jnp.exp(params["log_std"])
        noise = jax.random.normal(key, logits.shape)
        action = logits + std * noise
        logp = self.logp(params, obs, action)
        return action, logp, value

    # -- worker-side API -------------------------------------------------

    def compute_actions(self, obs: np.ndarray, key) -> Tuple[np.ndarray,
                                                             np.ndarray,
                                                             np.ndarray]:
        a, logp, v = self._sample_jit(self.params, jnp.asarray(obs), key)
        return np.asarray(a), np.asarray(logp), np.asarray(v)

    def compute_actions_keyed(self, obs: np.ndarray, key):
        """Like compute_actions, but carries the RNG key through the
        jit (split inside): returns (actions, logps, values, new_key).
        The sampler's per-step fast path."""
        a, logp, v, key = self._sample_step_jit(
            self.params, jnp.asarray(obs), key)
        return np.asarray(a), np.asarray(logp), np.asarray(v), key

    def compute_values(self, obs: np.ndarray) -> np.ndarray:
        return np.asarray(self._value_jit(self.params, jnp.asarray(obs)))

    def get_weights(self):
        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights) -> None:
        self.params = jax.tree.map(jnp.asarray, weights)


def compute_gae(batch: SampleBatch, gamma: float = 0.99,
                lam: float = 0.95, last_value: float = 0.0) -> SampleBatch:
    """GAE(λ) advantages + value targets over one episode fragment
    (reference: rllib/evaluation/postprocessing.py compute_advantages)."""
    rewards = batch[SampleBatch.REWARDS].astype(np.float64)
    values = batch[SampleBatch.VF_PREDS].astype(np.float64)
    terminated = batch[SampleBatch.TERMINATEDS]
    n = len(rewards)
    next_values = np.append(values[1:], last_value)
    deltas = rewards + gamma * next_values * (1 - terminated) - values
    adv = np.zeros(n)
    acc = 0.0
    for t in reversed(range(n)):
        acc = deltas[t] + gamma * lam * (1 - terminated[t]) * acc
        adv[t] = acc
    batch[SampleBatch.ADVANTAGES] = adv.astype(np.float32)
    batch[SampleBatch.VALUE_TARGETS] = (adv + values).astype(np.float32)
    return batch
