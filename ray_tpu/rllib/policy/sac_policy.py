"""SACPolicy: squashed-Gaussian actor for soft actor-critic.

Rollout-side half of SAC (reference: rllib/algorithms/sac): a tanh-squashed
diagonal-Gaussian actor rescaled to the Box bounds. The twin Q critics,
their targets, and the temperature live in the learner (algorithms/sac.py).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.models.catalog import ModelCatalog, mlp_apply, mlp_init

LOG_STD_MIN, LOG_STD_MAX = -20.0, 2.0


class SACPolicy:
    needs_gae = False

    def __init__(self, obs_space, action_space: Any,
                 model_config: Dict[str, Any] = None, seed: int = 0):
        import gymnasium as gym
        if not isinstance(action_space, gym.spaces.Box):
            raise ValueError("SACPolicy requires a Box action space")
        self.discrete = False
        self.action_space = action_space
        self.act_dim = int(np.prod(action_space.shape))
        self.low = np.asarray(action_space.low, np.float32).reshape(-1)
        self.high = np.asarray(action_space.high, np.float32).reshape(-1)
        model_config = model_config or {}
        enc_init, self._encode, feat_dim = ModelCatalog.get_encoder(
            obs_space, model_config)
        key = jax.random.PRNGKey(seed)
        k_enc, k_head = jax.random.split(key)
        self.params = {
            "encoder": enc_init(k_enc),
            # One head emitting [mu, log_std].
            "head": mlp_init(k_head, [feat_dim, 2 * self.act_dim]),
        }
        self._sample_jit = jax.jit(self.sample)

    # -- functional core -------------------------------------------------

    def dist_params(self, params, obs):
        feats = self._encode(params["encoder"], obs)
        out = mlp_apply(params["head"], feats)
        mu, log_std = jnp.split(out, 2, axis=-1)
        return mu, jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)

    def sample(self, params, obs, key):
        """Reparameterized squashed sample → (env_action, logp)."""
        mu, log_std = self.dist_params(params, obs)
        std = jnp.exp(log_std)
        eps = jax.random.normal(key, mu.shape)
        pre_tanh = mu + std * eps
        a = jnp.tanh(pre_tanh)
        # logp with tanh change-of-variables correction.
        gauss_logp = (-0.5 * (eps ** 2 + 2 * log_std
                              + jnp.log(2 * jnp.pi))).sum(-1)
        correction = jnp.log(1 - a ** 2 + 1e-6).sum(-1)
        logp = gauss_logp - correction
        scaled = self.low + (a + 1.0) * 0.5 * (self.high - self.low)
        return scaled, logp

    def logp_and_sample(self, params, obs, key):
        """Used by the learner's actor/critic losses (same math, jittable
        inside a larger update)."""
        return self.sample(params, obs, key)

    # -- worker-side API -------------------------------------------------

    def compute_actions(self, obs: np.ndarray, key) -> Tuple[np.ndarray,
                                                             np.ndarray,
                                                             np.ndarray]:
        a, logp = self._sample_jit(self.params, jnp.asarray(obs), key)
        zeros = np.zeros((obs.shape[0],), np.float32)
        return np.asarray(a), np.asarray(logp), zeros

    def compute_values(self, obs: np.ndarray) -> np.ndarray:
        return np.zeros((obs.shape[0],), np.float32)

    def get_weights(self):
        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights) -> None:
        self.params = jax.tree.map(jnp.asarray, weights)


class TD3Policy(SACPolicy):
    """Deterministic actor + fixed Gaussian exploration noise (canonical
    TD3 behavior policy). Reuses SACPolicy's network but ignores the
    log_std head at rollout: TD3's actor loss trains only the mean, so the
    sampled-std path would leave exploration scale untrained."""

    EXPLORATION_SIGMA = 0.1  # fraction of the half action range

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)

        def det(params, obs):
            mu, _ = self.dist_params(params, obs)
            a = jnp.tanh(mu)
            return self.low + (a + 1.0) * 0.5 * (self.high - self.low)

        self._det_jit = jax.jit(det)

    def compute_actions(self, obs: np.ndarray, key):
        a = np.asarray(self._det_jit(self.params, jnp.asarray(obs)))
        noise = np.asarray(jax.random.normal(key, a.shape)) * \
            self.EXPLORATION_SIGMA * (self.high - self.low) * 0.5
        a = np.clip(a + noise, self.low, self.high)
        zeros = np.zeros((obs.shape[0],), np.float32)
        return a.astype(np.float32), zeros, zeros
