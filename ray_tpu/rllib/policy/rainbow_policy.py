"""Rainbow Q-policy: noisy nets + C51 distributional value heads.

The remaining two Rainbow components (Hessel et al. 2018) on top of the
DQN stack's double-Q / dueling / n-step / prioritized replay (reference:
rllib/algorithms/dqn with num_atoms > 1 + noisy=True):

* **Noisy linear layers** (factorized Gaussian, Fortunato et al. 2017):
  each head layer carries (w_mu, w_sigma, b_mu, b_sigma); a forward pass
  under an explicit PRNG key perturbs weights with factorized noise, and
  exploration comes from the noise itself — no epsilon schedule.
* **C51 categorical distribution** (Bellemare et al. 2017): the head
  emits ``num_atoms`` logits per action over a fixed support
  [v_min, v_max]; Q(s,a) = sum_i p_i * z_i, and the learner minimizes
  cross-entropy against the projected target distribution.

Both compose with dueling: value and advantage streams each produce atom
logits, combined with the mean-advantage constraint per atom.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.models.catalog import ModelCatalog


def noisy_init(key, in_dim: int, out_dim: int) -> Dict[str, Any]:
    """Factorized-noisy linear parameters (mu uniform, sigma 0.5/sqrt)."""
    k_w, k_b = jax.random.split(key)
    bound = 1.0 / np.sqrt(in_dim)
    return {
        "w_mu": jax.random.uniform(k_w, (in_dim, out_dim),
                                   minval=-bound, maxval=bound),
        "w_sigma": jnp.full((in_dim, out_dim), 0.5 / np.sqrt(in_dim)),
        "b_mu": jax.random.uniform(k_b, (out_dim,),
                                   minval=-bound, maxval=bound),
        "b_sigma": jnp.full((out_dim,), 0.5 / np.sqrt(in_dim)),
    }


def _f(x):
    return jnp.sign(x) * jnp.sqrt(jnp.abs(x))


def noisy_apply(params: Dict[str, Any], x, key=None):
    """key=None -> deterministic mu-only pass (evaluation).

    Noise is sampled independently PER BATCH ROW (Fortunato et al. eq. 10
    in the batched setting): with shared noise, a whole gradient step
    chases one correlated perturbation — observed as oscillating
    collapse. The factorized form never materializes per-row weight
    matrices: x (w_sigma o eps_in eps_out^T) == ((x o eps_in) w_sigma)
    o eps_out."""
    if key is None:
        return x @ params["w_mu"] + params["b_mu"]
    in_dim, out_dim = params["w_mu"].shape
    batch = x.shape[0]
    k_in, k_out = jax.random.split(key)
    eps_in = _f(jax.random.normal(k_in, (batch, in_dim)))
    eps_out = _f(jax.random.normal(k_out, (batch, out_dim)))
    mu = x @ params["w_mu"] + params["b_mu"]
    noise = ((x * eps_in) @ params["w_sigma"]) * eps_out \
        + params["b_sigma"] * eps_out
    return mu + noise


class RainbowPolicy:
    """Distributional noisy Q policy (policy_class "rainbow")."""

    needs_gae = False

    def __init__(self, obs_space, action_space: Any,
                 model_config: Dict[str, Any] = None, seed: int = 0):
        import gymnasium as gym
        if not isinstance(action_space, gym.spaces.Discrete):
            raise ValueError("RainbowPolicy requires Discrete actions")
        self.discrete = True
        self.action_space = action_space
        self.act_dim = int(action_space.n)
        model_config = model_config or {}
        self.num_atoms = int(model_config.get("num_atoms", 51))
        self.v_min = float(model_config.get("v_min", -10.0))
        self.v_max = float(model_config.get("v_max", 10.0))
        self.noisy = bool(model_config.get("noisy", True))
        self.dueling = bool(model_config.get("dueling", True))
        self.support = jnp.linspace(self.v_min, self.v_max,
                                    self.num_atoms)
        enc_init, self._encode, feat_dim = ModelCatalog.get_encoder(
            obs_space, model_config)
        key = jax.random.PRNGKey(seed)
        k_enc, k_adv, k_val = jax.random.split(key, 3)
        heads = {"adv": noisy_init(k_adv, feat_dim,
                                   self.act_dim * self.num_atoms)}
        if self.dueling:
            heads["val"] = noisy_init(k_val, feat_dim, self.num_atoms)
        self.params = {"encoder": enc_init(k_enc), **heads}
        # Exploration: noisy nets explore via weight noise. epsilon kept
        # for API parity (synced by the DQN learner) but unused when
        # noisy=True.
        self.epsilon = 0.0
        self.fixed_epsilon = self.noisy
        self._dist_jit = jax.jit(self.logits_dist)

    # -- functional core -------------------------------------------------

    def logits_dist(self, params, obs, key=None):
        """-> [B, act_dim, num_atoms] log-probabilities."""
        feats = self._encode(params["encoder"], obs)
        k_adv = k_val = None
        if self.noisy and key is not None:
            k_adv, k_val = jax.random.split(key)
        adv = noisy_apply(params["adv"], feats, k_adv).reshape(
            (-1, self.act_dim, self.num_atoms))
        if self.dueling:
            val = noisy_apply(params["val"], feats, k_val).reshape(
                (-1, 1, self.num_atoms))
            logits = val + adv - adv.mean(axis=1, keepdims=True)
        else:
            logits = adv
        return jax.nn.log_softmax(logits, axis=-1)

    def q_values(self, params, obs, key=None):
        """Expected values under the categorical distribution."""
        log_p = self.logits_dist(params, obs, key)
        return (jnp.exp(log_p) * self.support).sum(-1)

    # -- worker-side API -------------------------------------------------

    def compute_actions(self, obs: np.ndarray, key) -> Tuple[np.ndarray,
                                                             np.ndarray,
                                                             np.ndarray]:
        k_noise, k_eps, k_rand = jax.random.split(key, 3)
        log_p = self._dist_jit(self.params, jnp.asarray(obs),
                               k_noise if self.noisy else None)
        q = (jnp.exp(log_p) * self.support).sum(-1)
        actions = np.asarray(q.argmax(-1))
        if not self.noisy and self.epsilon > 0:
            explore = np.asarray(
                jax.random.uniform(k_eps, (obs.shape[0],))) < self.epsilon
            rand = np.asarray(jax.random.randint(
                k_rand, (obs.shape[0],), 0, self.act_dim))
            actions = np.where(explore, rand, actions)
        zeros = np.zeros((obs.shape[0],), np.float32)
        return actions, zeros, zeros

    def compute_values(self, obs: np.ndarray) -> np.ndarray:
        log_p = self._dist_jit(self.params, jnp.asarray(obs), None)
        return np.asarray(((jnp.exp(log_p) * self.support).sum(-1)
                           ).max(-1))

    def get_weights(self):
        return {"params": jax.tree.map(np.asarray, self.params),
                "epsilon": self.epsilon}

    def set_weights(self, weights) -> None:
        if isinstance(weights, dict) and "params" in weights:
            self.params = jax.tree.map(jnp.asarray, weights["params"])
            if not self.fixed_epsilon:
                self.epsilon = float(weights.get("epsilon", self.epsilon))
        else:
            self.params = jax.tree.map(jnp.asarray, weights)


def project_distribution(next_log_p, rewards, discounts, dones, support,
                         v_min: float, v_max: float):
    """C51 categorical projection (Bellemare et al. 2017, alg. 1):
    shift the support by r + gamma^k * z, clip to [v_min, v_max], and
    distribute each atom's mass onto its two neighboring bins.

    next_log_p: [B, num_atoms] log-probs of the chosen next action.
    Returns [B, num_atoms] target probabilities (stop-gradient safe)."""
    num_atoms = support.shape[0]
    delta = (v_max - v_min) / (num_atoms - 1)
    # Tz: [B, atoms] target support positions
    tz = rewards[:, None] + (discounts * (1.0 - dones))[:, None] * \
        support[None, :]
    tz = jnp.clip(tz, v_min, v_max)
    b = (tz - v_min) / delta                    # fractional bin index
    lo = jnp.floor(b).astype(jnp.int32)
    hi = jnp.ceil(b).astype(jnp.int32)
    # When b lands exactly on a bin (lo == hi), give it full mass once.
    eq = (lo == hi).astype(jnp.float32)
    p_next = jnp.exp(next_log_p)                # [B, atoms]
    m_lo = p_next * ((hi.astype(jnp.float32) - b) + eq)
    m_hi = p_next * (b - lo.astype(jnp.float32))
    target = jnp.zeros_like(p_next)
    batch = jnp.arange(p_next.shape[0])[:, None]
    target = target.at[batch, lo].add(m_lo)
    target = target.at[batch, jnp.minimum(hi, num_atoms - 1)].add(m_hi)
    return target
