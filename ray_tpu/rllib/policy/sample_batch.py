"""SampleBatch: columnar container for trajectories.

Analog of the reference's rllib/policy/sample_batch.py: a dict of equal-
length numpy arrays with the standard column names, plus concat / slice /
shuffle / minibatch helpers. Kept as host numpy; the learner device_puts
whole minibatches (TPU-first: one transfer per SGD step).
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional

import numpy as np


class SampleBatch(dict):
    OBS = "obs"
    NEXT_OBS = "new_obs"
    ACTIONS = "actions"
    REWARDS = "rewards"
    TERMINATEDS = "terminateds"
    TRUNCATEDS = "truncateds"
    ACTION_LOGP = "action_logp"
    VF_PREDS = "vf_preds"
    ADVANTAGES = "advantages"
    VALUE_TARGETS = "value_targets"
    EPS_ID = "eps_id"

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        for k, v in list(self.items()):
            if not isinstance(v, np.ndarray):
                self[k] = np.asarray(v)

    def __len__(self) -> int:
        for v in self.values():
            return len(v)
        return 0

    @property
    def count(self) -> int:
        return len(self)

    @staticmethod
    def concat_samples(batches: List["SampleBatch"]) -> "SampleBatch":
        if not batches:
            return SampleBatch()
        keys = batches[0].keys()
        return SampleBatch({
            k: np.concatenate([np.asarray(b[k]) for b in batches])
            for k in keys})

    def slice(self, start: int, end: int) -> "SampleBatch":
        return SampleBatch({k: v[start:end] for k, v in self.items()})

    def shuffle(self, seed: Optional[int] = None) -> "SampleBatch":
        rng = np.random.default_rng(seed)
        perm = rng.permutation(len(self))
        return SampleBatch({k: v[perm] for k, v in self.items()})

    def minibatches(self, minibatch_size: int,
                    seed: Optional[int] = None) -> Iterator["SampleBatch"]:
        shuffled = self.shuffle(seed)
        for start in range(0, len(self), minibatch_size):
            mb = shuffled.slice(start, start + minibatch_size)
            if len(mb) == minibatch_size:
                yield mb

    def split_by_episode(self) -> List["SampleBatch"]:
        if self.EPS_ID not in self:
            return [self]
        eps = self[self.EPS_ID]
        out = []
        start = 0
        for i in range(1, len(eps) + 1):
            if i == len(eps) or eps[i] != eps[start]:
                out.append(self.slice(start, i))
                start = i
        return out


class MultiAgentBatch:
    """Per-policy SampleBatches from one joint rollout (analog of the
    reference's policy/sample_batch.py MultiAgentBatch): maps policy id →
    SampleBatch, with env_steps counting JOINT environment steps (each of
    which may contribute a row to several policies)."""

    def __init__(self, policy_batches, env_steps: int):
        self.policy_batches = dict(policy_batches)
        self.count = int(env_steps)

    def env_steps(self) -> int:
        return self.count

    def agent_steps(self) -> int:
        return sum(len(b) for b in self.policy_batches.values())

    def __len__(self) -> int:
        return self.count

    @staticmethod
    def concat_samples(batches) -> "MultiAgentBatch":
        merged = {}
        steps = 0
        for batch in batches:
            steps += batch.count
            for pid, sb in batch.policy_batches.items():
                merged.setdefault(pid, []).append(sb)
        return MultiAgentBatch(
            {pid: SampleBatch.concat_samples(parts)
             for pid, parts in merged.items()}, steps)
