"""R2D2 policy: recurrent (LSTM) Q-network with stored hidden states.

The policy half of R2D2 (Kapturowski et al. 2019; reference:
rllib/algorithms/r2d2 + the torch RNN model stack): an encoder feeds an
LSTM whose hidden state carries across env steps; the Q head (dueling)
reads the LSTM output. Rollout workers step it statefully (the worker
calls ``reset_state`` at episode boundaries and records the PRE-step
hidden state into every transition via ``state_rows``), so the learner
can re-run the recurrence from any stored position: sample a sequence
window, seed the LSTM with the stored state, burn in a few steps without
gradient, then TD-train the remainder (algorithms/r2d2.py).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.models.catalog import ModelCatalog, mlp_apply, mlp_init


def lstm_init(key, in_dim: int, hidden: int) -> Dict[str, Any]:
    k_w, _ = jax.random.split(key)
    scale = 1.0 / np.sqrt(in_dim + hidden)
    return {
        "w": jax.random.normal(k_w, (in_dim + hidden, 4 * hidden)) * scale,
        "b": jnp.zeros((4 * hidden,)),
    }


def lstm_step(params, h, c, x):
    """One LSTM cell step. x: [B, in], h/c: [B, hidden]. Forget-gate bias
    +1 (standard init: remember by default)."""
    hidden = h.shape[-1]
    z = jnp.concatenate([x, h], axis=-1) @ params["w"] + params["b"]
    i, f, g, o = jnp.split(z, 4, axis=-1)
    c_new = jax.nn.sigmoid(f + 1.0) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
    h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
    del hidden
    return h_new, c_new


def value_rescale(x, eps: float = 1e-3):
    """h(x) = sign(x)(sqrt(|x|+1)-1) + eps*x (R2D2's invertible value
    rescaling for raw-reward training)."""
    return jnp.sign(x) * (jnp.sqrt(jnp.abs(x) + 1.0) - 1.0) + eps * x


def value_rescale_inv(x, eps: float = 1e-3):
    inner = jnp.sqrt(1.0 + 4.0 * eps * (jnp.abs(x) + 1.0 + eps)) - 1.0
    return jnp.sign(x) * ((inner / (2.0 * eps)) ** 2 - 1.0)


class R2D2Policy:
    needs_gae = False

    def __init__(self, obs_space, action_space: Any,
                 model_config: Dict[str, Any] = None, seed: int = 0):
        import gymnasium as gym
        if not isinstance(action_space, gym.spaces.Discrete):
            raise ValueError("R2D2Policy requires Discrete actions")
        self.discrete = True
        self.action_space = action_space
        self.act_dim = int(action_space.n)
        model_config = model_config or {}
        self.hidden = int(model_config.get("lstm_cell_size", 64))
        enc_init, self._encode, feat_dim = ModelCatalog.get_encoder(
            obs_space, model_config)
        key = jax.random.PRNGKey(seed)
        k_enc, k_lstm, k_adv, k_val = jax.random.split(key, 4)
        self.params = {
            "encoder": enc_init(k_enc),
            "lstm": lstm_init(k_lstm, feat_dim, self.hidden),
            "adv_head": mlp_init(k_adv, [self.hidden, self.act_dim]),
            "value_head": mlp_init(k_val, [self.hidden, 1]),
        }
        self.epsilon = 1.0
        self.fixed_epsilon = False
        self._h = np.zeros((1, self.hidden), np.float32)
        self._c = np.zeros((1, self.hidden), np.float32)
        self.state_rows: Dict[str, np.ndarray] = {}
        self._step_jit = jax.jit(self._step)

    # -- functional core -------------------------------------------------

    def _q_from_h(self, params, h):
        value = mlp_apply(params["value_head"], h)
        adv = mlp_apply(params["adv_head"], h)
        return value + adv - adv.mean(-1, keepdims=True)

    def _step(self, params, obs, h, c):
        feats = self._encode(params["encoder"], obs)
        h, c = lstm_step(params["lstm"], h, c, feats)
        return self._q_from_h(params, h), h, c

    def q_seq(self, params, obs_seq, h0, c0):
        """Run the recurrence over a [B, T, ...] window from (h0, c0).
        Returns q [B, T, A] and the final state."""
        def scan_fn(carry, obs_t):
            h, c = carry
            feats = self._encode(params["encoder"], obs_t)
            h, c = lstm_step(params["lstm"], h, c, feats)
            return (h, c), self._q_from_h(params, h)

        obs_tmajor = jnp.moveaxis(obs_seq, 1, 0)  # [T, B, ...]
        (h, c), q = jax.lax.scan(scan_fn, (h0, c0), obs_tmajor)
        return jnp.moveaxis(q, 1, 0), (h, c)

    # -- worker-side API -------------------------------------------------

    def reset_state(self) -> None:
        self._h = np.zeros((1, self.hidden), np.float32)
        self._c = np.zeros((1, self.hidden), np.float32)

    def compute_actions(self, obs: np.ndarray, key) -> Tuple[np.ndarray,
                                                             np.ndarray,
                                                             np.ndarray]:
        # Record the PRE-step state: replaying the stored sequence from
        # this state reproduces this step's Q values exactly.
        self.state_rows = {"lstm_h": self._h[0].copy(),
                           "lstm_c": self._c[0].copy()}
        q, h, c = self._step_jit(self.params, jnp.asarray(obs),
                                 jnp.asarray(self._h),
                                 jnp.asarray(self._c))
        self._h = np.asarray(h)
        self._c = np.asarray(c)
        greedy = np.asarray(q.argmax(-1))
        k1, k2 = jax.random.split(key)
        explore = np.asarray(
            jax.random.uniform(k1, (obs.shape[0],))) < self.epsilon
        rand = np.asarray(jax.random.randint(
            k2, (obs.shape[0],), 0, self.act_dim))
        actions = np.where(explore, rand, greedy)
        zeros = np.zeros((obs.shape[0],), np.float32)
        return actions, zeros, zeros

    def compute_values(self, obs: np.ndarray) -> np.ndarray:
        q, _, _ = self._step_jit(self.params, jnp.asarray(obs),
                                 jnp.asarray(self._h),
                                 jnp.asarray(self._c))
        return np.asarray(q.max(-1))

    def compute_greedy(self, obs: np.ndarray) -> int:
        """Greedy eval step (Algorithm.compute_single_action/evaluate
        dispatch): argmax Q, advancing the recurrent state — recurrent
        evaluation is stateful by nature."""
        q, h, c = self._step_jit(self.params, jnp.asarray(obs),
                                 jnp.asarray(self._h),
                                 jnp.asarray(self._c))
        self._h = np.asarray(h)
        self._c = np.asarray(c)
        return int(np.asarray(q).argmax(-1)[0])

    def get_weights(self):
        return {"params": jax.tree.map(np.asarray, self.params),
                "epsilon": self.epsilon}

    def set_weights(self, weights) -> None:
        if isinstance(weights, dict) and "params" in weights:
            self.params = jax.tree.map(jnp.asarray, weights["params"])
            if not self.fixed_epsilon:
                self.epsilon = float(weights.get("epsilon", self.epsilon))
        else:
            self.params = jax.tree.map(jnp.asarray, weights)
