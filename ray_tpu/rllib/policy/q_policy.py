"""QPolicy: epsilon-greedy Q-network policy for value-based algorithms.

Rollout-side half of DQN (reference: rllib/algorithms/dqn — the torch
DQNTorchPolicy's action sampler): the worker holds the online Q params and
an exploration epsilon (synced from the learner with the weights, so the
schedule is driven centrally); the learner (algorithms/dqn.py) owns the
target network and the double-DQN update.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.models.catalog import ModelCatalog, mlp_apply, mlp_init


class QPolicy:
    needs_gae = False

    def __init__(self, obs_space, action_space: Any,
                 model_config: Dict[str, Any] = None, seed: int = 0):
        import gymnasium as gym
        if not isinstance(action_space, gym.spaces.Discrete):
            raise ValueError("QPolicy requires a Discrete action space")
        self.discrete = True
        self.action_space = action_space
        self.act_dim = int(action_space.n)
        model_config = model_config or {}
        enc_init, self._encode, feat_dim = ModelCatalog.get_encoder(
            obs_space, model_config)
        key = jax.random.PRNGKey(seed)
        k_enc, k_head, k_value = jax.random.split(key, 3)
        if model_config.get("dueling"):
            # Dueling architecture (reference: dqn dueling=True): separate
            # state-value and advantage streams, combined with the
            # mean-advantage identifiability constraint.
            self.params = {
                "encoder": enc_init(k_enc),
                "adv_head": mlp_init(k_head, [feat_dim, self.act_dim]),
                "value_head": mlp_init(k_value, [feat_dim, 1]),
            }
        else:
            self.params = {
                "encoder": enc_init(k_enc),
                "head": mlp_init(k_head, [feat_dim, self.act_dim]),
            }
        self.epsilon = 1.0
        # APEX-style per-worker exploration: a fixed epsilon survives
        # weight broadcasts (set by RolloutWorker when configured).
        self.fixed_epsilon = False
        self._q_jit = jax.jit(self.q_values)

    # -- functional core -------------------------------------------------

    def q_values(self, params, obs):
        feats = self._encode(params["encoder"], obs)
        if "value_head" in params:
            value = mlp_apply(params["value_head"], feats)
            adv = mlp_apply(params["adv_head"], feats)
            return value + adv - adv.mean(-1, keepdims=True)
        return mlp_apply(params["head"], feats)

    # -- worker-side API -------------------------------------------------

    def compute_actions(self, obs: np.ndarray, key) -> Tuple[np.ndarray,
                                                             np.ndarray,
                                                             np.ndarray]:
        q = self._q_jit(self.params, jnp.asarray(obs))
        greedy = np.asarray(q.argmax(-1))
        k1, k2 = jax.random.split(key)
        explore = np.asarray(
            jax.random.uniform(k1, (obs.shape[0],))) < self.epsilon
        random_a = np.asarray(jax.random.randint(
            k2, (obs.shape[0],), 0, self.act_dim))
        actions = np.where(explore, random_a, greedy)
        zeros = np.zeros((obs.shape[0],), np.float32)
        return actions, zeros, zeros

    def compute_values(self, obs: np.ndarray) -> np.ndarray:
        return np.asarray(
            self._q_jit(self.params, jnp.asarray(obs)).max(-1))

    def get_weights(self):
        return {"params": jax.tree.map(np.asarray, self.params),
                "epsilon": self.epsilon}

    def set_weights(self, weights) -> None:
        if isinstance(weights, dict) and "params" in weights:
            self.params = jax.tree.map(jnp.asarray, weights["params"])
            if not self.fixed_epsilon:
                self.epsilon = float(weights.get("epsilon", self.epsilon))
        else:
            self.params = jax.tree.map(jnp.asarray, weights)
