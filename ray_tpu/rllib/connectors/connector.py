"""Connectors: composable observation/action transform pipelines.

Analog of the reference's rllib/connectors/: small stateful transforms
applied between env and policy (obs side) and between policy and env
(action side), serialized with the policy so inference-time preprocessing
matches training-time exactly.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np


class Connector:
    def __call__(self, x):
        raise NotImplementedError

    def apply_readonly(self, x):
        """Transform without mutating connector state (stateless connectors
        are their own read-only form). Used for NEXT_OBS, which must see the
        same normalization as OBS but must not double-count frames."""
        return self(x)

    def get_state(self) -> Dict[str, Any]:
        return {}

    def set_state(self, state: Dict[str, Any]) -> None:
        pass


class FlattenObs(Connector):
    """Flatten any observation to a rank-1 float32 vector."""

    def __call__(self, obs):
        return np.asarray(obs, np.float32).reshape(-1)


class MeanStdFilter(Connector):
    """Running mean/std observation normalization (the reference's
    MeanStdFilter, rllib/utils/filter.py): Welford accumulation, applied
    as (x - mean) / std."""

    def __init__(self, clip: float = 10.0):
        self.clip = clip
        self._n = 0
        self._mean = None
        self._m2 = None

    def __call__(self, obs):
        x = np.asarray(obs, np.float64).reshape(-1)
        if self._mean is None:
            self._mean = np.zeros_like(x)
            self._m2 = np.zeros_like(x)
        self._n += 1
        delta = x - self._mean
        self._mean += delta / self._n
        self._m2 += delta * (x - self._mean)
        return self._normalize(x)

    def apply_readonly(self, obs):
        x = np.asarray(obs, np.float64).reshape(-1)
        if self._mean is None:
            return x.astype(np.float32)
        return self._normalize(x)

    def _normalize(self, x):
        std = np.sqrt(self._m2 / max(self._n - 1, 1)) + 1e-8
        out = (x - self._mean) / std
        return np.clip(out, -self.clip, self.clip).astype(np.float32)

    def get_state(self):
        return {"n": self._n,
                "mean": None if self._mean is None else self._mean.copy(),
                "m2": None if self._m2 is None else self._m2.copy()}

    def set_state(self, state):
        self._n = state["n"]
        self._mean = state["mean"]
        self._m2 = state["m2"]


class ClipActions(Connector):
    """Clip continuous actions into the Box bounds before env.step."""

    def __init__(self, low, high):
        self.low = np.asarray(low, np.float32)
        self.high = np.asarray(high, np.float32)

    def __call__(self, action):
        return np.clip(action, self.low, self.high)


class ObsConnectorPipeline(Connector):
    def __init__(self, connectors: List[Connector]):
        self.connectors = list(connectors)

    def __call__(self, obs):
        for c in self.connectors:
            obs = c(obs)
        return obs

    def apply_readonly(self, obs):
        for c in self.connectors:
            obs = c.apply_readonly(obs)
        return obs

    def get_state(self):
        return {i: c.get_state() for i, c in enumerate(self.connectors)}

    def set_state(self, state):
        for i, c in enumerate(self.connectors):
            if i in state:
                c.set_state(state[i])


class ActionConnectorPipeline(ObsConnectorPipeline):
    pass


def get_connectors(policy_config: Dict[str, Any], obs_space, action_space
                   ) -> (ObsConnectorPipeline, ActionConnectorPipeline):
    """Build pipelines from the ``observation_filter`` / ``clip_actions``
    entries of a policy config."""
    import gymnasium as gym
    from ray_tpu.rllib.models.catalog import ModelCatalog
    obs_connectors: List[Connector] = []
    if not ModelCatalog.is_image_space(obs_space):
        obs_connectors.append(FlattenObs())
        if policy_config.get("observation_filter") == "MeanStdFilter":
            obs_connectors.append(MeanStdFilter())
    action_connectors: List[Connector] = []
    if policy_config.get("clip_actions", True) and isinstance(
            action_space, gym.spaces.Box):
        action_connectors.append(
            ClipActions(action_space.low, action_space.high))
    return (ObsConnectorPipeline(obs_connectors),
            ActionConnectorPipeline(action_connectors))
