from ray_tpu.rllib.connectors.connector import (ActionConnectorPipeline,
                                                ClipActions, Connector,
                                                FlattenObs, MeanStdFilter,
                                                ObsConnectorPipeline,
                                                get_connectors)

__all__ = ["ActionConnectorPipeline", "ClipActions", "Connector",
           "FlattenObs", "MeanStdFilter", "ObsConnectorPipeline",
           "get_connectors"]
