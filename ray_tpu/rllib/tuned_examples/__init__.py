"""Tuned examples: the regression-benchmark registry.

Analog of the reference's rllib/tuned_examples/ YAMLs (e.g.
ppo/atari-ppo.yaml, ppo/cartpole-ppo.yaml): each entry is a tuned config
plus a stopping criterion (reward threshold within a training budget)
that CI asserts — algorithms are regression-tested on LEARNING CURVES,
not just finiteness. ``run_tuned_example`` is the harness the tests and
``bench.py`` share.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional


@dataclass
class TunedExample:
    name: str
    build_config: Callable[[], Any]  # () -> AlgorithmConfig, built lazily
    stop_reward: float               # CI tier: episode_reward_mean >= this
    max_iters: int                   # within this many algo.train() calls
    notes: str = ""
    extra: Dict[str, Any] = field(default_factory=dict)
    #: Nightly tier — the REFERENCE-grade stop reward (e.g. cartpole
    #: family gates at 150, matching tuned_examples/ppo/cartpole-ppo.yaml)
    #: with a budget sized for it. CI keeps the fast bar; the nightly
    #: bar is exercised by tests/test_rllib_tuned.py's RAY_TPU_NIGHTLY
    #: tier and documents measured headroom above the CI gate.
    nightly_stop_reward: Optional[float] = None
    nightly_max_iters: Optional[int] = None


def _cartpole_ppo():
    from ray_tpu.rllib import PPOConfig
    return (PPOConfig()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=2)
            .training(lr=1e-3, train_batch_size=1024, num_sgd_iter=10,
                      sgd_minibatch_size=256)
            .debugging(seed=7))


def _cartpole_a2c():
    from ray_tpu.rllib import A2CConfig
    return (A2CConfig()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=2, rollout_fragment_length=64)
            .training(lr=2e-3, train_batch_size=512)
            .debugging(seed=11))


def _cartpole_dqn():
    from ray_tpu.rllib import DQNConfig
    return (DQNConfig()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=1, rollout_fragment_length=256)
            .training(lr=8e-4, train_batch_size=64,
                      num_steps_sampled_before_learning_starts=500,
                      num_train_batches_per_iteration=32,
                      target_network_update_freq=128,
                      epsilon_timesteps=3000, dueling=True,
                      double_q=True)
            .debugging(seed=5))


def _cartpole_rainbow():
    """Five of the six Rainbow components (C51 + double + dueling + PER
    + 3-step). Noisy nets are implemented (policy/rainbow_policy.py) but
    off here: noise-driven exploration is reliably outperformed by the
    epsilon schedule at CartPole scale — q-value gaps outgrow the noise
    within a few hundred steps."""
    from ray_tpu.rllib import RainbowConfig
    cfg = (RainbowConfig()
           .environment("CartPole-v1")
           .rollouts(num_rollout_workers=1, rollout_fragment_length=256)
           .training(lr=8e-4, train_batch_size=64, v_min=0.0, v_max=120.0,
                     noisy=False, prioritized_replay=True, n_step=3,
                     epsilon_timesteps=3000,
                     num_steps_sampled_before_learning_starts=500,
                     num_train_batches_per_iteration=64,
                     target_network_update_freq=64)
           .debugging(seed=3))
    cfg.epsilon_initial = 1.0
    cfg.epsilon_final = 0.02
    return cfg


def _cartpole_r2d2():
    """Recurrent replay DQN: LSTM Q-net on sequence windows with stored
    hidden states + burn-in. CartPole learns through the recurrence
    (slower and noisier than feed-forward DQN — the tuned threshold
    reflects the method's variance at this scale)."""
    from ray_tpu.rllib import R2D2Config
    return (R2D2Config()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=1, rollout_fragment_length=256)
            .training(lr=1e-3, train_batch_size=32, seq_len=10, burn_in=4,
                      epsilon_timesteps=4000,
                      num_steps_sampled_before_learning_starts=500,
                      num_train_batches_per_iteration=64,
                      target_network_update_freq=128)
            .debugging(seed=3))


def _coordination_qmix():
    """QMIX on the cooperative coordination game: both agents must match
    the shared context to score — team reward only, credit assigned
    through the monotonic mixer."""
    from ray_tpu.rllib import QMixConfig
    from ray_tpu.rllib.env.examples import CoordinationGameEnv
    return (QMixConfig()
            .environment(CoordinationGameEnv,
                         env_config={"rounds": 10, "n_contexts": 2})
            .training(lr=5e-4, train_batch_size=32,
                      rollout_steps_per_iteration=200,
                      epsilon_timesteps=3000,
                      num_train_batches_per_iteration=32)
            .debugging(seed=7))


def _pendulum_sac():
    from ray_tpu.rllib import SACConfig
    return (SACConfig()
            .environment("Pendulum-v1")
            .rollouts(num_rollout_workers=1, rollout_fragment_length=256)
            .training(lr=3e-4, train_batch_size=256,
                      num_steps_sampled_before_learning_starts=500,
                      # 1 gradient step per env step (the canonical SAC
                      # ratio) — at 32/iter the 100-episode reward window
                      # barely moves inside the budget.
                      num_train_batches_per_iteration=256, tau=0.005,
                      model={"fcnet_hiddens": [256, 256]})
            .debugging(seed=2))


def _recsim_slateq():
    """SlateQ on the RecSim-style interest-evolution env: the clickbait
    knob anti-correlates immediate appeal with quality, so beating the
    random baseline (~14.1/episode) requires the learned choice model +
    item-level LTV decomposition."""
    from ray_tpu.rllib import SlateQConfig
    from ray_tpu.rllib.env.recsim import RecSimEnv
    return (SlateQConfig()
            .environment(RecSimEnv, env_config={"seed": 0})
            .debugging(seed=0))


def _spread_maddpg():
    """MADDPG on cooperative navigation (simple-spread shape): shared
    team reward = -sum of landmark distances; random ~= -66/episode."""
    from ray_tpu.rllib import MADDPGConfig
    from ray_tpu.rllib.env.examples import CooperativeNavEnv
    return (MADDPGConfig()
            .environment(CooperativeNavEnv, env_config={"seed": 0})
            .debugging(seed=0))


def _cartpole_alphazero():
    """AlphaZero's own example task (reference alpha_zero README):
    MCTS over clonable CartPole. Random ~= 20; 30-simulation search
    with learned priors passes 60 within the budget."""
    from ray_tpu.rllib import AlphaZeroConfig
    from ray_tpu.rllib.env.examples import ClonableCartPole
    return (AlphaZeroConfig()
            .environment(ClonableCartPole)
            .debugging(seed=0))


def _cartpole_ddppo():
    """Decentralized PPO: 2 workers gradient-allreducing per minibatch;
    the learning curve must track plain PPO's."""
    from ray_tpu.rllib import DDPPOConfig
    return (DDPPOConfig()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=2)
            .training(lr=5e-4, num_sgd_iter=4, sgd_minibatch_size=128)
            .debugging(seed=0))


def _pointgoal_dreamer():
    """Dreamer on the 1D reach-the-origin task: the world model fits in
    a few hundred steps, so latent imagination visibly improves the
    policy inside a CI budget (Pendulum-class tasks need 10^5+ frames —
    the reference tunes Dreamer on DMC over millions)."""
    from ray_tpu.rllib import DreamerConfig
    from ray_tpu.rllib.env.examples import PointGoalEnv
    return (DreamerConfig()
            .environment(PointGoalEnv)
            .training(prefill_steps=300, rollout_steps_per_iteration=150,
                      num_train_batches_per_iteration=20, seq_len=10,
                      imagine_horizon=8, action_repeat=1)
            .debugging(seed=0))


def _atari_ppo():
    """The north-star shape (reference: tuned_examples/ppo/atari-ppo.yaml)
    on the synthetic Catch game: pixels in, CNN policy, deepmind wrapper
    stack. dim=42/framestack=2 keep the CPU regression affordable; the
    bench runs the full 84x84x4."""
    from ray_tpu.rllib import PPOConfig
    from ray_tpu.rllib.env.atari import make_synthetic_atari
    return (PPOConfig()
            .environment(make_synthetic_atari,
                         env_config={"dim": 42, "framestack": 2,
                                     "drops": 2, "fall": 14})
            .rollouts(num_rollout_workers=2, rollout_fragment_length=128)
            .training(lr=8e-4, train_batch_size=1024, num_sgd_iter=6,
                      sgd_minibatch_size=256, entropy_coeff=0.01,
                      model={"conv_filters": [[16, 8, 4], [32, 4, 2],
                                              [32, 3, 2]],
                             "post_fcnet_dim": 128})
            .debugging(seed=17))


TUNED_EXAMPLES: Dict[str, TunedExample] = {
    "cartpole-ppo": TunedExample(
        "cartpole-ppo", _cartpole_ppo, stop_reward=60.0, max_iters=20,
        nightly_stop_reward=150.0, nightly_max_iters=80,
        notes="reference: tuned_examples/ppo/cartpole-ppo.yaml"),
    "cartpole-a2c": TunedExample(
        "cartpole-a2c", _cartpole_a2c, stop_reward=50.0, max_iters=30,
        nightly_stop_reward=150.0, nightly_max_iters=150,
        notes="reference: tuned_examples/a2c/cartpole-a2c.yaml"),
    "cartpole-dqn": TunedExample(
        "cartpole-dqn", _cartpole_dqn, stop_reward=50.0, max_iters=40,
        nightly_stop_reward=150.0, nightly_max_iters=200,
        notes="reference: tuned_examples/dqn/cartpole-dqn.yaml"),
    "cartpole-rainbow": TunedExample(
        "cartpole-rainbow", _cartpole_rainbow, stop_reward=65.0,
        max_iters=30, nightly_stop_reward=150.0, nightly_max_iters=120,
        notes="reference: rllib/algorithms/dqn with num_atoms>1 (Rainbow "
              "flags); C51 cross-entropy vs projected target"),
    "cartpole-r2d2": TunedExample(
        "cartpole-r2d2", _cartpole_r2d2, stop_reward=35.0, max_iters=70,
        nightly_stop_reward=100.0, nightly_max_iters=250,
        notes="reference: rllib/algorithms/r2d2"),
    "coordination-qmix": TunedExample(
        "coordination-qmix", _coordination_qmix, stop_reward=8.0,
        max_iters=40,
        notes="reference: rllib/algorithms/qmix; optimal team return 10, "
              "uniform-random ~= 10/9 with 3 actions x 2 contexts"),
    "pendulum-sac": TunedExample(
        "pendulum-sac", _pendulum_sac, stop_reward=-500.0, max_iters=75,
        nightly_stop_reward=-250.0, nightly_max_iters=250,
        notes="reference: tuned_examples/sac/pendulum-sac.yaml; random "
              "policy ~= -1200, tuned SAC reaches > -500"),
    "recsim-slateq": TunedExample(
        "recsim-slateq", _recsim_slateq, stop_reward=17.0, max_iters=10,
        notes="reference: rllib/algorithms/slateq; random slates ~= 14.1,"
              " myopic-greedy is capped by the clickbait knob, tuned "
              "SlateQ reaches ~18 within 8 iterations"),
    "spread-maddpg": TunedExample(
        "spread-maddpg", _spread_maddpg, stop_reward=-45.0, max_iters=14,
        notes="reference: rllib/algorithms/maddpg; random joint policy "
              "~= -66/episode, tuned MADDPG passes -45 by iteration ~8"),
    "cartpole-alphazero": TunedExample(
        "cartpole-alphazero", _cartpole_alphazero, stop_reward=60.0,
        nightly_stop_reward=100.0, nightly_max_iters=80,
        max_iters=35,
        notes="reference: rllib/algorithms/alpha_zero (one-player MCTS "
              "+ ranked rewards on sparse terminal scores); random "
              "~= 20, the 100-episode reward window passes 60 around "
              "iteration 25"),
    "cartpole-ddppo": TunedExample(
        "cartpole-ddppo", _cartpole_ddppo, stop_reward=60.0,
        nightly_stop_reward=150.0, nightly_max_iters=100,
        max_iters=30,
        notes="reference: rllib/algorithms/ddppo; no central learner - "
              "workers allreduce gradients per minibatch"),
    "pointgoal-dreamer": TunedExample(
        "pointgoal-dreamer", _pointgoal_dreamer, stop_reward=-45.0,
        max_iters=22,
        notes="reference: rllib/algorithms/dreamer (RSSM + latent "
              "imagination); random ~= -60/episode, passes -45 within "
              "~16 iterations"),
    "atari-ppo": TunedExample(
        "atari-ppo", _atari_ppo, stop_reward=0.0, max_iters=30,
        notes="reference: tuned_examples/ppo/atari-ppo.yaml; synthetic "
              "Catch: random ~= -1.6/drop-pair, threshold 0 requires "
              "pixel-driven paddle control"),
}


def run_tuned_example(name: str, *, max_iters: Optional[int] = None,
                      tier: str = "ci") -> Dict[str, Any]:
    """Train until the tuned stop_reward or the iteration budget; returns
    {passed, iterations, first_reward, best_reward, last_reward,
    env_steps_per_sec}. tier="nightly" gates at the REFERENCE-grade
    nightly_stop_reward (with its larger budget) when the example
    declares one."""
    import time

    ex = TUNED_EXAMPLES[name]
    stop_reward = ex.stop_reward
    budget = max_iters if max_iters is not None else ex.max_iters
    if tier == "nightly" and ex.nightly_stop_reward is not None:
        stop_reward = ex.nightly_stop_reward
        if max_iters is None:
            budget = ex.nightly_max_iters or ex.max_iters * 4
    algo = ex.build_config().build()
    first = best = last = float("-inf")
    iters = 0
    steps0 = 0
    t0 = time.perf_counter()
    try:
        for i in range(budget):
            res = algo.train()
            iters = i + 1
            last = res.get("episode_reward_mean", float("nan"))
            if iters == 1:
                first = last
            if last == last and last > best:  # skip NaN (no episodes yet)
                best = last
            steps0 = res.get("timesteps_total", steps0)
            if best >= stop_reward:
                break
        dt = time.perf_counter() - t0
    finally:
        algo.stop()
    return {
        "name": name,
        "passed": best >= stop_reward,
        "tier": tier,
        "stop_reward": stop_reward,
        "iterations": iters,
        "first_reward": first,
        "best_reward": best,
        "last_reward": last,
        "env_steps_per_sec": round(steps0 / dt, 1) if dt > 0 else 0.0,
    }
