"""ModelCatalog: observation encoders from model config.

Analog of the reference's rllib/models/catalog.py: maps (observation
space, model config) to a network. Two encoder families, both pure
pytree-params + functional apply so policies jit them:

* MLP (``fcnet_hiddens``) for flat observations;
* CNN (``conv_filters`` [[out_channels, kernel, stride], ...]) for image
  observations (rank-3 HWC), lowered to ``lax.conv_general_dilated`` —
  XLA tiles these onto the MXU on TPU.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

DEFAULT_CONV_FILTERS = [[16, 4, 2], [32, 4, 2], [64, 3, 1]]


def mlp_init(key, sizes: Sequence[int]):
    params = []
    for i in range(len(sizes) - 1):
        key, k1 = jax.random.split(key)
        w = jax.random.normal(k1, (sizes[i], sizes[i + 1])) * jnp.sqrt(
            2.0 / sizes[i])
        params.append({"w": w, "b": jnp.zeros((sizes[i + 1],))})
    return params


def mlp_apply(params, x, activate_last=False):
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1 or activate_last:
            x = jnp.tanh(x)
    return x


class ModelCatalog:
    """Builds (init_fn, apply_fn, feature_dim) encoders."""

    @staticmethod
    def is_image_space(obs_space) -> bool:
        shape = getattr(obs_space, "shape", None)
        return shape is not None and len(shape) == 3

    @staticmethod
    def get_encoder(obs_space, model_config: Dict[str, Any]
                    ) -> Tuple[Callable, Callable, int]:
        """Returns (init(key) -> params, apply(params, obs) -> [B, F],
        feature_dim). ``obs`` enters flattened for MLP, HWC for CNN."""
        if ModelCatalog.is_image_space(obs_space):
            return ModelCatalog._cnn_encoder(obs_space, model_config)
        obs_dim = int(np.prod(obs_space.shape))
        hiddens = tuple(model_config.get("fcnet_hiddens", (64, 64)))
        sizes = [obs_dim, *hiddens]

        def init(key):
            return {"mlp": mlp_init(key, sizes)}

        def apply(params, obs):
            obs = obs.reshape((obs.shape[0], -1))
            return mlp_apply(params["mlp"], obs, activate_last=True)

        return init, apply, hiddens[-1] if hiddens else obs_dim

    @staticmethod
    def _cnn_encoder(obs_space, model_config):
        h, w, c = obs_space.shape
        filters = model_config.get("conv_filters") or DEFAULT_CONV_FILTERS
        head_dim = int(model_config.get("post_fcnet_dim", 256))

        # Compute output spatial dims (SAME padding, strided).
        shapes = []
        ch, hh, ww = c, h, w
        for out_ch, k, s in filters:
            hh = -(-hh // s)
            ww = -(-ww // s)
            shapes.append((ch, out_ch, k))
            ch = out_ch
        flat_dim = hh * ww * ch

        def init(key):
            convs = []
            for in_ch, out_ch, k in shapes:
                key, k1 = jax.random.split(key)
                fan_in = in_ch * k * k
                convs.append({
                    "w": jax.random.normal(
                        k1, (k, k, in_ch, out_ch)) * jnp.sqrt(2.0 / fan_in),
                    "b": jnp.zeros((out_ch,)),
                })
            key, k2 = jax.random.split(key)
            head = mlp_init(k2, [flat_dim, head_dim])
            return {"convs": convs, "head": head}

        strides = [s for _, _, s in filters]

        # Pixel observations (uint8 spaces, e.g. wrapped Atari) are kept
        # uint8 end-to-end on the host (4x smaller sample batches) and
        # scaled to [0, 1] here, inside the jitted apply — the reference
        # does the same normalization in its vision models.
        scale = (np.float32(1.0 / 255.0)
                 if getattr(obs_space, "dtype", None) == np.uint8
                 else np.float32(1.0))

        def apply(params, obs):
            x = obs.reshape((-1, h, w, c)).astype(jnp.float32) * scale
            for conv, s in zip(params["convs"], strides):
                x = jax.lax.conv_general_dilated(
                    x, conv["w"], window_strides=(s, s), padding="SAME",
                    dimension_numbers=("NHWC", "HWIO", "NHWC"))
                x = jax.nn.relu(x + conv["b"])
            x = x.reshape((x.shape[0], -1))
            return mlp_apply(params["head"], x, activate_last=True)

        return init, apply, head_dim
