"""DDPG: deep deterministic policy gradient.

Analog of the reference's rllib/algorithms/ddpg. The reference builds TD3
*on top of* its DDPG stack; here the layering is inverted — the TD3 engine
(ray_tpu/rllib/algorithms/td3.py) already contains the DDPG update as its
degenerate case, so DDPG = TD3 with every-step actor updates, no
target-policy smoothing noise, and the classic DDPG default
hyperparameters. The twin-critic min reduces to a (slightly conservative)
single-critic target; exploration remains clipped Gaussian noise on the
deterministic actor (TD3Policy).
"""

from __future__ import annotations

from ray_tpu.rllib.algorithms.td3 import TD3, TD3Config


class DDPGConfig(TD3Config):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or DDPG)
        self.policy_delay = 1       # actor + targets update every step
        self.target_noise = 0.0     # no target-policy smoothing
        self.target_noise_clip = 0.0
        self.tau = 0.002


class DDPG(TD3):
    _default_config_class = DDPGConfig
