"""APEX-DDPG: distributed prioritized replay for continuous control.

Analog of the reference's rllib/algorithms/apex_ddpg (Horgan et al. 2018
applied to DDPG): many exploration actors feeding a central prioritized
buffer, a single continuous-control
learner. As with apex_dqn.py, the reference's dedicated replay-shard
actors collapse here because the learner owns its buffer — APEX-DDPG is
the DDPG engine under the APEX distributed configuration: a worker
fleet, prioritized replay, n-step returns, and slower target sync.
"""

from __future__ import annotations

from ray_tpu.rllib.algorithms.ddpg import DDPG, DDPGConfig


class ApexDDPGConfig(DDPGConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or ApexDDPG)
        self.num_rollout_workers = 4
        self.prioritized_replay = True
        self.n_step = 3
        self.replay_buffer_capacity = 200_000
        self.num_steps_sampled_before_learning_starts = 2000
        self.tau = 0.001  # APEX syncs targets more slowly


class ApexDDPG(DDPG):
    _default_config_class = ApexDDPGConfig
