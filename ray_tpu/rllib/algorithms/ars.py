"""ARS: augmented random search.

Analog of the reference's rllib/algorithms/ars (Mania et al. 2018): the
same antithetic random-perturbation machinery as ES (shared noise table,
evaluator actors), but the update keeps only the ``deltas_used`` best
directions (ranked by max(r+, r-)), weights them by the raw return
difference, and scales the step by the standard deviation of the used
returns instead of rank shaping — the "V1-t" variant of the paper, on the
catalog MLP policy.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithms.es import ES, ESConfig


class ARSConfig(ESConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or ARS)
        self.noise_stdev = 0.05
        self.stepsize = 0.03
        self.deltas_used = 8  # top directions kept per update

    def training(self, *, deltas_used=None, **kwargs) -> "ARSConfig":
        super().training(**kwargs)
        if deltas_used is not None:
            self.deltas_used = deltas_used
        return self


class ARS(ES):
    _default_config_class = ARSConfig

    def _gradient(self, indices, returns_pos, returns_neg) -> np.ndarray:
        config: ARSConfig = self.config
        dim = self._theta.size
        # Keep the top-k directions by best-of-pair return.
        scores = np.maximum(returns_pos, returns_neg)
        k = min(config.deltas_used, len(indices))
        top = np.argsort(scores)[::-1][:k]
        used = np.concatenate([returns_pos[top], returns_neg[top]])
        sigma_r = max(float(used.std()), 1e-6)
        g = np.zeros(dim, np.float32)
        for i in top:
            g += (returns_pos[i] - returns_neg[i]) * \
                self._noise[indices[i]:indices[i] + dim]
        return g / (k * sigma_r)
