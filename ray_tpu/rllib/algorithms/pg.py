"""PG: vanilla policy gradient (REINFORCE).

Analog of the reference's rllib/algorithms/pg: the plain on-policy
policy-gradient loss -logp(a|s) * R_t with discounted reward-to-go returns
(no critic baseline, no surrogate clipping). The rollout workers still
attach GAE fields, but PG trains on the Monte-Carlo value targets
(advantages computed with an untrained critic reduce to TD-λ returns; we
recompute pure reward-to-go here for fidelity to the reference's
post_process_advantages with use_critic=False).
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.policy.sample_batch import SampleBatch


def discounted_returns(batch: SampleBatch, gamma: float,
                       bootstrap_values=None) -> np.ndarray:
    """Per-episode discounted reward-to-go (the PG return target).

    Resets at every episode boundary: termination, truncation (TimeLimit),
    and eps_id seams — a concatenated multi-worker batch places unrelated
    episodes back to back, and rewards must never bleed across them.

    ``bootstrap_values`` (optional, aligned with ``new_obs``): at a
    *non-terminal* boundary (truncation, eps_id seam, or an unterminated
    batch tail) the return continues with gamma * V(new_obs[t]) instead of
    0 — callers with a value head (MARWIL) pass V(new_obs); pure
    Monte-Carlo PG leaves it None.
    """
    n = len(batch)
    out = np.zeros(n, np.float64)
    rewards = batch[SampleBatch.REWARDS].astype(np.float64)
    terminated = np.asarray(batch[SampleBatch.TERMINATEDS])
    truncated = batch.get(SampleBatch.TRUNCATEDS)
    eps_id = batch.get(SampleBatch.EPS_ID)

    def bootstrap(t):
        return (0.0 if bootstrap_values is None
                else float(bootstrap_values[t]))

    acc = bootstrap(n - 1) if n and not terminated[n - 1] else 0.0
    for t in reversed(range(n)):
        if terminated[t]:
            acc = 0.0
        elif ((truncated is not None and truncated[t])
              or (eps_id is not None and t + 1 < n
                  and eps_id[t] != eps_id[t + 1])):
            acc = bootstrap(t)
        acc = rewards[t] + gamma * acc
        out[t] = acc
    return out.astype(np.float32)


class PGConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or PG)
        self.lr = 4e-3


class PG(Algorithm):
    _default_config_class = PGConfig

    def setup(self, config: PGConfig) -> None:
        import jax
        import optax

        policy = self.local_policy
        self._optimizer = optax.adam(config.lr)
        self._opt_state = self._optimizer.init(policy.params)

        def loss_fn(params, mb):
            logp = policy.logp(params, mb["obs"], mb["actions"])
            return -(logp * mb["returns"]).mean()

        def update(params, opt_state, mb):
            loss, grads = jax.value_and_grad(loss_fn)(params, mb)
            updates, opt_state = self._optimizer.update(grads, opt_state,
                                                        params)
            return optax.apply_updates(params, updates), opt_state, loss

        self._update_jit = jax.jit(update)

    def training_step(self) -> Dict[str, Any]:
        import jax.numpy as jnp

        import ray_tpu
        config: PGConfig = self.config
        weights_ref = ray_tpu.put(self.get_weights())
        self.workers.sync_weights(weights_ref)
        per_worker = max(
            config.train_batch_size // self.workers.num_workers(), 1)
        batch = self.workers.sample(per_worker)
        self._timesteps_total += len(batch)
        returns = discounted_returns(batch, config.gamma)
        # Standardize returns — the classic variance-reduction trick.
        returns = (returns - returns.mean()) / max(returns.std(), 1e-8)
        device_mb = {
            "obs": jnp.asarray(batch[SampleBatch.OBS]),
            "actions": jnp.asarray(batch[SampleBatch.ACTIONS]),
            "returns": jnp.asarray(returns.astype(np.float32)),
        }
        params, self._opt_state, loss = self._update_jit(
            self.local_policy.params, self._opt_state, device_mb)
        self.local_policy.params = params
        return {"policy_loss": float(loss)}
