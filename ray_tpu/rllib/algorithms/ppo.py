"""PPO: clipped-surrogate policy optimization with a jitted JAX learner.

Analog of the reference's rllib/algorithms/ppo (torch loss in
ppo_torch_policy.py): sample via WorkerSet, normalize advantages, run
several epochs of minibatch SGD on the jit-compiled clipped surrogate +
value + entropy loss. On TPU the update jits onto the chip; scaling to a
learner mesh is `pjit` over the batch axis (the reference's multi-GPU
learner thread equivalent, SURVEY.md §2.5).
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.policy.sample_batch import SampleBatch


class PPOConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or PPO)
        self.clip_param = 0.2
        self.num_sgd_iter = 8
        self.sgd_minibatch_size = 128
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.0
        self.kl_target = 0.02
        self.lambda_ = 0.95
        self.lr = 3e-4
        #: jax backend for the learner's fused SGD program (e.g. "tpu"
        #: / "axon") while rollouts stay on the process default (cpu) —
        #: the reference's CPU-rollout/GPU-learner split, expressed as
        #: two jax backends in one process. None = process default.
        self.learner_backend = None
        #: >0: a LEARNER GROUP of this many gradient-shard actors
        #: (reference: rl_trainer/trainer_runner.py TrainerRunner +
        #: multi_gpu_learner_thread) — each minibatch splits across
        #: them, gradients average row-weighted, every shard applies
        #: the same averaged update (synchronous DP; optimizer states
        #: stay bit-identical across shards).
        self.num_learners = 0

    def training(self, *, clip_param=None, num_sgd_iter=None,
                 sgd_minibatch_size=None, vf_loss_coeff=None,
                 entropy_coeff=None, learner_backend=None,
                 num_learners=None,
                 **kwargs) -> "PPOConfig":
        super().training(**kwargs)
        if learner_backend is not None:
            self.learner_backend = learner_backend
        if num_learners is not None:
            self.num_learners = num_learners
        if clip_param is not None:
            self.clip_param = clip_param
        if num_sgd_iter is not None:
            self.num_sgd_iter = num_sgd_iter
        if sgd_minibatch_size is not None:
            self.sgd_minibatch_size = sgd_minibatch_size
        if vf_loss_coeff is not None:
            self.vf_loss_coeff = vf_loss_coeff
        if entropy_coeff is not None:
            self.entropy_coeff = entropy_coeff
        return self


def make_ppo_loss(policy, clip: float, vf_coeff: float,
                  ent_coeff: float):
    """The clipped-surrogate PPO loss bound to ``policy`` — shared by
    the central learner here and DDPPO's decentralized worker learners
    (ddppo.py), so the two can never silently diverge. Returns
    ``loss_fn(params, mb) -> (total, metrics)``."""
    import jax
    import jax.numpy as jnp

    def loss_fn(params, mb):
        logp = policy.logp(params, mb["obs"], mb["actions"])
        ratio = jnp.exp(logp - mb["old_logp"])
        adv = mb["advantages"]
        surrogate = jnp.minimum(
            ratio * adv,
            jnp.clip(ratio, 1 - clip, 1 + clip) * adv)
        values = policy._value(params, mb["obs"])
        vf_loss = jnp.mean((values - mb["value_targets"]) ** 2)
        entropy = jnp.mean(policy.entropy(params, mb["obs"]))
        total = (-jnp.mean(surrogate) + vf_coeff * vf_loss
                 - ent_coeff * entropy)
        approx_kl = jnp.mean(mb["old_logp"] - logp)
        return total, {"policy_loss": -jnp.mean(surrogate),
                       "vf_loss": vf_loss, "entropy": entropy,
                       "approx_kl": approx_kl}

    return loss_fn


class _PPOGradShard:
    """One learner-group shard (reference: trainer_runner's RLTrainer
    actor): holds a replica of the policy params + optimizer state,
    computes gradients on its minibatch slice, applies the group's
    averaged gradients. All shards apply IDENTICAL averaged updates, so
    params and optimizer states stay synchronized without a broadcast
    per step."""

    def __init__(self, policy, clip, vf_coeff, ent_coeff, lr):
        import jax
        import optax
        self.policy = policy
        loss_fn = make_ppo_loss(policy, clip, vf_coeff, ent_coeff)
        self._optimizer = optax.adam(lr)
        self.opt_state = self._optimizer.init(policy.params)

        def grads(params, mb):
            (loss, metrics), g = jax.value_and_grad(
                loss_fn, has_aux=True)(params, mb)
            metrics["total_loss"] = loss
            return g, metrics

        def apply(params, opt_state, g):
            updates, opt_state = self._optimizer.update(g, opt_state,
                                                        params)
            import optax as _optax
            return _optax.apply_updates(params, updates), opt_state

        self._grads_jit = jax.jit(grads)
        self._apply_jit = jax.jit(apply)

    def compute_gradients(self, mb):
        import jax
        import jax.numpy as jnp
        device_mb = {k: jnp.asarray(v) for k, v in mb.items()}
        g, metrics = self._grads_jit(self.policy.params, device_mb)
        import numpy as _np
        return (jax.tree.map(_np.asarray, g),
                {k: float(v) for k, v in metrics.items()})

    def apply_gradients(self, g):
        self.policy.params, self.opt_state = self._apply_jit(
            self.policy.params, self.opt_state, g)
        return True

    def get_params(self):
        import jax
        import numpy as _np
        return jax.tree.map(_np.asarray, self.policy.params)


class PPO(Algorithm):
    _default_config_class = PPOConfig
    _supports_multi_agent = True

    def _build_update(self, policy, config: PPOConfig):
        """One jitted clipped-surrogate update bound to ``policy``
        (multi-agent builds one per policy in the map)."""
        import jax
        import jax.numpy as jnp
        import optax

        optimizer = optax.adam(config.lr)
        opt_state = optimizer.init(policy.params)
        loss_fn = make_ppo_loss(policy, config.clip_param,
                                config.vf_loss_coeff,
                                config.entropy_coeff)

        def update(params, opt_state, mb):
            (loss, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, mb)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            metrics["total_loss"] = loss
            return params, opt_state, metrics

        backend = getattr(config, "learner_backend", None)
        if not backend:
            # Process-default (CPU) learner: per-minibatch dispatch.
            # XLA:CPU serializes intra-op threading inside while/scan
            # bodies, so the fused program below is a ~8x PESSIMIZATION
            # there — fusion pays only on an accelerator backend.
            return jax.jit(update), opt_state

        def run_epochs(params, opt_state, batch, perm):
            """The WHOLE minibatch-SGD schedule as one program: scan
            over [epochs*minibatches] permutation rows. One dispatch
            and one host->device batch transfer per iteration instead
            of one per minibatch — rollouts stay on host CPUs while
            this jits onto the chip (the reference's CPU-rollout/
            GPU-learner split as two jax backends in one process)."""
            def one(carry, idx):
                params, opt_state = carry
                mb = jax.tree.map(lambda a: a[idx], batch)
                params, opt_state, metrics = update(params, opt_state,
                                                    mb)
                return (params, opt_state), metrics

            (params, opt_state), metrics = jax.lax.scan(
                one, (params, opt_state), perm)
            last = jax.tree.map(lambda m: m[-1], metrics)
            # Params ALSO return as one flat vector: the host pulls one
            # array instead of one round-trip per leaf (the tunnel
            # charges per-transfer latency, not just bandwidth).
            flat = jnp.concatenate(
                [jnp.ravel(x) for x in jax.tree.leaves(params)])
            return flat, opt_state, last

        return jax.jit(run_epochs, backend=backend), opt_state

    def setup(self, config: PPOConfig) -> None:
        self._learner_shards = None
        if self.is_multi_agent:
            self._updates = {}
            self._opt_states = {}
            for pid, policy in self.local_policies.items():
                self._updates[pid], self._opt_states[pid] = \
                    self._build_update(policy, config)
            return
        n = int(getattr(config, "num_learners", 0) or 0)
        if n > 0:
            # Group mode: the shards own the optimizer states; building
            # the solo update too would allocate a dead moment tree and
            # leave self._opt_state silently diverging from the truth.
            self._update_jit = self._opt_state = None
            import ray_tpu
            shard_cls = ray_tpu.remote(_PPOGradShard)
            self._learner_shards = [
                shard_cls.remote(self.local_policy, config.clip_param,
                                 config.vf_loss_coeff,
                                 config.entropy_coeff, config.lr)
                for _ in range(n)]
            return
        self._update_jit, self._opt_state = self._build_update(
            self.local_policy, config)

    def _sgd(self, policy, update_jit, opt_state, batch: SampleBatch,
             config: PPOConfig) -> tuple:
        """Minibatch-SGD a policy on its (GAE-complete) batch; returns
        (opt_state, metrics). With learner_backend set, runs the fused
        run_epochs program on that device; otherwise per-minibatch
        dispatch on the process default."""
        import jax
        import jax.numpy as jnp
        adv = batch[SampleBatch.ADVANTAGES]
        adv = (adv - adv.mean()) / max(adv.std(), 1e-6)
        backend = getattr(config, "learner_backend", None)
        if not backend:
            sb = SampleBatch({
                "obs": batch[SampleBatch.OBS].astype(np.float32),
                "actions": batch[SampleBatch.ACTIONS],
                "old_logp":
                    batch[SampleBatch.ACTION_LOGP].astype(np.float32),
                "advantages": adv.astype(np.float32),
                "value_targets":
                    batch[SampleBatch.VALUE_TARGETS].astype(np.float32),
            })
            params = policy.params
            last_metrics: Dict[str, Any] = {}
            mb_size = min(config.sgd_minibatch_size, len(sb))
            for epoch in range(config.num_sgd_iter):
                for mb in sb.minibatches(mb_size, seed=epoch):
                    device_mb = {k: jnp.asarray(v)
                                 for k, v in mb.items()}
                    params, opt_state, metrics = update_jit(
                        params, opt_state, device_mb)
                    last_metrics = metrics
            policy.params = params
            return opt_state, {k: float(v)
                               for k, v in last_metrics.items()}

        # Fused path: each epoch permutes rows and covers floor(n/mb)
        # minibatches (the remainder rotates between epochs through the
        # permutation, matching the reference's drop-to-multiple).
        n = len(batch)
        mb_size = min(config.sgd_minibatch_size, n)
        n_mb = max(n // mb_size, 1)
        rng = np.random.default_rng(self.iteration)
        perm = np.stack([
            rng.permutation(n)[:n_mb * mb_size].reshape(n_mb, mb_size)
            for _ in range(config.num_sgd_iter)]).reshape(
                -1, mb_size).astype(np.int32)
        learner_dev = jax.devices(backend)[0]

        def put(a):
            # device_put from a NUMPY array streams at full tunnel
            # bandwidth; a committed cpu-jax array first goes through a
            # ~40x slower device-to-device path.
            return jax.device_put(np.asarray(a), learner_dev)

        device_batch = {
            "obs": put(np.asarray(batch[SampleBatch.OBS], np.float32)),
            "actions": put(np.asarray(batch[SampleBatch.ACTIONS])),
            "old_logp": put(np.asarray(
                batch[SampleBatch.ACTION_LOGP], np.float32)),
            "advantages": put(adv.astype(np.float32)),
            "value_targets": put(np.asarray(
                batch[SampleBatch.VALUE_TARGETS], np.float32)),
        }
        import jax.tree_util as jtu
        params = policy.params
        leaves, treedef = jtu.tree_flatten(params)
        shapes = [np.shape(x) for x in leaves]
        params_dev = jax.device_put(params, learner_dev)
        opt_state = jax.device_put(opt_state, learner_dev)
        flat, opt_state, metrics = update_jit(
            params_dev, opt_state, device_batch, put(perm))
        # One pull, then split host-side: worker weight sync and the
        # driver's cpu-jitted evaluation path get HOST arrays without
        # per-leaf tunnel round-trips.
        flat_np = np.asarray(flat)
        out, off = [], 0
        for shp in shapes:
            size = int(np.prod(shp)) if shp else 1
            out.append(flat_np[off:off + size].reshape(shp))
            off += size
        policy.params = jtu.tree_unflatten(treedef, out)
        return opt_state, {k: float(v) for k, v in metrics.items()}

    def _sgd_group(self, batch: SampleBatch, config: PPOConfig) -> dict:
        """Minibatch SGD over the learner group (num_learners > 0):
        every minibatch splits row-wise across the shard actors, their
        gradients average row-weighted (exactly the full-minibatch
        gradient — the PPO loss is mean-based), and every shard applies
        the same averaged update. Reference:
        rllib/core/rl_trainer/trainer_runner.py +
        rllib/execution/multi_gpu_learner_thread.py."""
        import jax
        import jax.numpy as jnp

        import ray_tpu
        adv = batch[SampleBatch.ADVANTAGES]
        adv = (adv - adv.mean()) / max(adv.std(), 1e-6)
        sb = SampleBatch({
            "obs": batch[SampleBatch.OBS].astype(np.float32),
            "actions": batch[SampleBatch.ACTIONS],
            "old_logp":
                batch[SampleBatch.ACTION_LOGP].astype(np.float32),
            "advantages": adv.astype(np.float32),
            "value_targets":
                batch[SampleBatch.VALUE_TARGETS].astype(np.float32),
        })
        shards = self._learner_shards
        mb_size = min(config.sgd_minibatch_size, len(sb))
        last_metrics: Dict[str, Any] = {}
        for epoch in range(config.num_sgd_iter):
            for mb in sb.minibatches(mb_size, seed=epoch):
                size = len(next(iter(mb.values())))
                n = min(len(shards), size)
                bounds = np.array_split(np.arange(size), n)
                slices = [
                    {k: np.asarray(v)[idx[0]:idx[-1] + 1]
                     for k, v in mb.items()} for idx in bounds]
                results = ray_tpu.get([
                    s.compute_gradients.remote(sl)
                    for s, sl in zip(shards, slices)])
                w = np.asarray([len(idx) / size for idx in bounds],
                               np.float64)
                avg = jax.tree.map(
                    lambda *g: np.tensordot(
                        w, np.stack(g), axes=1).astype(
                            np.asarray(g[0]).dtype),
                    *[g for g, _m in results])
                ray_tpu.get([s.apply_gradients.remote(avg)
                             for s in shards])
                metrics_list = [m for _g, m in results]
                last_metrics = {
                    k: float(np.dot(w, [m[k] for m in metrics_list]))
                    for k in metrics_list[0]}
        # Shard params stay synchronized (identical updates); pull once
        # for the driver's rollout policy.
        self.local_policy.params = jax.tree.map(
            jnp.asarray, ray_tpu.get(shards[0].get_params.remote()))
        return last_metrics

    def training_step(self) -> Dict[str, Any]:
        import ray_tpu
        config: PPOConfig = self.config
        if self.external_input is None:
            weights_ref = ray_tpu.put(self.get_weights())
            self.workers.sync_weights(weights_ref)
            per_worker = max(
                config.train_batch_size // self.workers.num_workers(), 1)
        else:
            per_worker = config.train_batch_size
        batch = self._sample_batch(per_worker)
        self._timesteps_total += len(batch)

        if self.is_multi_agent:
            out: Dict[str, Any] = {}
            for pid, sub in batch.policy_batches.items():
                self._opt_states[pid], metrics = self._sgd(
                    self.local_policies[pid], self._updates[pid],
                    self._opt_states[pid], sub, config)
                for k, v in metrics.items():
                    out[f"{pid}/{k}"] = v
            out["agent_steps_this_iter"] = batch.agent_steps()
            return out
        if self._learner_shards is not None:
            return self._sgd_group(batch, config)
        self._opt_state, metrics = self._sgd(
            self.local_policy, self._update_jit, self._opt_state, batch,
            config)
        return metrics
