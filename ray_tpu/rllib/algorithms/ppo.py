"""PPO: clipped-surrogate policy optimization with a jitted JAX learner.

Analog of the reference's rllib/algorithms/ppo (torch loss in
ppo_torch_policy.py): sample via WorkerSet, normalize advantages, run
several epochs of minibatch SGD on the jit-compiled clipped surrogate +
value + entropy loss. On TPU the update jits onto the chip; scaling to a
learner mesh is `pjit` over the batch axis (the reference's multi-GPU
learner thread equivalent, SURVEY.md §2.5).
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.policy.sample_batch import SampleBatch


class PPOConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or PPO)
        self.clip_param = 0.2
        self.num_sgd_iter = 8
        self.sgd_minibatch_size = 128
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.0
        self.kl_target = 0.02
        self.lambda_ = 0.95
        self.lr = 3e-4

    def training(self, *, clip_param=None, num_sgd_iter=None,
                 sgd_minibatch_size=None, vf_loss_coeff=None,
                 entropy_coeff=None, **kwargs) -> "PPOConfig":
        super().training(**kwargs)
        if clip_param is not None:
            self.clip_param = clip_param
        if num_sgd_iter is not None:
            self.num_sgd_iter = num_sgd_iter
        if sgd_minibatch_size is not None:
            self.sgd_minibatch_size = sgd_minibatch_size
        if vf_loss_coeff is not None:
            self.vf_loss_coeff = vf_loss_coeff
        if entropy_coeff is not None:
            self.entropy_coeff = entropy_coeff
        return self


class PPO(Algorithm):
    _default_config_class = PPOConfig

    def setup(self, config: PPOConfig) -> None:
        import jax
        import jax.numpy as jnp
        import optax

        policy = self.local_policy
        self._optimizer = optax.adam(config.lr)
        self._opt_state = self._optimizer.init(policy.params)
        clip = config.clip_param
        vf_coeff = config.vf_loss_coeff
        ent_coeff = config.entropy_coeff

        def loss_fn(params, mb):
            logp = policy.logp(params, mb["obs"], mb["actions"])
            ratio = jnp.exp(logp - mb["old_logp"])
            adv = mb["advantages"]
            surrogate = jnp.minimum(
                ratio * adv,
                jnp.clip(ratio, 1 - clip, 1 + clip) * adv)
            values = policy._value(params, mb["obs"])
            vf_loss = jnp.mean((values - mb["value_targets"]) ** 2)
            entropy = jnp.mean(policy.entropy(params, mb["obs"]))
            total = (-jnp.mean(surrogate) + vf_coeff * vf_loss
                     - ent_coeff * entropy)
            approx_kl = jnp.mean(mb["old_logp"] - logp)
            return total, {"policy_loss": -jnp.mean(surrogate),
                           "vf_loss": vf_loss, "entropy": entropy,
                           "approx_kl": approx_kl}

        def update(params, opt_state, mb):
            (loss, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, mb)
            updates, opt_state = self._optimizer.update(grads, opt_state,
                                                        params)
            params = optax.apply_updates(params, updates)
            metrics["total_loss"] = loss
            return params, opt_state, metrics

        self._update_jit = jax.jit(update)

    def training_step(self) -> Dict[str, Any]:
        import jax.numpy as jnp
        config: PPOConfig = self.config
        weights_ref = __import__("ray_tpu").put(self.get_weights())
        self.workers.sync_weights(weights_ref)
        per_worker = max(
            config.train_batch_size // self.workers.num_workers(), 1)
        batch = self.workers.sample(per_worker)
        self._timesteps_total += len(batch)

        adv = batch[SampleBatch.ADVANTAGES]
        adv = (adv - adv.mean()) / max(adv.std(), 1e-6)
        train_arrays = {
            "obs": batch[SampleBatch.OBS].astype(np.float32),
            "actions": batch[SampleBatch.ACTIONS],
            "old_logp": batch[SampleBatch.ACTION_LOGP].astype(np.float32),
            "advantages": adv.astype(np.float32),
            "value_targets":
                batch[SampleBatch.VALUE_TARGETS].astype(np.float32),
        }
        sb = SampleBatch(train_arrays)
        params = self.local_policy.params
        opt_state = self._opt_state
        last_metrics: Dict[str, Any] = {}
        mb_size = min(config.sgd_minibatch_size, len(sb))
        for epoch in range(config.num_sgd_iter):
            for mb in sb.minibatches(mb_size, seed=epoch):
                device_mb = {k: jnp.asarray(v) for k, v in mb.items()}
                params, opt_state, metrics = self._update_jit(
                    params, opt_state, device_mb)
                last_metrics = metrics
        self.local_policy.params = params
        self._opt_state = opt_state
        return {k: float(v) for k, v in last_metrics.items()}
