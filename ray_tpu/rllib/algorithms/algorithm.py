"""Algorithm: the RLlib driver loop.

Analog of the reference's rllib/algorithms/algorithm.py:150 (step :744,
training_step :1322): owns a WorkerSet and a learner policy; each train()
call broadcasts weights, samples, runs the algorithm's update, and returns
a result dict. Tune-compatible: implements the Trainable protocol surface
(train/save/restore/stop) so Tuner can tune algorithms.
"""

from __future__ import annotations

import pickle
import time
from typing import Any, Dict, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.evaluation.worker_set import WorkerSet
from ray_tpu.rllib.policy.jax_policy import JAXPolicy


class Algorithm:
    _default_config_class = AlgorithmConfig
    # Algorithms that run their own rollout/evaluation actors (ES/ARS)
    # instead of the standard WorkerSet set this to keep it empty.
    _own_rollout_actors = False
    # Algorithms whose learner handles a policy map (PPO today); others
    # reject config.multi_agent() up front instead of crashing in setup.
    _supports_multi_agent = False

    def __init__(self, config: Optional[AlgorithmConfig] = None, env=None,
                 **kwargs):
        if config is None:
            config = self.get_default_config()
        if env is not None:
            config.env = env
        self.config = config
        self.iteration = 0
        self._timesteps_total = 0
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        env_creator = config.env_creator()
        self._env_creator = env_creator
        probe_env = env_creator({})
        from ray_tpu.rllib.policy import make_policy
        self.is_multi_agent = getattr(config, "is_multi_agent", False)
        if self.is_multi_agent:
            if not self._supports_multi_agent:
                raise ValueError(
                    f"{type(self).__name__} does not support "
                    "config.multi_agent() yet; multi-agent training is "
                    "available on PPO.")
            if config.policy_mapping_fn is None:
                raise ValueError(
                    "Multi-agent configs need a policy_mapping_fn: "
                    "config.multi_agent(policies=..., "
                    "policy_mapping_fn=lambda agent_id: ...)")
            from ray_tpu.rllib.evaluation.multi_agent_worker import (
                resolve_policy_specs)
            specs = resolve_policy_specs(
                config.policies, config.policy_mapping_fn, probe_env)
            self.local_policies = {
                pid: make_policy(config.policy_config(), obs_space,
                                 act_space, seed=config.seed + i)
                for i, (pid, (obs_space, act_space)) in enumerate(
                    sorted(specs.items()))}
            self.local_policy = None
        else:
            self.local_policy = make_policy(
                config.policy_config(), probe_env.observation_space,
                probe_env.action_space, seed=config.seed)
        probe_env.close() if hasattr(probe_env, "close") else None
        # Callable input_ = EXTERNAL experience source (reference:
        # policy_server_input usage — config.offline_data(input_=lambda
        # ctx: PolicyServerInput(ctx, host, port))): the algorithm trains
        # from it instead of its own rollout workers; ctx hands the
        # source the live training policy for server-side inference.
        self.external_input = None
        input_cfg = getattr(config, "input_", None)
        if callable(input_cfg):
            class InputContext:
                policy = self.local_policy
                gamma = getattr(config, "gamma", 0.99)
                lam = getattr(config, "lambda_", 0.95)

            self.external_input = input_cfg(InputContext())
        self.workers = WorkerSet(
            env_creator, config.policy_config(),
            # Zero sampling actors only for offline/external algorithms
            # (input_ set); online algorithms keep the >=1 fallback —
            # their training_step divides by worker count.
            num_workers=(0 if (self._own_rollout_actors
                               or self.external_input is not None
                               or (config.num_rollout_workers == 0
                                   and getattr(config, "input_", None)))
                         else max(config.num_rollout_workers, 1)),
            seed=config.seed,
            num_cpus_per_worker=config.num_cpus_per_worker)
        self.setup(config)

    def _sample_batch(self, per_worker: int):
        """Training data for one step: the external input (client-server
        RL) when configured, this algorithm's rollout workers otherwise."""
        if self.external_input is not None:
            return self.external_input.next_batch(
                self.config.train_batch_size)
        return self.workers.sample(per_worker)

    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        return cls._default_config_class(algo_class=cls)

    def setup(self, config: AlgorithmConfig) -> None:
        """Algorithm-specific initialization (optimizers etc.)."""

    # -- Trainable protocol ---------------------------------------------

    def train(self) -> Dict[str, Any]:
        t0 = time.monotonic()
        self.iteration += 1
        results = self.training_step()
        stats = (self.external_input.episode_stats()
                 if self.external_input is not None
                 else self.workers.episode_stats())
        for k, v in stats.items():
            # training_step wins if it already reported the metric (e.g.
            # ES/ARS compute episode stats from their own evaluators).
            results.setdefault(k, v)
        results.update({
            "training_iteration": self.iteration,
            "timesteps_total": self._timesteps_total,
            "time_this_iter_s": time.monotonic() - t0,
        })
        interval = getattr(self.config, "evaluation_interval", 0)
        if interval and self.iteration % interval == 0:
            results["evaluation"] = self.evaluate()
        return results

    def evaluate(self) -> Dict[str, Any]:
        """Greedy evaluation episodes on a fresh local env (analog of the
        reference's Algorithm.evaluate with an evaluation WorkerSet;
        single-env here since the local policy is the learner copy)."""
        if self.is_multi_agent:
            # Joint greedy eval needs per-agent routing; the rollout
            # workers' episode stats already track joint returns.
            return self.workers.episode_stats()
        duration = getattr(self.config, "evaluation_duration", 3)
        env = self._env_creator(self.config.env_config)
        from ray_tpu.rllib.connectors import get_connectors
        obs_conn, act_conn = get_connectors(
            self.config.policy_config(), env.observation_space,
            env.action_space)
        rewards, lengths = [], []
        for ep in range(duration):
            obs, _ = env.reset(seed=10_000 + ep)
            total, steps, done = 0.0, 0, False
            while not done and steps < 10_000:
                action = self.compute_single_action(obs_conn(obs))
                if act_conn.connectors:
                    action = act_conn(action)
                obs, reward, terminated, truncated, _ = env.step(action)
                total += float(reward)
                steps += 1
                done = terminated or truncated
            rewards.append(total)
            lengths.append(steps)
        if hasattr(env, "close"):
            env.close()
        return {
            "episode_reward_mean": float(np.mean(rewards)),
            "episode_len_mean": float(np.mean(lengths)),
            "episodes_this_eval": duration,
        }

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def get_weights(self):
        if self.is_multi_agent:
            return {pid: p.get_weights()
                    for pid, p in self.local_policies.items()}
        return self.local_policy.get_weights()

    def set_weights(self, weights) -> None:
        if self.is_multi_agent:
            for pid, w in weights.items():
                self.local_policies[pid].set_weights(w)
            return
        self.local_policy.set_weights(weights)

    def compute_single_action(self, obs, explore: bool = False,
                              policy_id: Optional[str] = None):
        import jax
        if self.is_multi_agent:
            if policy_id is None:
                raise ValueError(
                    "Multi-agent algorithms need "
                    "compute_single_action(obs, policy_id=...)")
            policy = self.local_policies[policy_id]
        else:
            policy = self.local_policy
        obs = np.asarray(obs, np.float32)[None]
        if explore:
            key = jax.random.PRNGKey(int(time.monotonic_ns()) % (2**31))
            a, _, _ = policy.compute_actions(obs, key)
            return a[0] if policy.discrete is False else int(a[0])
        if hasattr(policy, "compute_greedy"):
            return policy.compute_greedy(obs)
        if hasattr(policy, "q_values"):  # value-based: greedy = argmax Q
            q = policy.q_values(policy.params, obs)
            return int(np.asarray(q).argmax(-1)[0])
        if hasattr(policy, "dist_params"):  # SAC: mean action
            mu, _ = policy.dist_params(policy.params, obs)
            a = np.tanh(np.asarray(mu)[0])
            return policy.low + (a + 1.0) * 0.5 * (policy.high - policy.low)
        logits = policy.logits(policy.params, obs)
        if policy.discrete:
            return int(np.asarray(logits).argmax(-1)[0])
        return np.asarray(logits)[0]

    def save(self, checkpoint_dir: Optional[str] = None) -> str:
        import os
        import tempfile
        checkpoint_dir = checkpoint_dir or tempfile.mkdtemp(
            prefix="rllib_ckpt_")
        os.makedirs(checkpoint_dir, exist_ok=True)
        path = os.path.join(checkpoint_dir, "algorithm_state.pkl")
        with open(path, "wb") as f:
            pickle.dump({
                "weights": self.get_weights(),
                "iteration": self.iteration,
                "timesteps_total": self._timesteps_total,
            }, f)
        return checkpoint_dir

    def restore(self, checkpoint_path: str) -> None:
        import os
        if os.path.isdir(checkpoint_path):
            checkpoint_path = os.path.join(checkpoint_path,
                                           "algorithm_state.pkl")
        with open(checkpoint_path, "rb") as f:
            state = pickle.load(f)
        self.set_weights(state["weights"])
        self.iteration = state["iteration"]
        self._timesteps_total = state["timesteps_total"]

    def stop(self) -> None:
        self.workers.stop()
