"""Dreamer (V1): learning behaviors by latent imagination.

Analog of the reference's rllib/algorithms/dreamer (Hafner et al. 2020):
a recurrent state-space world model (RSSM) is trained on replayed
sequences, and the policy is trained entirely INSIDE the model — the
actor unrolls imagined trajectories through the learned dynamics and
maximizes lambda-returns of predicted rewards, backpropagating through
the (reparameterized) latent transitions; the value function supplies
the bootstrap. Real env steps are only ever used to fit the world
model.

Pieces (all Gaussian, the V1 formulation):
  * RSSM: deterministic GRU path ``h_t = f(h_{t-1}, [z_{t-1}, a_{t-1}])``
    with stochastic state ``z_t`` — prior ``p(z_t | h_t)`` for
    imagination, posterior ``q(z_t | h_t, enc(o_t))`` for filtering.
  * Heads: observation decoder (reconstruction), reward predictor.
  * World-model loss: reconstruction MSE + reward MSE +
    max(KL(q || p), free_nats).
  * Behavior: tanh-Gaussian actor and value MLP on ``[h, z]``; imagined
    H-step rollouts from every posterior state; TD(lambda) returns;
    actor ascends them, value regresses them (stop-gradient).

The reference is image-based (pixel conv encoder/decoder on DMC);
vector observations use MLP encoder/decoder here — same latent
machinery, CI-affordable (its own tuned task is Pendulum-scale). Box
action spaces only, like the reference.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.policy.sample_batch import SampleBatch


class DreamerConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or Dreamer)
        self.lr = 6e-4              # world model
        self.actor_lr = 8e-5
        self.critic_lr = 8e-5
        self.deter_dim = 128        # GRU state
        self.stoch_dim = 16         # z
        self.hidden_dim = 128       # MLPs
        self.batch_size = 32        # sequences per world-model batch
        self.seq_len = 16
        self.imagine_horizon = 12
        self.free_nats = 1.0
        self.kl_coeff = 1.0
        self.lambda_ = 0.95
        self.explore_noise = 0.3
        self.num_train_batches_per_iteration = 40
        self.rollout_steps_per_iteration = 400
        self.prefill_steps = 1000   # random steps before learning
        self.replay_capacity_steps = 50_000
        #: env steps per policy decision (the reference's env wrapper
        #: uses action repeat 2 on control tasks; rewards sum across
        #: the repeat).
        self.action_repeat = 2

    def training(self, *, actor_lr=None, critic_lr=None, deter_dim=None,
                 stoch_dim=None, hidden_dim=None, seq_len=None,
                 imagine_horizon=None, free_nats=None, kl_coeff=None,
                 explore_noise=None, prefill_steps=None,
                 action_repeat=None,
                 rollout_steps_per_iteration=None,
                 num_train_batches_per_iteration=None,
                 **kwargs) -> "DreamerConfig":
        super().training(**kwargs)
        for name, val in (
                ("actor_lr", actor_lr), ("critic_lr", critic_lr),
                ("deter_dim", deter_dim), ("stoch_dim", stoch_dim),
                ("hidden_dim", hidden_dim), ("seq_len", seq_len),
                ("imagine_horizon", imagine_horizon),
                ("free_nats", free_nats), ("kl_coeff", kl_coeff),
                ("explore_noise", explore_noise),
                ("prefill_steps", prefill_steps),
                ("action_repeat", action_repeat),
                ("rollout_steps_per_iteration",
                 rollout_steps_per_iteration),
                ("num_train_batches_per_iteration",
                 num_train_batches_per_iteration)):
            if val is not None:
                setattr(self, name, val)
        return self


class Dreamer(Algorithm):
    _default_config_class = DreamerConfig
    _own_rollout_actors = True

    def setup(self, config: DreamerConfig) -> None:
        import gymnasium as gym
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.rllib.models.catalog import mlp_apply, mlp_init
        from ray_tpu.rllib.utils.replay_buffers import (
            SequenceReplayBuffer)

        env = self._env_creator(config.env_config)
        if not isinstance(env.action_space, gym.spaces.Box):
            raise ValueError(
                "Dreamer supports Box action spaces (the reference is "
                "likewise continuous-control only)")
        self._env = env
        self.obs_dim = int(np.prod(env.observation_space.shape))
        self.act_dim = int(np.prod(env.action_space.shape))
        self._act_lo = np.asarray(env.action_space.low, np.float32)
        self._act_hi = np.asarray(env.action_space.high, np.float32)
        D, Z, H = config.deter_dim, config.stoch_dim, config.hidden_dim

        key = jax.random.PRNGKey(config.seed)
        ks = iter(jax.random.split(key, 12))
        self.params = {
            "enc": mlp_init(next(ks), [self.obs_dim, H, H]),
            # GRU over input [z, a] with state h.
            "gru_x": mlp_init(next(ks), [Z + self.act_dim, 3 * D]),
            "gru_h": mlp_init(next(ks), [D, 3 * D]),
            "prior": mlp_init(next(ks), [D, H, 2 * Z]),
            "post": mlp_init(next(ks), [D + H, H, 2 * Z]),
            "dec": mlp_init(next(ks), [D + Z, H, H, self.obs_dim]),
            "rew": mlp_init(next(ks), [D + Z, H, 1]),
        }
        self.actor_params = mlp_init(next(ks),
                                     [D + Z, H, H, 2 * self.act_dim])
        self.critic_params = mlp_init(next(ks), [D + Z, H, H, 1])
        self._wm_opt = optax.adam(config.lr)
        self._actor_opt = optax.adam(config.actor_lr)
        self._critic_opt = optax.adam(config.critic_lr)
        self._wm_state = self._wm_opt.init(self.params)
        self._actor_state = self._actor_opt.init(self.actor_params)
        self._critic_state = self._critic_opt.init(self.critic_params)

        def gru(p, h, x):
            gx = mlp_apply(p["gru_x"], x)
            gh = mlp_apply(p["gru_h"], h)
            xr, xu, xc = jnp.split(gx, 3, axis=-1)
            hr, hu, hc = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            u = jax.nn.sigmoid(xu + hu)
            cand = jnp.tanh(xc + r * hc)
            return u * h + (1 - u) * cand

        def stats(raw):
            mean, std = jnp.split(raw, 2, axis=-1)
            return mean, jax.nn.softplus(std) + 0.1

        def prior_of(p, h):
            return stats(mlp_apply(p["prior"], h))

        def post_of(p, h, emb):
            return stats(mlp_apply(p["post"],
                                   jnp.concatenate([h, emb], -1)))

        def rssm_observe(p, obs_seq, act_seq, key):
            """obs [B,T,obs], act [B,T,act] (a_t taken AFTER o_t) ->
            posterior features [B,T,D+Z] + KL terms."""
            B, T = obs_seq.shape[:2]
            emb = mlp_apply(p["enc"], obs_seq)

            def step(carry, t):
                h, z, k = carry
                k, sub = jax.random.split(k)
                pm, ps = prior_of(p, h)
                qm, qs = post_of(p, h, emb[:, t])
                zq = qm + qs * jax.random.normal(sub, qm.shape)
                kl = (jnp.log(ps / qs) +
                      (qs ** 2 + (qm - pm) ** 2) / (2 * ps ** 2)
                      - 0.5).sum(-1)
                feat = jnp.concatenate([h, zq], -1)
                h_next = gru(p, h, jnp.concatenate(
                    [zq, act_seq[:, t]], -1))
                return (h_next, zq, k), (feat, kl)

            h0 = jnp.zeros((B, D))
            z0 = jnp.zeros((B, Z))
            (_, _, _), (feats, kls) = jax.lax.scan(
                step, (h0, z0, key), jnp.arange(T))
            # scan stacks on axis 0 -> [T,B,...]; put batch first.
            return (jnp.moveaxis(feats, 0, 1),
                    jnp.moveaxis(kls, 0, 1))

        def actor_dist(ap, feat):
            mean, std = stats(mlp_apply(ap, feat))
            return mean, std

        def actor_sample(ap, feat, key):
            mean, std = actor_dist(ap, feat)
            return jnp.tanh(mean + std * jax.random.normal(
                key, mean.shape))

        def imagine(p, ap, feat0, key, horizon):
            """Roll the PRIOR forward under the actor from [B,D+Z]
            starts; differentiable through z (reparameterized) for the
            actor gradient."""
            def step(carry, _):
                h, z, k = carry
                k, ka, kz = jax.random.split(k, 3)
                feat = jnp.concatenate([h, z], -1)
                a = actor_sample(ap, feat, ka)
                h = gru(p, h, jnp.concatenate([z, a], -1))
                pm, ps = prior_of(p, h)
                z = pm + ps * jax.random.normal(kz, pm.shape)
                return (h, z, k), jnp.concatenate([h, z], -1)

            h0 = feat0[..., :D]
            z0 = feat0[..., D:]
            (_, _, _), feats = jax.lax.scan(
                step, (h0, z0, key), None, length=horizon)
            return jnp.moveaxis(feats, 0, 1)  # [B,H,D+Z]

        gamma, lam = config.gamma, config.lambda_
        free_nats, kl_coeff = config.free_nats, config.kl_coeff

        def wm_loss(p, mb, key):
            feats, kls = rssm_observe(p, mb["obs"], mb["actions"], key)
            recon = mlp_apply(p["dec"], feats)
            rew = mlp_apply(p["rew"], feats)[..., 0]
            m = mb["mask"]
            recon_loss = (((recon - mb["obs"]) ** 2).mean(-1) * m).sum() \
                / jnp.maximum(m.sum(), 1.0)
            rew_loss = (((rew - mb["rewards"]) ** 2) * m).sum() / \
                jnp.maximum(m.sum(), 1.0)
            kl = jnp.maximum((kls * m).sum() / jnp.maximum(m.sum(), 1.0),
                             free_nats)
            return recon_loss + rew_loss + kl_coeff * kl, \
                (recon_loss, rew_loss, kl, feats)

        def lambda_returns(rew, values):
            """rew/values [B,H] along imagined states s_0..s_{H-1}:
            G_t = r_t + gamma*((1-lam)*V(s_{t+1}) + lam*G_{t+1}),
            seeded G_{H-1} = r_{H-1} + gamma*V(s_{H-1})."""
            H_ = rew.shape[1]
            seed = rew[:, -1] + gamma * values[:, -1]

            def step(ret, t):
                idx = H_ - 2 - t
                ret = rew[:, idx] + gamma * (
                    (1 - lam) * values[:, idx + 1] + lam * ret)
                return ret, ret

            _, rets = jax.lax.scan(step, seed, jnp.arange(H_ - 1))
            # rets covers t=H-2..0 (reverse order); append the seed.
            all_rets = jnp.concatenate(
                [rets[::-1], seed[None]], axis=0)   # [H,B]
            return jnp.moveaxis(all_rets, 0, 1)     # [B,H]

        def behavior_losses(ap, cp, p, feats, key):
            B = feats.shape[0] * feats.shape[1]
            starts = jax.lax.stop_gradient(
                feats.reshape(B, feats.shape[-1]))
            imag = imagine(p, ap, starts, key,
                           config.imagine_horizon)      # [B,H,D+Z]
            rew = mlp_apply(p["rew"], imag)[..., 0]
            values = mlp_apply(cp, imag)[..., 0]
            rets = lambda_returns(rew, values)
            actor_loss = -rets.mean()
            critic_loss = ((mlp_apply(cp, jax.lax.stop_gradient(imag))
                            [..., 0]
                            - jax.lax.stop_gradient(rets)) ** 2).mean()
            return actor_loss, critic_loss, rets

        def update(p, ap, cp, wm_s, a_s, c_s, mb, key):
            k1, k2, k3 = jax.random.split(key, 3)
            (wl, (rl, rwl, kl, feats)), wg = jax.value_and_grad(
                wm_loss, has_aux=True)(p, mb, k1)
            wu, wm_s = self._wm_opt.update(wg, wm_s, p)
            p = optax.apply_updates(p, wu)

            def a_loss(ap_):
                al, _, _ = behavior_losses(ap_, cp, p, feats, k2)
                return al

            al, ag = jax.value_and_grad(a_loss)(ap)
            au, a_s = self._actor_opt.update(ag, a_s, ap)
            ap = optax.apply_updates(ap, au)

            def c_loss(cp_):
                _, cl, _ = behavior_losses(ap, cp_, p, feats, k3)
                return cl

            cl, cg = jax.value_and_grad(c_loss)(cp)
            cu, c_s = self._critic_opt.update(cg, c_s, cp)
            cp = optax.apply_updates(cp, cu)
            metrics = {"wm_loss": wl, "recon_loss": rl,
                       "reward_loss": rwl, "kl": kl,
                       "actor_loss": al, "critic_loss": cl}
            return p, ap, cp, wm_s, a_s, c_s, metrics

        # Filtering step for acting: advance (h, z) with the posterior.
        def filter_step(p, h, z, a_prev, obs, key):
            h = gru(p, h, jnp.concatenate([z, a_prev], -1))
            emb = mlp_apply(p["enc"], obs)
            qm, qs = post_of(p, h, emb)
            z = qm + qs * jax.random.normal(key, qm.shape)
            return h, z

        self._update_jit = jax.jit(update)
        self._filter_jit = jax.jit(filter_step)
        self._actor_sample_jit = jax.jit(actor_sample)
        self._D, self._Z = D, Z
        self._key = jax.random.PRNGKey(config.seed + 5)
        self._buffer = SequenceReplayBuffer(
            capacity_episodes=max(
                config.replay_capacity_steps // 50, 64),
            seed=config.seed)
        self._rng = np.random.default_rng(config.seed)
        self._episode_rewards: List[float] = []
        self._reset_episode_state()

    def _reset_episode_state(self) -> None:
        self._obs, _ = self._env.reset()
        self._h = np.zeros(self._D, np.float32)
        self._z = np.zeros(self._Z, np.float32)
        self._a_prev = np.zeros(self.act_dim, np.float32)
        self._episode_reward = 0.0
        self._episode_rows: List[dict] = []

    # -- acting ----------------------------------------------------------

    def compute_single_action(self, obs, explore: bool = False,
                              policy_id=None):
        import jax
        import jax.numpy as jnp
        self._key, k1, k2 = jax.random.split(self._key, 3)
        h, z = self._filter_jit(
            self.params, jnp.asarray(self._h[None]),
            jnp.asarray(self._z[None]),
            jnp.asarray(self._a_prev[None]),
            jnp.asarray(np.asarray(obs, np.float32).reshape(1, -1)), k1)
        self._h = np.asarray(h[0])
        self._z = np.asarray(z[0])
        feat = jnp.concatenate([h, z], -1)
        a = np.asarray(self._actor_sample_jit(
            self.actor_params, feat, k2)[0])
        if explore:
            a = np.clip(a + self.config.explore_noise *
                        self._rng.standard_normal(a.shape), -1, 1)
        return self._act_lo + (a + 1.0) * 0.5 * (self._act_hi -
                                                 self._act_lo)

    def evaluate(self) -> Dict[str, Any]:
        """Noise-free episodes on a fresh env with a fresh filter state
        (the base evaluate would thread the collection episode's
        recurrent state into evaluation)."""
        saved = (self._env, self._obs, self._h, self._z, self._a_prev,
                 self._episode_reward, self._episode_rows)
        eval_env = self._env_creator(self.config.env_config)
        rewards = []
        try:
            for e in range(3):
                self._env = eval_env
                self._obs, _ = eval_env.reset(seed=10_000 + e)
                self._h = np.zeros(self._D, np.float32)
                self._z = np.zeros(self._Z, np.float32)
                self._a_prev = np.zeros(self.act_dim, np.float32)
                total, done = 0.0, False
                while not done:
                    a = self.compute_single_action(self._obs)
                    self._obs, r, term, trunc, _ = eval_env.step(
                        np.asarray(a, np.float32))
                    norm = 2.0 * (a - self._act_lo) / np.maximum(
                        self._act_hi - self._act_lo, 1e-8) - 1.0
                    self._a_prev = np.asarray(norm,
                                              np.float32).reshape(-1)
                    total += float(r)
                    done = term or trunc
                rewards.append(total)
        finally:
            close = getattr(eval_env, "close", None)
            if callable(close):
                close()
            (self._env, self._obs, self._h, self._z, self._a_prev,
             self._episode_reward, self._episode_rows) = saved
        return {"episode_reward_mean": float(np.mean(rewards)),
                "episodes_this_eval": len(rewards)}

    def training_step(self) -> Dict[str, Any]:
        import jax.numpy as jnp
        config: DreamerConfig = self.config
        for _ in range(config.rollout_steps_per_iteration):
            if self._timesteps_total < config.prefill_steps:
                action = self._env.action_space.sample()
                norm_a = 2.0 * (action - self._act_lo) / np.maximum(
                    self._act_hi - self._act_lo, 1e-8) - 1.0
            else:
                action = self.compute_single_action(self._obs,
                                                    explore=True)
                norm_a = 2.0 * (action - self._act_lo) / np.maximum(
                    self._act_hi - self._act_lo, 1e-8) - 1.0
            r, term, trunc = 0.0, False, False
            for _ in range(max(config.action_repeat, 1)):
                nxt, r_i, term, trunc, _ = self._env.step(
                    np.asarray(action, np.float32))
                r += float(r_i)
                if term or trunc:
                    break
            self._episode_rows.append({
                "obs": np.asarray(self._obs, np.float32).reshape(-1),
                "actions": np.asarray(norm_a, np.float32).reshape(-1),
                "rewards": np.float32(r),
                "terminateds": np.float32(term)})
            self._episode_reward += float(r)
            self._timesteps_total += 1
            self._obs = nxt
            self._a_prev = np.asarray(norm_a, np.float32).reshape(-1)
            if term or trunc:
                rows = self._episode_rows
                batch = SampleBatch({
                    k: np.stack([row[k] for row in rows])
                    for k in rows[0]})
                batch["eps_id"] = np.full(
                    len(rows), len(self._episode_rewards), np.int64)
                self._buffer.add(batch)
                self._episode_rewards.append(self._episode_reward)
                self._reset_episode_state()

        metrics = {}
        if self._timesteps_total >= config.prefill_steps and \
                len(self._buffer) >= config.batch_size * config.seq_len:
            import jax
            p, ap, cp = (self.params, self.actor_params,
                         self.critic_params)
            for _ in range(config.num_train_batches_per_iteration):
                mb = self._buffer.sample(config.batch_size,
                                         seq_len=config.seq_len)
                device_mb = {
                    "obs": jnp.asarray(mb["obs"]),
                    "actions": jnp.asarray(mb["actions"]),
                    "rewards": jnp.asarray(mb["rewards"]),
                    "mask": jnp.asarray(mb["mask"]),
                }
                self._key, sub = jax.random.split(self._key)
                (p, ap, cp, self._wm_state, self._actor_state,
                 self._critic_state, metrics) = self._update_jit(
                    p, ap, cp, self._wm_state, self._actor_state,
                    self._critic_state, device_mb, sub)
            self.params, self.actor_params, self.critic_params = \
                p, ap, cp
            metrics = {k: float(v) for k, v in metrics.items()}

        window = self._episode_rewards[-100:]
        metrics.update({
            "episode_reward_mean": (float(np.mean(window)) if window
                                    else float("nan")),
            "episodes_total": len(self._episode_rewards),
        })
        return metrics

    def get_weights(self):
        import jax
        return {"wm": jax.tree.map(np.asarray, self.params),
                "actor": jax.tree.map(np.asarray, self.actor_params),
                "critic": jax.tree.map(np.asarray, self.critic_params)}

    def set_weights(self, weights) -> None:
        import jax
        import jax.numpy as jnp
        self.params = jax.tree.map(jnp.asarray, weights["wm"])
        self.actor_params = jax.tree.map(jnp.asarray, weights["actor"])
        self.critic_params = jax.tree.map(jnp.asarray,
                                          weights["critic"])

    def stop(self) -> None:
        close = getattr(self._env, "close", None)
        if callable(close):
            close()
