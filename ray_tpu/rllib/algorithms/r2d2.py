"""R2D2: recurrent replay distributed DQN (Kapturowski et al. 2019).

Analog of the reference's rllib/algorithms/r2d2: DQN with an LSTM
Q-network trained on stored SEQUENCES. Each sampled window seeds the
LSTM with the hidden state recorded at collection time, burns in
``burn_in`` steps without gradient (re-warming the recurrence under
current weights), then TD-trains the remainder with double-Q targets and
R2D2's invertible value rescaling. Inherits the DQN engine's rollout /
target-sync / epsilon plumbing; replay and the update are sequence-
shaped (utils/replay_buffers.py SequenceReplayBuffer).
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig
from ray_tpu.rllib.utils.replay_buffers import SequenceReplayBuffer


class R2D2Config(DQNConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or R2D2)
        self.policy_class_name = "r2d2"
        self.seq_len = 10            # training window length
        self.burn_in = 4             # no-gradient warmup steps per window
        self.train_batch_size = 16   # sequences per minibatch
        self.replay_buffer_capacity = 2000  # episodes
        self.lstm_cell_size = 64
        self.use_value_rescaling = True
        self.n_step = 1              # within-sequence TD(0)
        self.prioritized_replay = False  # uniform sequence sampling

    def training(self, *, seq_len=None, burn_in=None, lstm_cell_size=None,
                 use_value_rescaling=None, **kwargs) -> "R2D2Config":
        super().training(**kwargs)
        for name, val in (("seq_len", seq_len), ("burn_in", burn_in),
                          ("lstm_cell_size", lstm_cell_size),
                          ("use_value_rescaling", use_value_rescaling)):
            if val is not None:
                setattr(self, name, val)
        return self

    def policy_config(self):
        base = super().policy_config()
        base["lstm_cell_size"] = self.lstm_cell_size
        return base


class R2D2(DQN):
    _default_config_class = R2D2Config

    def setup(self, config: R2D2Config) -> None:
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.rllib.policy.r2d2_policy import (value_rescale,
                                                      value_rescale_inv)

        if getattr(config, "input_", None):
            raise ValueError(
                "R2D2 trains on stored SEQUENCES with recurrent states "
                "recorded at collection time; offline JSON input "
                "(config.offline_data) carries neither and is not "
                "supported.")
        if config.prioritized_replay:
            raise ValueError(
                "R2D2 samples sequences uniformly; prioritized_replay "
                "is not supported (set it False).")
        policy = self.local_policy
        self._optimizer = optax.adam(config.lr)
        self._opt_state = self._optimizer.init(policy.params)
        self._target_params = jax.tree.map(jnp.asarray, policy.params)
        self._buffer = SequenceReplayBuffer(
            config.replay_buffer_capacity, seed=config.seed)
        self._grad_steps = 0
        self._reader = None
        gamma = config.gamma
        double_q = config.double_q
        burn_in = config.burn_in
        tau = config.tau
        rescale = config.use_value_rescaling

        def loss_fn(params, target_params, mb):
            obs = mb["obs"]                      # [B, T, ...]
            h0, c0 = mb["h0"], mb["c0"]          # [B, hidden]
            # Burn-in under stop_gradient: re-warm the recurrence with
            # current weights, but only the post-burn-in steps train.
            if burn_in > 0:
                _, (h_b, c_b) = policy.q_seq(
                    params, obs[:, :burn_in], h0, c0)
                h_on = jax.lax.stop_gradient(h_b)
                c_on = jax.lax.stop_gradient(c_b)
                _, (h_tb, c_tb) = policy.q_seq(
                    target_params, obs[:, :burn_in], h0, c0)
            else:
                h_on, c_on = h0, c0
                h_tb, c_tb = h0, c0
            train_obs = obs[:, burn_in:]
            q_online, _ = policy.q_seq(params, train_obs, h_on, c_on)
            q_target, _ = policy.q_seq(target_params, train_obs,
                                       jax.lax.stop_gradient(h_tb),
                                       jax.lax.stop_gradient(c_tb))
            actions = mb["actions"][:, burn_in:].astype(jnp.int32)
            rewards = mb["rewards"][:, burn_in:]
            dones = jnp.maximum(mb["terminateds"][:, burn_in:], 0.0)
            mask = mb["mask"][:, burn_in:]
            q_taken = jnp.take_along_axis(
                q_online, actions[..., None], -1)[..., 0]  # [B, T']
            # Next-step targets within the window: shift by one; the last
            # step of each window has no successor -> masked out.
            if double_q:
                a_star = q_online[:, 1:].argmax(-1)
                q_next = jnp.take_along_axis(
                    q_target[:, 1:], a_star[..., None], -1)[..., 0]
            else:
                q_next = q_target[:, 1:].max(-1)
            if rescale:
                q_next = value_rescale_inv(q_next)
            target = rewards[:, :-1] + gamma * (1.0 - dones[:, :-1]) * \
                q_next
            if rescale:
                target = value_rescale(target)
            td = q_taken[:, :-1] - jax.lax.stop_gradient(target)
            # Valid steps: real (mask) at t AND t+1 unless t is terminal
            # (terminal steps bootstrap nothing and are always valid).
            valid = mask[:, :-1] * jnp.maximum(
                mask[:, 1:], dones[:, :-1])
            huber = jnp.where(jnp.abs(td) < 1.0, 0.5 * td ** 2,
                              jnp.abs(td) - 0.5)
            denom = jnp.maximum(valid.sum(), 1.0)
            return (huber * valid).sum() / denom, td

        def update(params, target_params, opt_state, mb):
            (loss, td), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, target_params, mb)
            updates, opt_state = self._optimizer.update(grads, opt_state,
                                                        params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, td

        def soft_sync(params, target_params):
            return jax.tree.map(lambda p, t: tau * p + (1 - tau) * t,
                                params, target_params)

        self._update_jit = jax.jit(update)
        self._soft_sync_jit = jax.jit(soft_sync)

    def training_step(self) -> Dict[str, Any]:
        import jax.numpy as jnp

        import ray_tpu
        config: R2D2Config = self.config
        weights_ref = ray_tpu.put(self.get_weights())
        self.workers.sync_weights(weights_ref)
        batch = self.workers.sample(max(config.rollout_fragment_length, 1))
        self._timesteps_total += len(batch)
        self._buffer.add(batch)

        losses = []
        if len(self._buffer) >= \
                config.num_steps_sampled_before_learning_starts:
            params = self.local_policy.params
            for _ in range(config.num_train_batches_per_iteration):
                mb = self._buffer.sample(config.train_batch_size,
                                         config.seq_len)
                device_mb = {k: jnp.asarray(v) for k, v in mb.items()
                             if k in ("obs", "actions", "rewards",
                                      "terminateds", "mask", "h0", "c0")}
                params, self._opt_state, loss, _ = self._update_jit(
                    params, self._target_params, self._opt_state,
                    device_mb)
                losses.append(float(loss))
                self._grad_steps += 1
                if self._grad_steps % \
                        config.target_network_update_freq == 0:
                    self._target_params = self._soft_sync_jit(
                        params, self._target_params)
            self.local_policy.params = params
        return {
            "loss": float(np.mean(losses)) if losses else float("nan"),
            "epsilon": self._epsilon(),
            "replay_buffer_size": len(self._buffer),
            "gradient_steps_total": self._grad_steps,
        }
