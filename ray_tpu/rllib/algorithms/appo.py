"""APPO: asynchronous PPO — clipped surrogate over V-trace corrections.

Analog of the reference's rllib/algorithms/appo (IMPALA's architecture
with PPO's clipped loss): workers sample with slightly stale weights,
V-trace (algorithms/impala.py) corrects the off-policyness into value
targets and advantages, and the update applies the PPO clip against the
behavior log-probs instead of a plain policy gradient.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.algorithms.impala import compute_vtrace_targets
from ray_tpu.rllib.policy.sample_batch import SampleBatch


class APPOConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or APPO)
        self.lr = 5e-4
        self.clip_param = 0.3
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.num_sgd_iter = 2
        self.sgd_minibatch_size = 256
        self.vtrace_rho_clip = 1.0
        self.vtrace_c_clip = 1.0

    def training(self, *, clip_param=None, vf_loss_coeff=None,
                 entropy_coeff=None, num_sgd_iter=None,
                 sgd_minibatch_size=None, vtrace_rho_clip=None,
                 vtrace_c_clip=None, **kwargs) -> "APPOConfig":
        super().training(**kwargs)
        for name, val in (("clip_param", clip_param),
                          ("vf_loss_coeff", vf_loss_coeff),
                          ("entropy_coeff", entropy_coeff),
                          ("num_sgd_iter", num_sgd_iter),
                          ("sgd_minibatch_size", sgd_minibatch_size),
                          ("vtrace_rho_clip", vtrace_rho_clip),
                          ("vtrace_c_clip", vtrace_c_clip)):
            if val is not None:
                setattr(self, name, val)
        return self


class APPO(Algorithm):
    _default_config_class = APPOConfig

    def setup(self, config: APPOConfig) -> None:
        import jax
        import jax.numpy as jnp
        import optax

        policy = self.local_policy
        self._optimizer = optax.adam(config.lr)
        self._opt_state = self._optimizer.init(policy.params)
        clip = config.clip_param
        vf_coeff = config.vf_loss_coeff
        ent_coeff = config.entropy_coeff

        def loss_fn(params, mb):
            logp = policy.logp(params, mb["obs"], mb["actions"])
            ratio = jnp.exp(logp - mb["behavior_logp"])
            adv = mb["pg_advantages"]
            surrogate = jnp.minimum(
                ratio * adv, jnp.clip(ratio, 1 - clip, 1 + clip) * adv)
            values = policy._value(params, mb["obs"])
            vf_loss = jnp.mean((values - mb["vs"]) ** 2)
            entropy = jnp.mean(policy.entropy(params, mb["obs"]))
            total = (-jnp.mean(surrogate) + vf_coeff * vf_loss
                     - ent_coeff * entropy)
            return total, {"policy_loss": -jnp.mean(surrogate),
                           "vf_loss": vf_loss, "entropy": entropy}

        def update(params, opt_state, mb):
            (loss, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, mb)
            updates, opt_state = self._optimizer.update(grads, opt_state,
                                                        params)
            params = optax.apply_updates(params, updates)
            metrics["total_loss"] = loss
            return params, opt_state, metrics

        self._update_jit = jax.jit(update)

    def training_step(self) -> Dict[str, Any]:
        import jax.numpy as jnp

        import ray_tpu
        config: APPOConfig = self.config
        weights_ref = ray_tpu.put(self.get_weights())
        self.workers.sync_weights(weights_ref)
        per_worker = max(
            config.train_batch_size // self.workers.num_workers(), 1)
        batch = self.workers.sample(per_worker)
        self._timesteps_total += len(batch)

        policy = self.local_policy
        obs, vs, pg_adv = compute_vtrace_targets(
            policy, batch, config.gamma, config.vtrace_rho_clip,
            config.vtrace_c_clip)
        full = SampleBatch({
            "obs": obs,
            "actions": np.asarray(batch[SampleBatch.ACTIONS]),
            "behavior_logp": np.asarray(batch[SampleBatch.ACTION_LOGP],
                                        np.float32),
            "vs": vs,
            "pg_advantages": pg_adv,
        })
        params = policy.params
        metrics = {}
        for epoch in range(config.num_sgd_iter):
            for mb in full.minibatches(
                    min(config.sgd_minibatch_size, len(full)),
                    seed=self.iteration * 97 + epoch):
                device_mb = {k: jnp.asarray(v) for k, v in mb.items()}
                params, self._opt_state, metrics = self._update_jit(
                    params, self._opt_state, device_mb)
        policy.params = params
        return {k: float(v) for k, v in metrics.items()}
