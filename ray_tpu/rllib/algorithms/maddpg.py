"""MADDPG: multi-agent DDPG with centralized critics.

Analog of the reference's rllib/algorithms/maddpg (Lowe et al. 2017,
"Multi-Agent Actor-Critic for Mixed Cooperative-Competitive
Environments"): every agent keeps a DECENTRALIZED deterministic actor
``mu_i(o_i)`` usable with only its own observation at execution time,
but trains it against a CENTRALIZED critic ``Q_i(o_1..o_n, a_1..a_n)``
that sees the joint observation and joint action — sidestepping the
non-stationarity that breaks independent DDPG learners, because the
joint-conditioned value function is stationary as the other agents'
policies shift.

Updates (per agent i, from a replay buffer of joint transitions):
  * critic: TD toward ``r_i + gamma * Q_i'(o', mu_1'(o_1'),...,
    mu_n'(o_n'))`` with target actors/critics (polyak-averaged),
  * actor: deterministic policy gradient through the centralized critic
    with agent i's action from its CURRENT actor and the other agents'
    actions from the batch (the paper's Eq. 6 sampling approximation).

Collection is in-algorithm (joint transitions must stay synchronized,
like qmix.py); exploration is Gaussian action noise with linear decay.
Env contract: a MultiAgentEnv with simultaneous Box actions
(e.g. env/examples.py CooperativeNavEnv).
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.policy.sample_batch import SampleBatch
from ray_tpu.rllib.utils.replay_buffers import ReplayBuffer


class MADDPGConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or MADDPG)
        self.actor_lr = 1e-3     # reference MADDPGConfig knobs
        self.critic_lr = 1e-2
        self.tau = 0.01
        self.train_batch_size = 256
        self.replay_buffer_capacity = 50_000
        self.num_steps_sampled_before_learning_starts = 1000
        self.num_train_batches_per_iteration = 50
        self.rollout_steps_per_iteration = 500
        self.noise_initial = 0.5
        self.noise_final = 0.05
        self.noise_timesteps = 15_000

    def training(self, *, actor_lr=None, critic_lr=None, tau=None,
                 replay_buffer_capacity=None,
                 num_steps_sampled_before_learning_starts=None,
                 num_train_batches_per_iteration=None,
                 rollout_steps_per_iteration=None, noise_timesteps=None,
                 **kwargs) -> "MADDPGConfig":
        super().training(**kwargs)
        for name, val in (
                ("actor_lr", actor_lr), ("critic_lr", critic_lr),
                ("tau", tau),
                ("replay_buffer_capacity", replay_buffer_capacity),
                ("num_steps_sampled_before_learning_starts",
                 num_steps_sampled_before_learning_starts),
                ("num_train_batches_per_iteration",
                 num_train_batches_per_iteration),
                ("rollout_steps_per_iteration",
                 rollout_steps_per_iteration),
                ("noise_timesteps", noise_timesteps)):
            if val is not None:
                setattr(self, name, val)
        return self


class MADDPG(Algorithm):
    _default_config_class = MADDPGConfig
    _own_rollout_actors = True
    _supports_multi_agent = True

    def setup(self, config: MADDPGConfig) -> None:
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.rllib.models.catalog import mlp_apply, mlp_init

        env = self._env_creator(config.env_config)
        self._env = env
        obs0, _ = env.reset(seed=config.seed)
        self.agent_ids: List[str] = sorted(obs0.keys())
        self.n = len(self.agent_ids)
        self.obs_dims = [int(np.prod(
            env.observation_space_for(a).shape)) for a in self.agent_ids]
        self.act_dims = [int(np.prod(
            env.action_space_for(a).shape)) for a in self.agent_ids]
        self._act_lo = [np.asarray(env.action_space_for(a).low,
                                   np.float32) for a in self.agent_ids]
        self._act_hi = [np.asarray(env.action_space_for(a).high,
                                   np.float32) for a in self.agent_ids]
        joint_dim = sum(self.obs_dims) + sum(self.act_dims)
        hiddens = list(config.fcnet_hiddens)

        key = jax.random.PRNGKey(config.seed)
        keys = jax.random.split(key, 2 * self.n)
        self.params = {
            "actors": [mlp_init(keys[2 * i],
                                [self.obs_dims[i], *hiddens,
                                 self.act_dims[i]])
                       for i in range(self.n)],
            "critics": [mlp_init(keys[2 * i + 1],
                                 [joint_dim, *hiddens, 1])
                        for i in range(self.n)],
        }
        self._target = jax.tree.map(jnp.asarray, self.params)
        self._a_opt = optax.adam(config.actor_lr)
        self._c_opt = optax.adam(config.critic_lr)
        self._a_states = [self._a_opt.init(p)
                          for p in self.params["actors"]]
        self._c_states = [self._c_opt.init(p)
                          for p in self.params["critics"]]
        gamma, tau = config.gamma, config.tau
        n = self.n
        los = [jnp.asarray(lo.reshape(-1)) for lo in self._act_lo]
        his = [jnp.asarray(hi.reshape(-1)) for hi in self._act_hi]

        def act(actor, obs, j):
            """Deterministic actor for agent j, rescaled from tanh's
            [-1, 1] to the agent's Box bounds (same mapping as
            td3.py det_action) so the whole action space is reachable."""
            t = jnp.tanh(mlp_apply(actor, obs))
            return los[j] + (t + 1.0) * 0.5 * (his[j] - los[j])

        def critic(cr, obs_list, act_list):
            return mlp_apply(cr, jnp.concatenate(
                list(obs_list) + list(act_list), -1))[..., 0]

        def critic_loss(cr_i, i, params_t, mb):
            obs = [mb[f"obs_{j}"] for j in range(n)]
            nxt = [mb[f"new_obs_{j}"] for j in range(n)]
            acts = [mb[f"act_{j}"] for j in range(n)]
            a_next = [act(params_t["actors"][j], nxt[j], j)
                      for j in range(n)]
            q_next = critic(params_t["critics"][i], nxt, a_next)
            target = mb["rewards"][:, i] + gamma * \
                (1.0 - mb["dones"][:, 0]) * q_next
            q = critic(cr_i, obs, acts)
            return ((q - jax.lax.stop_gradient(target)) ** 2).mean()

        def actor_loss(actor_i, i, critics, mb):
            obs = [mb[f"obs_{j}"] for j in range(n)]
            acts = [mb[f"act_{j}"] for j in range(n)]
            acts = acts[:i] + [act(actor_i, obs[i], i)] + acts[i + 1:]
            return -critic(critics[i], obs, acts).mean()

        def update(params, params_t, a_states, c_states, mb):
            new_actors, new_critics = [], []
            new_a_states, new_c_states = [], []
            closses, alosses = [], []
            for i in range(n):
                cl, cg = jax.value_and_grad(critic_loss)(
                    params["critics"][i], i, params_t, mb)
                cu, cs = self._c_opt.update(cg, c_states[i],
                                            params["critics"][i])
                new_critics.append(optax.apply_updates(
                    params["critics"][i], cu))
                new_c_states.append(cs)
                crit_now = [*params["critics"][:i], new_critics[i],
                            *params["critics"][i + 1:]]
                al, ag = jax.value_and_grad(actor_loss)(
                    params["actors"][i], i, crit_now, mb)
                au, s = self._a_opt.update(ag, a_states[i],
                                           params["actors"][i])
                new_actors.append(optax.apply_updates(
                    params["actors"][i], au))
                new_a_states.append(s)
                closses.append(cl)
                alosses.append(al)
            params = {"actors": new_actors, "critics": new_critics}
            params_t = jax.tree.map(
                lambda t, p: (1 - tau) * t + tau * p, params_t, params)
            return (params, params_t, new_a_states, new_c_states,
                    sum(closses) / n, sum(alosses) / n)

        self._update_jit = jax.jit(update)
        self._act_jit = jax.jit(
            lambda actors, obs_list: [act(a, o, j) for j, (a, o) in
                                      enumerate(zip(actors, obs_list))])
        self._rng = np.random.default_rng(config.seed)
        self._buffer = ReplayBuffer(config.replay_buffer_capacity,
                                    seed=config.seed)
        self._obs = obs0
        self._episode_reward = 0.0
        self._episode_rewards: List[float] = []

    # -- acting ----------------------------------------------------------

    def _noise(self) -> float:
        c: MADDPGConfig = self.config
        frac = min(1.0, self._timesteps_total / max(c.noise_timesteps, 1))
        return c.noise_initial + frac * (c.noise_final - c.noise_initial)

    def compute_actions(self, obs_dict, noise: float = 0.0
                        ) -> Dict[str, np.ndarray]:
        import jax.numpy as jnp
        obs_list = [jnp.asarray(
            np.asarray(obs_dict[a], np.float32).reshape(1, -1))
            for a in self.agent_ids]
        acts = self._act_jit(self.params["actors"], obs_list)
        out = {}
        for i, aid in enumerate(self.agent_ids):
            a = np.asarray(acts[i][0], np.float32)
            if noise > 0:
                a = a + noise * self._rng.standard_normal(a.shape)
            out[aid] = np.clip(a, self._act_lo[i], self._act_hi[i]
                               ).astype(np.float32)
        return out

    def training_step(self) -> Dict[str, Any]:
        import jax.numpy as jnp
        config: MADDPGConfig = self.config
        sigma = self._noise()
        for _ in range(config.rollout_steps_per_iteration):
            acts = self.compute_actions(self._obs, sigma)
            nxt, rewards, terms, truncs, _ = self._env.step(acts)
            terminated = bool(terms.get("__all__"))
            done = terminated or bool(truncs.get("__all__"))
            self._episode_reward += float(sum(rewards.values()))
            row = {"rewards": np.asarray(
                [rewards[a] for a in self.agent_ids], np.float32),
                "dones": np.asarray([float(terminated)], np.float32)}
            for j, aid in enumerate(self.agent_ids):
                row[f"obs_{j}"] = np.asarray(self._obs[aid], np.float32)
                row[f"act_{j}"] = acts[aid]
                # nxt is always a valid observation; terminated rows are
                # masked out of the bootstrap by "dones", and truncated
                # rows NEED the real post-step obs to bootstrap through.
                row[f"new_obs_{j}"] = np.asarray(nxt[aid], np.float32)
            self._buffer.add(SampleBatch(
                {k: np.asarray(v)[None] for k, v in row.items()}))
            self._timesteps_total += 1
            if done:
                self._episode_rewards.append(self._episode_reward)
                self._episode_reward = 0.0
                self._obs, _ = self._env.reset()
            else:
                self._obs = nxt

        closses, alosses = [], []
        if len(self._buffer) >= max(
                config.num_steps_sampled_before_learning_starts,
                config.train_batch_size):
            params, target = self.params, self._target
            a_states, c_states = self._a_states, self._c_states
            for _ in range(config.num_train_batches_per_iteration):
                sampled = self._buffer.sample(config.train_batch_size)
                mb = {k: jnp.asarray(v) for k, v in sampled.items()}
                (params, target, a_states, c_states, cl, al) = \
                    self._update_jit(params, target, a_states,
                                     c_states, mb)
                closses.append(float(cl))
                alosses.append(float(al))
            self.params, self._target = params, target
            self._a_states, self._c_states = a_states, c_states

        window = self._episode_rewards[-100:]
        return {
            "critic_loss": float(np.mean(closses)) if closses else
            float("nan"),
            "actor_loss": float(np.mean(alosses)) if alosses else
            float("nan"),
            "noise_sigma": sigma,
            "episode_reward_mean": (float(np.mean(window)) if window
                                    else float("nan")),
            "episodes_total": len(self._episode_rewards),
        }

    def get_weights(self):
        import jax
        return {"maddpg_params": jax.tree.map(np.asarray, self.params),
                "maddpg_target": jax.tree.map(np.asarray, self._target)}

    def set_weights(self, weights) -> None:
        import jax
        import jax.numpy as jnp
        self.params = jax.tree.map(jnp.asarray, weights["maddpg_params"])
        self._target = jax.tree.map(jnp.asarray,
                                    weights["maddpg_target"])

    def stop(self) -> None:
        close = getattr(self._env, "close", None)
        if callable(close):
            close()
