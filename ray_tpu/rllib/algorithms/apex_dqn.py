"""APEX-DQN: distributed prioritized experience replay.

Analog of the reference's rllib/algorithms/apex_dqn (Horgan et al. 2018):
a fleet of exploration actors, each with a FIXED epsilon from the APEX
ladder 0.4^(1 + 7i/(N-1)) (per_worker_epsilon — the broadcast schedule is
ignored), feeding a central prioritized replay buffer; the learner runs
double + dueling DQN on 3-step returns with priority updates. The
reference dedicates replay-shard actors because its learner is remote
from its buffers; here the learner owns the buffer, so APEX reduces to
the DQN engine under its distributed configuration — same sampling
topology, same update.
"""

from __future__ import annotations

from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig


class ApexDQNConfig(DQNConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or ApexDQN)
        self.num_rollout_workers = 4
        self.per_worker_epsilon = True
        self.prioritized_replay = True
        self.double_q = True
        self.dueling = True
        self.n_step = 3
        self.replay_buffer_capacity = 200_000
        self.num_steps_sampled_before_learning_starts = 2000
        self.target_network_update_freq = 500
        self.train_batch_size = 64


class ApexDQN(DQN):
    _default_config_class = ApexDQNConfig
