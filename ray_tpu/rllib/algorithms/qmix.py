"""QMIX: monotonic value factorization for cooperative multi-agent RL.

Analog of the reference's rllib/algorithms/qmix (Rashid et al. 2018):
each agent has a utility network Q_i(o_i, a_i) (parameter-shared, agent
id one-hot appended); a MIXING network combines them into
Q_tot(s, a_1..a_n) with weights produced by hypernetworks of the global
state and constrained positive (abs), making Q_tot monotone in every
Q_i — so per-agent greedy argmax IS the joint greedy action, while
credit assignment trains through the team reward.

Env contract: a MultiAgentEnv whose agents act simultaneously with a
shared Discrete action space; the global state is the concatenation of
agent observations (the standard fallback when the env exposes none).
Collection is in-algorithm (one env, epsilon-greedy per agent): joint
transitions must stay synchronized, which the per-policy rollout workers
deliberately do not guarantee.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.policy.sample_batch import SampleBatch
from ray_tpu.rllib.utils.replay_buffers import ReplayBuffer


class QMixConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or QMix)
        self.lr = 5e-4
        self.train_batch_size = 32
        self.mixing_embed_dim = 32
        self.replay_buffer_capacity = 5000   # joint transitions
        self.num_steps_sampled_before_learning_starts = 200
        self.num_train_batches_per_iteration = 32
        self.target_network_update_freq = 100
        self.epsilon_initial = 1.0
        self.epsilon_final = 0.05
        self.epsilon_timesteps = 4000
        self.rollout_steps_per_iteration = 200
        self.double_q = True

    def training(self, *, mixing_embed_dim=None,
                 replay_buffer_capacity=None,
                 num_steps_sampled_before_learning_starts=None,
                 num_train_batches_per_iteration=None,
                 target_network_update_freq=None, epsilon_timesteps=None,
                 rollout_steps_per_iteration=None, double_q=None,
                 **kwargs) -> "QMixConfig":
        super().training(**kwargs)
        for name, val in (
                ("mixing_embed_dim", mixing_embed_dim),
                ("replay_buffer_capacity", replay_buffer_capacity),
                ("num_steps_sampled_before_learning_starts",
                 num_steps_sampled_before_learning_starts),
                ("num_train_batches_per_iteration",
                 num_train_batches_per_iteration),
                ("target_network_update_freq",
                 target_network_update_freq),
                ("epsilon_timesteps", epsilon_timesteps),
                ("rollout_steps_per_iteration",
                 rollout_steps_per_iteration),
                ("double_q", double_q)):
            if val is not None:
                setattr(self, name, val)
        return self


class QMix(Algorithm):
    _default_config_class = QMixConfig
    _own_rollout_actors = True
    _supports_multi_agent = True

    def setup(self, config: QMixConfig) -> None:
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.rllib.models.catalog import mlp_apply, mlp_init

        env = self._env_creator(config.env_config)
        self._env = env
        obs0, _ = env.reset(seed=config.seed)
        self.agent_ids: List[str] = sorted(obs0.keys())
        self.n_agents = len(self.agent_ids)
        any_id = self.agent_ids[0]
        self.obs_dim = int(np.prod(
            env.observation_space_for(any_id).shape))
        self.n_actions = int(env.action_space_for(any_id).n)
        self.state_dim = self.obs_dim * self.n_agents
        in_dim = self.obs_dim + self.n_agents  # + agent-id one-hot
        embed = config.mixing_embed_dim
        hiddens = list(config.fcnet_hiddens)

        key = jax.random.PRNGKey(config.seed)
        ks = jax.random.split(key, 6)
        n, a = self.n_agents, self.n_actions
        self.params = {
            # Shared per-agent utility net.
            "q": mlp_init(ks[0], [in_dim, *hiddens, a]),
            # Hypernetworks from the global state.
            "hyper_w1": mlp_init(ks[1], [self.state_dim, n * embed]),
            "hyper_b1": mlp_init(ks[2], [self.state_dim, embed]),
            "hyper_w2": mlp_init(ks[3], [self.state_dim, embed]),
            "hyper_b2": mlp_init(ks[4], [self.state_dim, embed, 1]),
        }
        self._target = jax.tree.map(jnp.asarray, self.params)
        self._optimizer = optax.adam(config.lr)
        self._opt_state = self._optimizer.init(self.params)
        eye = np.eye(self.n_agents, dtype=np.float32)
        self._agent_onehot = eye

        def agent_qs(params, obs_all):
            """obs_all [B, n, obs_dim] -> per-agent q [B, n, A]."""
            ids = jnp.broadcast_to(
                jnp.asarray(eye), obs_all.shape[:-1] + (n,))
            x = jnp.concatenate([obs_all, ids], axis=-1)
            return mlp_apply(params["q"], x)

        def mix(params, qs_taken, state):
            """qs_taken [B, n], state [B, state_dim] -> Q_tot [B].
            Monotone: mixing weights pass through abs()."""
            w1 = jnp.abs(mlp_apply(params["hyper_w1"], state)).reshape(
                (-1, n, embed))
            b1 = mlp_apply(params["hyper_b1"], state)
            hidden = jax.nn.elu(
                jnp.einsum("bn,bne->be", qs_taken, w1) + b1)
            w2 = jnp.abs(mlp_apply(params["hyper_w2"], state))
            b2 = mlp_apply(params["hyper_b2"], state)[..., 0]
            return (hidden * w2).sum(-1) + b2

        self._agent_qs = jax.jit(agent_qs)
        gamma = config.gamma
        double_q = config.double_q

        def loss_fn(params, target_params, mb):
            qs = agent_qs(params, mb["obs"])              # [B, n, A]
            q_taken = jnp.take_along_axis(
                qs, mb["actions"][..., None].astype(jnp.int32),
                -1)[..., 0]                               # [B, n]
            q_tot = mix(params, q_taken, mb["state"])
            qs_next_t = agent_qs(target_params, mb["new_obs"])
            if double_q:
                a_star = agent_qs(params, mb["new_obs"]).argmax(-1)
            else:
                a_star = qs_next_t.argmax(-1)
            q_next = jnp.take_along_axis(
                qs_next_t, a_star[..., None], -1)[..., 0]
            q_tot_next = mix(target_params, q_next, mb["new_state"])
            target = mb["rewards"] + gamma * (1.0 - mb["dones"]) * \
                q_tot_next
            td = q_tot - jax.lax.stop_gradient(target)
            return (td ** 2).mean(), td

        def update(params, target_params, opt_state, mb):
            (loss, _), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, target_params, mb)
            updates, opt_state = self._optimizer.update(grads, opt_state,
                                                        params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        self._update_jit = jax.jit(update)
        self._rng = np.random.default_rng(config.seed)
        self._buffer = ReplayBuffer(config.replay_buffer_capacity,
                                    seed=config.seed)
        self._grad_steps = 0
        self._obs = obs0
        self._episode_reward = 0.0
        self._episode_rewards: List[float] = []

    # -- collection ------------------------------------------------------

    def _epsilon(self) -> float:
        c: QMixConfig = self.config
        frac = min(1.0, self._timesteps_total / max(c.epsilon_timesteps, 1))
        return c.epsilon_initial + frac * (c.epsilon_final
                                           - c.epsilon_initial)

    def _obs_matrix(self, obs_dict) -> np.ndarray:
        return np.stack([np.asarray(obs_dict[aid], np.float32).reshape(-1)
                         for aid in self.agent_ids])

    def _act(self, obs_mat: np.ndarray, epsilon: float) -> np.ndarray:
        import jax.numpy as jnp
        qs = np.asarray(self._agent_qs(self.params,
                                       jnp.asarray(obs_mat[None])))[0]
        greedy = qs.argmax(-1)
        explore = self._rng.random(self.n_agents) < epsilon
        rand = self._rng.integers(0, self.n_actions, self.n_agents)
        return np.where(explore, rand, greedy)

    def training_step(self) -> Dict[str, Any]:
        import jax.numpy as jnp
        config: QMixConfig = self.config
        eps = self._epsilon()
        for _ in range(config.rollout_steps_per_iteration):
            obs_mat = self._obs_matrix(self._obs)
            acts = self._act(obs_mat, eps)
            action_dict = {aid: int(a)
                           for aid, a in zip(self.agent_ids, acts)}
            nxt, rewards, terms, truncs, _ = self._env.step(action_dict)
            terminated = bool(terms.get("__all__"))
            # Truncation ends the EPISODE but not the TASK: the TD
            # target still bootstraps through it (matching the
            # single-agent stack's terminateds/truncateds split).
            done = terminated or bool(truncs.get("__all__"))
            team_r = float(sum(rewards.values()))
            self._episode_reward += team_r
            if done:
                nxt_mat = obs_mat  # episode over: next state unused
            else:
                nxt_mat = self._obs_matrix(nxt)
            row = {"obs": obs_mat, "actions": acts,
                   "rewards": np.float32(team_r),
                   "dones": np.float32(terminated),
                   "state": obs_mat.reshape(-1),
                   "new_obs": nxt_mat,
                   "new_state": nxt_mat.reshape(-1)}
            self._buffer.add(SampleBatch(
                {k: np.asarray(v)[None] for k, v in row.items()}))
            self._timesteps_total += 1
            if done:
                self._episode_rewards.append(self._episode_reward)
                self._episode_reward = 0.0
                self._obs, _ = self._env.reset()
            else:
                self._obs = nxt

        losses = []
        if len(self._buffer) >= max(
                config.num_steps_sampled_before_learning_starts,
                config.train_batch_size):
            params = self.params
            for _ in range(config.num_train_batches_per_iteration):
                sampled = self._buffer.sample(config.train_batch_size)
                mb = {k: jnp.asarray(v) for k, v in sampled.items()}
                params, self._opt_state, loss = self._update_jit(
                    params, self._target, self._opt_state, mb)
                losses.append(float(loss))
                self._grad_steps += 1
                if self._grad_steps % \
                        config.target_network_update_freq == 0:
                    import jax
                    self._target = jax.tree.map(jnp.asarray, params)
            self.params = params

        window = self._episode_rewards[-100:]
        return {
            "loss": float(np.mean(losses)) if losses else float("nan"),
            "epsilon": eps,
            "episode_reward_mean": (float(np.mean(window)) if window
                                    else float("nan")),
            "episodes_total": len(self._episode_rewards),
        }

    def get_weights(self):
        """Checkpoint payload (Algorithm.save): the LEARNED state — the
        shared utility net, hypernet mixer, and target copy — not the
        unused probe policy."""
        import jax
        return {"qmix_params": jax.tree.map(np.asarray, self.params),
                "qmix_target": jax.tree.map(np.asarray, self._target)}

    def set_weights(self, weights) -> None:
        import jax
        import jax.numpy as jnp
        self.params = jax.tree.map(jnp.asarray, weights["qmix_params"])
        self._target = jax.tree.map(jnp.asarray, weights["qmix_target"])

    def compute_joint_action(self, obs_dict) -> Dict[str, int]:
        """Greedy joint action (monotonicity makes per-agent argmax the
        joint argmax)."""
        acts = self._act(self._obs_matrix(obs_dict), 0.0)
        return {aid: int(a) for aid, a in zip(self.agent_ids, acts)}

    def stop(self) -> None:
        close = getattr(self._env, "close", None)
        if callable(close):
            close()
