"""RandomAgent: the uniform-random baseline.

Analog of the reference's rllib/algorithms/random_agent.py: samples
actions uniformly from the action space and reports episode statistics —
the canonical sanity baseline for new environments and the zero point
for learning-curve gates (every tuned-example threshold in
tuned_examples/__init__.py is quoted against it).
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig


class RandomAgentConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or RandomAgent)
        self.rollout_steps_per_iteration = 1000

    def training(self, *, rollout_steps_per_iteration=None, **kwargs
                 ) -> "RandomAgentConfig":
        super().training(**kwargs)
        if rollout_steps_per_iteration is not None:
            self.rollout_steps_per_iteration = rollout_steps_per_iteration
        return self


class RandomAgent(Algorithm):
    _default_config_class = RandomAgentConfig
    _own_rollout_actors = True

    def setup(self, config: RandomAgentConfig) -> None:
        self._env = self._env_creator(config.env_config)
        self._env.action_space.seed(config.seed)
        self._obs, _ = self._env.reset(seed=config.seed)
        self._episode_reward = 0.0
        self._episode_rewards: List[float] = []

    def training_step(self) -> Dict[str, Any]:
        config: RandomAgentConfig = self.config
        for _ in range(config.rollout_steps_per_iteration):
            obs, r, term, trunc, _ = self._env.step(
                self._env.action_space.sample())
            self._episode_reward += float(r)
            self._timesteps_total += 1
            if term or trunc:
                self._episode_rewards.append(self._episode_reward)
                self._episode_reward = 0.0
                self._obs, _ = self._env.reset()
            else:
                self._obs = obs
        window = self._episode_rewards[-100:]
        return {
            "episode_reward_mean": (float(np.mean(window)) if window
                                    else float("nan")),
            "episodes_total": len(self._episode_rewards),
        }

    def get_weights(self):
        return {}

    def set_weights(self, weights) -> None:
        pass

    def stop(self) -> None:
        close = getattr(self._env, "close", None)
        if callable(close):
            close()
