"""AlphaStar: league-based self-play training.

Analog of the reference's rllib/algorithms/alpha_star/ (alpha_star.py +
league_builder.py AlphaStarLeagueBuilder): a LEAGUE of policies trains by
playing matches against each other in a two-player zero-sum
MultiAgentEnv —

* **main** — the flagship: plays PFSP matches against frozen league
  snapshots (and self-play); snapshots itself into the league when its
  league win-rate crosses ``win_rate_threshold_for_new_snapshot``.
* **main exploiters** — train ONLY against the learning main (finding its
  current weaknesses); snapshot-and-reset when they beat it reliably.
* **league exploiters** — train against PFSP over the whole league
  (finding global holes); snapshot when they beat the league.

Matchmaking probabilities and the snapshot threshold mirror the
reference's league builder knobs. PFSP (prioritized fictitious self-play)
weights opponents by how HARD they are for the learner —
(1 - win_rate)^2 — so training focuses where it loses.

TPU-first shape: one process owns every league policy (flax params are
cheap to hold; the big win is the shared jitted PPO update compiled ONCE
and reused by all learners), matches run on the driver; the learner SGD
is the same fused program PPO uses, so a ``learner_backend`` pushes all
league learning onto the chip.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.rllib.algorithms.ppo import PPO, PPOConfig
from ray_tpu.rllib.policy import make_policy
from ray_tpu.rllib.policy.jax_policy import compute_gae
from ray_tpu.rllib.policy.sample_batch import SampleBatch

_ROW_KEYS = (SampleBatch.OBS, SampleBatch.NEXT_OBS, SampleBatch.ACTIONS,
             SampleBatch.REWARDS, SampleBatch.TERMINATEDS,
             SampleBatch.TRUNCATEDS, SampleBatch.ACTION_LOGP,
             SampleBatch.VF_PREDS, SampleBatch.EPS_ID)


class AlphaStarConfig(PPOConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or AlphaStar)
        self.num_rollout_workers = 0
        # League composition (reference: league_builder.py knobs).
        self.num_main_exploiters = 1
        self.num_league_exploiters = 1
        self.win_rate_threshold_for_new_snapshot = 0.7
        self.prob_league_exploiter_match = 0.33
        self.prob_main_exploiter_match = 0.33
        self.prob_exploiter_vs_learning_main = 0.5
        self.matches_per_iteration = 16
        self.win_rate_ema = 0.15
        self.max_league_size = 12

    def league(self, *, num_main_exploiters=None,
               num_league_exploiters=None,
               win_rate_threshold_for_new_snapshot=None,
               prob_league_exploiter_match=None,
               prob_main_exploiter_match=None,
               prob_exploiter_vs_learning_main=None,
               matches_per_iteration=None, win_rate_ema=None,
               max_league_size=None, **_ignored) -> "AlphaStarConfig":
        for name, val in locals().items():
            if name not in ("self", "_ignored") and val is not None:
                setattr(self, name, val)
        return self


class AlphaStar(PPO):
    """League self-play on a two-player zero-sum MultiAgentEnv whose
    agents are ``p0``/``p1``."""

    _default_config_class = AlphaStarConfig
    _own_rollout_actors = True  # matches run on the driver's league loop

    def setup(self, config: AlphaStarConfig) -> None:
        import jax
        env = self._env_creator(config.env_config or {})
        if not {"p0", "p1"} <= set(getattr(env, "agent_ids", set())):
            raise ValueError(
                "AlphaStar needs a two-player MultiAgentEnv with agents "
                "'p0' and 'p1'")
        self._match_env = env
        obs_space = env.observation_space
        act_space = env.action_space
        pcfg = config.policy_config()

        def new_policy(seed):
            return make_policy(pcfg, obs_space, act_space, seed=seed)

        # Learning side of the league.
        self.learning: Dict[str, Any] = {"main": new_policy(config.seed)}
        for i in range(config.num_main_exploiters):
            self.learning[f"main_exploiter_{i}"] = new_policy(
                config.seed + 101 + i)
        for i in range(config.num_league_exploiters):
            self.learning[f"league_exploiter_{i}"] = new_policy(
                config.seed + 202 + i)
        # Shared fused PPO update: every policy has the same network
        # shape, so ONE jitted program serves all learners.
        self._updates = {}
        self._opt_states = {}
        for pid, policy in self.learning.items():
            self._updates[pid], self._opt_states[pid] = \
                self._build_update(policy, config)
        # Frozen league: starts with a snapshot of the initial main.
        self.league: Dict[str, Any] = {
            "main_v0": jax.tree.map(np.asarray,
                                    self.learning["main"].get_weights())}
        self._frozen_policy = new_policy(config.seed + 999)  # evaluator
        # EMA win-rates per (learner, opponent-name) pair.
        self.win_rates: Dict[Tuple[str, str], float] = {}
        self._snapshot_counter = {"main": 0}
        self._rng = np.random.default_rng(config.seed)
        self._key = jax.random.PRNGKey(config.seed ^ 0xA57A)

    # -- matchmaking -----------------------------------------------------

    def _pfsp_pick(self, learner: str,
                   candidates: List[str]) -> str:
        """Prioritized fictitious self-play: weight opponents by how
        often they BEAT the learner — (1 - p_win)^2 (reference:
        AlphaStar PFSP hard-opponent weighting)."""
        weights = np.array([
            (1.0 - self.win_rates.get((learner, c), 0.5)) ** 2 + 1e-3
            for c in candidates])
        return candidates[int(self._rng.choice(
            len(candidates), p=weights / weights.sum()))]

    def _pick_opponent(self, learner: str) -> Tuple[str, bool]:
        """Returns (opponent_name, opponent_is_learning)."""
        cfg: AlphaStarConfig = self.config
        snapshots = list(self.league)
        if learner.startswith("main_exploiter"):
            # Main exploiters hunt the LEARNING main (sometimes its
            # snapshots, so they generalize a little).
            if self._rng.random() < cfg.prob_exploiter_vs_learning_main:
                return "main", True
            mains = [s for s in snapshots if s.startswith("main_v")]
            return self._pfsp_pick(learner, mains or snapshots), False
        if learner.startswith("league_exploiter"):
            return self._pfsp_pick(learner, snapshots), False
        # The main: mostly PFSP vs league, sometimes pure self-play.
        r = self._rng.random()
        if r < cfg.prob_league_exploiter_match + \
                cfg.prob_main_exploiter_match:
            return self._pfsp_pick(learner, snapshots), False
        return "main", True

    def _opponent_policy(self, name: str, is_learning: bool):
        if is_learning:
            return self.learning[name]
        self._frozen_policy.set_weights(self.league[name])
        return self._frozen_policy

    # -- match loop ------------------------------------------------------

    def _play_match(self, learner_pid: str, learner_policy,
                    opponent_policy) -> Tuple[SampleBatch, float]:
        """One episode, learner as a random side; returns the learner's
        transition batch and its total (zero-sum) score."""
        import jax
        cfg: AlphaStarConfig = self.config
        env = self._match_env
        side = "p0" if self._rng.random() < 0.5 else "p1"
        other = "p1" if side == "p0" else "p0"
        pols = {side: learner_policy, other: opponent_policy}
        rows = {k: [] for k in _ROW_KEYS}
        obs, _ = env.reset()
        score = 0.0
        eps_id = int(self._rng.integers(1 << 31))
        done = False
        while not done:
            actions = {}
            meta = None
            for agent, pol in pols.items():
                if agent not in obs:
                    continue
                arr = np.asarray(obs[agent], np.float32)
                self._key, sub = jax.random.split(self._key)
                action, logp, value = pol.compute_actions(arr[None], sub)
                act = action[0]
                actions[agent] = (int(act) if pol.discrete
                                  else np.asarray(act))
                if agent == side:
                    meta = (arr, act, float(logp[0]), float(value[0]))
            nxt, rewards, terms, truncs, _ = env.step(actions)
            done = bool(terms.get("__all__") or truncs.get("__all__"))
            if meta is not None:
                arr, act, logp, value = meta
                reward = float(rewards.get(side, 0.0))
                score += reward
                rows[SampleBatch.OBS].append(arr)
                rows[SampleBatch.NEXT_OBS].append(
                    np.asarray(nxt.get(side, arr), np.float32))
                rows[SampleBatch.ACTIONS].append(act)
                rows[SampleBatch.REWARDS].append(np.float32(reward))
                rows[SampleBatch.TERMINATEDS].append(np.float32(done))
                rows[SampleBatch.TRUNCATEDS].append(np.float32(0.0))
                rows[SampleBatch.ACTION_LOGP].append(np.float32(logp))
                rows[SampleBatch.VF_PREDS].append(np.float32(value))
                rows[SampleBatch.EPS_ID].append(eps_id)
            obs = nxt
        batch = SampleBatch({k: np.asarray(v) for k, v in rows.items()})
        batch = compute_gae(batch, cfg.gamma, cfg.lambda_, 0.0)
        return batch, score

    def _note_result(self, learner: str, opponent: str,
                     score: float) -> None:
        cfg: AlphaStarConfig = self.config
        won = 1.0 if score > 0 else (0.5 if score == 0 else 0.0)
        key = (learner, opponent)
        prev = self.win_rates.get(key, 0.5)
        self.win_rates[key] = (1 - cfg.win_rate_ema) * prev + \
            cfg.win_rate_ema * won

    # -- league building -------------------------------------------------

    def _league_win_rate(self, learner: str) -> float:
        rates = [r for (lp, _op), r in self.win_rates.items()
                 if lp == learner]
        return float(np.mean(rates)) if rates else 0.5

    def _build_league(self) -> List[str]:
        """Snapshot learners that beat their opposition (reference:
        league_builder.build_league): main adds a copy and keeps
        learning; exploiters add a copy and RESET (hunt afresh)."""
        import jax
        cfg: AlphaStarConfig = self.config
        added = []
        for pid, policy in self.learning.items():
            if len(self.league) >= cfg.max_league_size:
                break  # cap holds even when several learners qualify
            if self._league_win_rate(pid) < \
                    cfg.win_rate_threshold_for_new_snapshot:
                continue
            base = "main" if pid == "main" else pid
            self._snapshot_counter[base] = \
                self._snapshot_counter.get(base, 0) + 1
            name = f"{base}_v{self._snapshot_counter[base]}"
            self.league[name] = jax.tree.map(np.asarray,
                                             policy.get_weights())
            added.append(name)
            for key in [k for k in self.win_rates if k[0] == pid]:
                del self.win_rates[key]  # fresh slate vs new opposition
            if pid != "main":
                # Exploiters restart from scratch after a successful hunt.
                fresh = make_policy(
                    cfg.policy_config(),
                    self._match_env.observation_space,
                    self._match_env.action_space,
                    seed=int(self._rng.integers(1 << 30)))
                policy.set_weights(fresh.get_weights())
                self._opt_states[pid] = self._build_update(
                    policy, cfg)[1]
        return added

    # -- Trainable -------------------------------------------------------

    def training_step(self) -> Dict[str, Any]:
        cfg: AlphaStarConfig = self.config
        results: Dict[str, Any] = {}
        for pid, policy in self.learning.items():
            parts, scores = [], []
            for _ in range(cfg.matches_per_iteration):
                opp, opp_learning = self._pick_opponent(pid)
                batch, score = self._play_match(
                    pid, policy, self._opponent_policy(opp, opp_learning))
                if len(batch):
                    parts.append(batch)
                scores.append(score)
                if opp != pid:
                    # Matches against the LEARNING main count too — a
                    # main exploiter's snapshot criterion is beating the
                    # live main, not just stale snapshots. Only pure
                    # self-play (learner vs itself) is uninformative.
                    self._note_result(pid, opp, score)
            if not parts:
                continue
            batch = SampleBatch.concat_samples(parts)
            self._timesteps_total += len(batch)
            self._opt_states[pid], metrics = self._sgd(
                policy, self._updates[pid], self._opt_states[pid],
                batch, cfg)
            results[f"{pid}/mean_score"] = float(np.mean(scores))
            results[f"{pid}/league_win_rate"] = self._league_win_rate(pid)
            for k, v in metrics.items():
                results[f"{pid}/{k}"] = v
        added = self._build_league()
        results["league_size"] = len(self.league)
        results["league_added"] = added
        results["win_rates"] = {f"{a} vs {b}": round(r, 3)
                                for (a, b), r in self.win_rates.items()}
        return results

    def win_rate_vs(self, snapshot: str, episodes: int = 50) -> float:
        """Evaluation: learning main's empirical win-rate against a
        frozen league snapshot."""
        wins = 0.0
        for _ in range(episodes):
            _, score = self._play_match(
                "main", self.learning["main"],
                self._opponent_policy(snapshot, False))
            wins += 1.0 if score > 0 else (0.5 if score == 0 else 0.0)
        return wins / episodes

    def get_weights(self):
        """save()/restore() round-trip the WHOLE league state (the base
        contract pickles get_weights; a bare main-params dict would lose
        the frozen snapshots and win matrix)."""
        import jax
        return {
            "learning": {pid: jax.tree.map(np.asarray, p.get_weights())
                         for pid, p in self.learning.items()},
            "league": self.league,
            "win_rates": dict(self.win_rates),
            "snapshot_counter": dict(self._snapshot_counter),
        }

    def set_weights(self, state: Dict[str, Any]) -> None:
        for pid, w in state["learning"].items():
            if pid in self.learning:
                self.learning[pid].set_weights(w)
        self.league = dict(state["league"])
        self.win_rates = dict(state["win_rates"])
        self._snapshot_counter = dict(state["snapshot_counter"])
