"""SlateQ: reinforcement learning for slate-based recommendation.

Analog of the reference's rllib/algorithms/slateq (Ie et al. 2019,
"SlateQ: A Tractable Decomposition for Reinforcement Learning with
Recommendation Sets"): the combinatorial slate action space is made
tractable by decomposing the slate's value under a conditional-logit
user-choice model into per-item long-term values:

    Q(s, A) = sum_{i in A} P(click i | s, A) * Q_item(s, i)

Two networks are learned jointly from logged interactions:
  * a **choice model** ``v(s, doc)`` trained by cross-entropy on which
    slate item the user actually clicked (the no-click option is a
    constant-logit outside option, matching the env's ground truth), and
  * an **item-level Q** ``Q_item(s, doc)`` trained by TD: on a click of
    doc ``d``, target ``r + gamma * max_A' Q(s', A')`` where the max
    enumerates all candidate slates using the decomposition (exact for
    the default 10-choose-3 = 120 slates; the reference's policy likewise
    scores all slates, slateq_tf_policy.py).

Collection is in-algorithm (epsilon-greedy over the decomposed argmax
slate) because slates of distinct indices do not fit the shared rollout
workers' Discrete/Box policy contract — same stance as QMIX's joint
collection (qmix.py).
"""

from __future__ import annotations

from itertools import combinations
from typing import Any, Dict, List

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.policy.sample_batch import SampleBatch
from ray_tpu.rllib.utils.replay_buffers import ReplayBuffer


class SlateQConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or SlateQ)
        self.lr = 1e-3
        self.lr_choice_model = 1e-3  # reference SlateQConfig knob
        self.train_batch_size = 64
        self.replay_buffer_capacity = 20_000
        self.num_steps_sampled_before_learning_starts = 500
        self.num_train_batches_per_iteration = 64
        self.target_network_update_freq = 200
        self.epsilon_initial = 1.0
        self.epsilon_final = 0.05
        self.epsilon_timesteps = 3000
        self.rollout_steps_per_iteration = 500
        self.fcnet_hiddens_per_candidate = (64, 32)  # reference knob

    def training(self, *, lr_choice_model=None,
                 replay_buffer_capacity=None,
                 num_steps_sampled_before_learning_starts=None,
                 num_train_batches_per_iteration=None,
                 target_network_update_freq=None, epsilon_timesteps=None,
                 rollout_steps_per_iteration=None,
                 fcnet_hiddens_per_candidate=None,
                 **kwargs) -> "SlateQConfig":
        super().training(**kwargs)
        for name, val in (
                ("lr_choice_model", lr_choice_model),
                ("replay_buffer_capacity", replay_buffer_capacity),
                ("num_steps_sampled_before_learning_starts",
                 num_steps_sampled_before_learning_starts),
                ("num_train_batches_per_iteration",
                 num_train_batches_per_iteration),
                ("target_network_update_freq", target_network_update_freq),
                ("epsilon_timesteps", epsilon_timesteps),
                ("rollout_steps_per_iteration",
                 rollout_steps_per_iteration),
                ("fcnet_hiddens_per_candidate",
                 fcnet_hiddens_per_candidate)):
            if val is not None:
                setattr(self, name, val)
        return self


class SlateQ(Algorithm):
    _default_config_class = SlateQConfig
    _own_rollout_actors = True

    def setup(self, config: SlateQConfig) -> None:
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.rllib.models.catalog import mlp_apply, mlp_init

        env = self._env_creator(config.env_config)
        self._env = env
        self.C = env.num_candidates
        self.k = env.slate_size
        self.T = env.num_topics
        self.doc_dim = self.T + 1
        self.no_click_score = env.no_click_score
        #: all unordered slates, [S, k] — the exact argmax domain.
        self.slates = np.asarray(
            list(combinations(range(self.C), self.k)), np.int32)

        per_cand = list(config.fcnet_hiddens_per_candidate)
        in_dim = self.T + self.doc_dim  # user ++ one doc's features
        key = jax.random.PRNGKey(config.seed)
        kq, kv = jax.random.split(key)
        self.params = {
            "q": mlp_init(kq, [in_dim, *per_cand, 1]),
            "choice": mlp_init(kv, [in_dim, *per_cand, 1]),
        }
        self._target = jax.tree.map(jnp.asarray, self.params)
        self._optimizer = optax.multi_transform(
            {"q": optax.adam(config.lr),
             "choice": optax.adam(config.lr_choice_model)},
            {"q": "q", "choice": "choice"})
        self._opt_state = self._optimizer.init(self.params)
        gamma = config.gamma
        slates = jnp.asarray(self.slates)            # [S, k]
        ncs = float(self.no_click_score)

        def per_item(net, user, docs):
            """user [B,T], docs [B,C,doc_dim] -> [B,C] scalars."""
            u = jnp.broadcast_to(user[:, None, :],
                                 (user.shape[0], docs.shape[1],
                                  user.shape[1]))
            x = jnp.concatenate([u, docs], -1)
            return mlp_apply(net, x)[..., 0]

        def slate_values(params, user, docs):
            """Decomposed Q(s, A) for every slate A -> [B, S]."""
            q = per_item(params["q"], user, docs)        # [B, C]
            v = per_item(params["choice"], user, docs)   # [B, C]
            qs = q[:, slates]                            # [B, S, k]
            vs = v[:, slates]                            # [B, S, k]
            logits = jnp.concatenate(
                [vs, jnp.full(vs.shape[:-1] + (1,), ncs)], -1)
            p = jax.nn.softmax(logits, -1)[..., :-1]     # click probs
            return (p * qs).sum(-1)                      # [B, S]

        def loss_fn(params, target_params, mb):
            user, docs = mb["user"], mb["docs"]
            # Choice-model cross-entropy on observed clicks (null = k).
            v = per_item(params["choice"], user, docs)   # [B, C]
            vslate = jnp.take_along_axis(v, mb["slate"], -1)  # [B, k]
            logits = jnp.concatenate(
                [vslate, jnp.full((v.shape[0], 1), ncs)], -1)
            logp = jax.nn.log_softmax(logits, -1)
            pick = mb["pick"][:, 0]                      # k == null
            choice_loss = -jnp.take_along_axis(
                logp, pick[:, None], -1)[:, 0].mean()
            # Item-level TD on the clicked doc only (no click => no
            # item-level credit, per the paper's decomposition).
            q = per_item(params["q"], user, docs)
            clicked_doc = jnp.take_along_axis(
                mb["slate"], jnp.minimum(pick, self.k - 1)[:, None], -1)
            q_taken = jnp.take_along_axis(q, clicked_doc, -1)[:, 0]
            next_best = slate_values(
                target_params, mb["next_user"], mb["next_docs"]).max(-1)
            target = mb["rewards"][:, 0] + gamma * \
                (1.0 - mb["dones"][:, 0]) * next_best
            clicked = (pick < self.k).astype(jnp.float32)
            td = (q_taken - jax.lax.stop_gradient(target)) * clicked
            q_loss = (td ** 2).sum() / jnp.maximum(clicked.sum(), 1.0)
            return q_loss + choice_loss, (q_loss, choice_loss)

        def update(params, target_params, opt_state, mb):
            (_, (ql, cl)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, target_params, mb)
            updates, opt_state = self._optimizer.update(
                grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, ql, cl

        def greedy_slate(params, user, docs):
            return slate_values(params, user, docs).argmax(-1)

        self._update_jit = jax.jit(update)
        self._greedy_jit = jax.jit(greedy_slate)
        self._slate_values_jit = jax.jit(slate_values)
        self._rng = np.random.default_rng(config.seed)
        self._buffer = ReplayBuffer(config.replay_buffer_capacity,
                                    seed=config.seed)
        self._grad_steps = 0
        self._obs, _ = env.reset(seed=config.seed)
        self._episode_reward = 0.0
        self._episode_rewards: List[float] = []

    # -- acting ----------------------------------------------------------

    def _epsilon(self) -> float:
        c: SlateQConfig = self.config
        frac = min(1.0, self._timesteps_total / max(c.epsilon_timesteps, 1))
        return c.epsilon_initial + frac * (c.epsilon_final
                                           - c.epsilon_initial)

    def compute_slate(self, obs: np.ndarray, epsilon: float = 0.0
                      ) -> np.ndarray:
        """The decomposition-argmax slate (epsilon-greedy over it)."""
        if self._rng.random() < epsilon:
            return self._rng.choice(self.C, self.k, replace=False)
        user, docs = self._env.split_obs(np.asarray(obs, np.float32))
        import jax.numpy as jnp
        s = int(self._greedy_jit(self.params, jnp.asarray(user[None]),
                                 jnp.asarray(docs[None]))[0])
        return self.slates[s]

    def training_step(self) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp
        config: SlateQConfig = self.config
        eps = self._epsilon()
        for _ in range(config.rollout_steps_per_iteration):
            user, docs = self._env.split_obs(self._obs)
            slate = self.compute_slate(self._obs, eps)
            nxt, r, term, trunc, info = self._env.step(slate)
            nuser, ndocs = self._env.split_obs(nxt)
            clicked = info.get("clicked", -1)  # slate POSITION, -1=null
            pick = clicked if clicked >= 0 else self.k
            self._episode_reward += r
            row = {"user": user, "docs": docs,
                   "slate": np.asarray(slate, np.int32),
                   "pick": np.asarray([pick], np.int32),
                   "rewards": np.asarray([r], np.float32),
                   "dones": np.asarray([float(term)], np.float32),
                   "next_user": nuser, "next_docs": ndocs}
            self._buffer.add(SampleBatch(
                {k: np.asarray(v)[None] for k, v in row.items()}))
            self._timesteps_total += 1
            if term or trunc:
                self._episode_rewards.append(self._episode_reward)
                self._episode_reward = 0.0
                self._obs, _ = self._env.reset()
            else:
                self._obs = nxt

        q_losses, c_losses = [], []
        if len(self._buffer) >= max(
                config.num_steps_sampled_before_learning_starts,
                config.train_batch_size):
            params = self.params
            for _ in range(config.num_train_batches_per_iteration):
                sampled = self._buffer.sample(config.train_batch_size)
                mb = {k: jnp.asarray(v) for k, v in sampled.items()}
                params, self._opt_state, ql, cl = self._update_jit(
                    params, self._target, self._opt_state, mb)
                q_losses.append(float(ql))
                c_losses.append(float(cl))
                self._grad_steps += 1
                if self._grad_steps % \
                        config.target_network_update_freq == 0:
                    self._target = jax.tree.map(jnp.asarray, params)
            self.params = params

        window = self._episode_rewards[-100:]
        return {
            "q_loss": float(np.mean(q_losses)) if q_losses else
            float("nan"),
            "choice_loss": float(np.mean(c_losses)) if c_losses else
            float("nan"),
            "epsilon": eps,
            "episode_reward_mean": (float(np.mean(window)) if window
                                    else float("nan")),
            "episodes_total": len(self._episode_rewards),
        }

    def get_weights(self):
        import jax
        return {"slateq_params": jax.tree.map(np.asarray, self.params),
                "slateq_target": jax.tree.map(np.asarray, self._target)}

    def set_weights(self, weights) -> None:
        import jax
        import jax.numpy as jnp
        self.params = jax.tree.map(jnp.asarray, weights["slateq_params"])
        self._target = jax.tree.map(jnp.asarray,
                                    weights["slateq_target"])

    def stop(self) -> None:
        close = getattr(self._env, "close", None)
        if callable(close):
            close()
