"""AlgorithmConfig: fluent configuration (reference:
rllib/algorithms/algorithm_config.py — .environment()/.rollouts()/
.training()/.framework()/.resources() chaining, frozen into an Algorithm).
"""

from __future__ import annotations

import copy
from typing import Any, Callable, Dict, Optional, Sequence, Type


class AlgorithmConfig:
    def __init__(self, algo_class: Optional[type] = None):
        self.algo_class = algo_class
        # environment
        self.env: Any = None
        self.env_config: Dict[str, Any] = {}
        # rollouts
        self.num_rollout_workers: int = 2
        # jax platform rollout workers pin THEIR process to ("cpu" —
        # samplers never grab the learner's chip or a remote-TPU
        # tunnel; None = leave the process default alone).
        self.rollout_backend: Optional[str] = "cpu"
        self.num_envs_per_worker = 1
        self.rollout_fragment_length: int = 256
        self.num_cpus_per_worker: float = 1.0
        # training
        self.gamma: float = 0.99
        self.lr: float = 5e-4
        self.train_batch_size: int = 512
        self.fcnet_hiddens: Sequence[int] = (64, 64)
        self.seed: int = 0
        # framework (always jax here; kept for API parity)
        self.framework_str: str = "jax"
        # policy implementation (rllib/policy/__init__.py registry)
        self.policy_class_name: str = "actor_critic"
        # preprocessing / connectors
        self.observation_filter: str = "NoFilter"
        self.clip_actions: bool = True
        self.conv_filters = None
        self.post_fcnet_dim: int = 256
        # offline data (reference: rllib/offline/)
        self.output: Any = None  # dir path → rollout workers write JSON
        self.input_: Any = None  # dir path → train from offline JSON
        # evaluation
        self.evaluation_interval: int = 0
        self.evaluation_duration: int = 3
        # multi-agent (reference: AlgorithmConfig.multi_agent):
        # policies: {policy_id: (obs_space, act_space) | None (infer from
        # the env's per-agent spaces)}; policy_mapping_fn: agent_id -> pid.
        self.policies: Optional[Dict[str, Any]] = None
        self.policy_mapping_fn: Optional[Callable[[str], str]] = None
        # algo-specific fields live on subclass-free dicts
        self.extra: Dict[str, Any] = {}

    # -- fluent sections -------------------------------------------------

    def environment(self, env=None, *, env_config: Optional[dict] = None
                    ) -> "AlgorithmConfig":
        if env is not None:
            self.env = env
        if env_config is not None:
            self.env_config = dict(env_config)
        return self

    def rollouts(self, *, num_rollout_workers: Optional[int] = None,
                 rollout_fragment_length: Optional[int] = None,
                 num_envs_per_worker: Optional[int] = None,
                 rollout_backend: Any = "__unset__",
                 **_ignored) -> "AlgorithmConfig":
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        if num_envs_per_worker is not None:
            self.num_envs_per_worker = num_envs_per_worker
        # Rollout workers are CPU samplers by default (reference: rollout
        # workers on CPU nodes, the learner owns the accelerator); pass
        # rollout_backend=None to let workers take whatever jax default
        # their process has (e.g. big-batch TPU inference rollouts).
        # Sentinel, not None: None is a MEANINGFUL value here, and a
        # later unrelated .rollouts() call must not silently reset it.
        if rollout_backend != "__unset__":
            self.rollout_backend = rollout_backend
        return self

    env_runners = rollouts  # new-stack alias

    def training(self, *, gamma: Optional[float] = None,
                 lr: Optional[float] = None,
                 train_batch_size: Optional[int] = None,
                 model: Optional[dict] = None,
                 **kwargs) -> "AlgorithmConfig":
        if gamma is not None:
            self.gamma = gamma
        if lr is not None:
            self.lr = lr
        if train_batch_size is not None:
            self.train_batch_size = train_batch_size
        if model:
            if "fcnet_hiddens" in model:
                self.fcnet_hiddens = tuple(model["fcnet_hiddens"])
            if "conv_filters" in model:
                self.conv_filters = [list(f)
                                     for f in model["conv_filters"]]
            if "post_fcnet_dim" in model:
                self.post_fcnet_dim = int(model["post_fcnet_dim"])
        self.extra.update(kwargs)
        return self

    def framework(self, framework: str = "jax") -> "AlgorithmConfig":
        if framework not in ("jax", "tf2", "torch"):
            raise ValueError(framework)
        self.framework_str = "jax"  # everything compiles to XLA here
        return self

    def resources(self, **_ignored) -> "AlgorithmConfig":
        return self

    def debugging(self, *, seed: Optional[int] = None, **_ignored
                  ) -> "AlgorithmConfig":
        if seed is not None:
            self.seed = seed
        return self

    def evaluation(self, *, evaluation_interval: Optional[int] = None,
                   evaluation_duration: Optional[int] = None,
                   **_ignored) -> "AlgorithmConfig":
        if evaluation_interval is not None:
            self.evaluation_interval = evaluation_interval
        if evaluation_duration is not None:
            self.evaluation_duration = evaluation_duration
        return self

    def offline_data(self, *, output=None, input_=None,
                     **_ignored) -> "AlgorithmConfig":
        if output is not None:
            self.output = output
        if input_ is not None:
            self.input_ = input_
        return self

    def multi_agent(self, *, policies: Optional[Dict[str, Any]] = None,
                    policy_mapping_fn: Optional[Callable[[str], str]] = None,
                    **_ignored) -> "AlgorithmConfig":
        if policies is not None:
            self.policies = dict(policies)
        if policy_mapping_fn is not None:
            self.policy_mapping_fn = policy_mapping_fn
        return self

    @property
    def is_multi_agent(self) -> bool:
        return bool(self.policies)

    def exploration(self, **kwargs) -> "AlgorithmConfig":
        self.extra.update(kwargs)
        return self

    # -- build -----------------------------------------------------------

    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)

    def to_dict(self) -> Dict[str, Any]:
        d = {k: v for k, v in self.__dict__.items()
             if k not in ("algo_class",)}
        d.update(d.pop("extra"))
        return d

    def build(self, env=None):
        if env is not None:
            self.env = env
        if self.algo_class is None:
            raise ValueError("No algo_class bound to this config")
        return self.algo_class(config=self)

    def env_creator(self) -> Callable:
        env = self.env
        env_config = self.env_config

        def create(cfg):
            merged = {**env_config, **(cfg or {})}
            if callable(env) and not isinstance(env, str):
                return env(merged)
            import gymnasium as gym
            return gym.make(env)

        return create

    def policy_config(self) -> Dict[str, Any]:
        if self.is_multi_agent and self.policy_mapping_fn is None:
            raise ValueError(
                "Multi-agent configs need a policy_mapping_fn: "
                "config.multi_agent(policies=..., "
                "policy_mapping_fn=lambda agent_id: ...)")
        return {
            "policies": self.policies,
            "policy_mapping_fn": self.policy_mapping_fn,
            "gamma": self.gamma,
            "lambda": self.extra.get("lambda", 0.95),
            "fcnet_hiddens": tuple(self.fcnet_hiddens),
            "conv_filters": self.conv_filters,
            "post_fcnet_dim": self.post_fcnet_dim,
            "env_config": self.env_config,
            "policy_class": self.policy_class_name,
            "observation_filter": self.observation_filter,
            "clip_actions": self.clip_actions,
            "output": self.output,
            "num_envs_per_worker": getattr(
                self, "num_envs_per_worker", 1),
            "rollout_backend": getattr(self, "rollout_backend", "cpu"),
        }
