"""Contextual bandits: LinUCB and linear Thompson sampling.

Analog of the reference's rllib/algorithms/bandit (BanditLinUCB /
BanditLinTS, backed by rllib/utils/exploration and the contrib bandit
models): one-step decision problems where the "episode" is a single
(context, action, reward) round. Exact linear-Gaussian posteriors per
arm — closed-form sherman-morrison updates, no gradient descent — so
the learner is a pure linear-algebra loop in jax.

The env contract is gymnasium-style with one step per episode: reset()
returns the context, step(arm) returns (next_context, reward, True, ...).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig


class BanditConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or BanditLinUCB)
        self.exploration = "ucb"        # "ucb" | "ts"
        self.ucb_alpha = 1.0            # confidence width
        self.ts_sigma = 1.0             # posterior noise scale
        self.lambda_reg = 1.0           # ridge prior precision
        self.rounds_per_iteration = 100

    def training(self, *, ucb_alpha=None, ts_sigma=None, lambda_reg=None,
                 rounds_per_iteration=None, **kwargs) -> "BanditConfig":
        super().training(**kwargs)
        for name, val in (("ucb_alpha", ucb_alpha),
                          ("ts_sigma", ts_sigma),
                          ("lambda_reg", lambda_reg),
                          ("rounds_per_iteration", rounds_per_iteration)):
            if val is not None:
                setattr(self, name, val)
        return self


class _LinearPosterior:
    """Per-arm ridge posterior: A = lambda*I + sum x x^T, b = sum r x.
    Maintains A_inv incrementally (Sherman–Morrison) and caches its
    Cholesky factor for Thompson draws — refactorized lazily only after
    this arm's posterior actually changed, so TS arm selection is
    O(dim^2) per untouched arm instead of O(dim^3) for every arm every
    round."""

    def __init__(self, dim: int, lam: float):
        self.A_inv = np.eye(dim, dtype=np.float64) / lam
        self.b = np.zeros(dim, np.float64)
        self._chol: Optional[np.ndarray] = None

    @property
    def theta(self) -> np.ndarray:
        return self.A_inv @ self.b

    @property
    def chol(self) -> np.ndarray:
        if self._chol is None:
            dim = self.A_inv.shape[0]
            self._chol = np.linalg.cholesky(
                self.A_inv + 1e-12 * np.eye(dim))
        return self._chol

    def update(self, x: np.ndarray, r: float) -> None:
        Ax = self.A_inv @ x
        denom = 1.0 + float(x @ Ax)
        self.A_inv -= np.outer(Ax, Ax) / denom
        self.b += r * x
        self._chol = None


class BanditLinUCB(Algorithm):
    """LinUCB (Li et al. 2010): pick argmax_a theta_a.x +
    alpha * sqrt(x^T A_a^-1 x). With exploration="ts", linear Thompson
    sampling instead: sample theta ~ N(mean, sigma^2 A^-1) per arm."""

    _default_config_class = BanditConfig
    # Bandits sample in-process (one env step per round, closed-form
    # updates) — no rollout actors.
    _own_rollout_actors = True

    def setup(self, config: BanditConfig) -> None:
        env = self._env_creator(config.env_config)
        self._env = env
        self.n_arms = int(env.action_space.n)
        self.dim = int(np.prod(env.observation_space.shape))
        self._arms = [
            _LinearPosterior(self.dim, config.lambda_reg)
            for _ in range(self.n_arms)]
        self._rng = np.random.default_rng(config.seed)
        self._obs, _ = env.reset(seed=config.seed)
        self._total_reward = 0.0
        self._total_rounds = 0
        self._reward_window: list = []

    def _select_arm(self, x: np.ndarray) -> int:
        config: BanditConfig = self.config
        scores = np.empty(self.n_arms)
        for a, post in enumerate(self._arms):
            mean = float(post.theta @ x)
            if config.exploration == "ts":
                # Sample from the posterior: theta_s = mean + sigma * L z
                # with L the (cached) Cholesky factor of A_inv.
                z = self._rng.standard_normal(self.dim)
                theta_s = post.theta + config.ts_sigma * (post.chol @ z)
                scores[a] = float(theta_s @ x)
            else:
                width = np.sqrt(max(float(x @ post.A_inv @ x), 0.0))
                scores[a] = mean + config.ucb_alpha * width
        return int(scores.argmax())

    def training_step(self) -> Dict[str, Any]:
        config: BanditConfig = self.config
        rewards = []
        for _ in range(config.rounds_per_iteration):
            x = np.asarray(self._obs, np.float64).reshape(-1)
            arm = self._select_arm(x)
            obs, reward, terminated, truncated, _ = self._env.step(arm)
            self._arms[arm].update(x, float(reward))
            rewards.append(float(reward))
            self._obs = (self._env.reset()[0]
                         if (terminated or truncated) else obs)
        self._total_reward += sum(rewards)
        self._total_rounds += len(rewards)
        self._timesteps_total += len(rewards)
        self._reward_window.extend(rewards)
        self._reward_window = self._reward_window[-1000:]
        return {
            "episode_reward_mean": float(np.mean(self._reward_window)),
            "mean_reward_this_iter": float(np.mean(rewards)),
            "cumulative_reward": self._total_reward,
            "rounds_total": self._total_rounds,
        }

    def compute_single_action(self, obs):
        return self._select_arm(np.asarray(obs, np.float64).reshape(-1))

    def get_state(self) -> Dict[str, Any]:
        return {"arms": [(p.A_inv, p.b) for p in self._arms],
                "total_reward": self._total_reward,
                "rounds": self._total_rounds}

    def set_state(self, state: Dict[str, Any]) -> None:
        for post, (a_inv, b) in zip(self._arms, state["arms"]):
            post.A_inv = np.asarray(a_inv)
            post.b = np.asarray(b)
            post._chol = None
        self._total_reward = state["total_reward"]
        self._total_rounds = state["rounds"]

    # Algorithm.save/restore persist via get_weights/set_weights — for a
    # bandit the "weights" ARE the arm posteriors, not the unused probe
    # policy.
    def get_weights(self):
        return self.get_state()

    def set_weights(self, weights) -> None:
        self.set_state(weights)

    def stop(self) -> None:
        close = getattr(self._env, "close", None)
        if callable(close):
            close()


class BanditLinTSConfig(BanditConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or BanditLinTS)
        self.exploration = "ts"


class BanditLinTS(BanditLinUCB):
    _default_config_class = BanditLinTSConfig


class BanditLinUCBConfig(BanditConfig):
    pass
