"""TD3: twin-delayed deep deterministic policy gradient.

Analog of the reference's rllib/algorithms/td3 (built on its DDPG stack):
a deterministic actor with clipped Gaussian exploration noise, twin Q
critics with polyak targets, target-policy smoothing noise, and delayed
actor updates. Rollouts use a
deterministic actor with fixed clipped Gaussian noise (TD3Policy); the
learner update is one jitted step.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.utils.replay_buffers import (PrioritizedReplayBuffer,
                                                 ReplayBuffer)


class TD3Config(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or TD3)
        self.policy_class_name = "td3"  # deterministic + fixed noise
        self.lr = 1e-3
        self.critic_lr = 1e-3
        self.train_batch_size = 256
        self.replay_buffer_capacity = 100_000
        self.num_steps_sampled_before_learning_starts = 500
        self.num_train_batches_per_iteration = 32
        self.tau = 0.005
        self.policy_delay = 2
        self.target_noise = 0.2
        self.target_noise_clip = 0.5
        self.prioritized_replay = False
        self.prioritized_replay_alpha = 0.6
        self.prioritized_replay_beta = 0.4
        self.n_step = 1  # multi-step returns (learner bootstraps gamma^k)

    def training(self, *, tau=None, critic_lr=None, policy_delay=None,
                 target_noise=None, target_noise_clip=None,
                 replay_buffer_capacity=None,
                 num_train_batches_per_iteration=None,
                 num_steps_sampled_before_learning_starts=None,
                 prioritized_replay=None, n_step=None,
                 **kwargs) -> "TD3Config":
        super().training(**kwargs)
        for name, val in (("tau", tau), ("critic_lr", critic_lr),
                          ("policy_delay", policy_delay),
                          ("target_noise", target_noise),
                          ("target_noise_clip", target_noise_clip),
                          ("replay_buffer_capacity", replay_buffer_capacity),
                          ("num_train_batches_per_iteration",
                           num_train_batches_per_iteration),
                          ("num_steps_sampled_before_learning_starts",
                           num_steps_sampled_before_learning_starts),
                          ("prioritized_replay", prioritized_replay),
                          ("n_step", n_step)):
            if val is not None:
                setattr(self, name, val)
        return self


class TD3(Algorithm):
    _default_config_class = TD3Config

    def setup(self, config: TD3Config) -> None:
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.rllib.models.catalog import mlp_apply, mlp_init

        policy = self.local_policy
        act_dim = policy.act_dim
        low = jnp.asarray(policy.low)
        high = jnp.asarray(policy.high)

        def det_action(actor_params, obs):
            mu, _ = policy.dist_params(actor_params, obs)
            return low + (jnp.tanh(mu) + 1.0) * 0.5 * (high - low)

        self._det_action = jax.jit(det_action)

        def q_apply(qparams, obs, act):
            x = jnp.concatenate(
                [obs.reshape((obs.shape[0], -1)), act], axis=-1)
            return mlp_apply(qparams, x)[..., 0]

        probe = self._env_creator(config.env_config)
        q_in = int(np.prod(probe.observation_space.shape)) + act_dim
        probe.close() if hasattr(probe, "close") else None
        key = jax.random.PRNGKey(config.seed + 17)
        k1, k2 = jax.random.split(key)
        hiddens = list(config.fcnet_hiddens) + [1]
        self._q_params = {"q1": mlp_init(k1, [q_in, *hiddens]),
                          "q2": mlp_init(k2, [q_in, *hiddens])}
        self._q_target = jax.tree.map(jnp.asarray, self._q_params)
        self._actor_target = jax.tree.map(jnp.asarray, policy.params)
        self._actor_opt = optax.adam(config.lr)
        self._critic_opt = optax.adam(config.critic_lr)
        self._actor_state = self._actor_opt.init(policy.params)
        self._critic_state = self._critic_opt.init(self._q_params)
        if config.prioritized_replay:
            self._buffer: ReplayBuffer = PrioritizedReplayBuffer(
                config.replay_buffer_capacity,
                alpha=config.prioritized_replay_alpha, seed=config.seed)
        else:
            self._buffer = ReplayBuffer(config.replay_buffer_capacity,
                                        seed=config.seed)
        self._updates = 0
        gamma, tau = config.gamma, config.tau
        noise, noise_clip = config.target_noise, config.target_noise_clip

        def critic_loss(q_params, q_target, actor_target, mb, key):
            next_a = det_action(actor_target, mb["new_obs"])
            # Target-policy smoothing: clipped noise on the target action.
            eps = jnp.clip(
                jax.random.normal(key, next_a.shape) * noise,
                -noise_clip, noise_clip) * (high - low) * 0.5
            next_a = jnp.clip(next_a + eps, low, high)
            q1_t = q_apply(q_target["q1"], mb["new_obs"], next_a)
            q2_t = q_apply(q_target["q2"], mb["new_obs"], next_a)
            # n-step rows carry their own bootstrap discount gamma^k.
            disc = mb.get("n_step_discount", gamma)
            target = mb["rewards"] + disc * (1 - mb["terminateds"]) * \
                jnp.minimum(q1_t, q2_t)
            target = jax.lax.stop_gradient(target)
            q1 = q_apply(q_params["q1"], mb["obs"], mb["actions"])
            q2 = q_apply(q_params["q2"], mb["obs"], mb["actions"])
            td = 0.5 * (q1 - target) + 0.5 * (q2 - target)
            w = mb.get("weights", jnp.ones_like(target))
            loss = (w * ((q1 - target) ** 2 + (q2 - target) ** 2)).mean()
            return loss, td

        def actor_loss(actor_params, q_params, mb):
            a = det_action(actor_params, mb["obs"])
            return -q_apply(q_params["q1"], mb["obs"], a).mean()

        def update(actor_params, actor_target, q_params, q_target,
                   actor_state, critic_state, mb, key, do_actor):
            (c_loss, td), c_grads = jax.value_and_grad(
                critic_loss, has_aux=True)(
                q_params, q_target, actor_target, mb, key)
            c_updates, critic_state = self._critic_opt.update(
                c_grads, critic_state, q_params)
            q_params = optax.apply_updates(q_params, c_updates)

            def actor_step(operand):
                actor_params, actor_state = operand
                a_loss, a_grads = jax.value_and_grad(actor_loss)(
                    actor_params, q_params, mb)
                a_updates, actor_state = self._actor_opt.update(
                    a_grads, actor_state, actor_params)
                return (optax.apply_updates(actor_params, a_updates),
                        actor_state, a_loss)

            def actor_skip(operand):
                actor_params, actor_state = operand
                return actor_params, actor_state, jnp.float32(0.0)

            actor_params, actor_state, a_loss = jax.lax.cond(
                do_actor, actor_step, actor_skip,
                (actor_params, actor_state))
            # Polyak targets (delayed with the actor in standard TD3; kept
            # per-step-simple here, gated on do_actor like the actor).
            polyak = lambda p, t: jnp.where(do_actor,
                                            tau * p + (1 - tau) * t, t)
            q_target = jax.tree.map(polyak, q_params, q_target)
            actor_target = jax.tree.map(polyak, actor_params, actor_target)
            return (actor_params, actor_target, q_params, q_target,
                    actor_state, critic_state, td,
                    {"critic_loss": c_loss, "actor_loss": a_loss})

        self._update_jit = jax.jit(update)
        self._key = jax.random.PRNGKey(config.seed + 31)

    def training_step(self) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp

        import ray_tpu
        config: TD3Config = self.config
        weights_ref = ray_tpu.put(self.get_weights())
        self.workers.sync_weights(weights_ref)
        batch = self.workers.sample(max(config.rollout_fragment_length, 1))
        self._timesteps_total += len(batch)
        if config.n_step > 1:
            from ray_tpu.rllib.utils.replay_buffers import n_step_transform
            batch = n_step_transform(batch, config.n_step, config.gamma)
        self._buffer.add(batch)
        metrics_out: Dict[str, Any] = {}
        if len(self._buffer) >= max(
                config.num_steps_sampled_before_learning_starts,
                config.train_batch_size):
            actor_params = self.local_policy.params
            for _ in range(config.num_train_batches_per_iteration):
                if config.prioritized_replay:
                    mb = self._buffer.sample(
                        config.train_batch_size,
                        beta=config.prioritized_replay_beta)
                else:
                    mb = self._buffer.sample(config.train_batch_size)
                device_mb = {k: jnp.asarray(v) for k, v in mb.items()
                             if k in ("obs", "new_obs", "actions",
                                      "rewards", "terminateds", "weights",
                                      "n_step_discount")}
                self._key, sub = jax.random.split(self._key)
                self._updates += 1
                do_actor = jnp.bool_(
                    self._updates % config.policy_delay == 0)
                (actor_params, self._actor_target, self._q_params,
                 self._q_target, self._actor_state, self._critic_state,
                 td, metrics) = self._update_jit(
                    actor_params, self._actor_target, self._q_params,
                    self._q_target, self._actor_state, self._critic_state,
                    device_mb, sub, do_actor)
                if config.prioritized_replay:
                    self._buffer.update_priorities(
                        mb["batch_indexes"], np.asarray(td))
            self.local_policy.params = actor_params
            metrics_out = {k: float(v) for k, v in metrics.items()}
        metrics_out["replay_buffer_size"] = len(self._buffer)
        return metrics_out
