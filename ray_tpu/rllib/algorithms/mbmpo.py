"""MBMPO: model-based meta-policy optimization.

Analog of the reference's rllib/algorithms/mbmpo (Clavera et al. 2018):
learn an ENSEMBLE of dynamics models from real transitions, then treat
EACH model as a meta-learning task — the policy inner-adapts on
imagined rollouts through model k and the outer (first-order) step
averages the post-adaptation gradients across models. Model bias
becomes task variation, so the meta-policy stays robust to any single
model's errors while nearly all gradient steps come from imagination
(the real env is touched only to refresh the transition buffer).

Env contract (matching the reference's pairing with reward-aware envs):
Box actions and a ``reward_fn(s, a, s') -> float`` the imagination can
evaluate without the env (env/examples.py PointGoalEnv).

Ensemble dynamics: K MLPs predicting normalized Δs from normalized
(s, a); inputs/targets standardized by running statistics of the real
buffer. ``dynamics_disagreement`` (std of ensemble predictions) is
exposed — the classic model-uncertainty gauge.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig


class MBMPOConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or MBMPO)
        self.lr = 1e-2                  # meta (outer) policy lr
        self.inner_lr = 0.1
        self.dynamics_lr = 1e-3
        self.ensemble_size = 3
        self.dynamics_hiddens = (64, 64)
        self.dynamics_epochs = 40
        self.dynamics_batch_size = 256
        self.real_steps_per_iteration = 400
        self.imagined_episodes = 16
        self.imagined_horizon = 20
        self.max_episode_steps = 30
        self.explore_noise = 0.3
        self.buffer_capacity = 20_000

    def training(self, *, inner_lr=None, dynamics_lr=None,
                 ensemble_size=None, dynamics_hiddens=None,
                 dynamics_epochs=None, dynamics_batch_size=None,
                 real_steps_per_iteration=None, imagined_episodes=None,
                 imagined_horizon=None, max_episode_steps=None,
                 explore_noise=None, **kwargs) -> "MBMPOConfig":
        super().training(**kwargs)
        for name, val in (
                ("inner_lr", inner_lr), ("dynamics_lr", dynamics_lr),
                ("ensemble_size", ensemble_size),
                ("dynamics_hiddens", dynamics_hiddens),
                ("dynamics_epochs", dynamics_epochs),
                ("dynamics_batch_size", dynamics_batch_size),
                ("real_steps_per_iteration", real_steps_per_iteration),
                ("imagined_episodes", imagined_episodes),
                ("imagined_horizon", imagined_horizon),
                ("max_episode_steps", max_episode_steps),
                ("explore_noise", explore_noise)):
            if val is not None:
                setattr(self, name, val)
        return self


class MBMPO(Algorithm):
    _default_config_class = MBMPOConfig
    _own_rollout_actors = True

    def setup(self, config: MBMPOConfig) -> None:
        import gymnasium as gym
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.rllib.models.catalog import mlp_apply, mlp_init

        env = self._env_creator(config.env_config)
        if not hasattr(env, "reward_fn"):
            raise ValueError(
                "MBMPO needs an env exposing reward_fn(s, a, s') — "
                "imagined rollouts must be rewardable without the env "
                "(see env/examples.py PointGoalEnv)")
        if not isinstance(env.action_space, gym.spaces.Box):
            raise ValueError("MBMPO supports Box action spaces")
        self._env = env
        policy = self.local_policy
        self.obs_dim = policy.obs_dim
        self.act_dim = policy.act_dim
        K = config.ensemble_size
        hid = list(config.dynamics_hiddens)
        key = jax.random.PRNGKey(config.seed + 11)
        ks = jax.random.split(key, K)
        in_dim = self.obs_dim + self.act_dim
        self.dyn_params = [
            mlp_init(ks[k], [in_dim, *hid, self.obs_dim])
            for k in range(K)]
        self._dyn_opt = optax.adam(config.dynamics_lr)
        self._dyn_states = [self._dyn_opt.init(p)
                            for p in self.dyn_params]
        self._meta_opt = optax.adam(config.lr)
        self._meta_state = self._meta_opt.init(policy.params)
        self._rng = np.random.default_rng(config.seed)
        self._key = jax.random.PRNGKey(config.seed + 23)

        # Running normalization stats (updated from the real buffer).
        self._stats = {
            "s_mean": np.zeros(self.obs_dim, np.float32),
            "s_std": np.ones(self.obs_dim, np.float32),
            "a_mean": np.zeros(self.act_dim, np.float32),
            "a_std": np.ones(self.act_dim, np.float32),
            "d_mean": np.zeros(self.obs_dim, np.float32),
            "d_std": np.ones(self.obs_dim, np.float32),
        }

        def dyn_forward(p, stats, s, a):
            x = jnp.concatenate(
                [(s - stats["s_mean"]) / stats["s_std"],
                 (a - stats["a_mean"]) / stats["a_std"]], -1)
            delta_n = mlp_apply(p, x)
            return s + delta_n * stats["d_std"] + stats["d_mean"]

        def dyn_loss(p, stats, s, a, s_next):
            pred_n = mlp_apply(p, jnp.concatenate(
                [(s - stats["s_mean"]) / stats["s_std"],
                 (a - stats["a_mean"]) / stats["a_std"]], -1))
            target_n = ((s_next - s) - stats["d_mean"]) / stats["d_std"]
            return ((pred_n - target_n) ** 2).mean()

        def dyn_update(p, opt_state, stats, s, a, s_next):
            loss, grads = jax.value_and_grad(dyn_loss)(
                p, stats, s, a, s_next)
            updates, opt_state = self._dyn_opt.update(grads, opt_state,
                                                      p)
            return optax.apply_updates(p, updates), opt_state, loss

        def reinforce_loss(params, obs, actions, advantages):
            logp = policy.logp(params, obs, actions)
            return -(logp * advantages).mean()

        grad_fn = jax.grad(reinforce_loss)
        inner_lr = config.inner_lr

        def inner_update(params, obs, actions, advantages):
            grads = grad_fn(params, obs, actions, advantages)
            return jax.tree.map(lambda p, g: p - inner_lr * g,
                                params, grads)

        self._dyn_forward_jit = jax.jit(dyn_forward)
        self._dyn_update_jit = jax.jit(dyn_update)
        self._inner_update_jit = jax.jit(inner_update)
        self._outer_grad_jit = jax.jit(grad_fn)
        self._buffer_s: List[np.ndarray] = []
        self._buffer_a: List[np.ndarray] = []
        self._buffer_ns: List[np.ndarray] = []
        self._episode_rewards: List[float] = []

    # -- real-env interaction -------------------------------------------

    def _collect_real(self, steps: int) -> None:
        import jax
        config: MBMPOConfig = self.config
        policy = self.local_policy
        obs, _ = self._env.reset(
            seed=int(self._rng.integers(1 << 30)))
        ep_reward, ep_len = 0.0, 0
        for _ in range(steps):
            vec = np.asarray(obs, np.float32).reshape(1, -1)
            self._key, sub = jax.random.split(self._key)
            action, _, _ = policy.compute_actions(vec, sub)
            a = np.asarray(action[0], np.float32)
            a = a + config.explore_noise * \
                self._rng.standard_normal(a.shape).astype(np.float32)
            nxt, r, term, trunc, _ = self._env.step(a)
            self._buffer_s.append(vec[0])
            self._buffer_a.append(a)
            self._buffer_ns.append(
                np.asarray(nxt, np.float32).reshape(-1))
            ep_reward += float(r)
            ep_len += 1
            self._timesteps_total += 1
            if term or trunc or ep_len >= config.max_episode_steps:
                self._episode_rewards.append(ep_reward)
                ep_reward, ep_len = 0.0, 0
                obs, _ = self._env.reset()
            else:
                obs = nxt
        cap = config.buffer_capacity
        del self._buffer_s[:-cap]
        del self._buffer_a[:-cap]
        del self._buffer_ns[:-cap]

    def _fit_dynamics(self) -> float:
        import jax.numpy as jnp
        config: MBMPOConfig = self.config
        s = np.stack(self._buffer_s)
        a = np.stack(self._buffer_a)
        ns = np.stack(self._buffer_ns)
        d = ns - s
        for name, arr in (("s", s), ("a", a), ("d", d)):
            self._stats[f"{name}_mean"] = arr.mean(0).astype(np.float32)
            self._stats[f"{name}_std"] = np.maximum(
                arr.std(0), 1e-3).astype(np.float32)
        stats = {k: jnp.asarray(v) for k, v in self._stats.items()}
        n = len(s)
        bs = min(config.dynamics_batch_size, n)
        losses = []
        for k in range(config.ensemble_size):
            p, st = self.dyn_params[k], self._dyn_states[k]
            # Each member sees its own bootstrap resample (the ensemble
            # diversity mechanism).
            rng = np.random.default_rng(1000 * k + self.iteration)
            for _ in range(config.dynamics_epochs):
                idx = rng.integers(0, n, bs)
                p, st, loss = self._dyn_update_jit(
                    p, st, stats,
                    jnp.asarray(s[idx]), jnp.asarray(a[idx]),
                    jnp.asarray(ns[idx]))
            self.dyn_params[k], self._dyn_states[k] = p, st
            losses.append(float(loss))
        return float(np.mean(losses))

    # -- imagination -----------------------------------------------------

    def _imagine_batch(self, params, model_idx: int):
        """Roll imagined episodes through ensemble member model_idx
        under ``params``; returns REINFORCE arrays (obs, act, adv) and
        the mean imagined return."""
        import jax
        import jax.numpy as jnp
        config: MBMPOConfig = self.config
        policy = self.local_policy
        stats = {k: jnp.asarray(v) for k, v in self._stats.items()}
        E, H = config.imagined_episodes, config.imagined_horizon
        # Start states resampled from REAL data (the standard MBRL
        # grounding for imagined rollouts).
        idx = self._rng.integers(0, len(self._buffer_s), E)
        s = jnp.asarray(np.stack([self._buffer_s[i] for i in idx]))
        saved = policy.params
        policy.params = params
        obs_rows, act_rows, rew_rows = [], [], []
        try:
            for _ in range(H):
                self._key, sub = jax.random.split(self._key)
                a, _, _ = policy.compute_actions(np.asarray(s), sub)
                a = jnp.asarray(a)
                s_next = self._dyn_forward_jit(
                    self.dyn_params[model_idx], stats, s, a)
                r = np.asarray([
                    self._env.reward_fn(np.asarray(s[i]),
                                        np.asarray(a[i]),
                                        np.asarray(s_next[i]))
                    for i in range(E)], np.float32)
                obs_rows.append(np.asarray(s))
                act_rows.append(np.asarray(a))
                rew_rows.append(r)
                s = s_next
        finally:
            policy.params = saved
        rew = np.stack(rew_rows, 1)              # [E, H]
        rets = np.cumsum(rew[:, ::-1], axis=1)[:, ::-1]
        adv = rets - rets.mean()
        adv = adv / max(adv.std(), 1e-6)
        obs = np.stack(obs_rows, 1).reshape(E * H, -1)
        act = np.stack(act_rows, 1).reshape(E * H, -1)
        import jax.numpy as jnp2
        return (jnp2.asarray(obs), jnp2.asarray(act),
                jnp2.asarray(adv.reshape(-1).astype(np.float32)),
                float(rew.sum(1).mean()))

    def dynamics_disagreement(self, s: np.ndarray, a: np.ndarray
                              ) -> float:
        """Std of ensemble next-state predictions — the model
        uncertainty gauge."""
        import jax.numpy as jnp
        stats = {k: jnp.asarray(v) for k, v in self._stats.items()}
        preds = [np.asarray(self._dyn_forward_jit(
            p, stats, jnp.asarray(s), jnp.asarray(a)))
            for p in self.dyn_params]
        return float(np.stack(preds).std(0).mean())

    # -- meta loop -------------------------------------------------------

    def training_step(self) -> Dict[str, Any]:
        import jax
        import optax
        config: MBMPOConfig = self.config
        policy = self.local_policy
        self._collect_real(config.real_steps_per_iteration)
        dyn_loss = self._fit_dynamics()

        meta_grads = None
        imag_returns = []
        for k in range(config.ensemble_size):
            obs, act, adv, _ = self._imagine_batch(policy.params, k)
            adapted = self._inner_update_jit(policy.params, obs, act,
                                             adv)
            obs2, act2, adv2, post_ret = self._imagine_batch(adapted, k)
            g = self._outer_grad_jit(adapted, obs2, act2, adv2)
            meta_grads = g if meta_grads is None else jax.tree.map(
                lambda x, y: x + y, meta_grads, g)
            imag_returns.append(post_ret)
        meta_grads = jax.tree.map(
            lambda g: g / config.ensemble_size, meta_grads)
        updates, self._meta_state = self._meta_opt.update(
            meta_grads, self._meta_state, policy.params)
        policy.params = optax.apply_updates(policy.params, updates)

        window = self._episode_rewards[-50:]
        return {
            "dynamics_loss": dyn_loss,
            "imagined_return_mean": float(np.mean(imag_returns)),
            "episode_reward_mean": (float(np.mean(window)) if window
                                    else float("nan")),
            "episodes_total": len(self._episode_rewards),
        }

    def get_weights(self):
        import jax
        return {"policy": self.local_policy.get_weights(),
                "dynamics": [jax.tree.map(np.asarray, p)
                             for p in self.dyn_params],
                "stats": dict(self._stats)}

    def set_weights(self, weights) -> None:
        import jax
        import jax.numpy as jnp
        self.local_policy.set_weights(weights["policy"])
        self.dyn_params = [jax.tree.map(jnp.asarray, p)
                           for p in weights["dynamics"]]
        self._stats = dict(weights["stats"])

    def stop(self) -> None:
        close = getattr(self._env, "close", None)
        if callable(close):
            close()
