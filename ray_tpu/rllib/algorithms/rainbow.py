"""Rainbow: the six-component DQN (Hessel et al. 2018).

Analog of the reference's DQN configured with num_atoms > 1 + noisy +
dueling + double + n-step + prioritized replay (rllib/algorithms/dqn
exposes Rainbow through those flags; this preset packages them and adds
the C51 cross-entropy loss over the projected target distribution).
Builds on the DQN engine: rollouts, replay, target syncs, and the
jitted-update loop are inherited; only the loss construction differs
(`_build_loss_fn`), and the policy is the noisy-distributional
RainbowPolicy (policy/rainbow_policy.py).
"""

from __future__ import annotations

from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig


class RainbowConfig(DQNConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or Rainbow)
        self.policy_class_name = "rainbow"
        # The Rainbow recipe: all six components on by default.
        self.double_q = True
        self.dueling = True
        self.prioritized_replay = True
        self.n_step = 3
        self.noisy = True
        self.num_atoms = 51
        self.v_min = -10.0
        self.v_max = 10.0
        # Noisy nets replace epsilon exploration.
        self.epsilon_initial = 0.0
        self.epsilon_final = 0.0

    def training(self, *, noisy=None, num_atoms=None, v_min=None,
                 v_max=None, **kwargs) -> "RainbowConfig":
        super().training(**kwargs)
        for name, val in (("noisy", noisy), ("num_atoms", num_atoms),
                          ("v_min", v_min), ("v_max", v_max)):
            if val is not None:
                setattr(self, name, val)
        return self

    def policy_config(self):
        base = super().policy_config()
        base.update(noisy=self.noisy, num_atoms=self.num_atoms,
                    v_min=self.v_min, v_max=self.v_max)
        return base


class Rainbow(DQN):
    _default_config_class = RainbowConfig

    def _build_loss_fn(self, policy, config):
        import jax
        import jax.numpy as jnp

        from ray_tpu.rllib.policy.rainbow_policy import \
            project_distribution

        gamma = config.gamma
        double_q = config.double_q
        noisy = config.noisy
        support = policy.support
        v_min, v_max = policy.v_min, policy.v_max

        def loss_fn(params, target_params, mb, key):
            # Noise only on the ONLINE current-state pass (the gradient
            # path that trains the sigmas). The action-selection and
            # target passes run mu-only: fresh noise there inflates the
            # max operator's overestimation bias — observed empirically
            # as runaway q-spread with collapsing rollouts.
            k_cur = jax.random.split(key, 1)[0] if noisy else None
            k_sel = k_tgt = None
            log_p = policy.logits_dist(params, mb["obs"], k_cur)
            actions = mb["actions"].astype(jnp.int32)
            batch = jnp.arange(actions.shape[0])
            chosen_log_p = log_p[batch, actions]          # [B, atoms]
            # Action selection for the target: online net (double) or
            # target net — both under their OWN noise samples.
            if double_q:
                q_sel = policy.q_values(params, mb["new_obs"], k_sel)
            else:
                q_sel = policy.q_values(target_params, mb["new_obs"],
                                        k_sel)
            a_star = q_sel.argmax(-1)
            next_log_p_all = policy.logits_dist(target_params,
                                                mb["new_obs"], k_tgt)
            next_log_p = next_log_p_all[batch, a_star]    # [B, atoms]
            done = jnp.maximum(mb["terminateds"], 0.0)
            disc = mb.get("n_step_discount", gamma)
            target = project_distribution(
                next_log_p, mb["rewards"], disc, done, support,
                v_min, v_max)
            target = jax.lax.stop_gradient(target)
            ce = -(target * chosen_log_p).sum(-1)         # [B]
            weights = mb.get("weights", jnp.ones_like(ce))
            # Cross-entropy doubles as the priority signal (the standard
            # distributional-PER choice).
            return (weights * ce).mean(), ce

        return loss_fn
