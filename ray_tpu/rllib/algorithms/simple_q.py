"""SimpleQ: the minimal deep Q-learner.

Analog of the reference's rllib/algorithms/simple_q — the pedagogical DQN
without double-Q, prioritized replay, or dueling heads. The reference
derives DQN from SimpleQ; here the DQN engine already covers the simple
update as a configuration (double_q=False, uniform replay), so SimpleQ is
that configuration with SimpleQ's defaults.
"""

from __future__ import annotations

from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig


class SimpleQConfig(DQNConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or SimpleQ)
        self.double_q = False
        self.prioritized_replay = False
        self.target_network_update_freq = 500
        self.replay_buffer_capacity = 50_000


class SimpleQ(DQN):
    _default_config_class = SimpleQConfig
