"""MARWIL: monotonic advantage re-weighted imitation learning.

Analog of the reference's rllib/algorithms/marwil (of which its BC is the
beta=0 special case): offline imitation where each logged action's
log-likelihood is weighted by exp(beta * advantage), advantage = (return -
V(s)) with a trained value head, normalized by a running estimate of the
squared-advantage moving average. beta=0 reduces to plain BC with a value
head; larger beta biases cloning toward better-than-average actions.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.algorithms.pg import discounted_returns
from ray_tpu.rllib.policy.sample_batch import SampleBatch


class MARWILConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or MARWIL)
        self.lr = 1e-3
        self.train_batch_size = 256
        self.num_rollout_workers = 0  # offline: WorkerSet stays empty
        self.num_train_batches_per_iteration = 16
        self.beta = 1.0
        self.vf_coeff = 1.0
        # Decay for the running ||adv||^2 estimate (reference:
        # marwil_torch_policy moving_average_sqd_adv_norm, update rate 1e-8
        # per sample there; per-batch here).
        self.moving_average_decay = 0.99

    def training(self, *, beta=None, vf_coeff=None,
                 moving_average_decay=None,
                 num_train_batches_per_iteration=None,
                 **kwargs) -> "MARWILConfig":
        super().training(**kwargs)
        for name, val in (("beta", beta), ("vf_coeff", vf_coeff),
                          ("moving_average_decay", moving_average_decay),
                          ("num_train_batches_per_iteration",
                           num_train_batches_per_iteration)):
            if val is not None:
                setattr(self, name, val)
        return self


class MARWIL(Algorithm):
    _default_config_class = MARWILConfig

    def __init__(self, config=None, **kwargs):
        cfg = config or self.get_default_config()
        if not cfg.input_:
            raise ValueError(
                "MARWIL is offline-only: set "
                "config.offline_data(input_=<dir of JSON experience files>)")
        super().__init__(config=config, **kwargs)

    def setup(self, config: MARWILConfig) -> None:
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.rllib.offline.json_reader import JsonReader
        self._reader = JsonReader(config.input_)
        # Running E[adv^2] for weight normalization; initialized from the
        # first minibatch so early weights don't explode (exp of a raw
        # CartPole-scale return would overflow against a norm of 1).
        self._adv_sq_norm = None
        policy = self.local_policy
        self._optimizer = optax.adam(config.lr)
        self._opt_state = self._optimizer.init(policy.params)
        beta, vf_coeff = config.beta, config.vf_coeff

        def loss_fn(params, mb, adv_norm):
            values = policy._value(params, mb["obs"])
            adv = mb["returns"] - values
            vf_loss = (adv ** 2).mean()
            # Weight uses the *normalized*, gradient-stopped advantage;
            # the exponent is clamped for numerical safety.
            weight = jnp.exp(jnp.clip(beta * jax.lax.stop_gradient(
                adv / jnp.sqrt(adv_norm + 1e-8)), -10.0, 10.0))
            logp = policy.logp(params, mb["obs"], mb["actions"])
            pi_loss = -(weight * logp).mean()
            return pi_loss + vf_coeff * vf_loss, (
                pi_loss, vf_loss, (adv ** 2).mean())

        def update(params, opt_state, mb, adv_norm):
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, mb, adv_norm)
            updates, opt_state = self._optimizer.update(grads, opt_state,
                                                        params)
            return optax.apply_updates(params, updates), opt_state, loss, aux

        self._update_jit = jax.jit(update)

    def training_step(self) -> Dict[str, Any]:
        import jax.numpy as jnp
        config: MARWILConfig = self.config
        batch_size = config.train_batch_size
        losses, pi_losses, vf_losses = [], [], []
        params = self.local_policy.params
        def attach_returns(fragment):
            # Bootstrap non-terminal fragment tails / truncations with the
            # current value estimate, else those steps' returns miss all
            # future reward and exp(beta*adv) silently drops them.
            # Datasets logged without new_obs fall back to pure
            # reward-to-go (the pre-bootstrap behavior).
            next_obs = fragment.get(SampleBatch.NEXT_OBS)
            values_next = (None if next_obs is None else
                           self.local_policy.compute_values(
                               np.asarray(next_obs, np.float32)))
            fragment["returns"] = discounted_returns(
                fragment, config.gamma, bootstrap_values=values_next)
            return fragment

        for _ in range(config.num_train_batches_per_iteration):
            mb = self._reader.next_batch(batch_size,
                                         transform=attach_returns)
            self._timesteps_total += batch_size
            device_mb = {
                "obs": jnp.asarray(np.asarray(mb[SampleBatch.OBS],
                                              np.float32)),
                "actions": jnp.asarray(mb[SampleBatch.ACTIONS]),
                "returns": jnp.asarray(np.asarray(mb["returns"],
                                                  np.float32)),
            }
            if self._adv_sq_norm is None:
                values = np.asarray(self.local_policy._value(
                    params, device_mb["obs"]))
                adv0 = np.asarray(mb["returns"], np.float32) - values
                self._adv_sq_norm = max(float((adv0 ** 2).mean()), 1e-8)
            params, self._opt_state, loss, aux = self._update_jit(
                params, self._opt_state, device_mb,
                jnp.float32(self._adv_sq_norm))
            pi_loss, vf_loss, adv_sq = aux
            d = config.moving_average_decay
            self._adv_sq_norm = (d * self._adv_sq_norm
                                 + (1 - d) * float(adv_sq))
            losses.append(float(loss))
            pi_losses.append(float(pi_loss))
            vf_losses.append(float(vf_loss))
        self.local_policy.params = params
        return {"loss": float(np.mean(losses)),
                "policy_loss": float(np.mean(pi_losses)),
                "vf_loss": float(np.mean(vf_losses)),
                "adv_sq_norm": self._adv_sq_norm}
