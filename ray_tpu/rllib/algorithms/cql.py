"""CQL: conservative Q-learning for offline RL.

Analog of the reference's rllib/algorithms/cql (built on its SAC stack,
as here): SAC's twin-critic maximum-entropy update plus the CQL(H)
conservative regularizer — for each critic, push down a log-sum-exp over
out-of-distribution actions (uniform proposals and current-policy samples
at s and s', importance-corrected by their log-densities) and push up the
Q of the logged dataset actions. Offline-only: the replay buffer is filled
once from JSON experience files and never touched by rollouts.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithms.sac import SAC, SACConfig
from ray_tpu.rllib.policy.sample_batch import SampleBatch


class CQLConfig(SACConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or CQL)
        self.num_rollout_workers = 0  # offline: WorkerSet stays empty
        self.min_q_weight = 5.0
        self.num_ood_actions = 4  # proposals per source per state
        self.num_train_batches_per_iteration = 32

    def training(self, *, min_q_weight=None, num_ood_actions=None,
                 **kwargs) -> "CQLConfig":
        super().training(**kwargs)
        if min_q_weight is not None:
            self.min_q_weight = min_q_weight
        if num_ood_actions is not None:
            self.num_ood_actions = num_ood_actions
        return self


class CQL(SAC):
    _default_config_class = CQLConfig

    def __init__(self, config=None, **kwargs):
        cfg = config or self.get_default_config()
        if not cfg.input_:
            raise ValueError(
                "CQL is offline-only: set "
                "config.offline_data(input_=<dir of JSON experience files>)")
        super().__init__(config=config, **kwargs)

    def _conservative_penalty(self, q_apply, q_params, actor_params, mb,
                              key):
        import jax
        import jax.numpy as jnp

        config: CQLConfig = self.config
        policy = self.local_policy
        n = config.num_ood_actions
        low = jnp.asarray(policy.low)
        high = jnp.asarray(policy.high)
        batch = mb["obs"].shape[0]
        act_dim = policy.act_dim
        k_rand, k_cur, k_next = jax.random.split(key, 3)

        # Proposal set: uniform actions + policy samples at s and s',
        # each importance-corrected by its proposal log-density (CQL(H)).
        rand_a = jax.random.uniform(
            k_rand, (n, batch, act_dim), minval=low, maxval=high)
        log_unif = -jnp.log(high - low).sum()  # density of U[low, high]

        def pi_samples(obs, key):
            keys = jax.random.split(key, n)
            return jax.vmap(
                lambda k: policy.logp_and_sample(actor_params, obs, k)
            )(keys)  # actions (n, B, A), logp (n, B)

        cur_a, cur_logp = pi_samples(mb["obs"], k_cur)
        next_a, next_logp = pi_samples(mb["new_obs"], k_next)

        penalty = 0.0
        for name in ("q1", "q2"):
            def q_at(actions):
                return jax.vmap(
                    lambda a: q_apply(q_params[name], mb["obs"], a)
                )(actions)  # (n, B)

            cat = jnp.concatenate([
                q_at(rand_a) - log_unif,
                q_at(cur_a) - jax.lax.stop_gradient(cur_logp),
                q_at(next_a) - jax.lax.stop_gradient(next_logp),
            ], axis=0)  # (3n, B)
            ood = jax.scipy.special.logsumexp(cat, axis=0) - jnp.log(3 * n)
            data_q = q_apply(q_params[name], mb["obs"], mb["actions"])
            penalty = penalty + (ood - data_q).mean()
        return config.min_q_weight * penalty

    def setup(self, config: CQLConfig) -> None:
        super().setup(config)
        from ray_tpu.rllib.offline.json_reader import JsonReader
        from ray_tpu.rllib.utils.replay_buffers import ReplayBuffer
        data = JsonReader(config.input_).read_all()
        if len(data) > self._buffer.capacity:
            # Offline training must see the whole dataset — never let the
            # inherited online-replay capacity ring-drop rows silently.
            self._buffer = ReplayBuffer(len(data), seed=config.seed)
        self._buffer.add(data)
        self._dataset_size = len(data)

    def training_step(self) -> Dict[str, Any]:
        config: CQLConfig = self.config
        out = self._train_on_buffer(config.num_train_batches_per_iteration)
        self._timesteps_total += (config.num_train_batches_per_iteration
                                  * config.train_batch_size)
        out["dataset_size"] = self._dataset_size
        return out
