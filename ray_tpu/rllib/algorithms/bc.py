"""BC: behavior cloning from offline data.

Analog of the reference's rllib/algorithms/bc (the offline-RL entry point
over rllib/offline/): supervised imitation of logged actions read from
JSON experience files — no environment interaction at all. The canonical
consumer of JsonWriter output (`config.offline_data(input_=dir)`).
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.policy.sample_batch import SampleBatch


class BCConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or BC)
        self.lr = 1e-3
        self.train_batch_size = 256
        self.num_rollout_workers = 0  # offline: WorkerSet stays empty
        self.num_train_batches_per_iteration = 16

    def training(self, *, num_train_batches_per_iteration=None,
                 **kwargs) -> "BCConfig":
        super().training(**kwargs)
        if num_train_batches_per_iteration is not None:
            self.num_train_batches_per_iteration = \
                num_train_batches_per_iteration
        return self


class BC(Algorithm):
    _default_config_class = BCConfig

    def __init__(self, config=None, **kwargs):
        # Validate BEFORE Algorithm.__init__ spawns anything: a setup()-time
        # failure would leak the already-created rollout actors.
        cfg = config or self.get_default_config()
        if not cfg.input_:
            raise ValueError(
                "BC is offline-only: set config.offline_data(input_=<dir "
                "of JSON experience files written by JsonWriter>)")
        super().__init__(config=config, **kwargs)

    def setup(self, config: BCConfig) -> None:
        import jax
        import optax

        from ray_tpu.rllib.offline.json_reader import JsonReader
        self._reader = JsonReader(config.input_)
        policy = self.local_policy
        self._optimizer = optax.adam(config.lr)
        self._opt_state = self._optimizer.init(policy.params)

        def loss_fn(params, mb):
            logp = policy.logp(params, mb["obs"], mb["actions"])
            return -logp.mean()

        def update(params, opt_state, mb):
            loss, grads = jax.value_and_grad(loss_fn)(params, mb)
            updates, opt_state = self._optimizer.update(grads, opt_state,
                                                        params)
            return optax.apply_updates(params, updates), opt_state, loss

        self._update_jit = jax.jit(update)

    def training_step(self) -> Dict[str, Any]:
        import jax.numpy as jnp
        config: BCConfig = self.config
        batch_size = config.train_batch_size
        losses = []
        params = self.local_policy.params
        for _ in range(config.num_train_batches_per_iteration):
            mb = self._reader.next_batch(batch_size)
            self._timesteps_total += batch_size
            device_mb = {
                "obs": jnp.asarray(np.asarray(mb[SampleBatch.OBS],
                                              np.float32)),
                "actions": jnp.asarray(mb[SampleBatch.ACTIONS]),
            }
            params, self._opt_state, loss = self._update_jit(
                params, self._opt_state, device_mb)
            losses.append(float(loss))
        self.local_policy.params = params
        return {"loss": float(np.mean(losses)),
                "num_batches": len(losses)}
