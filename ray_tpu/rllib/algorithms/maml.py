"""MAML: model-agnostic meta-learning for RL (first-order).

Analog of the reference's rllib/algorithms/maml (Finn et al. 2017):
meta-train policy initializations that ADAPT to a new task in a handful
of gradient steps. Each meta-iteration samples a batch of tasks from
``task_sampler`` (env_config variations — e.g. hidden goals the
observation never reveals); per task, the INNER loop collects episodes
with the meta-policy and takes ``inner_steps`` REINFORCE updates; the
OUTER update averages the post-adaptation policy gradients across tasks
(first-order MAML — the Hessian term dropped, the variant the original
paper shows matches full MAML on RL benchmarks and what the reference's
``use_meta_sgd=False`` path approximates).

Discrete or Box actions via the standard JAXPolicy. ``adapt(env)``
exposes the deployment-time story: clone the meta-policy, run the inner
loop against a fresh task, return the adapted policy.

Honest scope note: on the hidden-goal point families the tests use,
first-order MAML reliably reaches strong post-adaptation returns where
an unlucky random initialization can be 2x worse — but a LUCKY random
init adapts comparably (one-step REINFORCE is powerful on these
families), so the tested property is reliable adaptation quality, not
dominance over every init. The reference's full second-order variant
targets harder families (its MuJoCo benchmarks) that a CI budget
cannot train.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig


class MAMLConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or MAML)
        self.inner_lr = 0.1
        self.lr = 1e-2                  # meta (outer) learning rate
        self.inner_steps = 1
        self.episodes_per_inner_batch = 8
        self.tasks_per_iteration = 5
        self.max_episode_steps = 30
        #: callable (rng) -> env_config for one sampled task; set via
        #: .training(task_sampler=...). Defaults to the identity task.
        self.task_sampler: Optional[Callable] = None

    def training(self, *, inner_lr=None, inner_steps=None,
                 episodes_per_inner_batch=None, tasks_per_iteration=None,
                 max_episode_steps=None, task_sampler=None,
                 **kwargs) -> "MAMLConfig":
        super().training(**kwargs)
        for name, val in (
                ("inner_lr", inner_lr), ("inner_steps", inner_steps),
                ("episodes_per_inner_batch", episodes_per_inner_batch),
                ("tasks_per_iteration", tasks_per_iteration),
                ("max_episode_steps", max_episode_steps),
                ("task_sampler", task_sampler)):
            if val is not None:
                setattr(self, name, val)
        return self


class MAML(Algorithm):
    _default_config_class = MAMLConfig
    _own_rollout_actors = True

    def setup(self, config: MAMLConfig) -> None:
        import jax
        import jax.numpy as jnp
        import optax

        policy = self.local_policy
        self._meta_opt = optax.adam(config.lr)
        self._meta_state = self._meta_opt.init(policy.params)
        self._rng = np.random.default_rng(config.seed)
        self._key = jax.random.PRNGKey(config.seed + 3)
        inner_lr = config.inner_lr

        def reinforce_loss(params, obs, actions, advantages):
            logp = policy.logp(params, obs, actions)
            return -(logp * advantages).mean()

        grad_fn = jax.grad(reinforce_loss)

        def inner_update(params, obs, actions, returns):
            grads = grad_fn(params, obs, actions, returns)
            return jax.tree.map(lambda p, g: p - inner_lr * g,
                                params, grads)

        self._inner_update_jit = jax.jit(inner_update)
        self._outer_grad_jit = jax.jit(grad_fn)
        self._episode_rewards: List[float] = []
        self._post_adapt_rewards: List[float] = []

    # -- rollout helpers -------------------------------------------------

    def _collect(self, env, params, episodes: int):
        """REINFORCE batch: (obs, actions, per-step returns-to-go,
        mean episode return)."""
        import jax
        import jax.numpy as jnp
        policy = self.local_policy
        config: MAMLConfig = self.config
        all_obs, all_act, all_ret = [], [], []
        ep_returns = []
        saved = policy.params
        policy.params = params
        try:
            for _ in range(episodes):
                obs, _ = env.reset(
                    seed=int(self._rng.integers(1 << 30)))
                rows_obs, rows_act, rows_rew = [], [], []
                for _ in range(config.max_episode_steps):
                    vec = np.asarray(obs, np.float32).reshape(1, -1)
                    self._key, sub = jax.random.split(self._key)
                    action, _, _ = policy.compute_actions(vec, sub)
                    act = action[0]
                    act_env = (int(act) if policy.discrete
                               else np.asarray(act))
                    obs, r, term, trunc, _ = env.step(act_env)
                    rows_obs.append(vec[0])
                    rows_act.append(act)
                    rows_rew.append(float(r))
                    if term or trunc:
                        break
                rets = np.cumsum(rows_rew[::-1])[::-1]
                all_obs.append(np.stack(rows_obs))
                all_act.append(np.stack(rows_act))
                all_ret.append(np.asarray(rets, np.float32))
                ep_returns.append(float(np.sum(rows_rew)))
        finally:
            policy.params = saved
        # Per-timestep baseline across the episode batch (episodes on
        # this contract share the horizon): REINFORCE variance drops
        # far below the global-mean baseline, which MAML's one-step
        # adaptation signal needs.
        # Flattened returns-to-go, globally standardized — the variant
        # that adapts most strongly here (per-timestep baselines were
        # tried and shrink the one-step adaptation signal below noise).
        rets = np.stack(all_ret)                     # [E, T]
        adv = rets - rets.mean()
        adv = adv / max(adv.std(), 1e-6)
        obs = np.concatenate(all_obs)
        act = np.concatenate(all_act)
        return (jnp.asarray(obs), jnp.asarray(act),
                jnp.asarray(adv.reshape(-1)),
                float(np.mean(ep_returns)))

    def _adapt_params(self, env, params):
        """Run the inner loop; returns (adapted params, pre-adapt
        return)."""
        config: MAMLConfig = self.config
        pre = None
        for _ in range(config.inner_steps):
            obs, act, ret, mean_ret = self._collect(
                env, params, config.episodes_per_inner_batch)
            if pre is None:
                pre = mean_ret
            params = self._inner_update_jit(params, obs, act, ret)
        return params, pre

    def adapt(self, env, inner_steps: Optional[int] = None):
        """Deployment-time adaptation: inner-loop the meta-policy on a
        fresh task env; returns adapted params (use with
        policy.compute_actions)."""
        config: MAMLConfig = self.config
        params = self.local_policy.params
        steps = (config.inner_steps if inner_steps is None
                 else inner_steps)
        for _ in range(steps):
            obs, act, ret, _ = self._collect(
                env, params, config.episodes_per_inner_batch)
            params = self._inner_update_jit(params, obs, act, ret)
        return params

    # -- meta loop -------------------------------------------------------

    def training_step(self) -> Dict[str, Any]:
        import jax
        import optax
        config: MAMLConfig = self.config
        sampler = config.task_sampler or (lambda rng: {})
        policy = self.local_policy
        meta_grads = None
        pre_returns, post_returns = [], []
        for _ in range(config.tasks_per_iteration):
            env = self._env_creator(
                dict(config.env_config, **sampler(self._rng)))
            try:
                adapted, pre = self._adapt_params(env, policy.params)
                obs, act, ret, post = self._collect(
                    env, adapted, config.episodes_per_inner_batch)
                # First-order MAML: outer gradient evaluated at the
                # ADAPTED parameters, applied to the meta-parameters.
                g = self._outer_grad_jit(adapted, obs, act, ret)
                meta_grads = g if meta_grads is None else jax.tree.map(
                    lambda a, b: a + b, meta_grads, g)
                pre_returns.append(pre)
                post_returns.append(post)
                self._timesteps_total += int(obs.shape[0])
            finally:
                close = getattr(env, "close", None)
                if callable(close):
                    close()
        meta_grads = jax.tree.map(
            lambda g: g / config.tasks_per_iteration, meta_grads)
        updates, self._meta_state = self._meta_opt.update(
            meta_grads, self._meta_state, policy.params)
        policy.params = optax.apply_updates(policy.params, updates)
        pre, post = float(np.mean(pre_returns)), \
            float(np.mean(post_returns))
        self._episode_rewards.append(post)
        self._post_adapt_rewards.append(post)
        return {
            "pre_adaptation_return": pre,
            "post_adaptation_return": post,
            "adaptation_gain": post - pre,
            "episode_reward_mean": post,
        }

    def get_weights(self):
        return self.local_policy.get_weights()

    def set_weights(self, weights) -> None:
        self.local_policy.set_weights(weights)
