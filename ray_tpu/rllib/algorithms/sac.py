"""SAC: soft actor-critic with twin Q critics and auto-tuned temperature.

Analog of the reference's rllib/algorithms/sac: off-policy maximum-entropy
RL for continuous control. The learner holds twin Q networks + polyak
targets and a log-temperature tuned toward the -|A| target entropy; the
squashed-Gaussian actor (policy/sac_policy.py) samples on the rollout
workers. All three updates fuse into one jitted step.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.policy.sample_batch import SampleBatch
from ray_tpu.rllib.utils.replay_buffers import ReplayBuffer


class SACConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or SAC)
        self.policy_class_name = "sac"
        self.lr = 3e-4
        self.critic_lr = 3e-4
        self.alpha_lr = 3e-4
        self.train_batch_size = 256
        self.replay_buffer_capacity = 100_000
        self.num_steps_sampled_before_learning_starts = 500
        self.num_train_batches_per_iteration = 32
        self.tau = 0.005
        self.initial_alpha = 0.1
        self.target_entropy: Any = "auto"

    def training(self, *, tau=None, critic_lr=None, alpha_lr=None,
                 initial_alpha=None, target_entropy=None,
                 replay_buffer_capacity=None,
                 num_train_batches_per_iteration=None,
                 num_steps_sampled_before_learning_starts=None,
                 **kwargs) -> "SACConfig":
        super().training(**kwargs)
        for name, val in (("tau", tau), ("critic_lr", critic_lr),
                          ("alpha_lr", alpha_lr),
                          ("initial_alpha", initial_alpha),
                          ("target_entropy", target_entropy),
                          ("replay_buffer_capacity", replay_buffer_capacity),
                          ("num_train_batches_per_iteration",
                           num_train_batches_per_iteration),
                          ("num_steps_sampled_before_learning_starts",
                           num_steps_sampled_before_learning_starts)):
            if val is not None:
                setattr(self, name, val)
        return self


class SAC(Algorithm):
    _default_config_class = SACConfig

    def _conservative_penalty(self, q_apply, q_params, actor_params, mb,
                              key):
        """Extra critic-loss term; traced into the jitted update. CQL
        overrides this with the conservative regularizer."""
        return 0.0

    def setup(self, config: SACConfig) -> None:
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.rllib.models.catalog import mlp_apply, mlp_init

        policy = self.local_policy
        act_dim = policy.act_dim

        # Twin Q networks over [obs, action] (flat obs only).
        def q_apply(qparams, obs, act):
            x = jnp.concatenate(
                [obs.reshape((obs.shape[0], -1)), act], axis=-1)
            return mlp_apply(qparams, x)[..., 0]

        key = jax.random.PRNGKey(config.seed + 7)
        k1, k2 = jax.random.split(key)
        probe = self._env_creator(config.env_config)
        q_in = int(np.prod(probe.observation_space.shape)) + act_dim
        probe.close() if hasattr(probe, "close") else None
        hiddens = list(config.fcnet_hiddens) + [1]
        self._q_params = {
            "q1": mlp_init(k1, [q_in, *hiddens]),
            "q2": mlp_init(k2, [q_in, *hiddens]),
        }
        self._q_target = jax.tree.map(jnp.asarray, self._q_params)
        self._log_alpha = jnp.asarray(np.log(config.initial_alpha))
        self._actor_opt = optax.adam(config.lr)
        self._critic_opt = optax.adam(config.critic_lr)
        self._alpha_opt = optax.adam(config.alpha_lr)
        self._actor_state = self._actor_opt.init(policy.params)
        self._critic_state = self._critic_opt.init(self._q_params)
        self._alpha_state = self._alpha_opt.init(self._log_alpha)
        self._buffer = ReplayBuffer(config.replay_buffer_capacity,
                                    seed=config.seed)
        target_entropy = (-float(act_dim)
                          if config.target_entropy == "auto"
                          else float(config.target_entropy))
        gamma, tau = config.gamma, config.tau

        def critic_loss(q_params, q_target, actor_params, log_alpha, mb,
                        key):
            k_target, k_penalty = jax.random.split(key)
            next_a, next_logp = policy.logp_and_sample(
                actor_params, mb["new_obs"], k_target)
            q1_t = q_apply(q_target["q1"], mb["new_obs"], next_a)
            q2_t = q_apply(q_target["q2"], mb["new_obs"], next_a)
            alpha = jnp.exp(log_alpha)
            q_next = jnp.minimum(q1_t, q2_t) - alpha * next_logp
            done = mb["terminateds"]
            target = mb["rewards"] + gamma * (1 - done) * q_next
            target = jax.lax.stop_gradient(target)
            q1 = q_apply(q_params["q1"], mb["obs"], mb["actions"])
            q2 = q_apply(q_params["q2"], mb["obs"], mb["actions"])
            td = ((q1 - target) ** 2 + (q2 - target) ** 2).mean()
            # Hook for conservative variants (CQL overrides; 0 for SAC).
            return td + self._conservative_penalty(
                q_apply, q_params, actor_params, mb, k_penalty)

        def actor_loss(actor_params, q_params, log_alpha, mb, key):
            a, logp = policy.logp_and_sample(actor_params, mb["obs"], key)
            q1 = q_apply(q_params["q1"], mb["obs"], a)
            q2 = q_apply(q_params["q2"], mb["obs"], a)
            q = jnp.minimum(q1, q2)
            alpha = jax.lax.stop_gradient(jnp.exp(log_alpha))
            return (alpha * logp - q).mean(), logp

        def alpha_loss(log_alpha, logp):
            return (-log_alpha * jax.lax.stop_gradient(
                logp + target_entropy)).mean()

        def update(actor_params, q_params, q_target, log_alpha,
                   actor_state, critic_state, alpha_state, mb, key):
            k1, k2 = jax.random.split(key)
            c_loss, c_grads = jax.value_and_grad(critic_loss)(
                q_params, q_target, actor_params, log_alpha, mb, k1)
            c_updates, critic_state = self._critic_opt.update(
                c_grads, critic_state, q_params)
            q_params = optax.apply_updates(q_params, c_updates)

            (a_loss, logp), a_grads = jax.value_and_grad(
                actor_loss, has_aux=True)(actor_params, q_params,
                                          log_alpha, mb, k2)
            a_updates, actor_state = self._actor_opt.update(
                a_grads, actor_state, actor_params)
            actor_params = optax.apply_updates(actor_params, a_updates)

            al_loss, al_grad = jax.value_and_grad(alpha_loss)(
                log_alpha, logp)
            al_update, alpha_state = self._alpha_opt.update(
                al_grad, alpha_state, log_alpha)
            log_alpha = optax.apply_updates(log_alpha, al_update)

            q_target = jax.tree.map(
                lambda p, t: tau * p + (1 - tau) * t, q_params, q_target)
            metrics = {"critic_loss": c_loss, "actor_loss": a_loss,
                       "alpha_loss": al_loss,
                       "alpha": jnp.exp(log_alpha),
                       "entropy": -logp.mean()}
            return (actor_params, q_params, q_target, log_alpha,
                    actor_state, critic_state, alpha_state, metrics)

        self._update_jit = jax.jit(update)
        self._key = jax.random.PRNGKey(config.seed + 99)

    def _train_on_buffer(self, num_batches: int) -> Dict[str, Any]:
        """Run ``num_batches`` jitted SAC updates from the replay buffer
        (shared by SAC's online loop and CQL's offline-only loop)."""
        import jax
        import jax.numpy as jnp

        config: SACConfig = self.config
        actor_params = self.local_policy.params
        metrics: Dict[str, Any] = {}
        for _ in range(num_batches):
            mb = self._buffer.sample(config.train_batch_size)
            device_mb = {k: jnp.asarray(v) for k, v in mb.items()
                         if k in ("obs", "new_obs", "actions",
                                  "rewards", "terminateds")}
            self._key, sub = jax.random.split(self._key)
            (actor_params, self._q_params, self._q_target,
             self._log_alpha, self._actor_state, self._critic_state,
             self._alpha_state, metrics) = self._update_jit(
                actor_params, self._q_params, self._q_target,
                self._log_alpha, self._actor_state, self._critic_state,
                self._alpha_state, device_mb, sub)
        self.local_policy.params = actor_params
        return {k: float(v) for k, v in metrics.items()}

    def training_step(self) -> Dict[str, Any]:
        import ray_tpu
        config: SACConfig = self.config
        weights_ref = ray_tpu.put(self.get_weights())
        self.workers.sync_weights(weights_ref)
        batch = self.workers.sample(max(config.rollout_fragment_length, 1))
        self._timesteps_total += len(batch)
        self._buffer.add(batch)
        metrics_out: Dict[str, Any] = {}
        if len(self._buffer) >= max(
                config.num_steps_sampled_before_learning_starts,
                config.train_batch_size):
            metrics_out = self._train_on_buffer(
                config.num_train_batches_per_iteration)
        metrics_out["replay_buffer_size"] = len(self._buffer)
        return metrics_out
