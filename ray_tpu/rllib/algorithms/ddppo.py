"""DDPPO: decentralized distributed PPO.

Analog of the reference's rllib/algorithms/ddppo (Wijmans et al. 2019):
sample batches NEVER travel to the driver — every rollout worker runs
the full PPO minibatch-SGD loop on its OWN samples, all-reducing
gradients with its peers once per minibatch (the reference does this
with torch DDP over NCCL/Gloo; here the workers form a
``ray_tpu.util.collective`` group and ring-allreduce the flattened
gradient vector). All workers start from identical weights and apply
identical averaged gradients with identical optimizer states, so their
parameters stay bit-synchronized without any central learner; the
driver only triggers iterations, aggregates metrics, and mirrors worker
0's weights for checkpointing/evaluation.

Scaling consequence (the reference's pitch): driver bandwidth drops
from O(train_batch) per iteration to O(metrics), so rollout fleet size
stops being bounded by the learner's ingest rate.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithms.ppo import PPO, PPOConfig
from ray_tpu.rllib.policy.sample_batch import SampleBatch


class DDPPOConfig(PPOConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or DDPPO)
        self.num_rollout_workers = 2
        #: steps EACH worker samples and learns on per iteration (the
        #: reference's rollout_fragment_length * num_envs_per_worker;
        #: train_batch_size is ignored by design — there is no central
        #: batch).
        self.steps_per_worker = 256

    def training(self, *, steps_per_worker=None,
                 **kwargs) -> "DDPPOConfig":
        super().training(**kwargs)
        if steps_per_worker is not None:
            self.steps_per_worker = steps_per_worker
        return self


def _flat(grads):
    import jax
    import jax.numpy as jnp
    leaves, treedef = jax.tree.flatten(grads)
    return (jnp.concatenate([jnp.ravel(g) for g in leaves]),
            [g.shape for g in leaves], treedef)


def _unflat(vec, shapes, treedef):
    import jax
    import jax.numpy as jnp
    out, off = [], 0
    for shp in shapes:
        n = int(np.prod(shp)) if shp else 1
        out.append(jnp.reshape(vec[off:off + n], shp))
        off += n
    return jax.tree.unflatten(treedef, out)


def _worker_learn(worker, cfg: Dict[str, Any], iteration: int):
    """Runs ON each rollout worker (via worker.apply): sample locally,
    PPO-SGD locally, ring-allreduce gradients per minibatch. Every
    worker must call this the same number of times with the same cfg —
    the allreduces are collective."""
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.util import collective

    policy = worker.policy
    state = getattr(worker, "_ddppo", None)
    if state is None or state.get("reset_epoch") != cfg["reset_epoch"]:
        # reset_epoch bumps when the driver re-broadcast weights
        # (restore/set_weights): fresh params need a fresh optimizer
        # state on every worker, identically.
        from ray_tpu.rllib.algorithms.ppo import make_ppo_loss
        optimizer = optax.adam(cfg["lr"])
        loss_fn = make_ppo_loss(policy, cfg["clip_param"],
                                cfg["vf_loss_coeff"],
                                cfg["entropy_coeff"])

        def total_loss(params, mb):
            return loss_fn(params, mb)[0]

        grad_fn = jax.jit(jax.value_and_grad(total_loss))

        def apply_fn(params, opt_state, flat_grad, shapes_treedef):
            grads = _unflat(flat_grad, *shapes_treedef)
            updates, opt_state = optimizer.update(grads, opt_state,
                                                  params)
            return optax.apply_updates(params, updates), opt_state

        state = {
            "optimizer": optimizer,
            "opt_state": optimizer.init(policy.params),
            "grad_fn": grad_fn,
            "apply_fn": jax.jit(apply_fn, static_argnums=(3,)),
            "reset_epoch": cfg["reset_epoch"],
        }
        worker._ddppo = state

    batch = worker.sample(cfg["steps_per_worker"])
    adv = np.asarray(batch[SampleBatch.ADVANTAGES], np.float32)
    adv = (adv - adv.mean()) / max(adv.std(), 1e-6)
    sb = SampleBatch({
        "obs": np.asarray(batch[SampleBatch.OBS], np.float32),
        "actions": np.asarray(batch[SampleBatch.ACTIONS]),
        "old_logp": np.asarray(batch[SampleBatch.ACTION_LOGP],
                               np.float32),
        "advantages": adv,
        "value_targets": np.asarray(batch[SampleBatch.VALUE_TARGETS],
                                    np.float32),
    })
    params = policy.params
    mb_size = min(cfg["sgd_minibatch_size"], len(sb))
    last_loss = 0.0
    for epoch in range(cfg["num_sgd_iter"]):
        # Same seed on every worker -> same MINIBATCH COUNT and order
        # of collective calls (contents differ: local data).
        for mb in sb.minibatches(mb_size, seed=1000 * iteration + epoch):
            device_mb = {k: jnp.asarray(v) for k, v in mb.items()}
            loss, grads = state["grad_fn"](params, device_mb)
            vec, shapes, treedef = _flat(grads)
            # DDPPO's core move: gradients average ACROSS workers here;
            # no sample or gradient ever reaches the driver.
            total = collective.allreduce(np.asarray(vec), op="sum",
                                         group_name=cfg["group_name"])
            avg = total / cfg["world_size"]
            params, state["opt_state"] = state["apply_fn"](
                params, state["opt_state"], jnp.asarray(avg),
                (tuple(shapes), treedef))
            last_loss = float(loss)
    policy.params = params
    # Episode stats flow through WorkerSet.episode_stats (Algorithm
    # .train) — only scalars travel back here.
    return {"steps": len(sb), "loss": last_loss}


class DDPPO(PPO):
    _default_config_class = DDPPOConfig
    _supports_multi_agent = False

    def setup(self, config: DDPPOConfig) -> None:
        if self.workers.num_workers() < 2:
            raise ValueError(
                "DDPPO is decentralized across workers: set "
                ".rollouts(num_rollout_workers=2) or more")
        # No central learner state (PPO.setup would build one); workers
        # bit-synchronize by averaging gradients, starting from the
        # driver's initial weights.
        import ray_tpu

        from ray_tpu.util import collective
        self._group_name = f"ddppo-{id(self):x}"
        workers = self.workers.remote_workers
        collective.create_collective_group(
            workers, len(workers), list(range(len(workers))),
            group_name=self._group_name)
        #: bumps whenever driver weights must overwrite the workers'
        #: (initial broadcast, restore(), set_weights()) — workers
        #: rebuild their optimizer state when they see a new epoch.
        self._reset_epoch = 0
        self._weights_dirty = True

    def set_weights(self, weights) -> None:
        """Driver-side weight injection (restore(), manual set) must
        reach the decentralized learners — mark for re-broadcast; the
        next training_step ships them before learning."""
        super().set_weights(weights)
        self._weights_dirty = True

    def training_step(self) -> Dict[str, Any]:
        import ray_tpu
        config: DDPPOConfig = self.config
        if self._weights_dirty:
            self._reset_epoch += 1
            self._weights_dirty = False
            weights_ref = ray_tpu.put(self.get_weights())
            self.workers.sync_weights(weights_ref)
        cfg = {
            "lr": config.lr,
            "clip_param": config.clip_param,
            "vf_loss_coeff": config.vf_loss_coeff,
            "entropy_coeff": config.entropy_coeff,
            "num_sgd_iter": config.num_sgd_iter,
            "sgd_minibatch_size": config.sgd_minibatch_size,
            "steps_per_worker": config.steps_per_worker,
            "group_name": self._group_name,
            "world_size": self.workers.num_workers(),
            "reset_epoch": self._reset_epoch,
        }
        results = ray_tpu.get(
            [w.apply.remote(_worker_learn, cfg, self.iteration)
             for w in self.workers.remote_workers])
        self._timesteps_total += sum(r["steps"] for r in results)
        # Workers stay bit-identical; mirror worker 0 for save/evaluate.
        self.local_policy.set_weights(
            ray_tpu.get(self.workers.remote_workers[0]
                        .get_weights.remote()))
        return {"loss": float(np.mean([r["loss"] for r in results])),
                "steps_this_iter": sum(r["steps"] for r in results)}
