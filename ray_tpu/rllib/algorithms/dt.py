"""DT: Decision Transformer — offline RL as sequence modeling.

Analog of the reference's rllib/algorithms/dt (Chen et al. 2021): logged
episodes become sequences of (return-to-go, observation, action) token
triples; a small causal transformer is trained to predict each action
from the tokens before it. At evaluation time the agent CONDITIONS on a
high target return — writing the desired outcome into the prompt — and
decrements it by the observed rewards as the episode unfolds, so the
policy extracted from mixed-quality data can outperform the average
behavior that produced it.

Offline-only like bc.py: set ``config.offline_data(input_=<dir>)`` with
JsonWriter output. Discrete action spaces train with cross-entropy; Box
action spaces with MSE on tanh-squashed predictions. The transformer is
self-contained (learned position embeddings, pre-LN blocks, causal mask
over the 3K-token interleaving) — the models/gpt.py stack is an LM with
token vocabularies, the wrong shape for continuous embeddings here.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.policy.sample_batch import SampleBatch


class DTConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or DT)
        self.lr = 1e-3
        self.train_batch_size = 64
        self.num_rollout_workers = 0   # offline: WorkerSet stays empty
        self.num_train_batches_per_iteration = 50
        self.context_len = 20          # K timesteps = 3K tokens
        self.embed_dim = 64
        self.n_layers = 2
        self.n_heads = 4
        self.max_ep_len = 1000         # timestep-embedding table size
        #: return-to-go the evaluator conditions on (reference: DTConfig
        #: target_return); None = max return seen in the dataset.
        self.target_return = None
        self.rtg_scale = 100.0         # normalizes RTG token magnitudes

    def training(self, *, context_len=None, embed_dim=None, n_layers=None,
                 n_heads=None, target_return=None, rtg_scale=None,
                 num_train_batches_per_iteration=None, max_ep_len=None,
                 **kwargs) -> "DTConfig":
        super().training(**kwargs)
        for name, val in (("context_len", context_len),
                          ("embed_dim", embed_dim),
                          ("n_layers", n_layers), ("n_heads", n_heads),
                          ("target_return", target_return),
                          ("rtg_scale", rtg_scale),
                          ("max_ep_len", max_ep_len),
                          ("num_train_batches_per_iteration",
                           num_train_batches_per_iteration)):
            if val is not None:
                setattr(self, name, val)
        return self


class DT(Algorithm):
    _default_config_class = DTConfig

    def __init__(self, config=None, **kwargs):
        cfg = config or self.get_default_config()
        if not cfg.input_:
            raise ValueError(
                "DT is offline-only: set config.offline_data(input_=<dir "
                "of JSON experience files written by JsonWriter>)")
        super().__init__(config=config, **kwargs)

    # -- model -----------------------------------------------------------

    def _build_model(self, config: DTConfig):
        import jax
        import jax.numpy as jnp

        D, H, L = config.embed_dim, config.n_heads, config.n_layers
        K = config.context_len
        obs_dim, act_dim = self._obs_dim, self._act_dim
        discrete = self._discrete

        def dense(key, din, dout):
            k1, _ = jax.random.split(key)
            return {"w": jax.random.normal(k1, (din, dout)) * 0.02,
                    "b": jnp.zeros((dout,))}

        def apply_dense(p, x):
            return x @ p["w"] + p["b"]

        key = jax.random.PRNGKey(config.seed)
        ks = iter(jax.random.split(key, 16 + 8 * L))
        act_in = act_dim  # one-hot width (discrete) or raw dims (Box)
        params = {
            "embed_rtg": dense(next(ks), 1, D),
            "embed_obs": dense(next(ks), obs_dim, D),
            "embed_act": dense(next(ks), act_in, D),
            "embed_t": jax.random.normal(
                next(ks), (config.max_ep_len, D)) * 0.02,
            "head": dense(next(ks), D, act_dim),
            "ln_f": {"g": jnp.ones((D,)), "b": jnp.zeros((D,))},
            "blocks": [],
        }
        for _ in range(L):
            params["blocks"].append({
                "ln1": {"g": jnp.ones((D,)), "b": jnp.zeros((D,))},
                "ln2": {"g": jnp.ones((D,)), "b": jnp.zeros((D,))},
                "qkv": dense(next(ks), D, 3 * D),
                "proj": dense(next(ks), D, D),
                "fc1": dense(next(ks), D, 4 * D),
                "fc2": dense(next(ks), 4 * D, D),
            })

        def ln(p, x):
            mu = x.mean(-1, keepdims=True)
            var = x.var(-1, keepdims=True)
            return (x - mu) / jnp.sqrt(var + 1e-5) * p["g"] + p["b"]

        def block(p, x, mask):
            B, T, _ = x.shape
            h = ln(p["ln1"], x)
            qkv = apply_dense(p["qkv"], h).reshape(B, T, 3, H, D // H)
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            att = jnp.einsum("bthd,bshd->bhts", q, k) / np.sqrt(D // H)
            att = jnp.where(mask, att, -1e9)
            att = jax.nn.softmax(att, -1)
            out = jnp.einsum("bhts,bshd->bthd", att, v).reshape(B, T, D)
            x = x + apply_dense(p["proj"], out)
            h = ln(p["ln2"], x)
            h = jax.nn.gelu(apply_dense(p["fc1"], h))
            return x + apply_dense(p["fc2"], h)

        causal = jnp.tril(jnp.ones((3 * K, 3 * K), bool))[None, None]

        def forward(p, rtg, obs, act, timesteps, pad_mask):
            """rtg [B,K,1], obs [B,K,obs_dim], act [B,K,act_in],
            timesteps [B,K] int, pad_mask [B,K] -> action preds [B,K,.]
            read from each OBS token position (sees rtg_t, obs_t and
            everything before, not act_t)."""
            B, K_, _ = obs.shape
            te = p["embed_t"][timesteps]                      # [B,K,D]
            tok_r = apply_dense(p["embed_rtg"], rtg) + te
            tok_o = apply_dense(p["embed_obs"], obs) + te
            tok_a = apply_dense(p["embed_act"], act) + te
            # Interleave [r_0,o_0,a_0, r_1,o_1,a_1, ...] -> [B,3K,D].
            x = jnp.stack([tok_r, tok_o, tok_a], axis=2).reshape(
                B, 3 * K_, -1)
            m = jnp.repeat(pad_mask, 3, axis=-1)              # [B,3K]
            mask = causal[:, :, :3 * K_, :3 * K_] & \
                m[:, None, None, :].astype(bool)
            for bp in p["blocks"]:
                x = block(bp, x, mask)
            x = ln(p["ln_f"], x)
            obs_tokens = x.reshape(B, K_, 3, -1)[:, :, 1]     # o_t slots
            return apply_dense(p["head"], obs_tokens)         # [B,K,act]

        return params, forward

    # -- setup -----------------------------------------------------------

    def setup(self, config: DTConfig) -> None:
        import gymnasium as gym
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.rllib.offline.json_reader import JsonReader

        pol = self.local_policy
        self._obs_dim = pol.obs_dim
        space = pol.action_space
        self._discrete = isinstance(space, gym.spaces.Discrete)
        self._act_dim = (int(space.n) if self._discrete
                         else int(np.prod(space.shape)))

        # Slice the dataset into episodes once, up front.
        batch = JsonReader(config.input_).read_all()
        obs = np.asarray(batch[SampleBatch.OBS], np.float32)
        acts = np.asarray(batch[SampleBatch.ACTIONS])
        if not self._discrete:
            # Normalize logged Box actions to [-1, 1] — the range
            # tanh(pred) is fit against; evaluate_env maps back.
            lo = np.asarray(space.low, np.float32).reshape(-1)
            hi = np.asarray(space.high, np.float32).reshape(-1)
            acts = 2.0 * (np.asarray(acts, np.float32).reshape(
                len(acts), -1) - lo) / np.maximum(hi - lo, 1e-8) - 1.0
        rews = np.asarray(batch[SampleBatch.REWARDS], np.float32)
        eps = np.asarray(batch[SampleBatch.EPS_ID])
        self._episodes: List[Dict[str, np.ndarray]] = []
        for e in np.unique(eps):
            idx = np.where(eps == e)[0]
            r = rews[idx]
            rtg = np.cumsum(r[::-1])[::-1].copy()  # returns-to-go
            self._episodes.append({
                "obs": obs[idx], "actions": acts[idx], "rtg": rtg,
                "timesteps": np.arange(len(idx)) % config.max_ep_len})
        self._dataset_max_return = max(
            float(ep["rtg"][0]) for ep in self._episodes)

        self.params, self._forward = self._build_model(config)
        self._optimizer = optax.adam(config.lr)
        self._opt_state = self._optimizer.init(self.params)
        forward = self._forward
        discrete, act_dim = self._discrete, self._act_dim

        def loss_fn(params, mb):
            preds = forward(params, mb["rtg"], mb["obs"], mb["act_in"],
                            mb["timesteps"], mb["mask"])
            m = mb["mask"]
            if discrete:
                logp = jax.nn.log_softmax(preds, -1)
                picked = jnp.take_along_axis(
                    logp, mb["actions"][..., None].astype(jnp.int32),
                    -1)[..., 0]
                return -(picked * m).sum() / jnp.maximum(m.sum(), 1.0)
            err = ((jnp.tanh(preds) - mb["actions"]) ** 2).mean(-1)
            return (err * m).sum() / jnp.maximum(m.sum(), 1.0)

        def update(params, opt_state, mb):
            loss, grads = jax.value_and_grad(loss_fn)(params, mb)
            updates, opt_state = self._optimizer.update(
                grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        self._update_jit = jax.jit(update)
        self._forward_jit = jax.jit(forward)
        self._rng = np.random.default_rng(config.seed)

    def _sample_minibatch(self, config: DTConfig) -> Dict[str, Any]:
        import jax.numpy as jnp
        K = config.context_len
        B = config.train_batch_size
        rows = {"rtg": [], "obs": [], "actions": [], "act_in": [],
                "timesteps": [], "mask": []}
        # Episodes weighted by length (reference DT samples timesteps
        # uniformly over the dataset).
        lens = np.asarray([len(ep["obs"]) for ep in self._episodes],
                          np.float64)
        p = lens / lens.sum()
        for _ in range(B):
            ep = self._episodes[self._rng.choice(len(self._episodes), p=p)]
            T = len(ep["obs"])
            end = int(self._rng.integers(1, T + 1))
            start = max(0, end - K)
            sl = slice(start, end)
            n = end - start
            pad = K - n

            def padk(x, extra=()):
                out = np.zeros((K,) + tuple(extra), np.float32)
                v = np.asarray(x, np.float32)
                out[pad:] = v.reshape((n,) + tuple(extra))
                return out

            rows["rtg"].append(padk(ep["rtg"][sl] / config.rtg_scale,
                                    (1,)))
            rows["obs"].append(padk(ep["obs"][sl], (self._obs_dim,)))
            a = ep["actions"][sl]
            # a_t rides in its own token AFTER o_t in the interleave, so
            # the causal mask alone keeps it out of a_t's own prediction
            # (read at the o_t position) — no shifting needed.
            if self._discrete:
                rows["actions"].append(padk(a))
                onehot = np.zeros((K, self._act_dim), np.float32)
                onehot[np.arange(pad, K), np.asarray(a, int)] = 1.0
                rows["act_in"].append(onehot)
            else:
                av = padk(a, (self._act_dim,))
                rows["actions"].append(av)
                rows["act_in"].append(av)
            ts = np.zeros(K, np.int32)
            ts[pad:] = ep["timesteps"][sl]
            rows["timesteps"].append(ts)
            m = np.zeros(K, np.float32)
            m[pad:] = 1.0
            rows["mask"].append(m)
        out = {k: jnp.asarray(np.stack(v)) for k, v in rows.items()}
        out["timesteps"] = out["timesteps"].astype(jnp.int32)
        if self._discrete:
            out["actions"] = out["actions"].astype(jnp.int32)
        return out

    def training_step(self) -> Dict[str, Any]:
        config: DTConfig = self.config
        losses = []
        params = self.params
        for _ in range(config.num_train_batches_per_iteration):
            mb = self._sample_minibatch(config)
            self._timesteps_total += config.train_batch_size
            params, self._opt_state, loss = self._update_jit(
                params, self._opt_state, mb)
            losses.append(float(loss))
        self.params = params
        return {"loss": float(np.mean(losses)),
                "dataset_max_return": self._dataset_max_return,
                "num_batches": len(losses)}

    # -- return-conditioned rollout --------------------------------------

    def evaluate_env(self, env, target_return: float = None,
                     episodes: int = 5, seed: int = 0) -> float:
        """Roll out with return-to-go conditioning (the DT inference
        procedure): prompt with the target, decrement by observed
        rewards each step."""
        import jax.numpy as jnp
        config: DTConfig = self.config
        if target_return is None:
            target_return = (config.target_return
                             if config.target_return is not None
                             else self._dataset_max_return)
        K = config.context_len
        total = 0.0
        for e in range(episodes):
            obs, _ = env.reset(seed=seed + e)
            rtg = [float(target_return)]
            obs_hist = [np.asarray(obs, np.float32).reshape(-1)]
            act_hist: List[Any] = []
            done = False
            t = 0
            while not done:
                n = min(len(obs_hist), K)
                pad = K - n
                rtg_w = np.zeros((K, 1), np.float32)
                rtg_w[pad:, 0] = np.asarray(rtg[-n:]) / config.rtg_scale
                obs_w = np.zeros((K, self._obs_dim), np.float32)
                obs_w[pad:] = np.stack(obs_hist[-n:])
                # Window timesteps t-n+1..t: every action but the final
                # one is known; the final a_t slot stays zero (the o_t
                # position that predicts it never attends to it).
                act_w = np.zeros((K, self._act_dim), np.float32)
                prev = act_hist[-(n - 1):] if n > 1 else []
                for i, a in enumerate(prev):
                    if self._discrete:
                        act_w[pad + i, int(a)] = 1.0
                    else:
                        act_w[pad + i] = a
                ts = np.zeros(K, np.int32)
                ts[pad:] = [min(t - n + 1 + i, config.max_ep_len - 1)
                            for i in range(n)]
                m = np.zeros(K, np.float32)
                m[pad:] = 1.0
                preds = self._forward_jit(
                    self.params, jnp.asarray(rtg_w[None]),
                    jnp.asarray(obs_w[None]), jnp.asarray(act_w[None]),
                    jnp.asarray(ts[None]), jnp.asarray(m[None]))
                pred = np.asarray(preds[0, -1])
                if self._discrete:
                    action = int(pred.argmax())
                    hist_entry: Any = action
                else:
                    # Model space is the normalized [-1, 1] cube (same
                    # normalization training fit against); map to env
                    # bounds only for stepping.
                    norm = np.tanh(pred)
                    space = self.local_policy.action_space
                    lo = np.asarray(space.low, np.float32)
                    hi = np.asarray(space.high, np.float32)
                    action = lo + (norm + 1.0) * 0.5 * (hi - lo)
                    hist_entry = norm
                obs, r, term, trunc, _ = env.step(action)
                done = term or trunc
                total += float(r)
                t += 1
                act_hist.append(hist_entry)
                obs_hist.append(np.asarray(obs, np.float32).reshape(-1))
                rtg.append(rtg[-1] - float(r))
        return total / episodes

    def get_weights(self):
        import jax
        return {"dt_params": jax.tree.map(np.asarray, self.params)}

    def set_weights(self, weights) -> None:
        import jax
        import jax.numpy as jnp
        self.params = jax.tree.map(jnp.asarray, weights["dt_params"])
