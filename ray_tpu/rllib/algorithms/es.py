"""ES: OpenAI-style evolution strategies.

Analog of the reference's rllib/algorithms/es: derivative-free policy
search. A shared Gaussian noise table lives in the object store; evaluator
actors draw antithetic perturbation pairs theta ± sigma*eps (eps = a slice
of the table addressed by index, so only indices travel back), roll out
one episode per perturbation, and the driver combines centered-rank
weighted noise into a gradient estimate applied with Adam. No
backpropagation anywhere — the policy network only runs forward, which
makes ES trivially parallel across CPU actors while the MLP forward is
still XLA-compiled.

Differences from the reference: no observation mean/std filter (the
connector-level MeanStd filter covers that capability elsewhere), and the
policy is the standard catalog MLP rather than a bespoke ES net.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig


def create_shared_noise(count: int = 1_000_000, seed: int = 42
                        ) -> np.ndarray:
    """The shared noise table (reference: es/utils.py create_shared_noise):
    one big float32 Gaussian array; perturbations are random slices."""
    return np.random.default_rng(seed).standard_normal(
        count).astype(np.float32)


def centered_ranks(x: np.ndarray) -> np.ndarray:
    """Rank-transform returns to [-0.5, 0.5] (reference:
    es/utils.py compute_centered_ranks) — scale-free fitness shaping."""
    ranks = np.empty(x.size, dtype=np.float32)
    ranks[x.ravel().argsort()] = np.arange(x.size, dtype=np.float32)
    return (ranks / (x.size - 1) - 0.5).reshape(x.shape)


class ESWorker:
    """Perturbation evaluator actor: holds the env, a policy skeleton and
    the noise table; evaluates antithetic pairs deterministically."""

    def __init__(self, env_creator, policy_config, noise, worker_index=0,
                 seed=0):
        import jax

        from ray_tpu.rllib.policy import make_policy
        self.env = env_creator(policy_config.get("env_config") or {})
        self.policy = make_policy(policy_config,
                                  self.env.observation_space,
                                  self.env.action_space, seed=seed)
        from jax.flatten_util import ravel_pytree
        _, self._unravel = ravel_pytree(self.policy.params)
        self.noise = np.asarray(noise)
        self._logits = jax.jit(self.policy.logits)
        self._rng = np.random.default_rng(seed * 1000 + worker_index)
        self.worker_index = worker_index
        if not self.policy.discrete:
            space = self.env.action_space
            self._low = np.asarray(space.low, np.float32)
            self._high = np.asarray(space.high, np.float32)

    def _rollout(self, theta: np.ndarray, horizon: int):
        params = self._unravel(theta)
        obs, _ = self.env.reset(
            seed=int(self._rng.integers(0, 2**31 - 1)))
        total, steps, done = 0.0, 0, False
        while not done and steps < horizon:
            logits = np.asarray(self._logits(
                params, np.asarray(obs, np.float32).reshape(1, -1)))
            if self.policy.discrete:
                action = int(logits.argmax(-1)[0])
            else:
                # A perturbed unbounded head can leave the action space;
                # clip like the clip_actions connector does elsewhere.
                action = np.clip(logits[0], self._low, self._high)
            obs, reward, terminated, truncated, _ = self.env.step(action)
            total += float(reward)
            steps += 1
            done = terminated or truncated
        return total, steps

    def do_rollouts(self, theta: np.ndarray, num_pairs: int, sigma: float,
                    horizon: int) -> Dict[str, Any]:
        theta = np.asarray(theta, np.float32)
        dim = theta.size
        indices, r_pos, r_neg, lengths = [], [], [], []
        for _ in range(num_pairs):
            idx = int(self._rng.integers(0, self.noise.size - dim + 1))
            eps = self.noise[idx:idx + dim]
            ret_p, len_p = self._rollout(theta + sigma * eps, horizon)
            ret_n, len_n = self._rollout(theta - sigma * eps, horizon)
            indices.append(idx)
            r_pos.append(ret_p)
            r_neg.append(ret_n)
            lengths.extend((len_p, len_n))
        return {
            "noise_indices": np.asarray(indices, np.int64),
            "returns_pos": np.asarray(r_pos, np.float32),
            "returns_neg": np.asarray(r_neg, np.float32),
            "lengths": np.asarray(lengths, np.int64),
        }


class ESConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or ES)
        self.num_rollout_workers = 2       # evaluator actors
        self.noise_stdev = 0.05
        self.stepsize = 0.03
        self.num_rollout_pairs_per_worker = 10
        self.episode_horizon = 1000
        self.noise_table_size = 1_000_000
        self.fcnet_hiddens = (32, 32)

    def training(self, *, noise_stdev=None, stepsize=None,
                 num_rollout_pairs_per_worker=None, episode_horizon=None,
                 noise_table_size=None, **kwargs) -> "ESConfig":
        super().training(**kwargs)
        for name, val in (
                ("noise_stdev", noise_stdev), ("stepsize", stepsize),
                ("num_rollout_pairs_per_worker",
                 num_rollout_pairs_per_worker),
                ("episode_horizon", episode_horizon),
                ("noise_table_size", noise_table_size)):
            if val is not None:
                setattr(self, name, val)
        return self


class ES(Algorithm):
    _default_config_class = ESConfig
    _own_rollout_actors = True

    def setup(self, config: ESConfig) -> None:
        import optax
        from jax.flatten_util import ravel_pytree

        theta0, self._unravel = ravel_pytree(self.local_policy.params)
        if int(theta0.size) > config.noise_table_size:
            raise ValueError(
                f"Policy has {int(theta0.size)} parameters but the shared "
                f"noise table holds only {config.noise_table_size}; raise "
                "config.training(noise_table_size=...) above the parameter "
                "count")
        self._noise = create_shared_noise(config.noise_table_size,
                                          seed=config.seed + 123)
        noise_ref = ray_tpu.put(self._noise)
        worker_cls = ray_tpu.remote(ESWorker)
        self._es_workers = [
            worker_cls.options(num_cpus=config.num_cpus_per_worker).remote(
                self._env_creator, config.policy_config(), noise_ref,
                worker_index=i + 1, seed=config.seed)
            for i in range(max(config.num_rollout_workers, 1))]
        self._theta = np.asarray(theta0, np.float32)
        self._optimizer = optax.adam(config.stepsize)
        self._opt_state = self._optimizer.init(self._theta)
        self._episodes_total = 0

    def _gradient(self, indices, returns_pos, returns_neg) -> np.ndarray:
        """Centered-rank antithetic gradient estimate (maximization)."""
        dim = self._theta.size
        ranks = centered_ranks(
            np.concatenate([returns_pos, returns_neg]))
        w = ranks[:len(returns_pos)] - ranks[len(returns_pos):]
        g = np.zeros(dim, np.float32)
        for weight, idx in zip(w, indices):
            g += weight * self._noise[idx:idx + dim]
        return g / max(len(indices), 1)

    def training_step(self) -> Dict[str, Any]:
        import optax
        config: ESConfig = self.config
        theta_ref = ray_tpu.put(self._theta)
        results = ray_tpu.get([
            w.do_rollouts.remote(theta_ref,
                                 config.num_rollout_pairs_per_worker,
                                 config.noise_stdev,
                                 config.episode_horizon)
            for w in self._es_workers])
        indices = np.concatenate([r["noise_indices"] for r in results])
        returns_pos = np.concatenate([r["returns_pos"] for r in results])
        returns_neg = np.concatenate([r["returns_neg"] for r in results])
        lengths = np.concatenate([r["lengths"] for r in results])
        self._timesteps_total += int(lengths.sum())
        self._episodes_total += lengths.size

        grad = self._gradient(indices, returns_pos, returns_neg)
        # optax minimizes; ES ascends the return.
        updates, self._opt_state = self._optimizer.update(
            -grad, self._opt_state, self._theta)
        self._theta = np.asarray(optax.apply_updates(self._theta, updates),
                                 np.float32)
        self.local_policy.params = self._unravel(self._theta)

        all_returns = np.concatenate([returns_pos, returns_neg])
        return {
            "episode_reward_mean": float(all_returns.mean()),
            "episode_reward_max": float(all_returns.max()),
            "episode_len_mean": float(lengths.mean()),
            "episodes_total": self._episodes_total,
            "grad_norm": float(np.linalg.norm(grad)),
            "update_ratio": float(
                np.linalg.norm(np.asarray(updates))
                / (np.linalg.norm(self._theta) + 1e-8)),
        }

    def stop(self) -> None:
        for w in self._es_workers:
            ray_tpu.kill(w)
        super().stop()
