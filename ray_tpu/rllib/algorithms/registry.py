"""Algorithm registry (reference: rllib/algorithms/registry.py):
name → (Algorithm class, default config)."""

from __future__ import annotations

from typing import Tuple, Type


def get_algorithm_class(name: str) -> Type:
    from ray_tpu.rllib.algorithms.a2c import A2C
    from ray_tpu.rllib.algorithms.a3c import A3C
    from ray_tpu.rllib.algorithms.apex_dqn import ApexDQN
    from ray_tpu.rllib.algorithms.apex_ddpg import ApexDDPG
    from ray_tpu.rllib.algorithms.alpha_star import AlphaStar
    from ray_tpu.rllib.algorithms.alpha_zero import AlphaZero
    from ray_tpu.rllib.algorithms.appo import APPO
    from ray_tpu.rllib.algorithms.ars import ARS
    from ray_tpu.rllib.algorithms.bandit import BanditLinTS, BanditLinUCB
    from ray_tpu.rllib.algorithms.bc import BC
    from ray_tpu.rllib.algorithms.cql import CQL
    from ray_tpu.rllib.algorithms.crr import CRR
    from ray_tpu.rllib.algorithms.ddpg import DDPG
    from ray_tpu.rllib.algorithms.ddppo import DDPPO
    from ray_tpu.rllib.algorithms.dqn import DQN
    from ray_tpu.rllib.algorithms.dreamer import Dreamer
    from ray_tpu.rllib.algorithms.dt import DT
    from ray_tpu.rllib.algorithms.es import ES
    from ray_tpu.rllib.algorithms.impala import Impala
    from ray_tpu.rllib.algorithms.maddpg import MADDPG
    from ray_tpu.rllib.algorithms.maml import MAML
    from ray_tpu.rllib.algorithms.marwil import MARWIL
    from ray_tpu.rllib.algorithms.mbmpo import MBMPO
    from ray_tpu.rllib.algorithms.pg import PG
    from ray_tpu.rllib.algorithms.ppo import PPO
    from ray_tpu.rllib.algorithms.qmix import QMix
    from ray_tpu.rllib.algorithms.r2d2 import R2D2
    from ray_tpu.rllib.algorithms.random_agent import RandomAgent
    from ray_tpu.rllib.algorithms.rainbow import Rainbow
    from ray_tpu.rllib.algorithms.sac import SAC
    from ray_tpu.rllib.algorithms.simple_q import SimpleQ
    from ray_tpu.rllib.algorithms.slateq import SlateQ
    from ray_tpu.rllib.algorithms.td3 import TD3

    table = {"PPO": PPO, "DQN": DQN, "SAC": SAC, "A2C": A2C, "A3C": A3C,
             "IMPALA": Impala, "TD3": TD3, "BC": BC, "APPO": APPO,
             "PG": PG, "MARWIL": MARWIL, "DDPG": DDPG, "SIMPLEQ": SimpleQ,
             "APEX": ApexDQN, "APEX-DQN": ApexDQN,
             "APEX-DDPG": ApexDDPG, "RANDOM": RandomAgent, "RAINBOW": Rainbow,
             "R2D2": R2D2, "QMIX": QMix, "MADDPG": MADDPG,
             "SLATEQ": SlateQ,
             "ES": ES, "ARS": ARS, "CQL": CQL, "DT": DT, "CRR": CRR,
             "DDPPO": DDPPO, "ALPHAZERO": AlphaZero,
             "ALPHASTAR": AlphaStar, "DREAMER": Dreamer,
             "MAML": MAML, "MBMPO": MBMPO,
             "BANDITLINUCB": BanditLinUCB, "BANDITLINTS": BanditLinTS}
    try:
        return table[name.upper()]
    except KeyError:
        raise ValueError(
            f"Unknown algorithm {name!r}; available: {sorted(table)}"
        ) from None
