"""AlphaZero: one-player MCTS planning + learned policy/value priors.

Analog of the reference's rllib/algorithms/alpha_zero (Silver et al.
2017 adapted to single-player envs, with the "ranked rewards" (R2)
strategy of Laterre et al. 2018): each move runs a PUCT tree search
over CLONABLE env states (``get_state``/``set_state`` — the env is the
simulator), guided by a policy/value network; visit counts become the
policy training target, and the value head regresses the R2 binary
reward (+1 when the episode return beats the rolling percentile of
recent returns, -1 otherwise) — the single-player stand-in for
two-player self-play win/loss that also normalizes rewards.

Env contract (reference README): Discrete actions; observations either
a plain vector or a dict ``{"obs": vec, "action_mask": 0/1 vec}``;
``get_state() -> opaque`` and ``set_state(s)`` restore mid-episode.
env/examples.py ClonableCartPole adapts CartPole (the reference's own
example task). Exploration: Dirichlet noise on the root priors +
sampling from visit counts; evaluation uses noiseless argmax.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.policy.sample_batch import SampleBatch
from ray_tpu.rllib.utils.replay_buffers import ReplayBuffer


class AlphaZeroConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or AlphaZero)
        self.lr = 5e-4
        self.train_batch_size = 128
        self.num_train_batches_per_iteration = 16
        self.replay_buffer_capacity = 20_000
        #: MCTS knobs (reference: alpha_zero.py mcts_config defaults).
        self.num_simulations = 30
        self.c_puct = 1.25
        self.dirichlet_alpha = 0.3
        self.dirichlet_epsilon = 0.25
        self.temperature = 1.0
        #: R2 ranked-rewards knobs.
        self.ranked_rewards_percentile = 75
        self.ranked_rewards_buffer = 100
        self.episodes_per_iteration = 4
        self.max_episode_steps = 200

    def training(self, *, num_simulations=None, c_puct=None,
                 dirichlet_alpha=None, dirichlet_epsilon=None,
                 temperature=None, ranked_rewards_percentile=None,
                 ranked_rewards_buffer=None, episodes_per_iteration=None,
                 max_episode_steps=None, replay_buffer_capacity=None,
                 num_train_batches_per_iteration=None,
                 **kwargs) -> "AlphaZeroConfig":
        super().training(**kwargs)
        for name, val in (
                ("num_simulations", num_simulations),
                ("c_puct", c_puct),
                ("dirichlet_alpha", dirichlet_alpha),
                ("dirichlet_epsilon", dirichlet_epsilon),
                ("temperature", temperature),
                ("ranked_rewards_percentile", ranked_rewards_percentile),
                ("ranked_rewards_buffer", ranked_rewards_buffer),
                ("episodes_per_iteration", episodes_per_iteration),
                ("max_episode_steps", max_episode_steps),
                ("replay_buffer_capacity", replay_buffer_capacity),
                ("num_train_batches_per_iteration",
                 num_train_batches_per_iteration)):
            if val is not None:
                setattr(self, name, val)
        return self


class _Node:
    """One tree node: per-action visit/value/prior stats."""

    __slots__ = ("n", "w", "p", "children", "legal")

    def __init__(self, priors: np.ndarray, legal: np.ndarray):
        a = len(priors)
        self.n = np.zeros(a, np.float32)
        self.w = np.zeros(a, np.float32)
        self.p = priors
        self.legal = legal
        self.children: Dict[int, "_Node"] = {}

    def q(self) -> np.ndarray:
        return self.w / np.maximum(self.n, 1.0)


def _split_obs(obs) -> tuple:
    if isinstance(obs, dict):
        return (np.asarray(obs["obs"], np.float32).reshape(-1),
                np.asarray(obs["action_mask"], np.float32))
    return np.asarray(obs, np.float32).reshape(-1), None


class AlphaZero(Algorithm):
    _default_config_class = AlphaZeroConfig
    _own_rollout_actors = True

    def setup(self, config: AlphaZeroConfig) -> None:
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.rllib.models.catalog import mlp_apply, mlp_init

        env = self._env_creator(config.env_config)
        for attr in ("get_state", "set_state"):
            if not hasattr(env, attr):
                raise ValueError(
                    "AlphaZero needs a clonable env exposing get_state/"
                    "set_state (the env IS the MCTS simulator; see "
                    "env/examples.py ClonableCartPole)")
        self._env = env
        obs0, _ = env.reset(seed=config.seed)
        vec, mask = _split_obs(obs0)
        self.obs_dim = len(vec)
        self.n_actions = int(env.action_space.n)
        hiddens = list(config.fcnet_hiddens)
        key = jax.random.PRNGKey(config.seed)
        kt, kp, kv = jax.random.split(key, 3)
        self.params = {
            "torso": mlp_init(kt, [self.obs_dim, *hiddens]),
            "pi": mlp_init(kp, [hiddens[-1], self.n_actions]),
            "v": mlp_init(kv, [hiddens[-1], 1]),
        }
        self._optimizer = optax.adam(config.lr)
        self._opt_state = self._optimizer.init(self.params)

        def priors_and_value(params, obs):
            h = mlp_apply(params["torso"], obs, activate_last=True)
            logits = mlp_apply(params["pi"], h)
            v = jnp.tanh(mlp_apply(params["v"], h)[..., 0])
            return logits, v

        def loss_fn(params, mb):
            logits, v = priors_and_value(params, mb["obs"])
            # Illegal actions are masked out of the CE support.
            logits = jnp.where(mb["mask"] > 0, logits, -1e9)
            logp = jax.nn.log_softmax(logits, -1)
            pi_loss = -(mb["tree_policy"] * logp).sum(-1).mean()
            v_loss = ((v - mb["z"]) ** 2).mean()
            return pi_loss + v_loss, (pi_loss, v_loss)

        def update(params, opt_state, mb):
            (_, (pl, vl)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, mb)
            updates, opt_state = self._optimizer.update(
                grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, pl, vl

        self._pv_jit = jax.jit(priors_and_value)
        self._update_jit = jax.jit(update)
        self._rng = np.random.default_rng(config.seed)
        self._buffer = ReplayBuffer(config.replay_buffer_capacity,
                                    seed=config.seed)
        #: rolling episode returns for the R2 threshold.
        self._returns_window: List[float] = []
        self._episode_rewards: List[float] = []

    # -- network wrapper -------------------------------------------------

    def _evaluate(self, vec: np.ndarray, mask: Optional[np.ndarray]):
        import jax.numpy as jnp
        logits, v = self._pv_jit(self.params, jnp.asarray(vec[None]))
        logits = np.asarray(logits[0], np.float64)
        if mask is not None:
            logits = np.where(mask > 0, logits, -1e9)
        e = np.exp(logits - logits.max())
        return e / e.sum(), float(v[0])

    # -- MCTS ------------------------------------------------------------

    def _expand(self, obs) -> _Node:
        vec, mask = _split_obs(obs)
        priors, value = self._evaluate(vec, mask)
        legal = mask if mask is not None else \
            np.ones(self.n_actions, np.float32)
        return _Node(priors.astype(np.float32), legal), value

    def _simulate(self, root: _Node, config: AlphaZeroConfig) -> None:
        """One PUCT descent from the current env state (restored
        afterwards). Undiscounted, board-game style: the backed-up
        value is the net's [-1, 1] estimate at the leaf, or — at a
        terminal — the RANKED transform of the env's sparse episode
        score, keeping tree values and value-head targets on one
        scale (the reference wraps the env in ranked_rewards.py for
        exactly this)."""
        env = self._env
        saved = env.get_state()
        node = root
        path: List[tuple] = []
        value = 0.0
        while True:
            total_n = node.n.sum()
            u = config.c_puct * node.p * \
                math.sqrt(total_n + 1e-8) / (1.0 + node.n)
            score = node.q() + u
            score = np.where(node.legal > 0, score, -np.inf)
            a = int(score.argmax())
            path.append((node, a))
            obs, r, term, trunc, _ = env.step(a)
            if term or trunc:
                value = self._rank(float(r))
                break
            child = node.children.get(a)
            if child is None:
                child, value = self._expand(obs)
                node.children[a] = child
                break
            node = child
        for node, a in path:
            node.n[a] += 1.0
            node.w[a] += value
        env.set_state(saved)

    def _tree_policy(self, root: _Node,
                     config: AlphaZeroConfig) -> np.ndarray:
        counts = root.n ** (1.0 / max(config.temperature, 1e-3))
        total = counts.sum()
        if total <= 0:
            legal = root.legal / root.legal.sum()
            return legal.astype(np.float32)
        return (counts / total).astype(np.float32)

    def _search(self, obs, explore: bool):
        """One full MCTS from the CURRENT env state: returns
        (tree_policy, legal_mask). Exploration adds Dirichlet noise to
        the root priors (the single code path self-play and
        compute_action share)."""
        config: AlphaZeroConfig = self.config
        root, _ = self._expand(obs)
        if explore:
            noise = self._rng.dirichlet(
                np.full(self.n_actions, config.dirichlet_alpha))
            root.p = ((1 - config.dirichlet_epsilon) * root.p +
                      config.dirichlet_epsilon *
                      noise.astype(np.float32))
        for _ in range(config.num_simulations):
            self._simulate(root, config)
        return self._tree_policy(root, config), root.legal

    def compute_action(self, obs, explore: bool = False) -> int:
        """MCTS move from the CURRENT env state (must correspond to
        ``obs``). Exploit mode: argmax visit counts, no noise."""
        pi, legal = self._search(obs, explore)
        if explore:
            return int(self._rng.choice(self.n_actions, p=pi))
        return int(np.where(legal > 0, pi, 0.0).argmax())

    # -- self-play + training -------------------------------------------

    def _rank(self, episode_score: float) -> float:
        """R2 transform WITHOUT recording: +-1 against the rolling
        percentile (simulated episodes must not pollute the window)."""
        config: AlphaZeroConfig = self.config
        window = self._returns_window
        if not window:
            return 1.0
        threshold = np.percentile(
            window, config.ranked_rewards_percentile)
        if episode_score > threshold:
            return 1.0
        if episode_score < threshold:
            return -1.0
        return float(self._rng.choice([-1.0, 1.0]))

    def _env_running_score(self) -> float:
        """Accumulated-but-unpaid score of the current episode, for
        budget-exhausted self-play (ClonableCartPole exposes it as
        episode_score; envs without the hook contribute 0)."""
        hook = getattr(self._env, "episode_score", None)
        return float(hook()) if callable(hook) else 0.0

    def _ranked_reward(self, episode_return: float) -> float:
        """Rank AND record — for completed self-play episodes."""
        config: AlphaZeroConfig = self.config
        z = self._rank(episode_return)
        self._returns_window.append(episode_return)
        del self._returns_window[:-config.ranked_rewards_buffer]
        return z

    def training_step(self) -> Dict[str, Any]:
        import jax.numpy as jnp
        config: AlphaZeroConfig = self.config
        for _ in range(config.episodes_per_iteration):
            obs, _ = self._env.reset()
            rows = []
            episode_return = 0.0
            terminated = False
            for _ in range(config.max_episode_steps):
                vec, _ = _split_obs(obs)
                pi, legal = self._search(obs, explore=True)
                a = int(self._rng.choice(self.n_actions, p=pi))
                rows.append({"obs": vec, "tree_policy": pi,
                             "mask": legal})
                obs, r, term, trunc, _ = self._env.step(a)
                episode_return += float(r)
                self._timesteps_total += 1
                if term or trunc:
                    terminated = True
                    break
            if not terminated:
                # Sparse-score envs pay only at termination; an episode
                # that outlives the step budget is the BEST outcome and
                # must rank as such, not as 0.
                episode_return += float(self._env_running_score())
            z = self._ranked_reward(episode_return)
            self._episode_rewards.append(episode_return)
            for row in rows:
                row["z"] = np.asarray([z], np.float32)
                self._buffer.add(SampleBatch(
                    {k: np.asarray(v)[None] for k, v in row.items()}))

        pi_losses, v_losses = [], []
        if len(self._buffer) >= config.train_batch_size:
            params = self.params
            for _ in range(config.num_train_batches_per_iteration):
                sampled = self._buffer.sample(config.train_batch_size)
                mb = {k: jnp.asarray(v) for k, v in sampled.items()}
                mb["z"] = mb["z"][:, 0]
                params, self._opt_state, pl, vl = self._update_jit(
                    params, self._opt_state, mb)
                pi_losses.append(float(pl))
                v_losses.append(float(vl))
            self.params = params

        window = self._episode_rewards[-100:]
        return {
            "policy_loss": float(np.mean(pi_losses)) if pi_losses
            else float("nan"),
            "value_loss": float(np.mean(v_losses)) if v_losses
            else float("nan"),
            "episode_reward_mean": (float(np.mean(window)) if window
                                    else float("nan")),
            "episodes_total": len(self._episode_rewards),
        }

    def evaluate(self) -> Dict[str, Any]:
        """Noiseless-argmax MCTS episodes (exploit mode) — overrides the
        base evaluate, whose flat-vector JAXPolicy path fits neither the
        dict observations nor the tree search."""
        config: AlphaZeroConfig = self.config
        episodes = getattr(config, "evaluation_num_episodes", 3) or 3
        rewards = []
        for _ in range(episodes):
            obs, _ = self._env.reset()
            total, terminated = 0.0, False
            for _ in range(config.max_episode_steps):
                a = self.compute_action(obs, explore=False)
                obs, r, term, trunc, _ = self._env.step(a)
                total += float(r)
                if term or trunc:
                    terminated = True
                    break
            if not terminated:
                total += float(self._env_running_score())
            rewards.append(total)
        return {"episode_reward_mean": float(np.mean(rewards)),
                "episodes_this_eval": len(rewards)}

    def get_weights(self):
        import jax
        return {"az_params": jax.tree.map(np.asarray, self.params)}

    def set_weights(self, weights) -> None:
        import jax
        import jax.numpy as jnp
        self.params = jax.tree.map(jnp.asarray, weights["az_params"])

    def stop(self) -> None:
        close = getattr(self._env, "close", None)
        if callable(close):
            close()
