"""A3C: asynchronous advantage actor-critic.

Analog of the reference's rllib/algorithms/a3c: same loss as A2C, but
gradients are computed from per-worker batches and applied in *arrival
order* — each worker samples with the weights it was handed at launch, so
later updates in a round are computed against slightly stale parameters
(the hogwild-style asynchrony that distinguishes A3C from A2C's
synchronous barrier). One training_step launches every worker once and
drains completions with ray.wait.
"""

from __future__ import annotations

from typing import Any, Dict

from ray_tpu.rllib.algorithms.a2c import A2C, A2CConfig


class A3CConfig(A2CConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or A3C)
        self.lr = 1e-3
        self.grad_clip = 40.0


class A3C(A2C):
    _default_config_class = A3CConfig

    def training_step(self) -> Dict[str, Any]:
        import ray_tpu
        config: A3CConfig = self.config
        workers = self.workers.remote_workers
        per_worker = max(config.train_batch_size // len(workers), 1)
        # Launch: every worker gets the current weights, then samples.
        weights_ref = ray_tpu.put(self.get_weights())
        pending = {}
        for w in workers:
            w.set_weights.remote(weights_ref)
            pending[w.sample.remote(per_worker)] = w
        metrics: Dict[str, Any] = {}
        n_applied = 0
        # Drain in completion order; each batch's gradient was computed
        # from launch-time weights but is applied to the newest params.
        while pending:
            done, _ = ray_tpu.wait(list(pending), num_returns=1)
            ref = done[0]
            pending.pop(ref)
            batch = ray_tpu.get(ref)
            self._timesteps_total += len(batch)
            params, self._opt_state, metrics = self._update_jit(
                self.local_policy.params, self._opt_state,
                self._device_minibatch(batch))
            self.local_policy.params = params
            n_applied += 1
        out = {k: float(v) for k, v in metrics.items()}
        out["async_grad_updates"] = n_applied
        return out
