"""IMPALA: importance-weighted actor-learner with V-trace corrections.

Analog of the reference's rllib/algorithms/impala: rollout workers sample
with (slightly) stale weights; the learner corrects the off-policyness with
V-trace (Espeholt et al. 2018) — clipped importance ratios rho/c reweight
the TD errors into corrected value targets ``vs`` and policy-gradient
advantages. The per-fragment V-trace recursion runs on host numpy (tiny),
the gradient update is one jitted step.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.policy.sample_batch import SampleBatch


def vtrace(behavior_logp: np.ndarray, target_logp: np.ndarray,
           rewards: np.ndarray, values: np.ndarray, bootstrap: float,
           gamma: float, rho_clip: float = 1.0, c_clip: float = 1.0):
    """Single-fragment V-trace: returns (vs, pg_advantages)."""
    T = len(rewards)
    rho = np.minimum(np.exp(target_logp - behavior_logp), rho_clip)
    c = np.minimum(np.exp(target_logp - behavior_logp), c_clip)
    values_tp1 = np.append(values[1:], bootstrap)
    deltas = rho * (rewards + gamma * values_tp1 - values)
    acc = 0.0
    vs_minus_v = np.zeros(T, np.float32)
    for t in range(T - 1, -1, -1):
        acc = deltas[t] + gamma * c[t] * acc
        vs_minus_v[t] = acc
    vs = vs_minus_v + values
    vs_tp1 = np.append(vs[1:], bootstrap)
    pg_adv = rho * (rewards + gamma * vs_tp1 - values)
    return vs.astype(np.float32), pg_adv.astype(np.float32)




def compute_vtrace_targets(policy, batch: SampleBatch, gamma: float,
                           rho_clip: float, c_clip: float):
    """Per-episode-fragment V-trace targets against the CURRENT policy:
    returns (obs, vs, pg_advantages) as numpy arrays. Shared by IMPALA and
    APPO (their losses differ; the correction does not)."""
    import jax.numpy as jnp
    obs = np.asarray(batch[SampleBatch.OBS], np.float32)
    target_logp = np.asarray(policy.logp(
        policy.params, jnp.asarray(obs),
        jnp.asarray(batch[SampleBatch.ACTIONS])))
    values = np.asarray(policy._value(policy.params, jnp.asarray(obs)))
    vs_all: List[np.ndarray] = []
    adv_all: List[np.ndarray] = []
    start = 0
    for frag in batch.split_by_episode():
        n = len(frag)
        terminated = frag[SampleBatch.TERMINATEDS][-1] > 0
        # Truncation bootstrap approximates V(s_T) for V(s_{T+1}) (the
        # post-fragment observation isn't in the batch).
        bootstrap = 0.0 if terminated else float(values[start + n - 1])
        vs, adv = vtrace(
            np.asarray(frag[SampleBatch.ACTION_LOGP], np.float32),
            target_logp[start:start + n],
            np.asarray(frag[SampleBatch.REWARDS], np.float32),
            values[start:start + n], bootstrap, gamma, rho_clip, c_clip)
        vs_all.append(vs)
        adv_all.append(adv)
        start += n
    return obs, np.concatenate(vs_all), np.concatenate(adv_all)


class ImpalaConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or Impala)
        self.lr = 6e-4
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.vtrace_rho_clip = 1.0
        self.vtrace_c_clip = 1.0

    def training(self, *, vf_loss_coeff=None, entropy_coeff=None,
                 vtrace_rho_clip=None, vtrace_c_clip=None,
                 **kwargs) -> "ImpalaConfig":
        super().training(**kwargs)
        for name, val in (("vf_loss_coeff", vf_loss_coeff),
                          ("entropy_coeff", entropy_coeff),
                          ("vtrace_rho_clip", vtrace_rho_clip),
                          ("vtrace_c_clip", vtrace_c_clip)):
            if val is not None:
                setattr(self, name, val)
        return self


class Impala(Algorithm):
    _default_config_class = ImpalaConfig

    def setup(self, config: ImpalaConfig) -> None:
        import jax
        import jax.numpy as jnp
        import optax

        policy = self.local_policy
        self._optimizer = optax.adam(config.lr)
        self._opt_state = self._optimizer.init(policy.params)
        vf_coeff = config.vf_loss_coeff
        ent_coeff = config.entropy_coeff

        def loss_fn(params, mb):
            logp = policy.logp(params, mb["obs"], mb["actions"])
            pg_loss = -(logp * mb["pg_advantages"]).mean()
            values = policy._value(params, mb["obs"])
            vf_loss = jnp.mean((values - mb["vs"]) ** 2)
            entropy = jnp.mean(policy.entropy(params, mb["obs"]))
            total = pg_loss + vf_coeff * vf_loss - ent_coeff * entropy
            return total, {"policy_loss": pg_loss, "vf_loss": vf_loss,
                           "entropy": entropy}

        def update(params, opt_state, mb):
            (loss, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, mb)
            updates, opt_state = self._optimizer.update(grads, opt_state,
                                                        params)
            params = optax.apply_updates(params, updates)
            metrics["total_loss"] = loss
            return params, opt_state, metrics

        self._update_jit = jax.jit(update)

    def training_step(self) -> Dict[str, Any]:
        import jax.numpy as jnp

        import ray_tpu
        config: ImpalaConfig = self.config
        weights_ref = ray_tpu.put(self.get_weights())
        self.workers.sync_weights(weights_ref)
        per_worker = max(
            config.train_batch_size // self.workers.num_workers(), 1)
        batch = self.workers.sample(per_worker)
        self._timesteps_total += len(batch)

        policy = self.local_policy
        obs, vs, pg_adv = compute_vtrace_targets(
            policy, batch, config.gamma, config.vtrace_rho_clip,
            config.vtrace_c_clip)
        device_mb = {
            "obs": jnp.asarray(obs),
            "actions": jnp.asarray(batch[SampleBatch.ACTIONS]),
            "vs": jnp.asarray(vs),
            "pg_advantages": jnp.asarray(pg_adv),
        }
        params, self._opt_state, metrics = self._update_jit(
            policy.params, self._opt_state, device_mb)
        policy.params = params
        return {k: float(v) for k, v in metrics.items()}
