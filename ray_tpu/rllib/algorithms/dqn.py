"""DQN: double deep Q-learning with target network and replay.

Analog of the reference's rllib/algorithms/dqn: epsilon-greedy rollout
workers feed a (optionally prioritized) replay buffer; the learner runs a
jitted double-DQN update (online net picks argmax actions, target net
scores them) with Huber loss, syncing the target every
``target_network_update_freq`` gradient steps and annealing epsilon over
``epsilon_timesteps``. Supports offline input (config.offline_data) — the
buffer is filled from JSON files instead of rollouts.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.policy.sample_batch import SampleBatch
from ray_tpu.rllib.utils.replay_buffers import (PrioritizedReplayBuffer,
                                                ReplayBuffer)


class DQNConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or DQN)
        self.policy_class_name = "q"
        self.lr = 5e-4
        self.train_batch_size = 32
        self.replay_buffer_capacity = 50_000
        self.num_steps_sampled_before_learning_starts = 1000
        self.target_network_update_freq = 500  # gradient steps
        self.num_train_batches_per_iteration = 32
        self.double_q = True
        self.prioritized_replay = False
        self.prioritized_replay_alpha = 0.6
        self.prioritized_replay_beta = 0.4
        self.epsilon_initial = 1.0
        self.epsilon_final = 0.02
        self.epsilon_timesteps = 10_000
        self.tau = 1.0  # hard target sync by default
        self.dueling = False  # dueling value/advantage streams
        self.n_step = 1  # multi-step returns (learner bootstraps gamma^n)
        self.per_worker_epsilon = False  # APEX exploration ladder

    def training(self, *, replay_buffer_capacity=None,
                 target_network_update_freq=None, double_q=None,
                 prioritized_replay=None, epsilon_timesteps=None,
                 epsilon_final=None, num_train_batches_per_iteration=None,
                 num_steps_sampled_before_learning_starts=None,
                 tau=None, dueling=None, n_step=None,
                 per_worker_epsilon=None, **kwargs) -> "DQNConfig":
        super().training(**kwargs)
        for name, val in (
                ("replay_buffer_capacity", replay_buffer_capacity),
                ("target_network_update_freq", target_network_update_freq),
                ("double_q", double_q),
                ("prioritized_replay", prioritized_replay),
                ("epsilon_timesteps", epsilon_timesteps),
                ("epsilon_final", epsilon_final),
                ("num_train_batches_per_iteration",
                 num_train_batches_per_iteration),
                ("num_steps_sampled_before_learning_starts",
                 num_steps_sampled_before_learning_starts),
                ("tau", tau), ("dueling", dueling), ("n_step", n_step),
                ("per_worker_epsilon", per_worker_epsilon)):
            if val is not None:
                setattr(self, name, val)
        return self

    def policy_config(self) -> dict:
        """DQN-family extensions (dueling heads, APEX epsilon ladder) —
        kept off the generic base per its algo-specific-fields rule."""
        base = super().policy_config()
        base["dueling"] = self.dueling
        base["per_worker_epsilon"] = self.per_worker_epsilon
        return base


class DQN(Algorithm):
    _default_config_class = DQNConfig

    def setup(self, config: DQNConfig) -> None:
        import jax
        import jax.numpy as jnp
        import optax

        policy = self.local_policy
        self._optimizer = optax.adam(config.lr)
        self._opt_state = self._optimizer.init(policy.params)
        self._target_params = jax.tree.map(jnp.asarray, policy.params)
        if config.prioritized_replay:
            self._buffer: ReplayBuffer = PrioritizedReplayBuffer(
                config.replay_buffer_capacity,
                alpha=config.prioritized_replay_alpha, seed=config.seed)
        else:
            self._buffer = ReplayBuffer(config.replay_buffer_capacity,
                                        seed=config.seed)
        self._grad_steps = 0
        self._reader = None
        if config.input_:
            from ray_tpu.rllib.offline.json_reader import JsonReader
            self._reader = JsonReader(config.input_)
        tau = config.tau
        loss_fn = self._build_loss_fn(policy, config)
        self._learn_key = jax.random.PRNGKey(config.seed + 99)

        def update(params, target_params, opt_state, mb, key):
            (loss, td), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, target_params, mb, key)
            updates, opt_state = self._optimizer.update(grads, opt_state,
                                                        params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, td

        def soft_sync(params, target_params):
            return jax.tree.map(lambda p, t: tau * p + (1 - tau) * t,
                                params, target_params)

        self._update_jit = jax.jit(update)
        self._soft_sync_jit = jax.jit(soft_sync)

    def _build_loss_fn(self, policy, config):
        """Returns loss_fn(params, target_params, mb, key) -> (loss, td).
        Rainbow overrides this with the C51 distributional loss; the key
        feeds noisy-net sampling and is unused here."""
        import jax
        import jax.numpy as jnp
        gamma = config.gamma
        double_q = config.double_q

        def loss_fn(params, target_params, mb, key):
            q_all = policy.q_values(params, mb["obs"])
            q_taken = jnp.take_along_axis(
                q_all, mb["actions"][..., None].astype(jnp.int32),
                -1)[..., 0]
            q_next_target = policy.q_values(target_params, mb["new_obs"])
            if double_q:
                a_star = policy.q_values(params, mb["new_obs"]).argmax(-1)
                q_next = jnp.take_along_axis(
                    q_next_target, a_star[..., None], -1)[..., 0]
            else:
                q_next = q_next_target.max(-1)
            done = jnp.maximum(mb["terminateds"], 0.0)
            # n-step rows carry their own bootstrap discount gamma^k
            # (windows cut short at non-terminal boundaries have k < n).
            disc = mb.get("n_step_discount", gamma)
            target = mb["rewards"] + disc * (1.0 - done) * q_next
            td = q_taken - jax.lax.stop_gradient(target)
            huber = jnp.where(jnp.abs(td) < 1.0, 0.5 * td ** 2,
                              jnp.abs(td) - 0.5)
            weights = mb.get("weights", jnp.ones_like(td))
            return (weights * huber).mean(), td

        return loss_fn

    def _epsilon(self) -> float:
        config: DQNConfig = self.config
        frac = min(1.0, self._timesteps_total /
                   max(config.epsilon_timesteps, 1))
        return config.epsilon_initial + frac * (
            config.epsilon_final - config.epsilon_initial)

    def get_weights(self):
        weights = self.local_policy.get_weights()  # {"params", "epsilon"}
        weights["epsilon"] = self._epsilon()
        return weights

    def training_step(self) -> Dict[str, Any]:
        import jax.numpy as jnp

        import ray_tpu
        config: DQNConfig = self.config
        if self._reader is not None:
            batch = SampleBatch.concat_samples(
                [self._reader.next()
                 for _ in range(config.num_train_batches_per_iteration)])
        else:
            weights_ref = ray_tpu.put(self.get_weights())
            self.workers.sync_weights(weights_ref)
            per_worker = max(
                config.rollout_fragment_length, 1)
            batch = self.workers.sample(per_worker)
        self._timesteps_total += len(batch)
        if config.n_step > 1:
            from ray_tpu.rllib.utils.replay_buffers import n_step_transform
            batch = n_step_transform(batch, config.n_step, config.gamma)
        self._buffer.add(batch)

        losses = []
        if len(self._buffer) >= max(
                config.num_steps_sampled_before_learning_starts,
                config.train_batch_size):
            params = self.local_policy.params
            for _ in range(config.num_train_batches_per_iteration):
                if config.prioritized_replay:
                    mb = self._buffer.sample(
                        config.train_batch_size,
                        beta=config.prioritized_replay_beta)
                else:
                    mb = self._buffer.sample(config.train_batch_size)
                device_mb = {k: jnp.asarray(v) for k, v in mb.items()
                             if k in ("obs", "new_obs", "actions", "rewards",
                                      "terminateds", "weights",
                                      "n_step_discount")}
                import jax as _jax
                self._learn_key, k_step = _jax.random.split(self._learn_key)
                params, self._opt_state, loss, td = self._update_jit(
                    params, self._target_params, self._opt_state, device_mb,
                    k_step)
                losses.append(float(loss))
                self._grad_steps += 1
                if config.prioritized_replay:
                    self._buffer.update_priorities(
                        mb["batch_indexes"], np.asarray(td))
                if self._grad_steps % config.target_network_update_freq == 0:
                    self._target_params = self._soft_sync_jit(
                        params, self._target_params)
            self.local_policy.params = params
        return {
            "loss": float(np.mean(losses)) if losses else float("nan"),
            "epsilon": self._epsilon(),
            "replay_buffer_size": len(self._buffer),
            "gradient_steps_total": self._grad_steps,
        }
