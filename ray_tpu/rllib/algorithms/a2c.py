"""A2C: synchronous advantage actor-critic.

Analog of the reference's rllib/algorithms/a2c: the PPO machinery without
the clipped surrogate — one vanilla policy-gradient + value + entropy
update per sampled batch (single epoch, whole batch).
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.policy.sample_batch import SampleBatch


class A2CConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or A2C)
        self.lr = 1e-3
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.grad_clip = 40.0

    def training(self, *, vf_loss_coeff=None, entropy_coeff=None,
                 grad_clip=None, **kwargs) -> "A2CConfig":
        super().training(**kwargs)
        if vf_loss_coeff is not None:
            self.vf_loss_coeff = vf_loss_coeff
        if entropy_coeff is not None:
            self.entropy_coeff = entropy_coeff
        if grad_clip is not None:
            self.grad_clip = grad_clip
        return self


class A2C(Algorithm):
    _default_config_class = A2CConfig

    def setup(self, config: A2CConfig) -> None:
        import jax
        import jax.numpy as jnp
        import optax

        policy = self.local_policy
        self._optimizer = optax.chain(
            optax.clip_by_global_norm(config.grad_clip),
            optax.adam(config.lr))
        self._opt_state = self._optimizer.init(policy.params)
        vf_coeff = config.vf_loss_coeff
        ent_coeff = config.entropy_coeff

        def loss_fn(params, mb):
            logp = policy.logp(params, mb["obs"], mb["actions"])
            pg_loss = -(logp * mb["advantages"]).mean()
            values = policy._value(params, mb["obs"])
            vf_loss = jnp.mean((values - mb["value_targets"]) ** 2)
            entropy = jnp.mean(policy.entropy(params, mb["obs"]))
            total = pg_loss + vf_coeff * vf_loss - ent_coeff * entropy
            return total, {"policy_loss": pg_loss, "vf_loss": vf_loss,
                           "entropy": entropy}

        def update(params, opt_state, mb):
            (loss, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, mb)
            updates, opt_state = self._optimizer.update(grads, opt_state,
                                                        params)
            params = optax.apply_updates(params, updates)
            metrics["total_loss"] = loss
            return params, opt_state, metrics

        self._update_jit = jax.jit(update)

    def _device_minibatch(self, batch: SampleBatch):
        """Normalize advantages and stage the A2C loss inputs on device
        (shared with A3C's per-worker async updates)."""
        import jax.numpy as jnp
        adv = batch[SampleBatch.ADVANTAGES]
        batch[SampleBatch.ADVANTAGES] = (
            (adv - adv.mean()) / max(adv.std(), 1e-8)).astype(np.float32)
        return {k: jnp.asarray(v) for k, v in batch.items()
                if k in ("obs", "actions", "advantages", "value_targets")}

    def training_step(self) -> Dict[str, Any]:
        import ray_tpu
        config: A2CConfig = self.config
        weights_ref = ray_tpu.put(self.get_weights())
        self.workers.sync_weights(weights_ref)
        per_worker = max(
            config.train_batch_size // self.workers.num_workers(), 1)
        batch = self.workers.sample(per_worker)
        self._timesteps_total += len(batch)
        params, self._opt_state, metrics = self._update_jit(
            self.local_policy.params, self._opt_state,
            self._device_minibatch(batch))
        self.local_policy.params = params
        return {k: float(v) for k, v in metrics.items()}
