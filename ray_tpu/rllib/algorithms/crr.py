"""CRR: critic-regularized regression for offline RL.

Analog of the reference's rllib/algorithms/crr (Wang et al. 2020,
"Critic Regularized Regression"): behavior cloning where each logged
action's log-likelihood is WEIGHTED by its advantage under a learned
critic — the policy imitates only the parts of the dataset the critic
thinks beat the current policy, which filters mixed-quality data
without ever evaluating out-of-distribution actions (the failure mode
plain offline actor-critic hits).

Updates from a once-loaded JSON dataset (bc.py's offline contract):
  * critic: TD toward ``r + gamma * E_{a'~pi}[Q_target(s', a')]``
    (exact expectation for Discrete; policy samples for Box),
  * actor: ``-f(A(s,a)) * log pi(a|s)`` with ``A = Q(s,a) -
    E_{a~pi}Q(s,a)`` and ``f`` either ``binary`` (1[A>0], the paper's
    best-performing "indicator" variant) or ``exp`` (exp(A/beta),
    clipped — the reference's weight_type choices).

The actor is the standard JAXPolicy (so Algorithm.evaluate works
unchanged); the critic is owned here: Q(s, .) vector head for Discrete,
Q(s, a) scalar head for Box.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.policy.sample_batch import SampleBatch


class CRRConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or CRR)
        self.lr = 3e-4
        self.critic_lr = 3e-4
        self.train_batch_size = 256
        self.num_rollout_workers = 0   # offline: WorkerSet stays empty
        self.num_train_batches_per_iteration = 64
        self.tau = 0.005
        self.weight_type = "binary"    # "binary" | "exp"
        self.beta = 1.0                # exp temperature
        self.weight_clip = 20.0
        self.n_action_samples = 4      # E_{a~pi}Q estimator (Box only)

    def training(self, *, critic_lr=None, tau=None, weight_type=None,
                 beta=None, weight_clip=None, n_action_samples=None,
                 num_train_batches_per_iteration=None,
                 **kwargs) -> "CRRConfig":
        super().training(**kwargs)
        for name, val in (("critic_lr", critic_lr), ("tau", tau),
                          ("weight_type", weight_type), ("beta", beta),
                          ("weight_clip", weight_clip),
                          ("n_action_samples", n_action_samples),
                          ("num_train_batches_per_iteration",
                           num_train_batches_per_iteration)):
            if val is not None:
                setattr(self, name, val)
        return self


class CRR(Algorithm):
    _default_config_class = CRRConfig

    def __init__(self, config=None, **kwargs):
        cfg = config or self.get_default_config()
        if not cfg.input_:
            raise ValueError(
                "CRR is offline-only: set config.offline_data("
                "input_=<dir of JSON experience files>)")
        if cfg.weight_type not in ("binary", "exp"):
            raise ValueError(
                f"weight_type must be 'binary' or 'exp', got "
                f"{cfg.weight_type!r}")
        super().__init__(config=config, **kwargs)

    def setup(self, config: CRRConfig) -> None:
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.rllib.models.catalog import mlp_apply, mlp_init
        from ray_tpu.rllib.offline.json_reader import JsonReader

        self._reader = JsonReader(config.input_)
        policy = self.local_policy
        discrete = policy.discrete
        obs_dim = policy.obs_dim
        act_dim = policy.act_dim
        hiddens = list(config.fcnet_hiddens)
        key = jax.random.PRNGKey(config.seed + 7)
        q_out = act_dim if discrete else 1
        q_in = obs_dim if discrete else obs_dim + act_dim
        self._q_params = mlp_init(key, [q_in, *hiddens, q_out])
        self._q_target = jax.tree.map(jnp.asarray, self._q_params)
        self._actor_opt = optax.adam(config.lr)
        self._critic_opt = optax.adam(config.critic_lr)
        self._actor_state = self._actor_opt.init(policy.params)
        self._critic_state = self._critic_opt.init(self._q_params)
        gamma, tau = config.gamma, config.tau
        beta, wclip = config.beta, config.weight_clip
        binary = config.weight_type == "binary"
        n_samples = config.n_action_samples

        if discrete:
            def q_all(qp, obs):
                return mlp_apply(qp, obs)                     # [B, A]

            def exp_q(qp, actor_params, obs):
                logits = policy.logits(actor_params, obs)
                pi = jax.nn.softmax(logits, -1)
                return (pi * q_all(qp, obs)).sum(-1)          # [B]

            def q_of(qp, obs, actions):
                return jnp.take_along_axis(
                    q_all(qp, obs),
                    actions[..., None].astype(jnp.int32), -1)[..., 0]
        else:
            def q_of(qp, obs, actions):
                x = jnp.concatenate([obs, actions], -1)
                return mlp_apply(qp, x)[..., 0]

            def exp_q(qp, actor_params, obs, key=None):
                vals = []
                for i in range(n_samples):
                    k = jax.random.fold_in(key, i)
                    a, _, _ = policy._sample(actor_params, obs, k)
                    vals.append(q_of(qp, obs, a))
                return jnp.stack(vals).mean(0)

        def critic_loss(qp, q_target, actor_params, mb, key):
            if discrete:
                q_next = exp_q(q_target, actor_params, mb["new_obs"])
            else:
                q_next = exp_q(q_target, actor_params, mb["new_obs"],
                               key=key)
            target = mb["rewards"] + gamma * \
                (1.0 - mb["terminateds"]) * q_next
            q = q_of(qp, mb["obs"], mb["actions"])
            return ((q - jax.lax.stop_gradient(target)) ** 2).mean()

        def actor_loss(actor_params, qp, mb, key):
            q = q_of(qp, mb["obs"], mb["actions"])
            if discrete:
                v = exp_q(qp, actor_params, mb["obs"])
            else:
                v = exp_q(qp, actor_params, mb["obs"], key=key)
            adv = jax.lax.stop_gradient(q - v)
            if binary:
                w = (adv > 0).astype(jnp.float32)
            else:
                w = jnp.clip(jnp.exp(adv / beta), 0.0, wclip)
            logp = policy.logp(actor_params, mb["obs"], mb["actions"])
            return -(w * logp).mean(), w.mean()

        def update(actor_params, qp, q_target, actor_state,
                   critic_state, mb, key):
            k1, k2 = jax.random.split(key)
            c_loss, c_grads = jax.value_and_grad(critic_loss)(
                qp, q_target, actor_params, mb, k1)
            cu, critic_state = self._critic_opt.update(
                c_grads, critic_state, qp)
            qp = optax.apply_updates(qp, cu)
            (a_loss, w_mean), a_grads = jax.value_and_grad(
                actor_loss, has_aux=True)(actor_params, qp, mb, k2)
            au, actor_state = self._actor_opt.update(
                a_grads, actor_state, actor_params)
            actor_params = optax.apply_updates(actor_params, au)
            q_target = jax.tree.map(
                lambda t, p: (1 - tau) * t + tau * p, q_target, qp)
            return (actor_params, qp, q_target, actor_state,
                    critic_state,
                    {"critic_loss": c_loss, "actor_loss": a_loss,
                     "weight_mean": w_mean})

        self._update_jit = jax.jit(update)
        self._key = jax.random.PRNGKey(config.seed + 13)

    def training_step(self) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp
        config: CRRConfig = self.config
        params = self.local_policy.params
        metrics = {}
        for _ in range(config.num_train_batches_per_iteration):
            mb = self._reader.next_batch(config.train_batch_size)
            self._timesteps_total += config.train_batch_size
            device_mb = {
                "obs": jnp.asarray(np.asarray(
                    mb[SampleBatch.OBS], np.float32)),
                "actions": jnp.asarray(np.asarray(
                    mb[SampleBatch.ACTIONS])),
                "rewards": jnp.asarray(np.asarray(
                    mb[SampleBatch.REWARDS], np.float32)),
                "new_obs": jnp.asarray(np.asarray(
                    mb[SampleBatch.NEXT_OBS], np.float32)),
                "terminateds": jnp.asarray(np.asarray(
                    mb[SampleBatch.TERMINATEDS], np.float32)),
            }
            self._key, sub = jax.random.split(self._key)
            (params, self._q_params, self._q_target,
             self._actor_state, self._critic_state, metrics) = \
                self._update_jit(params, self._q_params, self._q_target,
                                 self._actor_state, self._critic_state,
                                 device_mb, sub)
        self.local_policy.params = params
        return {k: float(v) for k, v in metrics.items()}
