"""Replay buffers (reference: rllib/utils/replay_buffers/replay_buffer.py):
uniform ReplayBuffer + PrioritizedReplayBuffer over SampleBatch storage."""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ray_tpu.rllib.policy.sample_batch import SampleBatch


class ReplayBuffer:
    def __init__(self, capacity: int = 100_000, seed: Optional[int] = None):
        self.capacity = capacity
        self._rows: List[dict] = []
        self._next = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return len(self._rows)

    def add(self, batch: SampleBatch) -> None:
        for i in range(len(batch)):
            row = {k: v[i] for k, v in batch.items()}
            if len(self._rows) < self.capacity:
                self._rows.append(row)
            else:
                self._rows[self._next] = row
                self._next = (self._next + 1) % self.capacity

    def sample(self, num_items: int) -> SampleBatch:
        idx = self._rng.integers(0, len(self._rows), num_items)
        keys = self._rows[0].keys()
        return SampleBatch(
            {k: np.stack([self._rows[i][k] for i in idx]) for k in keys})


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritization (alpha) with IS weights (beta)."""

    def __init__(self, capacity: int = 100_000, alpha: float = 0.6,
                 seed: Optional[int] = None):
        super().__init__(capacity, seed)
        self.alpha = alpha
        self._priorities: List[float] = []
        self._max_priority = 1.0

    def add(self, batch: SampleBatch) -> None:
        for i in range(len(batch)):
            row = {k: v[i] for k, v in batch.items()}
            if len(self._rows) < self.capacity:
                self._rows.append(row)
                self._priorities.append(self._max_priority)
            else:
                self._rows[self._next] = row
                self._priorities[self._next] = self._max_priority
                self._next = (self._next + 1) % self.capacity

    def sample(self, num_items: int, beta: float = 0.4) -> SampleBatch:
        pri = np.asarray(self._priorities) ** self.alpha
        probs = pri / pri.sum()
        idx = self._rng.choice(len(self._rows), num_items, p=probs)
        weights = (len(self._rows) * probs[idx]) ** (-beta)
        weights = weights / weights.max()
        keys = self._rows[0].keys()
        out = SampleBatch(
            {k: np.stack([self._rows[i][k] for i in idx]) for k in keys})
        out["weights"] = weights.astype(np.float32)
        out["batch_indexes"] = idx
        return out

    def update_priorities(self, idx, priorities) -> None:
        for i, p in zip(idx, priorities):
            self._priorities[int(i)] = float(abs(p)) + 1e-6
            self._max_priority = max(self._max_priority, self._priorities[int(i)])


def n_step_transform(batch: "SampleBatch", n: int,
                     gamma: float) -> "SampleBatch":
    """Rewrite transitions as n-step returns (reference: rllib's
    adjust_nstep in replay-buffer utils): reward_t <- sum_i gamma^i
    r_{t+i}, new_obs_t <- obs after the window, terminated_t <- whether
    the window hit a terminal. Windows never cross episode boundaries
    (terminated/truncated/eps_id seams). Windows cut short at a
    non-terminal boundary cover k < n steps, so each row carries its own
    bootstrap discount gamma^k in "n_step_discount" — the learner uses it
    instead of a fixed gamma^n.
    """
    if n <= 1:
        return batch
    size = len(batch)
    rewards = np.asarray(batch[SampleBatch.REWARDS], np.float64)
    terminated = np.asarray(batch[SampleBatch.TERMINATEDS])
    truncated = batch.get(SampleBatch.TRUNCATEDS)
    eps_id = batch.get(SampleBatch.EPS_ID)
    new_obs = np.asarray(batch[SampleBatch.NEXT_OBS])

    def boundary(t):  # episode ends AFTER step t
        return bool(terminated[t]) or \
            (truncated is not None and bool(truncated[t])) or \
            (eps_id is not None and t + 1 < size
             and eps_id[t] != eps_id[t + 1])

    out_r = np.zeros(size, np.float32)
    out_disc = np.zeros(size, np.float32)
    out_new_obs = new_obs.copy()
    out_term = np.asarray(terminated, np.float32).copy()
    for t in range(size):
        acc, disc = 0.0, 1.0
        for i in range(n):
            j = t + i
            if j >= size:
                break
            acc += disc * rewards[j]
            disc *= gamma
            out_new_obs[t] = new_obs[j]
            out_term[t] = np.float32(terminated[j])
            if boundary(j):
                break
        out_r[t] = acc
        out_disc[t] = disc  # gamma^k for the k steps actually covered
    out = SampleBatch(dict(batch))
    out[SampleBatch.REWARDS] = out_r
    out[SampleBatch.NEXT_OBS] = out_new_obs
    out[SampleBatch.TERMINATEDS] = out_term
    out["n_step_discount"] = out_disc
    return out


class SequenceReplayBuffer:
    """Episode-organized replay for recurrent learners (reference:
    R2D2's sequence storage in rllib/algorithms/r2d2 + replay_buffers/
    utils): stores whole episodes, samples fixed-length windows with the
    recurrent state recorded at the window start, zero-padding short
    windows with a validity mask."""

    def __init__(self, capacity_episodes: int = 2000,
                 seed: Optional[int] = None):
        self.capacity = capacity_episodes
        self._episodes: List[dict] = []
        self._next = 0
        self._steps = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._steps

    def add(self, batch: SampleBatch) -> None:
        for ep in batch.split_by_episode():
            data = {k: np.asarray(v) for k, v in ep.items()}
            self._steps += len(ep)
            if len(self._episodes) < self.capacity:
                self._episodes.append(data)
            else:
                evicted = self._episodes[self._next]
                self._steps -= len(next(iter(evicted.values())))
                self._episodes[self._next] = data
                self._next = (self._next + 1) % self.capacity

    def sample(self, num_seqs: int, seq_len: int) -> dict:
        """-> dict of [num_seqs, seq_len, ...] arrays plus "mask"
        [num_seqs, seq_len] (1 = real step) and "h0"/"c0" from the
        stored per-step recurrent state at each window start."""
        assert self._episodes, "sample() on an empty buffer"
        keys = self._episodes[0].keys()
        out = {k: [] for k in keys}
        masks = []
        for _ in range(num_seqs):
            ep = self._episodes[self._rng.integers(len(self._episodes))]
            ep_len = len(next(iter(ep.values())))
            start = int(self._rng.integers(
                0, max(ep_len - seq_len, 0) + 1))
            end = min(start + seq_len, ep_len)
            pad = seq_len - (end - start)
            for k in keys:
                window = ep[k][start:end]
                if pad:
                    window = np.concatenate(
                        [window, np.zeros((pad,) + window.shape[1:],
                                          window.dtype)])
                out[k].append(window)
            masks.append(np.concatenate(
                [np.ones(end - start, np.float32),
                 np.zeros(pad, np.float32)]))
        stacked = {k: np.stack(v) for k, v in out.items()}
        stacked["mask"] = np.stack(masks)
        # Window-start recurrent state (stored pre-step by the policy;
        # absent for consumers with no per-step recurrent columns, e.g.
        # Dreamer's world-model sequences).
        if "lstm_h" in stacked:
            stacked["h0"] = stacked.pop("lstm_h")[:, 0]
        if "lstm_c" in stacked:
            stacked["c0"] = stacked.pop("lstm_c")[:, 0]
        return stacked
