"""MultiAgentEnv: the multi-agent environment contract.

Analog of the reference's rllib/env/multi_agent_env.py: one env hosting
several agents; every API surface is keyed by agent id. reset() returns
(obs_dict, info_dict); step(action_dict) returns per-agent obs/reward/
terminated/truncated/info dicts, with the special "__all__" key in
terminateds/truncateds ending the episode for everyone. Agents may come
and go between steps (only agents present in the obs dict act next step).
"""

from __future__ import annotations

from typing import Any, Dict, Set, Tuple


class MultiAgentEnv:
    #: ids of all agents that can ever appear (subclasses set this).
    agent_ids: Set[str] = set()

    def reset(self, *, seed=None, options=None
              ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        raise NotImplementedError

    def step(self, action_dict: Dict[str, Any]):
        """→ (obs, rewards, terminateds, truncateds, infos), all keyed by
        agent id; terminateds/truncateds also carry "__all__"."""
        raise NotImplementedError

    def observation_space_for(self, agent_id: str):
        """Per-agent observation space (override for heterogeneous
        agents; defaults to a shared ``observation_space`` attribute)."""
        return self.observation_space

    def action_space_for(self, agent_id: str):
        return self.action_space

    def close(self) -> None:
        pass
