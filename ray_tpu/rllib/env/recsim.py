"""RecSim-style slate recommendation environment.

Analog of the RecSim "interest evolution" environment the reference's
SlateQ is written against (reference: rllib/algorithms/slateq/slateq.py
targets google-research/recsim; rllib/env/wrappers/recsim.py adapts it).
A user with a latent interest vector is shown a slate of ``slate_size``
documents out of ``num_candidates`` per step; a conditional-logit choice
model (with a no-click option) picks at most one document; clicking
yields engagement reward and nudges the user's interest toward the
clicked document's topic. The myopic greedy policy (recommend the
highest-immediate-engagement docs) is suboptimal when quality and
clickbaitiness are anti-correlated — the long-term-value signal SlateQ
exists to capture.

Observation: a flat ``Box`` concatenating the user interest vector and
the per-candidate feature rows ``[topic (T), quality (1)]``, i.e.
``T + C * (T + 1)`` floats. Action: ``MultiDiscrete([C] * slate_size)``
— a slate of candidate indices (the shape the reference's RecSim
wrapper exposes, rllib/env/wrappers/recsim.py
MultiDiscreteToDiscreteActionWrapper's input). Duplicate indices are
legal (the conditional logit runs over the slate as presented — a
repeated document simply occupies two positions), so generic consumers
like the RandomAgent baseline can ``action_space.sample()`` safely;
SlateQ itself always emits distinct slates.
"""

from __future__ import annotations

from typing import Optional

import gymnasium as gym
import numpy as np


class RecSimEnv(gym.Env):
    def __init__(self, config: Optional[dict] = None):
        config = config or {}
        self.num_candidates = int(config.get("num_candidates", 10))
        self.slate_size = int(config.get("slate_size", 3))
        self.num_topics = int(config.get("num_topics", 5))
        self.horizon = int(config.get("horizon", 50))
        #: choice-model temperature: higher = clickier users.
        self.choice_beta = float(config.get("choice_beta", 5.0))
        self.no_click_score = float(config.get("no_click_score", 1.0))
        #: interest drift rate toward clicked topics.
        self.interest_lr = float(config.get("interest_lr", 0.3))
        #: anti-correlation between immediate appeal and quality — the
        #: "clickbait" knob that makes myopic ranking suboptimal.
        self.clickbait = float(config.get("clickbait", 0.8))
        T, C = self.num_topics, self.num_candidates
        self.observation_space = gym.spaces.Box(
            -np.inf, np.inf, (T + C * (T + 1),), np.float32)
        self.action_space = gym.spaces.MultiDiscrete(
            [C] * self.slate_size)
        self._rng = np.random.default_rng(config.get("seed"))
        self._user = None
        self._docs = None
        self._t = 0

    # -- internals -------------------------------------------------------

    def _sample_docs(self) -> np.ndarray:
        """[C, T+1] rows of topic-simplex + quality. Quality is
        anti-correlated with peak topic appeal by ``clickbait``."""
        C, T = self.num_candidates, self.num_topics
        topics = self._rng.dirichlet(np.full(T, 0.3), size=C)
        appeal = topics.max(-1)
        noise = self._rng.random(C)
        quality = (1 - self.clickbait) * noise + \
            self.clickbait * (1.0 - appeal)
        return np.concatenate(
            [topics, quality[:, None]], axis=-1).astype(np.float32)

    def _obs(self) -> np.ndarray:
        return np.concatenate(
            [self._user, self._docs.reshape(-1)]).astype(np.float32)

    def choice_probs(self, slate: np.ndarray) -> np.ndarray:
        """True conditional-logit click distribution over the slate's
        items plus the trailing no-click option — exposed so tests can
        assert against the ground truth the agent must learn."""
        topics = self._docs[slate, :-1]
        scores = self.choice_beta * (topics @ self._user)
        logits = np.concatenate([scores, [self.no_click_score]])
        e = np.exp(logits - logits.max())
        return e / e.sum()

    # -- gym API ---------------------------------------------------------

    def reset(self, *, seed=None, options=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        T = self.num_topics
        u = self._rng.dirichlet(np.full(T, 0.5))
        self._user = u.astype(np.float32)
        self._docs = self._sample_docs()
        self._t = 0
        return self._obs(), {}

    def step(self, action):
        slate = np.asarray(action, np.int64).reshape(-1)
        if slate.size != self.slate_size or slate.min() < 0 or \
                slate.max() >= self.num_candidates:
            raise ValueError(
                f"slate must be {self.slate_size} doc indices in "
                f"[0, {self.num_candidates}), got {slate!r}")
        probs = self.choice_probs(slate)
        pick = self._rng.choice(self.slate_size + 1, p=probs)
        reward = 0.0
        if pick < self.slate_size:  # a real click, not the null option
            doc = self._docs[slate[pick]]
            topic, quality = doc[:-1], float(doc[-1])
            reward = quality
            u = self._user + self.interest_lr * \
                (topic - self._user) * quality
            self._user = (u / max(u.sum(), 1e-6)).astype(np.float32)
        self._docs = self._sample_docs()
        self._t += 1
        done = self._t >= self.horizon
        return self._obs(), float(reward), done, False, \
            {"clicked": int(pick) if pick < self.slate_size else -1}

    def split_obs(self, obs: np.ndarray):
        """(user [T], docs [C, T+1]) view of a flat observation."""
        T, C = self.num_topics, self.num_candidates
        return obs[:T], obs[T:].reshape(C, T + 1)
