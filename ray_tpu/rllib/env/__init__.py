from ray_tpu.rllib.env.multi_agent_env import MultiAgentEnv

__all__ = ["MultiAgentEnv"]
