from ray_tpu.rllib.env.external_env import ExternalEnv
from ray_tpu.rllib.env.multi_agent_env import MultiAgentEnv
from ray_tpu.rllib.env.policy_client import PolicyClient
from ray_tpu.rllib.env.policy_server_input import PolicyServerInput

__all__ = ["ExternalEnv", "MultiAgentEnv", "PolicyClient",
           "PolicyServerInput"]
