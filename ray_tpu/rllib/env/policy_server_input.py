"""PolicyServerInput: train from environments living OUTSIDE the cluster.

Analog of the reference's rllib/env/policy_server_input.py:26 — an HTTP
server embedded in the learner process that external
:class:`~ray_tpu.rllib.env.policy_client.PolicyClient` processes talk to:
they query actions (server-side inference against the LIVE training
policy), log rewards, and end episodes; completed fragments are
GAE-postprocessed and queued as SampleBatches for the training loop.

Use with ``config.offline_data(input_=lambda ctx:
PolicyServerInput(ctx, host, port))`` — the algorithm then trains from
the server's queue instead of its own rollout workers.
"""

from __future__ import annotations

import pickle
import queue
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

import numpy as np

from ray_tpu.rllib.policy.jax_policy import compute_gae
from ray_tpu.rllib.policy.sample_batch import SampleBatch

__all__ = ["PolicyServerInput"]

# Wire commands (reference: policy_client.py Commands).
START_EPISODE = "START_EPISODE"
GET_ACTION = "GET_ACTION"
LOG_ACTION = "LOG_ACTION"
LOG_RETURNS = "LOG_RETURNS"
END_EPISODE = "END_EPISODE"
GET_WEIGHTS = "GET_WEIGHTS"


class _Episode:
    def __init__(self, episode_id: str, training_enabled: bool):
        self.episode_id = episode_id
        self.training_enabled = training_enabled
        # Serializes this episode's transition sequence: the HTTP server
        # is threaded, so pipelined requests for ONE episode must not
        # interleave _record_prev (a torn prev_* update corrupts the
        # (obs, action, reward) alignment the GAE pass consumes).
        self.lock = threading.Lock()
        self.rows: Dict[str, list] = {k: [] for k in (
            SampleBatch.OBS, SampleBatch.NEXT_OBS, SampleBatch.ACTIONS,
            SampleBatch.REWARDS, SampleBatch.TERMINATEDS,
            SampleBatch.TRUNCATEDS, SampleBatch.ACTION_LOGP,
            SampleBatch.VF_PREDS, SampleBatch.EPS_ID)}
        self.prev_obs = None
        self.prev_action = None
        self.prev_logp = 0.0
        self.prev_vf = 0.0
        self.pending_reward = 0.0
        self.total_reward = 0.0
        self.length = 0


class PolicyServerInput:
    """HTTP ingest for external experience + server-side inference.

    ``ctx`` is whatever exposes ``policy`` (the live training policy) —
    the :class:`InputContext` the algorithm passes to the ``input_``
    callable. ``next_batch(min_rows)`` blocks until that much training
    data arrived."""

    def __init__(self, ctx, address: str = "127.0.0.1", port: int = 0,
                 gamma: Optional[float] = None,
                 lam: Optional[float] = None):
        import jax
        self._policy = ctx.policy if hasattr(ctx, "policy") else ctx
        # GAE discounting follows the ALGORITHM's config (the ctx the
        # input_ callable receives); explicit kwargs override.
        self._gamma = (gamma if gamma is not None
                       else getattr(ctx, "gamma", 0.99))
        self._lam = lam if lam is not None else getattr(ctx, "lam", 0.95)
        self._key = jax.random.PRNGKey(0xE17)
        self._episodes: Dict[str, _Episode] = {}
        self._lock = threading.Lock()
        self._batches: "queue.Queue" = queue.Queue()
        self._rows_ready = 0
        self.episode_rewards: list = []
        self.episode_lengths: list = []
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # noqa: N802 - stdlib API
                pass  # no per-request stderr spam

            def do_POST(self):  # noqa: N802 - stdlib API
                length = int(self.headers.get("Content-Length", 0))
                try:
                    req = pickle.loads(self.rfile.read(length))
                    out = outer._handle(req)
                    payload = pickle.dumps({"ok": True, "result": out})
                except Exception as exc:  # noqa: BLE001 - ship to client
                    payload = pickle.dumps({"ok": False,
                                            "error": repr(exc)})
                self.send_response(200)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

        self._server = ThreadingHTTPServer((address, port), Handler)
        self.address = address
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name=f"ray_tpu-policy-server-{self.port}")
        self._thread.start()

    # -- command handling -------------------------------------------------

    def _handle(self, req: dict) -> Any:
        cmd = req["command"]
        if cmd == START_EPISODE:
            eid = req.get("episode_id") or __import__("uuid").uuid4().hex
            with self._lock:
                self._episodes[eid] = _Episode(
                    eid, req.get("training_enabled", True))
            return eid
        if cmd == GET_WEIGHTS:
            return self._policy.get_weights()
        ep = self._episode(req["episode_id"])
        with ep.lock:  # concurrent requests for one episode serialize
            if cmd == GET_ACTION:
                return self._get_action(ep, req["observation"])
            if cmd == LOG_ACTION:
                return self._log_action(ep, req["observation"],
                                        req["action"],
                                        logp=req.get("logp"),
                                        vf=req.get("vf"))
            if cmd == LOG_RETURNS:
                ep.pending_reward += float(req["reward"])
                ep.total_reward += float(req["reward"])
                return None
            if cmd == END_EPISODE:
                return self._end_episode(ep, req["observation"])
        raise ValueError(f"unknown command {cmd!r}")

    def _episode(self, eid: str) -> _Episode:
        with self._lock:
            ep = self._episodes.get(eid)
        if ep is None:
            raise KeyError(f"episode {eid} not started")
        return ep

    def _record_prev(self, ep: _Episode, obs, done: bool) -> None:
        """Seal the previous (obs, action) pair now that its reward and
        successor observation are known."""
        if ep.prev_obs is None:
            return
        rows = ep.rows
        rows[SampleBatch.OBS].append(np.asarray(ep.prev_obs))
        rows[SampleBatch.NEXT_OBS].append(np.asarray(obs))
        rows[SampleBatch.ACTIONS].append(ep.prev_action)
        rows[SampleBatch.REWARDS].append(np.float32(ep.pending_reward))
        rows[SampleBatch.TERMINATEDS].append(np.float32(done))
        rows[SampleBatch.TRUNCATEDS].append(np.float32(0.0))
        rows[SampleBatch.ACTION_LOGP].append(np.float32(ep.prev_logp))
        rows[SampleBatch.VF_PREDS].append(np.float32(ep.prev_vf))
        rows[SampleBatch.EPS_ID].append(
            abs(hash(ep.episode_id)) % (1 << 31))
        ep.pending_reward = 0.0
        ep.length += 1

    def _get_action(self, ep: _Episode, obs):
        import jax
        self._record_prev(ep, obs, done=False)
        arr = np.asarray(obs)
        with self._lock:  # concurrent episodes share the stream: a
            # duplicated split would correlate their action sampling
            self._key, sub = jax.random.split(self._key)
        action, logp, value = self._policy.compute_actions(arr[None], sub)
        act = action[0]
        ep.prev_obs = arr
        ep.prev_action = act
        ep.prev_logp = float(logp[0])
        ep.prev_vf = float(value[0])
        return (int(act) if getattr(self._policy, "discrete", True)
                else np.asarray(act))

    def _log_action(self, ep: _Episode, obs, action,
                    logp: Optional[float] = None,
                    vf: Optional[float] = None) -> None:
        """Logged action: local-inference clients supply the logp/value
        their (synced) policy copy computed — surrogate ratios stay
        correct; without them (truly off-policy loggers), the value head
        still evaluates the observation (GAE needs it) and logp is 0."""
        self._record_prev(ep, obs, done=False)
        arr = np.asarray(obs)
        ep.prev_obs = arr
        ep.prev_action = action
        ep.prev_logp = float(logp) if logp is not None else 0.0
        if vf is not None:
            ep.prev_vf = float(vf)
        else:
            try:
                ep.prev_vf = float(
                    self._policy.compute_values(arr[None])[0])
            except Exception:  # noqa: BLE001 - value head optional
                ep.prev_vf = 0.0

    def _end_episode(self, ep: _Episode, obs) -> None:
        self._record_prev(ep, obs, done=True)
        with self._lock:
            self._episodes.pop(ep.episode_id, None)
            self.episode_rewards.append(ep.total_reward)
            self.episode_lengths.append(ep.length)
        if ep.training_enabled and ep.rows[SampleBatch.OBS]:
            batch = SampleBatch(
                {k: np.asarray(v) for k, v in ep.rows.items()})
            if getattr(self._policy, "needs_gae", True):
                batch = compute_gae(batch, self._gamma, self._lam, 0.0)
            self._batches.put(batch)
            with self._lock:
                self._rows_ready += len(batch)

    # -- training-loop face ----------------------------------------------

    def next(self) -> SampleBatch:
        """One completed episode fragment (blocks)."""
        return self._batches.get()

    def next_batch(self, min_rows: int,
                   timeout: Optional[float] = None) -> SampleBatch:
        """Accumulate completed episodes until ``min_rows`` training rows
        (reference: PolicyServerInput.next feeding train batches). With a
        timeout, returns whatever arrived by the deadline (raises
        queue.Empty only if NOTHING did)."""
        import time as _time
        deadline = (None if timeout is None
                    else _time.monotonic() + timeout)
        parts = [self._batches.get(timeout=timeout)]
        rows = len(parts[0])
        while rows < min_rows:
            if deadline is not None:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    break
            else:
                remaining = None
            try:
                part = self._batches.get(
                    timeout=0.05 if remaining is None
                    else min(0.05, remaining))
            except queue.Empty:
                continue
            parts.append(part)
            rows += len(part)
        return SampleBatch.concat_samples(parts)

    def episode_stats(self, window: int = 100) -> Dict[str, float]:
        rewards = self.episode_rewards[-window:]
        lengths = self.episode_lengths[-window:]
        return {
            "episodes": len(self.episode_rewards),
            "episode_reward_mean": (float(np.mean(rewards)) if rewards
                                    else float("nan")),
            "episode_len_mean": (float(np.mean(lengths)) if lengths
                                 else float("nan")),
        }

    def shutdown(self) -> None:
        self._server.shutdown()
