"""Atari preprocessing wrappers + a dependency-free Atari-shaped env.

Analog of the reference's rllib/env/wrappers/atari_wrappers.py (the
deepmind preprocessing stack: NoopReset, MaxAndSkip, EpisodicLife,
FireReset, WarpFrame, ClipReward, FrameStack, wrap_deepmind) — rebuilt
without cv2: the 84x84 warp is an area-weighted numpy resize, and frames
stay uint8 end-to-end (the CNN catalog scales to [0,1] inside jit, so
sample batches are 4x smaller than float32).

Because ALE is not a baked-in dependency, :class:`SyntheticAtariEnv`
provides a 210x160x3 uint8 game (Catch at Atari geometry: a falling ball,
a player paddle, +1/-1 reward per drop) with real credit-assignment
structure — a CNN policy must localize the ball and move the paddle to
score. It drives the PPO pixels-per-second north-star bench
(BASELINE.json: "RLlib PPO Atari with JAX policy learner") and the
pixel-pipeline regression tests on any machine; plugging a real
``gymnasium.make("ALE/...")`` env into ``wrap_deepmind`` uses the exact
same wrapper stack.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

try:  # spaces only; the wrappers work with any gymnasium-API env
    from gymnasium import spaces
except ImportError:  # pragma: no cover - gymnasium is a baked-in dep
    spaces = None


# ---------------------------------------------------------------------------
# Wrappers (gymnasium API: reset(seed=...) -> (obs, info);
#           step(a) -> (obs, reward, terminated, truncated, info))
# ---------------------------------------------------------------------------


class _Wrapper:
    """Minimal wrapper base (duck-typed; works with any gymnasium-API
    env, including other wrappers)."""

    def __init__(self, env):
        self.env = env
        self.observation_space = env.observation_space
        self.action_space = env.action_space

    def reset(self, *, seed: Optional[int] = None, options=None):
        return self.env.reset(seed=seed)

    def step(self, action):
        return self.env.step(action)

    def __getattr__(self, name):  # delegate e.g. .ale, .unwrapped
        return getattr(self.env, name)

    @property
    def unwrapped(self):
        return getattr(self.env, "unwrapped", self.env)


class NoopResetEnv(_Wrapper):
    """Start each episode with a random number of no-ops (reference:
    atari_wrappers.py NoopResetEnv) so deterministic envs don't yield a
    single start state."""

    def __init__(self, env, noop_max: int = 30, noop_action: int = 0):
        super().__init__(env)
        self.noop_max = noop_max
        self.noop_action = noop_action
        self._rng = np.random.default_rng(0)

    def reset(self, *, seed: Optional[int] = None, options=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        obs, info = self.env.reset(seed=seed)
        for _ in range(int(self._rng.integers(1, self.noop_max + 1))):
            obs, _, terminated, truncated, info = self.env.step(
                self.noop_action)
            if terminated or truncated:
                obs, info = self.env.reset()
        return obs, info


class MaxAndSkipEnv(_Wrapper):
    """Repeat the action ``skip`` frames; observe the pixelwise max of
    the last two (ALE sprites flicker on alternate frames)."""

    def __init__(self, env, skip: int = 4):
        super().__init__(env)
        self.skip = skip

    def step(self, action):
        total = 0.0
        frames = []
        terminated = truncated = False
        info = {}
        obs = None
        for _ in range(self.skip):
            obs, reward, terminated, truncated, info = self.env.step(action)
            frames.append(obs)
            total += float(reward)
            if terminated or truncated:
                break
        if len(frames) >= 2:
            obs = np.maximum(frames[-1], frames[-2])
        return obs, total, terminated, truncated, info


class EpisodicLifeEnv(_Wrapper):
    """End the learning episode on each life lost (value bootstraps stay
    honest) while only truly resetting the game when it's over. Requires
    an ALE-style ``lives()``; pass-through otherwise."""

    def __init__(self, env):
        super().__init__(env)
        self._lives = 0
        self._real_done = True

    def _env_lives(self) -> Optional[int]:
        ale = getattr(self.unwrapped, "ale", None)
        if ale is not None:
            return ale.lives()
        lives = getattr(self.unwrapped, "lives", None)
        return lives() if callable(lives) else None

    def step(self, action):
        obs, reward, terminated, truncated, info = self.env.step(action)
        self._real_done = terminated or truncated
        lives = self._env_lives()
        if lives is not None and 0 < lives < self._lives:
            terminated = True
        if lives is not None:
            self._lives = lives
        return obs, reward, terminated, truncated, info

    def reset(self, *, seed: Optional[int] = None, options=None):
        if self._real_done:
            obs, info = self.env.reset(seed=seed)
        else:  # life lost: keep playing from the current state
            obs, _, terminated, truncated, info = self.env.step(0)
            if terminated or truncated:
                obs, info = self.env.reset(seed=seed)
        lives = self._env_lives()
        self._lives = lives if lives is not None else 0
        return obs, info


class FireResetEnv(_Wrapper):
    """Press FIRE after reset for games that need it to start. Applied
    only when the env's action meanings include FIRE."""

    def __init__(self, env, fire_action: int = 1):
        super().__init__(env)
        self.fire_action = fire_action

    def reset(self, *, seed: Optional[int] = None, options=None):
        obs, info = self.env.reset(seed=seed)
        obs, _, terminated, truncated, info = self.env.step(self.fire_action)
        if terminated or truncated:
            obs, info = self.env.reset(seed=seed)
        return obs, info


def _area_resize(img: np.ndarray, out_h: int, out_w: int) -> np.ndarray:
    """Area-interpolated grayscale resize in pure numpy (the cv2
    INTER_AREA replacement). Splits each axis into ``out`` nearly-equal
    pixel bins and averages — exact for integer ratios, well-behaved for
    210->84 / 160->84."""
    h, w = img.shape
    # Bin edges: out_h+1 monotone integers covering [0, h].
    ye = (np.arange(out_h + 1) * h) // out_h
    xe = (np.arange(out_w + 1) * w) // out_w
    # Row-sum prefix trick: cumulative sums make each bin an O(1) slice.
    csum = np.zeros((h + 1, w + 1), np.float64)
    csum[1:, 1:] = np.cumsum(np.cumsum(img, axis=0), axis=1)
    areas = ((ye[1:] - ye[:-1])[:, None] * (xe[1:] - xe[:-1])[None, :])
    sums = (csum[ye[1:]][:, xe[1:]] - csum[ye[1:]][:, xe[:-1]]
            - csum[ye[:-1]][:, xe[1:]] + csum[ye[:-1]][:, xe[:-1]])
    return sums / areas


class WarpFrame(_Wrapper):
    """RGB -> grayscale, resized to ``dim``x``dim`` uint8 (the deepmind
    84x84 warp)."""

    LUMA = np.array([0.299, 0.587, 0.114], np.float32)

    def __init__(self, env, dim: int = 84):
        super().__init__(env)
        self.dim = dim
        if spaces is not None:
            self.observation_space = spaces.Box(
                0, 255, (dim, dim, 1), np.uint8)

    def _warp(self, frame):
        gray = np.asarray(frame, np.float32) @ self.LUMA
        out = _area_resize(gray, self.dim, self.dim)
        return np.clip(out, 0, 255).astype(np.uint8)[..., None]

    def reset(self, *, seed: Optional[int] = None, options=None):
        obs, info = self.env.reset(seed=seed)
        return self._warp(obs), info

    def step(self, action):
        obs, reward, terminated, truncated, info = self.env.step(action)
        return self._warp(obs), reward, terminated, truncated, info


class ClipRewardEnv(_Wrapper):
    """sign(reward): the deepmind cross-game reward normalization."""

    def step(self, action):
        obs, reward, terminated, truncated, info = self.env.step(action)
        return obs, float(np.sign(reward)), terminated, truncated, info


class FrameStackEnv(_Wrapper):
    """Stack the last ``k`` frames on the channel axis (uint8 in, uint8
    out): 84x84x1 k=4 -> 84x84x4, the velocity information a single
    frame lacks."""

    def __init__(self, env, k: int = 4):
        super().__init__(env)
        self.k = k
        self._frames: list = []
        shp = env.observation_space.shape
        if spaces is not None:
            self.observation_space = spaces.Box(
                0, 255, (shp[0], shp[1], shp[2] * k), np.uint8)

    def _obs(self):
        return np.concatenate(self._frames, axis=-1)

    def reset(self, *, seed: Optional[int] = None, options=None):
        obs, info = self.env.reset(seed=seed)
        self._frames = [obs] * self.k
        return self._obs(), info

    def step(self, action):
        obs, reward, terminated, truncated, info = self.env.step(action)
        self._frames = self._frames[1:] + [obs]
        return self._obs(), reward, terminated, truncated, info


def _action_meanings(env) -> Tuple[str, ...]:
    fn = getattr(getattr(env, "unwrapped", env), "get_action_meanings", None)
    try:
        return tuple(fn()) if callable(fn) else ()
    except Exception:  # noqa: BLE001 - non-ALE env
        return ()


def wrap_deepmind(env, dim: int = 84, framestack: int = 4,
                  frameskip: int = 4, episodic_life: bool = True,
                  clip_rewards: bool = True, noop_max: int = 30):
    """The full deepmind stack (reference: atari_wrappers.py
    wrap_deepmind), in the canonical order."""
    meanings = _action_meanings(env)
    if noop_max > 0:
        env = NoopResetEnv(env, noop_max=noop_max)
    if frameskip > 1:
        env = MaxAndSkipEnv(env, skip=frameskip)
    if episodic_life:
        env = EpisodicLifeEnv(env)
    if "FIRE" in meanings:
        env = FireResetEnv(env, fire_action=meanings.index("FIRE"))
    env = WarpFrame(env, dim=dim)
    if clip_rewards:
        env = ClipRewardEnv(env)
    if framestack > 1:
        env = FrameStackEnv(env, k=framestack)
    return env


# ---------------------------------------------------------------------------
# Synthetic Atari-shaped env
# ---------------------------------------------------------------------------


class SyntheticAtariEnv:
    """Catch at Atari geometry: 210x160x3 uint8 frames, a ball falling
    from a random column, a paddle on the bottom row driven by
    {NOOP, LEFT, RIGHT}. +1 per catch, -1 per miss, ``drops`` drops per
    episode. Solvable only by reading the pixels (ball x vs paddle x), so
    a learning curve here certifies the full CNN pipeline.
    """

    BALL = 8        # ball edge, px
    PADDLE_W = 24
    PADDLE_H = 6
    H, W = 210, 160
    STEP_X = 8      # paddle speed px/step
    FALL = 6        # ball speed px/step

    def __init__(self, config: Optional[dict] = None):
        config = config or {}
        self.drops = int(config.get("drops", 8))
        self.FALL = int(config.get("fall", self.FALL))
        self._seed = int(config.get("seed", 0))
        self._rng = np.random.default_rng(self._seed)
        if spaces is not None:
            self.observation_space = spaces.Box(
                0, 255, (self.H, self.W, 3), np.uint8)
            self.action_space = spaces.Discrete(3)
        self._frame = np.zeros((self.H, self.W, 3), np.uint8)

    def get_action_meanings(self):
        return ["NOOP", "LEFT", "RIGHT"]

    def _render(self) -> np.ndarray:
        f = self._frame
        f[:] = 0
        by, bx = int(self.ball_y), int(self.ball_x)
        f[max(by, 0):by + self.BALL, bx:bx + self.BALL, :] = (255, 255, 255)
        py = self.H - self.PADDLE_H
        px = int(self.paddle_x)
        f[py:, px:px + self.PADDLE_W, :] = (92, 186, 92)
        return f.copy()

    def _new_drop(self):
        self.ball_x = int(self._rng.integers(0, self.W - self.BALL))
        self.ball_y = 0

    def reset(self, *, seed: Optional[int] = None, options=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self.paddle_x = (self.W - self.PADDLE_W) // 2
        self.drops_left = self.drops
        self._new_drop()
        return self._render(), {}

    def step(self, action):
        action = int(action)
        if action == 1:
            self.paddle_x = max(self.paddle_x - self.STEP_X, 0)
        elif action == 2:
            self.paddle_x = min(self.paddle_x + self.STEP_X,
                                self.W - self.PADDLE_W)
        self.ball_y += self.FALL
        reward = 0.0
        if self.ball_y + self.BALL >= self.H - self.PADDLE_H:
            caught = (self.paddle_x - self.BALL < self.ball_x
                      < self.paddle_x + self.PADDLE_W)
            reward = 1.0 if caught else -1.0
            self.drops_left -= 1
            if self.drops_left > 0:
                self._new_drop()
        terminated = self.drops_left <= 0
        return self._render(), reward, terminated, False, {}


def make_synthetic_atari(config: Optional[dict] = None):
    """Env-creator for ``.environment(make_synthetic_atari)``: the
    synthetic game under the standard deepmind wrapper stack (no
    episodic-life/noop: the synthetic game has no lives and a random
    first drop already decorrelates starts)."""
    config = dict(config or {})
    framestack = int(config.pop("framestack", 4))
    frameskip = int(config.pop("frameskip", 1))
    dim = int(config.pop("dim", 84))
    env = SyntheticAtariEnv(config)
    return wrap_deepmind(env, dim=dim, framestack=framestack,
                         frameskip=frameskip, episodic_life=False,
                         noop_max=0)
