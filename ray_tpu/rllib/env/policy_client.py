"""PolicyClient: the environment-side half of client-server RL.

Analog of the reference's rllib/env/policy_client.py:58 — a process that
OWNS an environment (simulator, game, website backend) and connects to a
learner's :class:`~ray_tpu.rllib.env.policy_server_input.PolicyServerInput`
over HTTP. Two inference modes:

* ``remote`` — every get_action round-trips to the server, which runs the
  LIVE training policy (always-fresh actions; one RTT per step).
* ``local`` — the client pulls policy weights every ``update_interval``
  seconds and runs inference in-process (no per-step RTT; logged actions
  ship to the server for training).
"""

from __future__ import annotations

import pickle
import threading
import time
import urllib.request
from typing import Any, Optional

__all__ = ["PolicyClient"]

from ray_tpu.rllib.env.policy_server_input import (END_EPISODE, GET_ACTION,
                                                   GET_WEIGHTS, LOG_ACTION,
                                                   LOG_RETURNS,
                                                   START_EPISODE)


class PolicyClient:
    def __init__(self, address: str, inference_mode: str = "remote",
                 update_interval: float = 10.0,
                 policy_config: Optional[dict] = None,
                 observation_space=None, action_space=None):
        if not address.startswith("http"):
            address = f"http://{address}"
        self.address = address
        if inference_mode not in ("remote", "local"):
            raise ValueError("inference_mode must be 'remote' or 'local'")
        self.inference_mode = inference_mode
        self._local_policy = None
        self._update_interval = update_interval
        self._stop = False
        if inference_mode == "local":
            if policy_config is None or observation_space is None or \
                    action_space is None:
                raise ValueError(
                    "local inference needs policy_config, "
                    "observation_space and action_space (the client "
                    "builds its own policy copy)")
            import jax

            from ray_tpu.rllib.policy import make_policy
            self._local_policy = make_policy(
                policy_config, observation_space, action_space, seed=0)
            self._key = jax.random.PRNGKey(0xC11E)
            self.update_policy_weights()
            threading.Thread(target=self._weight_update_loop,
                             daemon=True,
                             name="ray_tpu-policy-client-sync").start()

    # -- wire ------------------------------------------------------------

    def _send(self, **req) -> Any:
        data = pickle.dumps(req)
        http_req = urllib.request.Request(
            self.address, data=data,
            headers={"Content-Type": "application/octet-stream"})
        with urllib.request.urlopen(http_req, timeout=60) as resp:
            reply = pickle.loads(resp.read())
        if not reply.get("ok"):
            raise RuntimeError(
                f"policy server error: {reply.get('error')}")
        return reply.get("result")

    # -- episode API -----------------------------------------------------

    def start_episode(self, episode_id: Optional[str] = None,
                      training_enabled: bool = True) -> str:
        return self._send(command=START_EPISODE, episode_id=episode_id,
                          training_enabled=training_enabled)

    def get_action(self, episode_id: str, observation):
        if self._local_policy is not None:
            import jax
            import numpy as np
            arr = np.asarray(observation)
            self._key, sub = jax.random.split(self._key)
            action, logp, value = self._local_policy.compute_actions(
                arr[None], sub)
            act = (int(action[0]) if self._local_policy.discrete
                   else np.asarray(action[0]))
            # Ship OUR logp/value with the transition: the synced local
            # copy IS (a recent snapshot of) the training policy, so
            # surrogate ratios stay meaningful server-side.
            self._send(command=LOG_ACTION, episode_id=episode_id,
                       observation=observation, action=act,
                       logp=float(logp[0]), vf=float(value[0]))
            return act
        return self._send(command=GET_ACTION, episode_id=episode_id,
                          observation=observation)

    def log_action(self, episode_id: str, observation, action) -> None:
        self._send(command=LOG_ACTION, episode_id=episode_id,
                   observation=observation, action=action)

    def log_returns(self, episode_id: str, reward: float,
                    info: Optional[dict] = None) -> None:
        self._send(command=LOG_RETURNS, episode_id=episode_id,
                   reward=float(reward))

    def end_episode(self, episode_id: str, observation) -> None:
        self._send(command=END_EPISODE, episode_id=episode_id,
                   observation=observation)

    def update_policy_weights(self) -> None:
        if self._local_policy is not None:
            self._local_policy.set_weights(self._send(command=GET_WEIGHTS))

    def _weight_update_loop(self) -> None:
        while not self._stop:
            time.sleep(self._update_interval)
            try:
                self.update_policy_weights()
            except Exception:  # noqa: BLE001 - server restarting
                pass

    def stop(self) -> None:
        self._stop = True
