"""ExternalEnv: environments that drive THEMSELVES instead of being
stepped.

Analog of the reference's rllib/env/external_env.py:22 — the agent loop
lives in the environment (a simulator, a website, a game server), and the
framework answers its action queries instead of calling reset()/step().
The episode API (start_episode / get_action / log_action / log_returns /
end_episode) runs on the environment's own thread; a queue-pair per
episode hands observations to the sampler and actions back.

TPU-first integration: rather than a dedicated poll/send sampler stack,
:class:`GymAdapter` exposes the queue protocol as a plain reset()/step()
environment, so external envs ride the SAME vectorized samplers (and
batched-inference path) every other env uses — RolloutWorker detects an
ExternalEnv and wraps it automatically.
"""

from __future__ import annotations

import queue
import threading
import uuid
from typing import Any, Dict, Optional

__all__ = ["ExternalEnv", "GymAdapter"]


class _EpisodeDone(Exception):
    pass


class _ExternalEnvEpisode:
    """One running episode: observation/action handoff + reward ledger
    (reference: external_env.py:244 _ExternalEnvEpisode)."""

    def __init__(self, episode_id: str, training_enabled: bool = True):
        self.episode_id = episode_id
        self.training_enabled = training_enabled
        # env thread -> sampler: (obs, reward_since_last, done)
        self.obs_q: "queue.Queue" = queue.Queue(maxsize=1)
        # sampler -> env thread: action
        self.action_q: "queue.Queue" = queue.Queue(maxsize=1)
        self.pending_reward = 0.0
        self.done = False
        self.logged_action: Optional[Any] = None

    def push_obs(self, obs, done: bool = False) -> None:
        reward = self.pending_reward
        self.pending_reward = 0.0
        self.done = self.done or done
        self.obs_q.put((obs, reward, done, self.logged_action))
        self.logged_action = None

    def wait_action(self, timeout: Optional[float] = None):
        return self.action_q.get(timeout=timeout)


class ExternalEnv(threading.Thread):
    """Subclass and implement :meth:`run` with your agent loop
    (reference: external_env.py:22). Example::

        class MySim(ExternalEnv):
            def run(self):
                while True:
                    eid = self.start_episode()
                    obs = self.sim.reset()
                    done = False
                    while not done:
                        action = self.get_action(eid, obs)
                        obs, reward, done = self.sim.step(action)
                        self.log_returns(eid, reward)
                    self.end_episode(eid, obs)
    """

    def __init__(self, action_space=None, observation_space=None,
                 max_concurrent: int = 100):
        super().__init__(daemon=True)
        self.action_space = action_space
        self.observation_space = observation_space
        self._episodes: Dict[str, _ExternalEnvEpisode] = {}
        self._max_concurrent = max_concurrent
        self._lock = threading.Lock()
        # Episodes with an observation waiting for an action.
        self._ready: "queue.Queue" = queue.Queue()

    # -- episode API (called from the env's run() thread) ----------------

    def run(self) -> None:
        raise NotImplementedError(
            "Subclasses of ExternalEnv must implement run() — the "
            "environment's own agent loop.")

    def start_episode(self, episode_id: Optional[str] = None,
                      training_enabled: bool = True) -> str:
        episode_id = episode_id or uuid.uuid4().hex
        with self._lock:
            if episode_id in self._episodes:
                raise ValueError(f"episode {episode_id} already started")
            if len(self._episodes) >= self._max_concurrent:
                raise RuntimeError(
                    f"too many concurrent episodes (max "
                    f"{self._max_concurrent})")
            self._episodes[episode_id] = _ExternalEnvEpisode(
                episode_id, training_enabled)
        return episode_id

    def get_action(self, episode_id: str, observation):
        """Block until the policy answers with an action."""
        ep = self._get(episode_id)
        ep.push_obs(observation)
        self._ready.put(ep)
        return ep.wait_action()

    def log_action(self, episode_id: str, observation, action) -> None:
        """Record an action the CALLER chose (off-policy data)."""
        ep = self._get(episode_id)
        ep.logged_action = action
        ep.push_obs(observation)
        self._ready.put(ep)
        ep.wait_action()  # sampler echoes the logged action back

    def log_returns(self, episode_id: str, reward: float,
                    info: Optional[dict] = None) -> None:
        self._get(episode_id).pending_reward += float(reward)

    def end_episode(self, episode_id: str, observation) -> None:
        ep = self._get(episode_id)
        ep.push_obs(observation, done=True)
        self._ready.put(ep)
        with self._lock:
            self._episodes.pop(episode_id, None)

    def _get(self, episode_id: str) -> _ExternalEnvEpisode:
        with self._lock:
            ep = self._episodes.get(episode_id)
        if ep is None:
            raise KeyError(
                f"episode {episode_id} is not running (not started with "
                "a name, or already ended)")
        return ep


class GymAdapter:
    """Exposes an ExternalEnv through reset()/step() so the standard
    (vectorized, batched-inference) samplers drive it unchanged — the
    queue protocol inverted back into a pull interface. One adapter
    serves episodes strictly sequentially; concurrency comes from
    num_envs_per_worker adapters over one shared ExternalEnv."""

    def __init__(self, external: ExternalEnv):
        self._external = external
        self._episode: Optional[_ExternalEnvEpisode] = None
        self._last_obs = None
        self.action_space = external.action_space
        self.observation_space = external.observation_space
        if not external.is_alive():
            try:
                external.start()
            except RuntimeError:
                pass  # another adapter already started the thread

    def _next_ready(self, timeout: float = 60.0) -> _ExternalEnvEpisode:
        return self._external._ready.get(timeout=timeout)

    def reset(self, seed=None, **_kw):
        # The env thread decides when episodes begin; reset == wait for
        # the next observation that needs an action.
        ep = self._next_ready()
        obs, _reward, done, _logged = ep.obs_q.get(timeout=60)
        if done:
            # Zero-step episode; recurse to the next real one.
            return self.reset()
        self._episode = ep
        self._last_obs = obs
        return obs, {}

    def step(self, action):
        ep = self._episode
        if ep is None:
            raise RuntimeError("step() before reset()")
        ep.action_q.put(action)
        nxt = self._next_ready()
        obs, reward, done, logged = nxt.obs_q.get(timeout=60)
        if nxt is not ep:
            # A different episode surfaced (concurrent episodes on one
            # adapter): truncate OURS — with our own last observation as
            # the terminal obs (a foreign episode's obs in NEXT_OBS would
            # pollute the value bootstrap) — and re-queue the surfaced
            # one for the next reset().
            nxt.obs_q.put((obs, reward, done, logged))
            self._external._ready.put(nxt)
            self._episode = None
            return self._last_obs, 0.0, False, True, {}
        if done:
            self._episode = None
            return obs, reward, True, False, {}
        self._last_obs = obs
        return obs, reward, False, False, {"logged_action": logged}
