"""Small multi-agent example envs for tests and tuned examples
(reference: rllib/examples/env/ — two-step game, coordination tasks)."""

from __future__ import annotations

from typing import Optional

import numpy as np

from ray_tpu.rllib.env.multi_agent_env import MultiAgentEnv

try:
    from gymnasium import spaces
except ImportError:  # pragma: no cover
    spaces = None


class CoordinationGameEnv(MultiAgentEnv):
    """Cooperative context matching (QMIX's home turf): each round both
    agents observe the same one-hot context and must BOTH play the action
    equal to the context index to score — the team earns 1.0 only on
    joint success, split evenly, so credit assignment runs through the
    team reward. ``rounds`` rounds per episode; optimal team return =
    rounds; uniform-random = rounds / actions^2."""

    def __init__(self, config: Optional[dict] = None):
        config = dict(config or {})
        self.rounds = int(config.get("rounds", 10))
        self.n_contexts = int(config.get("n_contexts", 2))
        self.n_actions = int(config.get("n_actions", 3))
        self._seed = int(config.get("seed", 0))
        self.agent_ids = {"a0", "a1"}
        self._rng = np.random.default_rng(self._seed)
        if spaces is not None:
            self.observation_space = spaces.Box(
                0.0, 1.0, (self.n_contexts,), np.float32)
            self.action_space = spaces.Discrete(self.n_actions)
        self._t = 0
        self._ctx = 0

    def _obs(self):
        onehot = np.zeros(self.n_contexts, np.float32)
        onehot[self._ctx] = 1.0
        return {"a0": onehot.copy(), "a1": onehot.copy()}

    def reset(self, *, seed=None, options=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._t = 0
        self._ctx = int(self._rng.integers(self.n_contexts))
        return self._obs(), {}

    def step(self, action_dict):
        match = all(int(action_dict[aid]) == self._ctx
                    for aid in ("a0", "a1"))
        r = 0.5 if match else 0.0
        self._t += 1
        done = self._t >= self.rounds
        self._ctx = int(self._rng.integers(self.n_contexts))
        obs = self._obs()
        rewards = {"a0": r, "a1": r}
        terms = {"a0": done, "a1": done, "__all__": done}
        truncs = {"a0": False, "a1": False, "__all__": False}
        return obs, rewards, terms, truncs, {}


class CooperativeNavEnv(MultiAgentEnv):
    """Continuous cooperative navigation (MADDPG's home turf — the
    "simple spread" task of the MPE suite the reference's MADDPG README
    points at): ``n_agents`` point masses must cover ``n_agents``
    landmarks in the 2D unit box. The team reward (shared equally) is
    minus the sum over landmarks of the distance to the CLOSEST agent —
    maximized only when the agents divide the landmarks among
    themselves, which requires coordinating through the joint state.
    Observations: own position ++ all landmark offsets ++ other agents'
    positions. Actions: Box(2,) velocity in [-1, 1], integrated with
    ``dt``."""

    def __init__(self, config: Optional[dict] = None):
        config = dict(config or {})
        self.n_agents = int(config.get("n_agents", 2))
        self.horizon = int(config.get("horizon", 25))
        self.dt = float(config.get("dt", 0.15))
        self.agent_ids = {f"a{i}" for i in range(self.n_agents)}
        self._ids = sorted(self.agent_ids)
        obs_dim = 2 + 2 * self.n_agents + 2 * (self.n_agents - 1)
        if spaces is not None:
            # Landmark offsets span [-3, 3]: positions clip at +-2 and
            # landmarks spawn in [-1, 1].
            self.observation_space = spaces.Box(
                -3.0, 3.0, (obs_dim,), np.float32)
            self.action_space = spaces.Box(-1.0, 1.0, (2,), np.float32)
        self._rng = np.random.default_rng(config.get("seed", 0))
        self._pos = None
        self._landmarks = None
        self._t = 0

    def _obs(self):
        out = {}
        for i, aid in enumerate(self._ids):
            others = np.delete(self._pos, i, axis=0)
            out[aid] = np.concatenate(
                [self._pos[i], (self._landmarks - self._pos[i]).ravel(),
                 others.ravel()]).astype(np.float32)
        return out

    def _team_reward(self) -> float:
        d = np.linalg.norm(
            self._landmarks[:, None, :] - self._pos[None, :, :], axis=-1)
        return float(-d.min(axis=1).sum())

    def reset(self, *, seed=None, options=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._pos = self._rng.uniform(-1, 1, (self.n_agents, 2))
        self._landmarks = self._rng.uniform(-1, 1, (self.n_agents, 2))
        self._t = 0
        return self._obs(), {}

    def step(self, action_dict):
        for i, aid in enumerate(self._ids):
            a = np.clip(np.asarray(action_dict[aid], np.float64), -1, 1)
            self._pos[i] = np.clip(self._pos[i] + self.dt * a, -2, 2)
        self._t += 1
        done = self._t >= self.horizon
        r = self._team_reward() / self.n_agents
        obs = self._obs()
        rewards = {aid: r for aid in self._ids}
        terms = {aid: done for aid in self._ids}
        terms["__all__"] = done
        truncs = {aid: False for aid in self._ids}
        truncs["__all__"] = False
        return obs, rewards, terms, truncs, {}


class ClonableCartPole:
    """Sparse-reward CartPole with ``get_state``/``set_state`` — the
    clonable-env contract AlphaZero's MCTS needs (reference:
    rllib/algorithms/alpha_zero README + its test task
    examples/env/cartpole_sparse_rewards.py). Reward accumulates
    silently and pays out ONLY at termination (the episode score) — the
    board-game shape AlphaZero's undiscounted backup expects; the
    algorithm's ranked-rewards transform then maps that score to +-1.
    Observations are the prescribed dict {"obs", "action_mask"} (every
    move legal here)."""

    def __init__(self, config: Optional[dict] = None):
        import gymnasium as gym
        config = dict(config or {})
        # UNWRAPPED: gym's TimeLimit wrapper counts every step — MCTS
        # simulations would burn the episode budget and set_state cannot
        # restore the wrapper's counter. AlphaZeroConfig.max_episode_steps
        # bounds self-play episodes instead.
        self._env = gym.make("CartPole-v1").unwrapped
        self.action_space = self._env.action_space
        # The DECLARED space matches the emitted dict (the reference
        # declares a Dict space for its sparse-rewards CartPole too).
        self.observation_space = spaces.Dict({
            "obs": self._env.observation_space,
            "action_mask": spaces.Box(0.0, 1.0, (self.action_space.n,),
                                      np.float32),
        })
        self._steps = 0
        self._running = 0.0

    def _obs(self, raw):
        return {"obs": np.asarray(raw, np.float32),
                "action_mask": np.ones(self.action_space.n, np.float32)}

    def reset(self, *, seed=None, options=None):
        raw, info = self._env.reset(seed=seed, options=options)
        self._steps = 0
        self._running = 0.0
        return self._obs(raw), info

    def step(self, action):
        raw, r, term, trunc, info = self._env.step(int(action))
        self._steps += 1
        self._running += float(r)
        score = self._running if term else 0.0
        return self._obs(raw), score, term, trunc, info

    def get_state(self):
        env = self._env.unwrapped
        return (np.array(env.state, np.float64), self._steps,
                self._running, env.steps_beyond_terminated)

    def set_state(self, state):
        arr, steps, running, beyond = state
        env = self._env.unwrapped
        env.state = tuple(arr.tolist())
        # Restored states may predate a simulated termination — without
        # this, post-restore steps hit gym's already-terminated warning
        # path and return 0 reward.
        env.steps_beyond_terminated = beyond
        self._steps = steps
        self._running = running

    def episode_score(self) -> float:
        """Accumulated-but-unpaid score (AlphaZero reads this when its
        step budget ends an episode before the env does)."""
        return self._running

    def close(self):
        self._env.close()


class PointGoalEnv:
    """1D point-mass reach-the-goal task: obs = [pos], Box action moves
    the point, reward = -|pos - goal|, 30-step episodes. goal defaults
    to the origin; a HIDDEN nonzero goal (env_config {"goal": g},
    deliberately absent from the observation) turns it into a meta-RL
    task family — the policy must adapt from REWARDS (MAML's home
    turf). The world model is learnable in a few hundred steps, which
    also makes this the CI-affordable learning gate for model-based
    algorithms (Dreamer) whose sample cost on classic-control tasks
    far exceeds a test budget; random ~= -60/episode (goal 0),
    competent ~= -40 or better."""

    def __init__(self, config: Optional[dict] = None):
        from gymnasium import spaces as _spaces
        config = dict(config or {})
        self.goal = float(config.get("goal", 0.0))
        self.horizon = int(config.get("horizon", 30))
        self.observation_space = _spaces.Box(-5.0, 5.0, (1,), np.float32)
        self.action_space = _spaces.Box(-1.0, 1.0, (1,), np.float32)
        self._rng = np.random.default_rng(config.get("seed", 0))
        self.pos = 0.0
        self._t = 0

    def reset(self, *, seed=None, options=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self.pos = float(self._rng.uniform(-3, 3))
        self._t = 0
        return np.array([self.pos], np.float32), {}

    def step(self, action):
        a = float(np.clip(np.asarray(action).reshape(-1), -1, 1)[0])
        self.pos = float(np.clip(self.pos + a, -5, 5))
        self._t += 1
        return (np.array([self.pos], np.float32),
                -abs(self.pos - self.goal),
                False, self._t >= self.horizon, {})

    def reward_fn(self, state, action, next_state) -> float:
        """Known reward over (s, a, s') — the contract model-based
        algorithms (MBMPO) need to roll imagined trajectories without
        the env (the reference likewise pairs MBMPO with envs exposing
        reward functions)."""
        return -abs(float(np.asarray(next_state).reshape(-1)[0])
                    - self.goal)

    def close(self):
        pass


class TwoPlayerRepeatedRPS(MultiAgentEnv):
    """Two-player repeated rock-paper-scissors — the competitive
    self-play testbed for league training (AlphaStar's match shape at
    CI scale). Each round both agents pick {0,1,2}; rewards are the
    zero-sum payoff; observations are one-hot encodings of BOTH last
    moves (own, opponent's) so a policy can learn to exploit an
    opponent's conditional biases. Episodes run ``rounds`` rounds."""

    agent_ids = {"p0", "p1"}

    def __init__(self, config: Optional[dict] = None):
        import gymnasium.spaces as _spaces
        config = dict(config or {})
        self.rounds = int(config.get("rounds", 8))
        self.observation_space = _spaces.Box(0.0, 1.0, (6,), np.float32)
        self.action_space = _spaces.Discrete(3)
        self._t = 0
        self._last = {"p0": None, "p1": None}

    def _obs_for(self, me: str, other: str) -> np.ndarray:
        obs = np.zeros(6, np.float32)
        if self._last[me] is not None:
            obs[self._last[me]] = 1.0
            obs[3 + self._last[other]] = 1.0
        return obs

    def reset(self, *, seed=None, options=None):
        self._t = 0
        self._last = {"p0": None, "p1": None}
        return ({"p0": self._obs_for("p0", "p1"),
                 "p1": self._obs_for("p1", "p0")}, {})

    def step(self, action_dict):
        a0 = int(action_dict["p0"])
        a1 = int(action_dict["p1"])
        # 0 beats 2, 1 beats 0, 2 beats 1 (rock/paper/scissors cycle).
        if a0 == a1:
            r0 = 0.0
        elif (a0 - a1) % 3 == 1:
            r0 = 1.0
        else:
            r0 = -1.0
        self._last = {"p0": a0, "p1": a1}
        self._t += 1
        done = self._t >= self.rounds
        obs = {"p0": self._obs_for("p0", "p1"),
               "p1": self._obs_for("p1", "p0")}
        return (obs, {"p0": r0, "p1": -r0},
                {"__all__": done, "p0": done, "p1": done},
                {"__all__": False, "p0": False, "p1": False}, {})
